"""Latency-budget ledger unit tests (runtime/latency_budget.py).

The tentpole claim is *conservation*: every closed epoch's attributed
components plus the ``unattributed_ms`` residual equal the measured
end-to-end wall time, regardless of how noisy the externally-measured
splits are.  These tests drive the cursor arithmetic with synthetic
clocks (no sleeps), so the invariant is checked exactly.
"""

import pytest

from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.latency_budget import (
    BUDGET_COMPONENTS,
    CONSERVATION_EPSILON_MS,
    EpochBudget,
    LatencyBudgetLedger,
    latency_budget,
    tail_attribution,
)


@pytest.fixture
def ledger():
    lg = LatencyBudgetLedger()
    counters.erase_prefix("budget.")
    yield lg
    counters.erase_prefix("budget.")


def _conserved(row):
    total = sum(row["components"].values()) + row["unattributed_ms"]
    assert total == pytest.approx(row["e2e_ms"], abs=0.01), row
    return row


class TestCursor:
    def test_advance_attributes_segments_and_conserves(self, ledger):
        bud = ledger.begin("k", start=10.0)
        bud.advance("ingest_wait", now=10.002)     # 2 ms
        bud.advance("host_sync", now=10.010)       # 8 ms
        bud.advance("device_exec", now=10.013)     # 3 ms
        row = _conserved(ledger.close(bud, now=10.013))
        assert row["components"] == {
            "ingest_wait": pytest.approx(2.0),
            "host_sync": pytest.approx(8.0),
            "device_exec": pytest.approx(3.0),
        }
        assert row["e2e_ms"] == pytest.approx(13.0)
        assert row["unattributed_ms"] == 0.0
        assert row["top_component"] == "host_sync"

    def test_stale_now_clamps_to_cursor(self):
        bud = EpochBudget("k", 10.0)
        bud.advance("ingest_wait", now=10.005)
        # a stamp from an out-of-order clock read earlier than the
        # cursor must attribute nothing, never go negative
        assert bud.advance("host_sync", now=10.001) == 0.0
        assert "host_sync" not in bud.components
        assert bud.cursor == 10.005

    def test_advance_split_clips_overclaim_to_segment(self, ledger):
        bud = ledger.begin("k", start=0.0)
        # segment is 10 ms, but the external measurements claim 9 + 8:
        # the second split gets clipped to the 1 ms remainder and the
        # primary gets nothing — conservation survives the over-claim
        bud.advance_split(
            {"device_exec": 9.0, "payload_apply": 8.0},
            primary="collect_block",
            now=0.010,
        )
        row = _conserved(ledger.close(bud, now=0.010))
        assert row["components"]["device_exec"] == pytest.approx(9.0)
        assert row["components"]["payload_apply"] == pytest.approx(1.0)
        assert "collect_block" not in row["components"]

    def test_advance_split_remainder_goes_to_primary(self, ledger):
        bud = ledger.begin("k", start=0.0)
        # splits cover 3 of the 10 ms; primary absorbs the rest, and a
        # None measurement (solver did not report the stage) is 0
        bud.advance_split(
            {"device_exec": 2.0, "payload_apply": None, "program": 1.0},
            primary="collect_block",
            now=0.010,
        )
        row = _conserved(ledger.close(bud, now=0.010))
        assert row["components"]["collect_block"] == pytest.approx(7.0)

    def test_final_component_absorbs_close_tail(self, ledger):
        bud = ledger.begin("k", start=0.0)
        bud.advance("program", now=0.004)
        row = _conserved(
            ledger.close(bud, final_component="ack_rtt", now=0.009)
        )
        assert row["components"]["ack_rtt"] == pytest.approx(5.0)
        assert row["unattributed_ms"] == 0.0

    def test_unstamped_gap_is_unattributed(self, ledger):
        bud = ledger.begin("k", start=0.0)
        bud.advance("program", now=0.004)
        # no final_component: the [cursor, close] tail is exactly the
        # residual the drift SLO pages on
        row = _conserved(ledger.close(bud, now=0.010))
        assert row["unattributed_ms"] == pytest.approx(6.0)


class TestLedgerLifecycle:
    def test_begin_dedups_by_key(self, ledger):
        a = ledger.begin("k", start=0.0)
        b = ledger.begin("k", start=99.0)
        assert a is b

    def test_close_records_stats_for_every_component(self, ledger):
        bud = ledger.begin("k", start=0.0)
        bud.advance("host_sync", now=0.010)
        ledger.close(bud, now=0.010)
        stats = counters.get_statistics("budget.")
        # zeros included: an idle component's p99 of 0 is information
        for comp in BUDGET_COMPONENTS:
            assert f"budget.{comp}_ms" in stats, comp
        assert "budget.e2e_ms" in stats
        assert "budget.unattributed_ms" in stats
        assert counters.get_counter("budget.epochs") == 1

    def test_close_is_idempotent(self, ledger):
        bud = ledger.begin("k", start=0.0)
        assert ledger.close(bud, now=0.001) is not None
        assert ledger.close(bud, now=0.002) is None
        assert counters.get_counter("budget.epochs") == 1

    def test_requeued_status_counts_separately(self, ledger):
        bud = ledger.begin("k", start=0.0)
        bud.advance("fence_hold", now=0.003)
        row = ledger.close(bud, status="requeued", now=0.003)
        assert row["status"] == "requeued"
        assert counters.get_counter("budget.requeued_epochs") == 1

    def test_discard_drops_without_stats(self, ledger):
        ledger.begin("k", start=0.0)
        ledger.discard("k")
        assert ledger.of("k") is None
        assert counters.get_counter("budget.discarded") == 1
        assert counters.get_counter("budget.epochs") is None
        assert ledger.last_epochs() == []

    def test_eviction_at_capacity_is_counted(self, ledger):
        from openr_tpu.runtime import latency_budget as mod

        for i in range(mod._MAX_ACTIVE + 3):
            ledger.begin(("leak", i), start=0.0)
        assert counters.get_counter("budget.evicted") == 3
        # the oldest leaked epochs were the ones evicted
        assert ledger.of(("leak", 0)) is None
        assert ledger.of(("leak", 3)) is not None


class TestTraceIntegration:
    def test_begin_for_trace_anchors_at_trace_start(self, ledger):
        from openr_tpu.runtime.tracing import tracer

        tracer.clear()
        ctx = tracer.start_trace("convergence", node="n0")
        try:
            bud = latency_budget.begin_for_trace(ctx)
            assert bud is not None
            # anchored at the trace's own monotonic start, so the first
            # advance() sees the queue wait that preceded the pickup
            assert bud.start == pytest.approx(tracer.trace_start(ctx))
            bud.advance("ingest_wait")
            assert bud.components.get("ingest_wait", 0.0) >= 0.0
            assert latency_budget.of_trace(ctx) is bud
        finally:
            latency_budget.discard_trace(ctx)
            tracer.clear()
            counters.erase_prefix("budget.")

    def test_close_trace_returns_conserved_row(self, ledger):
        from openr_tpu.runtime.tracing import tracer

        tracer.clear()
        ctx = tracer.start_trace("convergence", node="n0")
        try:
            bud = latency_budget.begin_for_trace(ctx)
            bud.advance("host_sync")
            row = latency_budget.close_trace(
                ctx, final_component="ack_rtt"
            )
            assert row is not None
            _conserved(row)
            assert latency_budget.of_trace(ctx) is None
        finally:
            tracer.clear()
            counters.erase_prefix("budget.")


class TestReporting:
    def test_report_shape_and_conservation_block(self, ledger):
        for i in range(4):
            bud = ledger.begin(("e", i), start=0.0)
            bud.advance("host_sync", now=0.002 + i * 0.001)
            ledger.close(bud, final_component="ack_rtt",
                         now=0.004 + i * 0.001)
        rep = ledger.report()
        assert rep["taxonomy"] == list(BUDGET_COMPONENTS)
        assert "host_sync" in rep["components"]
        assert rep["conservation"]["epochs"] == 4
        assert rep["conservation"]["epsilon_ms"] == CONSERVATION_EPSILON_MS
        assert len(rep["last_epochs"]) == 4
        assert rep["tail"]["ranked"], rep["tail"]

    def test_snapshot_compact_annex(self, ledger):
        bud = ledger.begin("k", start=0.0)
        bud.advance("program", now=0.003)
        ledger.close(bud, now=0.003)
        snap = ledger.snapshot()
        assert snap["epochs"] == 1
        assert set(snap["components"]) == set(BUDGET_COMPONENTS)
        assert snap["e2e"].get("count") == 1
        assert len(snap["last_epochs"]) == 1


class TestTailAttribution:
    def test_top2_coverage_ranks_the_moving_components(self):
        # host_sync owns the tail (40 ms of the 41 ms p50->p99 gap),
        # program wiggles by 1 ms, device_exec is flat
        e2e = [10.0] * 9 + [51.0]
        comps = {
            "host_sync": [5.0] * 9 + [45.0],
            "program": [1.0] * 9 + [2.0],
            "device_exec": [4.0] * 10,
        }
        out = tail_attribution(comps, e2e)
        assert out["e2e_gap_ms"] == pytest.approx(41.0)
        assert out["ranked"][0]["component"] == "host_sync"
        assert out["top2_coverage"] == pytest.approx(1.0)

    def test_empty_samples_report_none_coverage(self):
        out = tail_attribution({c: [] for c in BUDGET_COMPONENTS}, [])
        assert out["e2e_gap_ms"] == 0.0
        assert out["ranked"] == []
        assert out["top2_coverage"] is None
