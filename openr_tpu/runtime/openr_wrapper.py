"""OpenrWrapper — one node's complete module stack, in-process.

Role of the reference's openr/tests/OpenrWrapper.h:38: instantiate the full
module chain per "node" (kvstore, spark, link-monitor, decision, fib) with
all queues wired exactly as the daemon does (ref Main.cpp:223-266), over a
shared MockIoMesh — an emulated multi-node network in one process with
sped-up timers (ref OpenrSystemTest.cpp:38-48). The daemon composition
root (main.py) uses the same wiring with real I/O providers.
"""

from __future__ import annotations

from typing import Optional

from openr_tpu.config import (
    DecisionConfig,
    FibConfig,
    KvstoreConfig,
    LinkMonitorConfig,
    SparkConfig,
)
from openr_tpu.decision.decision import Decision
from openr_tpu.fib import Fib, MockFibService
from openr_tpu.fib.fib_service import FibServiceBase
from openr_tpu.kvstore.kvstore import KvStore
from openr_tpu.link_monitor import LinkMonitor
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.prefix_manager import OriginatedPrefix, PrefixManager
from openr_tpu.spark import IoProvider, Spark
from openr_tpu.types import (
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixType,
)

# sped-up timers for in-process emulation (ref OpenrSystemTest.cpp:38-48)
EMULATION_SPARK_CONFIG = SparkConfig(
    hello_time_s=0.08,
    fastinit_hello_time_ms=20,
    keepalive_time_s=0.05,
    hold_time_s=0.4,
    graceful_restart_time_s=0.5,
    handshake_time_ms=40,
    min_packets_per_sec=0,
)


class OpenrWrapper:
    """The whole-stack-per-node seam (SURVEY §4 item 4)."""

    def __init__(
        self,
        node_name: str,
        io_provider: IoProvider,
        kv_ports: dict[str, int],
        areas: Optional[list[str]] = None,
        spark_config: Optional[SparkConfig] = None,
        kvstore_config: Optional[KvstoreConfig] = None,
        decision_config: Optional[DecisionConfig] = None,
        fib_config: Optional[FibConfig] = None,
        lm_config: Optional[LinkMonitorConfig] = None,
        fib_service: Optional[FibServiceBase] = None,
        originated_prefixes: Optional[list[OriginatedPrefix]] = None,
        solver_backend: str = "cpu",
        enable_ctrl: bool = False,
        ctrl_port: int = 0,
        persistent_store=None,
        kvstore_port_of=None,
        node_label: int = 0,
        policy_manager=None,
        origination_policy: str = "",
        plugins: Optional[list[str]] = None,
        running_config=None,
        monitor=None,
        kv_listen_addr: str = "127.0.0.1",
        resolve_area=None,
        area_policies: Optional[dict[str, str]] = None,
    ):
        self.node_name = node_name
        self.kv_ports = kv_ports  # shared node -> kvstore port registry
        areas = areas or ["0"]

        # queues (ref Main.cpp:223-239)
        self.neighbor_updates_queue = ReplicateQueue(f"{node_name}.neighborUpdates")
        self.peer_updates_queue = ReplicateQueue(f"{node_name}.peerUpdates")
        self.kv_request_queue = ReplicateQueue(f"{node_name}.kvRequests")
        self.kvstore_updates_queue = ReplicateQueue(f"{node_name}.kvStoreUpdates")
        self.kvstore_events_queue = ReplicateQueue(f"{node_name}.kvStoreEvents")
        self.interface_updates_queue = ReplicateQueue(f"{node_name}.interfaceUpdates")
        self.static_routes_queue = ReplicateQueue(f"{node_name}.staticRoutes")
        self.route_updates_queue = ReplicateQueue(f"{node_name}.routeUpdates")
        self.fib_updates_queue = ReplicateQueue(f"{node_name}.fibRouteUpdates")
        self.prefix_updates_queue = ReplicateQueue(f"{node_name}.prefixUpdates")
        self.log_sample_queue = ReplicateQueue(f"{node_name}.logSamples")

        kv_cfg = kvstore_config or KvstoreConfig()
        kv_server_ssl = kv_client_ssl = None
        if kv_cfg.enable_secure_peers:
            # peer-plane TLS reuses the ctrl-plane certificates; the CA
            # is mandatory (mutual auth — unauthenticated flooding would
            # let any on-path host inject LSDB state)
            from openr_tpu.config import (
                ConfigError,
                build_client_ssl_context,
                build_server_ssl_context,
            )

            if running_config is None:
                raise ConfigError(
                    "kvstore enable_secure_peers needs the running config "
                    "(thrift_server certificate paths)"
                )
            ts = running_config.raw.thrift_server
            if not ts.x509_ca_path:
                raise ConfigError(
                    "kvstore enable_secure_peers requires x509_ca_path "
                    "(mutual auth on the peer plane)"
                )
            kv_server_ssl = build_server_ssl_context(ts)
            kv_client_ssl = build_client_ssl_context(
                ts.x509_ca_path, ts.x509_cert_path, ts.x509_key_path
            )
        self.kvstore = KvStore(
            node_name,
            kv_cfg,
            areas,
            self.peer_updates_queue.get_reader(),
            self.kv_request_queue.get_reader(),
            self.kvstore_updates_queue,
            self.kvstore_events_queue,
            listen_addr=kv_listen_addr,
            server_ssl=kv_server_ssl,
            client_ssl=kv_client_ssl,
        )
        self.spark = Spark(
            node_name,
            spark_config or EMULATION_SPARK_CONFIG,
            io_provider,
            self.neighbor_updates_queue,
            interface_updates_queue=self.interface_updates_queue.get_reader(),
            # area negotiation (ref AreaConfiguration matchers): the
            # daemon passes Config.match_neighbor_area; default = every
            # neighbor in the first configured area
            resolve_area=resolve_area
            or (lambda node, iface, _a=areas[0]: _a),
        )
        self.link_monitor = LinkMonitor(
            node_name,
            lm_config or LinkMonitorConfig(use_rtt_metric=False),
            self.neighbor_updates_queue.get_reader(),
            self.kvstore_events_queue.get_reader(),
            self.peer_updates_queue,
            self.kv_request_queue,
            interface_updates_queue=self.interface_updates_queue,
            prefix_updates_queue=self.prefix_updates_queue,
            persistent_store=persistent_store,
            # segment-routing node label advertised in the adjacency DB
            # (ref enableSegmentRouting + node segment label config)
            node_label=node_label,
            # default: in-process port registry; the daemon passes a hook
            # that reads the kvstore_port learned via the spark handshake
            kvstore_port_of=kvstore_port_of
            or (lambda ev: ("127.0.0.1", self.kv_ports[ev.node_name])),
            advertise_throttle_s=0.002,
        )
        self.decision = Decision(
            node_name,
            decision_config or DecisionConfig(debounce_min_ms=5, debounce_max_ms=25),
            self.kvstore_updates_queue.get_reader(),
            self.static_routes_queue.get_reader(),
            self.route_updates_queue,
            solver_backend=solver_backend,
            persistent_store=persistent_store,
            log_sample_queue=self.log_sample_queue,
        )
        self.ctrl: "CtrlServer | None" = None
        self._enable_ctrl = enable_ctrl
        self._ctrl_port = ctrl_port
        self._running_config = running_config
        self._persistent_store = persistent_store
        self._monitor = monitor
        self.plugin_host = None
        if plugins:
            from openr_tpu.plugins import PluginArgs, PluginHost

            self.plugin_host = PluginHost(
                PluginArgs(
                    node_name=node_name,
                    config=running_config,
                    prefix_updates_queue=self.prefix_updates_queue,
                    static_routes_queue=self.static_routes_queue,
                    kv_request_queue=self.kv_request_queue,
                    route_updates_reader=self.route_updates_queue.get_reader,
                ),
                plugins,
            )
        self.prefix_manager = PrefixManager(
            node_name,
            areas,
            self.prefix_updates_queue.get_reader(),
            self.fib_updates_queue.get_reader(),
            self.kv_request_queue,
            static_routes_queue=self.static_routes_queue,
            kvstore_updates_queue=self.kvstore_updates_queue,
            originated_prefixes=originated_prefixes or [],
            sync_throttle_s=0.002,
            policy_manager=policy_manager,
            origination_policy=origination_policy,
            area_policies=area_policies,
        )
        self.fib_service = fib_service or MockFibService()
        self.fib = Fib(
            node_name,
            fib_config or FibConfig(route_delete_delay_ms=0),
            self.fib_service,
            self.route_updates_queue.get_reader(),
            self.fib_updates_queue,
            log_sample_queue=self.log_sample_queue,
            retry_initial_backoff_s=0.02,
            retry_max_backoff_s=0.2,
        )
        # fleet-convergence backchannel: FIB acks for origin-stamped
        # events flood back as monitor:conv-ack:<node> keys
        self.fib.attach_kvstore(self.kvstore)

    def set_monitor(self, monitor) -> None:
        """Attach the Monitor actor for ctrl event-log introspection.
        The monitor consumes this wrapper's log-sample queue, so it is
        constructed after the wrapper; call before start()."""
        self._monitor = monitor
        if self.ctrl is not None:
            self.ctrl.monitor = monitor
        # fleet health: the monitor advertises monitor:health:<node>
        # through this node's KvStore (runtime/monitor.py _health_loop)
        if hasattr(monitor, "attach_fleet_sources"):
            monitor.attach_fleet_sources(kvstore=self.kvstore)

    async def start(self, *interfaces: str) -> None:
        """Reference start order (Main.cpp): kvstore -> link-monitor ->
        decision -> fib -> spark (discovery last, once consumers exist)."""
        await self.kvstore.start()
        self.kv_ports[self.node_name] = self.kvstore.port
        # peers learn our kvstore endpoint through the spark handshake
        self.spark.kvstore_port = self.kvstore.port
        for iface in interfaces:
            self.spark.add_interface(iface)
        await self.prefix_manager.start()
        await self.link_monitor.start()
        # plugins attach after link-monitor, before decision/fib start
        # consuming their injections (ref Main.cpp:485-509)
        if self.plugin_host is not None:
            await self.plugin_host.start()
        await self.decision.start()
        await self.fib.start()
        await self.spark.start()
        if self._enable_ctrl:
            from openr_tpu.ctrl import CtrlServer

            self.ctrl = CtrlServer(
                self.node_name,
                kvstore=self.kvstore,
                decision=self.decision,
                fib=self.fib,
                link_monitor=self.link_monitor,
                prefix_manager=self.prefix_manager,
                spark=self.spark,
                kvstore_updates_queue=self.kvstore_updates_queue,
                fib_updates_queue=self.fib_updates_queue,
                listen_port=self._ctrl_port,
                config=self._running_config,
                persistent_store=self._persistent_store,
                monitor=self._monitor,
            )
            await self.ctrl.start()

    async def stop(self) -> None:
        """Reverse teardown (ref Main.cpp:592-599)."""
        if self.ctrl is not None:
            await self.ctrl.stop()
        if self.plugin_host is not None:
            await self.plugin_host.stop()
        for q in (
            self.kvstore_updates_queue,
            self.kvstore_events_queue,
            self.route_updates_queue,
            self.fib_updates_queue,
            self.interface_updates_queue,
            self.prefix_updates_queue,
        ):
            q.close()
        for actor in (
            self.spark,
            self.fib,
            self.decision,
            self.link_monitor,
            self.prefix_manager,
            self.kvstore,
        ):
            await actor.stop()

    # -- convenience -------------------------------------------------------

    def advertise_prefix(
        self,
        prefix: str,
        ptype: PrefixType = PrefixType.BREEZE,
        dest_areas: tuple[str, ...] = (),
        **entry_kw,
    ) -> None:
        """Originate a prefix through PrefixManager (the real path).

        Default type is BREEZE (operator injection) — NOT LOOPBACK, which
        LinkMonitor owns via full-set syncs and would silently withdraw.
        """
        ptype = entry_kw.pop("type", ptype)
        self.prefix_updates_queue.push(
            PrefixEvent(
                event_type=PrefixEventType.ADD_PREFIXES,
                type=ptype,
                prefixes=[PrefixEntry(prefix=prefix, type=ptype, **entry_kw)],
                dest_areas=dest_areas,
            )
        )

    def withdraw_prefix(
        self, prefix: str, ptype: PrefixType = PrefixType.BREEZE
    ) -> None:
        self.prefix_updates_queue.push(
            PrefixEvent(
                event_type=PrefixEventType.WITHDRAW_PREFIXES,
                type=ptype,
                prefixes=[PrefixEntry(prefix=prefix, type=ptype)],
            )
        )

    @property
    def fib_routes(self) -> dict:
        """Programmed routes in the (mock) FIB agent."""
        return self.fib_service.unicast
