"""DUAL flood-topology optimization — SPT flooding for KvStore.

Role of the reference's openr/kvstore/Dual.{h,cpp} (:27-100): full-mesh
flooding costs O(peers²) messages per publication; the Diffusing Update
Algorithm (EIGRP-style) computes a spanning tree per flood root over the
live peer graph, and publications then travel only tree edges
(parent + children), reaching every node exactly once.

Per root, each node runs the classic DUAL state machine:

  PASSIVE  route believed loop-free; successor (parent toward the root)
           satisfies the feasibility condition FC: the neighbor's
           reported distance is strictly below this node's feasible
           distance FD (so routing through it can never loop back).
  ACTIVE   the successor was lost/worsened and no neighbor satisfies
           FC: the node freezes its route, QUERYs every neighbor, and
           the computation DIFFUSES — a queried neighbor whose own
           successor is invalidated goes ACTIVE itself and defers its
           REPLY until its own subtree settles. When all replies are
           in, FD resets and the best neighbor is adopted (ref Dual.h
           PASSIVE/ACTIVE0-3; this implementation collapses the three
           ACTIVE sub-states into reply bookkeeping).

Parent adoption is signalled with FLOOD_TOPO_SET child add/remove
commands (ref KvStore.h:438-456), giving each node its child set; the
flood set is {parent} | children. Nodes with no reachable root fall
back to full-mesh flooding (and KvStore's periodic full sync + TTL
refresh heal any transient tree breakage during reconvergence).

Messages ride the existing peer RPC sessions ("kvstore.dual"), like the
reference rides its thrift sessions.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field
from typing import Callable, Optional

log = logging.getLogger(__name__)

INF = 1 << 30
_LINK_COST = 1  # peer-graph edges are unit cost (ref Dual unit metric)


class DualState(enum.Enum):
    PASSIVE = 0
    ACTIVE = 1


@dataclass
class _RootState:
    """Per-root DUAL bookkeeping on one node."""

    root: str
    dist: int = INF
    feasible_dist: int = INF
    successor: Optional[str] = None
    state: DualState = DualState.PASSIVE
    reported: dict = field(default_factory=dict)  # peer -> its distance
    pending_replies: set = field(default_factory=set)
    # peers whose QUERY we must answer once we go PASSIVE again
    deferred_replies: set = field(default_factory=set)
    children: set = field(default_factory=set)


class Dual:
    """One per KvStore area. `send(peer, msg)` delivers a dual message
    over that peer's session (fire-and-forget; losses are healed by the
    next update), `is_root` marks this node as a flood-root candidate
    (ref flood_root_id config)."""

    def __init__(
        self,
        node_name: str,
        send: Callable[[str, dict], None],
        is_root: bool = False,
        on_parent_change: Optional[Callable[[str, Optional[str]], None]] = None,
    ):
        self.node_name = node_name
        self._send = send
        self.is_root = is_root
        # (root, new_parent) hook: KvStore full-syncs with a newly
        # adopted parent so publications flooded over the tree while it
        # was forming are caught up (ref dual parent-change sync)
        self._on_parent_change = on_parent_change
        self.peers: set[str] = set()
        self.roots: dict[str, _RootState] = {}
        if is_root:
            rs = self._root_state(node_name)
            rs.dist = 0
            rs.feasible_dist = 0

    # -- introspection -------------------------------------------------------

    def _root_state(self, root: str) -> _RootState:
        rs = self.roots.get(root)
        if rs is None:
            rs = self.roots[root] = _RootState(root=root)
        return rs

    def current_root(self) -> Optional[str]:
        """Lowest-id root with a loop-free PASSIVE route (ref
        getSptRootId: ordered preference across known roots)."""
        for root in sorted(self.roots):
            rs = self.roots[root]
            if rs.state is DualState.PASSIVE and rs.dist < INF:
                return root
        return None

    def flood_peers(self) -> Optional[set[str]]:
        """SPT peers to flood to, or None => full-mesh fallback (no
        converged root, or mid-diffusion)."""
        root = self.current_root()
        if root is None:
            return None
        rs = self.roots[root]
        out = set(rs.children) & self.peers
        if rs.successor is not None:
            out.add(rs.successor)
        return out

    def status(self) -> dict:
        return {
            root: {
                "state": rs.state.name,
                "dist": rs.dist,
                "parent": rs.successor,
                "children": sorted(rs.children),
            }
            for root, rs in sorted(self.roots.items())
        }

    # -- peer lifecycle ------------------------------------------------------

    def peer_up(self, peer: str) -> None:
        # NO early return for known peers: (re)introducing every root on
        # peer_up is idempotent and heals any messages lost while the
        # session was down or half-open — including our child claim on
        # the parent (a lost topo_set would otherwise silently detach
        # this node's subtree from the flood tree).
        self.peers.add(peer)
        for root, rs in self.roots.items():
            self._send(peer, self._update_msg(root, peer))
            if rs.successor == peer:
                self._send(
                    peer, {"type": "topo_set", "root": root, "child": True}
                )

    def peer_down(self, peer: str) -> None:
        self.peers.discard(peer)
        for rs in self.roots.values():
            rs.reported.pop(peer, None)
            rs.children.discard(peer)
            rs.deferred_replies.discard(peer)
            if peer in rs.pending_replies:
                rs.pending_replies.discard(peer)
                self._maybe_finish_active(rs)
            if rs.successor == peer:
                self._local_computation(rs)

    # -- message handling ----------------------------------------------------

    def handle_message(self, sender: str, msg: dict) -> None:
        mtype = msg.get("type")
        root = msg.get("root", "")
        if sender not in self.peers:
            # message from a peer we don't (or no longer) track — e.g.
            # one in flight across a peer deletion. Adopting it would
            # resurrect a ghost that no lifecycle event ever removes (and
            # that flooding can't reach); drop it — the sender's next
            # peer_up re-introduces state on both sides. This covers
            # topo_set too: an in-flight child claim from a removed peer
            # would leak a ghost child forever (peer_up re-sends the
            # claim, so dropping loses nothing).
            return
        if mtype == "topo_set":
            rs = self._root_state(root)
            if msg.get("child"):
                rs.children.add(sender)
            else:
                rs.children.discard(sender)
            return
        rs = self._root_state(root)
        dist = int(msg.get("dist", INF))
        if mtype == "update":
            rs.reported[sender] = dist
            self._local_computation(rs)
        elif mtype == "query":
            rs.reported[sender] = dist
            was_passive = rs.state is DualState.PASSIVE
            self._local_computation(rs)
            if rs.state is DualState.PASSIVE:
                self._send(sender, self._reply_msg(root, rs, sender))
            elif was_passive:
                # this query invalidated our route: the computation
                # DIFFUSES — answer once our own subtree settles
                rs.deferred_replies.add(sender)
            else:
                # already mid-diffusion: reply with the frozen distance
                # immediately (EIGRP's non-successor-query rule) so two
                # mutually-querying nodes can never deadlock
                self._send(sender, self._reply_msg(root, rs, sender))
        elif mtype == "reply":
            rs.reported[sender] = dist
            if sender in rs.pending_replies:
                rs.pending_replies.discard(sender)
                self._maybe_finish_active(rs)

    # -- DUAL core -----------------------------------------------------------

    def _adv_dist(self, rs: _RootState, peer: str) -> int:
        """Split horizon with poisoned reverse: a node's distance is
        advertised as INF to its own successor — the neighbor a route
        goes THROUGH must never route back through us, and without this
        two mutually-dependent neighbors count to infinity one update at
        a time when the root disconnects."""
        return INF if rs.successor == peer else rs.dist

    def _update_msg(self, root: str, peer: str) -> dict:
        rs = self.roots[root]
        return {"type": "update", "root": root, "dist": self._adv_dist(rs, peer)}

    def _reply_msg(self, root: str, rs: _RootState, peer: str) -> dict:
        return {"type": "reply", "root": root, "dist": self._adv_dist(rs, peer)}

    def _best_neighbor(self, rs: _RootState, feasible_only: bool):
        """(neighbor, via-distance) minimizing reported+cost; ties break
        on name for determinism."""
        best = None
        for peer in sorted(rs.reported):
            if peer not in self.peers:
                continue
            rep = rs.reported[peer]
            if rep >= INF:
                continue
            if feasible_only and not rep < rs.feasible_dist:
                continue
            via = rep + _LINK_COST
            if best is None or via < best[1]:
                best = (peer, via)
        return best

    def _local_computation(self, rs: _RootState) -> None:
        """Re-evaluate the successor after any input change (ref
        Dual::processUpdate / peerDown)."""
        if rs.root == self.node_name:
            return  # we ARE the root: dist 0, no successor
        if rs.state is DualState.ACTIVE:
            return  # frozen until the diffusing computation completes
        old = (rs.dist, rs.successor)
        best = self._best_neighbor(rs, feasible_only=True)
        if best is not None:
            rs.successor, rs.dist = best[0], best[1]
            rs.feasible_dist = min(rs.feasible_dist, rs.dist)
        else:
            any_best = self._best_neighbor(rs, feasible_only=False)
            if any_best is None:
                # no path at all: converge on unreachable
                rs.successor, rs.dist = None, INF
                rs.feasible_dist = INF
            else:
                # reachable but no FEASIBLE successor: diffuse
                self._go_active(rs)
                return
        self._after_route_change(rs, old)

    def _go_active(self, rs: _RootState) -> None:
        rs.state = DualState.ACTIVE
        old = (rs.dist, rs.successor)
        best = self._best_neighbor(rs, feasible_only=False)
        assert best is not None
        rs.successor, rs.dist = best[0], best[1]
        rs.feasible_dist = rs.dist  # FD resets at the ACTIVE transition
        rs.pending_replies = set(self.peers)
        self._after_route_change(rs, old, send_updates=False)
        if not rs.pending_replies:
            self._finish_active(rs)
            return
        for peer in list(rs.pending_replies):
            self._send(
                peer,
                {
                    "type": "query",
                    "root": rs.root,
                    "dist": self._adv_dist(rs, peer),
                },
            )

    def _maybe_finish_active(self, rs: _RootState) -> None:
        if rs.state is DualState.ACTIVE and not rs.pending_replies:
            self._finish_active(rs)

    def _finish_active(self, rs: _RootState) -> None:
        rs.state = DualState.PASSIVE
        rs.feasible_dist = INF  # free choice now that the diffusion ended
        self._local_computation(rs)
        # answer neighbors that queried us mid-diffusion
        for peer in list(rs.deferred_replies):
            rs.deferred_replies.discard(peer)
            if peer in self.peers:
                self._send(peer, self._reply_msg(rs.root, rs, peer))

    def _after_route_change(
        self, rs: _RootState, old: tuple, send_updates: bool = True
    ) -> None:
        dist_changed = rs.dist != old[0]
        parent_changed = rs.successor != old[1]
        if parent_changed:
            if old[1] is not None and old[1] in self.peers:
                self._send(
                    old[1],
                    {"type": "topo_set", "root": rs.root, "child": False},
                )
            if rs.successor is not None:
                self._send(
                    rs.successor,
                    {"type": "topo_set", "root": rs.root, "child": True},
                )
            if self._on_parent_change is not None:
                self._on_parent_change(rs.root, rs.successor)
        # a successor change alone changes each peer's split-horizon view
        if (dist_changed or parent_changed) and send_updates:
            for peer in self.peers:
                self._send(peer, self._update_msg(rs.root, peer))
