// Native rtnetlink bulk route programmer.
//
// Role of the reference's C++ openr/nl/ fast path
// (NetlinkProtocolSocket.h:69-70 claims 100k routes < 2s): the Python
// asyncio client (openr_tpu/platform/netlink.py) is fine for steady-state
// deltas, but a full-table sync of ~100k routes pays ~20us of interpreter
// overhead per message. This extension keeps the whole encode -> send ->
// ack pipeline in C++ with a bounded in-flight window, reading route
// specs from a single packed buffer prepared by numpy on the Python side.
//
// Exposed as openr_tpu_native.bulk_route_op(fd-less; owns its own
// netlink socket per call):
//   bulk_route_op(op, table, protocol, buf) -> (ok_count, err_count)
//     op:    0 = RTM_NEWROUTE (replace), 1 = RTM_DELROUTE
//     buf:   packed records, little-endian:
//            u8  family (2=v4, 10=v6)
//            u8  prefix_len
//            u8  n_nexthops
//            u8  pad
//            u32 metric
//            u8[16] dst (4 used for v4)
//            per nexthop: u32 ifindex, u32 weight, u8[16] gateway
//                         (all-zero gateway = none)
//
// Built with setuptools (build_native.py) via the CPython C API —
// no pybind11 in the image.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>

#include <linux/netlink.h>
#include <linux/rtnetlink.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

constexpr int kWindow = 256;  // ref: <=500 in flight (h:33-70)

struct NhRec {
  uint32_t ifindex;
  uint32_t weight;
  uint8_t gateway[16];
};

struct __attribute__((packed)) RouteHdr {
  uint8_t family;
  uint8_t prefix_len;
  uint8_t n_nexthops;
  uint8_t pad;
  uint32_t metric;
  uint8_t dst[16];
};

size_t align4(size_t n) { return (n + 3) & ~size_t(3); }

void put_rta(std::vector<uint8_t>& buf, uint16_t type, const void* data,
             size_t len) {
  rtattr rta;
  rta.rta_len = static_cast<uint16_t>(RTA_LENGTH(len));
  rta.rta_type = type;
  size_t start = buf.size();
  buf.resize(start + align4(rta.rta_len), 0);
  std::memcpy(buf.data() + start, &rta, sizeof(rta));
  std::memcpy(buf.data() + start + RTA_LENGTH(0), data, len);
}

bool gw_present(const uint8_t* gw) {
  static const uint8_t zeros[16] = {0};
  return std::memcmp(gw, zeros, 16) != 0;
}

// drain acks without blocking the send pipeline more than necessary
int drain_acks(int fd, int* inflight, int* ok, int* err, bool block) {
  uint8_t rbuf[1 << 16];
  while (*inflight > 0) {
    ssize_t n = recv(fd, rbuf, sizeof(rbuf), block ? 0 : MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
      if (errno == EINTR) continue;
      return -1;
    }
    size_t off = 0;
    while (off + sizeof(nlmsghdr) <= static_cast<size_t>(n)) {
      auto* hdr = reinterpret_cast<nlmsghdr*>(rbuf + off);
      if (hdr->nlmsg_len < sizeof(nlmsghdr)) break;
      if (hdr->nlmsg_type == NLMSG_ERROR) {
        auto* e = reinterpret_cast<nlmsgerr*>(NLMSG_DATA(hdr));
        if (e->error == 0) {
          ++*ok;
        } else {
          ++*err;
        }
        --*inflight;
      }
      off += align4(hdr->nlmsg_len);
    }
    if (!block) return 0;
    block = false;  // one blocking read per call is enough
  }
  return 0;
}

PyObject* bulk_route_op(PyObject*, PyObject* args) {
  int op;
  int table;
  int protocol;
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "iiiy*", &op, &table, &protocol, &view)) {
    return nullptr;
  }

  int fd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_ROUTE);
  if (fd < 0) {
    PyBuffer_Release(&view);
    return PyErr_SetFromErrno(PyExc_OSError);
  }
  // big socket buffers: we pipeline hard. RCVBUFFORCE bypasses the
  // rmem_max clamp when CAP_NET_ADMIN (which route programming needs
  // anyway); plain RCVBUF is the fallback.
  int sz = 1 << 21;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
  if (setsockopt(fd, SOL_SOCKET, SO_RCVBUFFORCE, &sz, sizeof(sz)) < 0) {
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
  }
  // error acks must not echo the whole original request: 256 in-flight
  // NACKs of multipath messages could overflow the ack queue and abort
  // the run mid-stream
  int one = 1;
  setsockopt(fd, SOL_NETLINK, NETLINK_CAP_ACK, &one, sizeof(one));
  sockaddr_nl addr{};
  addr.nl_family = AF_NETLINK;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    PyBuffer_Release(&view);
    return PyErr_SetFromErrno(PyExc_OSError);
  }

  const auto* p = static_cast<const uint8_t*>(view.buf);
  const auto* end = p + view.len;
  int ok = 0, err = 0, inflight = 0;
  uint32_t seq = 0;
  std::vector<uint8_t> msg;
  msg.reserve(512);
  int rc = 0;

  Py_BEGIN_ALLOW_THREADS
  while (p + sizeof(RouteHdr) <= end) {
    RouteHdr rh;
    std::memcpy(&rh, p, sizeof(rh));
    p += sizeof(rh);
    size_t nh_bytes = size_t(rh.n_nexthops) * sizeof(NhRec);
    if (p + nh_bytes > end) break;
    const auto* nhs = reinterpret_cast<const NhRec*>(p);
    p += nh_bytes;

    size_t addr_len = rh.family == AF_INET ? 4 : 16;
    msg.clear();
    msg.resize(NLMSG_HDRLEN + sizeof(rtmsg), 0);
    auto* rtm = reinterpret_cast<rtmsg*>(msg.data() + NLMSG_HDRLEN);
    rtm->rtm_family = rh.family;
    rtm->rtm_dst_len = rh.prefix_len;
    rtm->rtm_table = table < 256 ? table : RT_TABLE_UNSPEC;
    rtm->rtm_protocol = static_cast<uint8_t>(protocol);
    rtm->rtm_scope = RT_SCOPE_UNIVERSE;
    rtm->rtm_type = RTN_UNICAST;
    put_rta(msg, RTA_DST, rh.dst, addr_len);
    if (table >= 256) {
      uint32_t t32 = static_cast<uint32_t>(table);
      put_rta(msg, RTA_TABLE, &t32, 4);
    }
    if (rh.metric) put_rta(msg, RTA_PRIORITY, &rh.metric, 4);
    if (op == 0 && rh.n_nexthops == 1) {
      if (gw_present(nhs[0].gateway)) {
        put_rta(msg, RTA_GATEWAY, nhs[0].gateway, addr_len);
      }
      if (nhs[0].ifindex) {
        int32_t ifx = static_cast<int32_t>(nhs[0].ifindex);
        put_rta(msg, RTA_OIF, &ifx, 4);
      }
    } else if (op == 0 && rh.n_nexthops > 1) {
      std::vector<uint8_t> mp;
      for (int i = 0; i < rh.n_nexthops; ++i) {
        std::vector<uint8_t> nested;
        if (gw_present(nhs[i].gateway)) {
          put_rta(nested, RTA_GATEWAY, nhs[i].gateway, addr_len);
        }
        rtnexthop rtnh{};
        rtnh.rtnh_len = static_cast<uint16_t>(sizeof(rtnexthop) + nested.size());
        rtnh.rtnh_hops =
            nhs[i].weight > 0 ? static_cast<uint8_t>(nhs[i].weight - 1) : 0;
        rtnh.rtnh_ifindex = static_cast<int>(nhs[i].ifindex);
        size_t start = mp.size();
        mp.resize(start + align4(rtnh.rtnh_len), 0);
        std::memcpy(mp.data() + start, &rtnh, sizeof(rtnh));
        std::memcpy(mp.data() + start + sizeof(rtnh), nested.data(),
                    nested.size());
      }
      put_rta(msg, RTA_MULTIPATH, mp.data(), mp.size());
    }

    auto* nlh = reinterpret_cast<nlmsghdr*>(msg.data());
    nlh->nlmsg_len = static_cast<uint32_t>(msg.size());
    nlh->nlmsg_type = op == 0 ? RTM_NEWROUTE : RTM_DELROUTE;
    nlh->nlmsg_flags = NLM_F_REQUEST | NLM_F_ACK;
    if (op == 0) nlh->nlmsg_flags |= NLM_F_CREATE | NLM_F_REPLACE;
    nlh->nlmsg_seq = ++seq;

    for (;;) {
      if (send(fd, msg.data(), msg.size(), 0) >= 0) break;
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == ENOBUFS) {
        // window full at the kernel: drain acks, then retry
        if (drain_acks(fd, &inflight, &ok, &err, true) < 0) {
          rc = -1;
          break;
        }
        continue;
      }
      if (errno == EINTR) continue;
      rc = -1;
      break;
    }
    if (rc < 0) break;
    ++inflight;
    if (inflight >= kWindow) {
      if (drain_acks(fd, &inflight, &ok, &err, true) < 0) {
        rc = -1;
        break;
      }
    } else {
      drain_acks(fd, &inflight, &ok, &err, false);
    }
  }
  if (rc == 0) {
    while (inflight > 0) {
      if (drain_acks(fd, &inflight, &ok, &err, true) < 0) {
        rc = -1;
        break;
      }
    }
  }
  Py_END_ALLOW_THREADS

  close(fd);
  PyBuffer_Release(&view);
  if (rc < 0 && ok + err == 0) {
    return PyErr_SetFromErrno(PyExc_OSError);
  }
  return Py_BuildValue("(ii)", ok, err);
}

PyMethodDef kMethods[] = {
    {"bulk_route_op", bulk_route_op, METH_VARARGS,
     "bulk_route_op(op, table, protocol, packed_routes) -> (ok, err)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "openr_tpu_native",
    "Native rtnetlink bulk route programmer (role of openr/nl fast path)",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_openr_tpu_native() { return PyModule_Create(&kModule); }
