"""Device-plane observability tests: HBM gauges + live-buffer census,
profiler single-flight capture, the XLA kernel cost ledger, numerical-
health sentinels, watchdog gauge pruning, event-log drop accounting,
and KvStore-advertised fleet health. All on the virtual-CPU backend —
the graceful-degradation path (no memory_stats) is itself under test."""

import asyncio
import time
from types import SimpleNamespace

import numpy as np

from openr_tpu.config import MonitorConfig, WatchdogConfig
from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime import device_stats
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.monitor import LogSample, Monitor, Watchdog
from tests.conftest import run_async


# -- counter erase API ------------------------------------------------------

def test_counter_erase_and_prefix():
    counters.set_counter("erasetest.a", 1)
    counters.set_counter("erasetest.ab", 2)
    assert counters.erase("erasetest.a") is True
    assert counters.erase("erasetest.a") is False
    assert counters.get_counter("erasetest.a") is None
    assert counters.get_counter("erasetest.ab") == 2
    # trailing-dot discipline: erasing reader "r" must not swallow "r2"
    counters.set_counter("erasetest.q.reader.r.depth", 3)
    counters.set_counter("erasetest.q.reader.r2.depth", 4)
    n = counters.erase_prefix("erasetest.q.reader.r.")
    assert n == 1
    assert counters.get_counter("erasetest.q.reader.r.depth") is None
    assert counters.get_counter("erasetest.q.reader.r2.depth") == 4
    counters.erase_prefix("erasetest.")


# -- device snapshot + census ----------------------------------------------

def test_collect_device_stats_cpu_backend():
    snap = device_stats.collect_device_stats(allow_import=True)
    assert snap["backend"] == "cpu"
    assert len(snap["devices"]) == 8  # conftest's virtual mesh
    for entry in snap["devices"]:
        # graceful degradation: no memory_stats on cpu -> id/platform only
        assert "hbm_in_use_mb" not in entry
        assert entry["platform"] == "cpu"


def test_live_buffer_census_attributes_pools():
    import jax

    held = [jax.device_put(np.zeros(1024, np.float32))]
    device_stats.register_pool("censustest", lambda: held)
    try:
        census = device_stats.live_buffer_census(allow_import=True)
        pool = census["pools"]["censustest"]
        assert pool["count"] == 1
        assert pool["bytes"] == 4096
        assert census["bytes"] >= pool["bytes"]
        # other pools (earlier tests' solvers) may attribute bytes too —
        # ours must at least be carved out of the unattributed remainder
        assert census["other_bytes"] <= census["bytes"] - pool["bytes"]

        snap = device_stats.export_device_gauges(allow_import=True)
        assert snap["backend"] == "cpu"
        assert counters.get_counter("device.count") == 8
        assert counters.get_counter("device.pool.censustest.count") == 1
    finally:
        device_stats.unregister_pool("censustest")
    # unregister erases the pool's gauges from the fabric
    assert counters.get_counter("device.pool.censustest.count") is None
    assert device_stats.peak_hbm_mb() == (None, "cpu")


def test_solver_registers_weakref_pool():
    """Each TpuSpfSolver registers a census pool that must not pin the
    solver alive; after the solver goes away the pool reads empty."""
    import gc

    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from tests.test_spf_solver import prefix_db, square_states

    ps = PrefixState()
    ps.update_prefix_database(prefix_db("d", "fd00::d/128"))
    solver = TpuSpfSolver("a")
    solver.build_route_db("a", square_states(), ps)
    census = device_stats.live_buffer_census()
    assert census["pools"]["tpu_solver:a"]["count"] > 0
    del solver
    gc.collect()
    census = device_stats.live_buffer_census()
    assert census["pools"]["tpu_solver:a"]["count"] == 0
    device_stats.unregister_pool("tpu_solver:a")


# -- profiler capture -------------------------------------------------------

def test_profiler_round_trip_and_single_flight(tmp_path):
    import jax

    out = str(tmp_path / "trace")
    started = device_stats.profiler_start(out)
    assert started["ok"] and started["out_dir"] == out
    # single-flight: the XLA profiler is process-global
    try:
        device_stats.profiler_start()
        raise AssertionError("second start must refuse")
    except RuntimeError as e:
        assert "already capturing" in str(e)
    assert device_stats.profiler_status()["capturing"] is True
    # some device work so the trace is non-empty
    jax.jit(lambda x: x * 2)(np.arange(16)).block_until_ready()
    stopped = device_stats.profiler_stop()
    assert stopped["ok"] and stopped["files"] > 0
    assert device_stats.profiler_status() == {"capturing": False}
    try:
        device_stats.profiler_stop()
        raise AssertionError("stop without start must refuse")
    except RuntimeError:
        pass


def test_profiler_auto_stop(tmp_path):
    started = device_stats.profiler_start(
        str(tmp_path / "auto"), seconds=0.2
    )
    assert started["auto_stop_s"] == 0.2
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if not device_stats.profiler_status()["capturing"]:
            break
        time.sleep(0.05)
    assert device_stats.profiler_status() == {"capturing": False}


# -- kernel cost ledger -----------------------------------------------------

def test_instrument_jit_records_cost_and_calls():
    import jax

    from openr_tpu.ops.xla_cache import instrument_jit, ledger

    fn = instrument_jit(
        "ledgertest", jax.jit(lambda x: (x * 2 + 1).sum())
    )
    x = np.arange(64, dtype=np.float32)
    assert float(fn(x)) == float((x * 2 + 1).sum())
    fn(x)
    entry = ledger.snapshot()["ledgertest"]
    assert entry["calls"] == 2
    assert entry["aot"] is True
    assert entry["compile_ms"] >= 0.0
    assert entry["flops"] > 0  # cost_analysis saw the adds/muls


def test_solver_build_populates_ledger():
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from openr_tpu.ops.xla_cache import ledger
    from tests.test_spf_solver import prefix_db, square_states

    ps = PrefixState()
    ps.update_prefix_database(prefix_db("d", "fd00::d/128"))
    solver = TpuSpfSolver("a")
    solver.build_route_db("a", square_states(), ps)
    kname = solver.last_timing["areas"]["0"]["kernel"]
    assert kname.startswith("pipeline[")
    assert kname in ledger.snapshot()
    assert ledger.snapshot()[kname]["calls"] >= 1
    device_stats.unregister_pool("tpu_solver:a")


# -- numerical-health sentinels --------------------------------------------

def test_ucmp_weight_anomalies_dtype_aware():
    from openr_tpu.decision.tpu_solver import _ucmp_weight_anomalies

    assert _ucmp_weight_anomalies(
        np.array([1.0, np.nan, np.inf, 2.0])
    ) == 2
    assert _ucmp_weight_anomalies(np.array([1, -3, 2], np.int64)) == 1
    assert _ucmp_weight_anomalies(np.array([1, 2], np.uint32)) == 0


def test_pipeline_sentinels_count_unreachable_rows():
    """An announced-but-disconnected node must show up in the pipeline's
    tail sentinels without disturbing the routes themselves."""
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from tests.test_link_state import adj, adj_db
    from tests.test_spf_solver import prefix_db, square_states

    states = square_states()
    # an island (e -- f) the root can never reach, announcing a prefix
    states["0"].update_adjacency_database(
        adj_db("e", [adj("e", "f")], node_label=105)
    )
    states["0"].update_adjacency_database(
        adj_db("f", [adj("f", "e")], node_label=106)
    )
    ps = PrefixState()
    ps.update_prefix_database(prefix_db("d", "fd00::d/128"))
    ps.update_prefix_database(prefix_db("e", "fd00::e/128"))
    solver = TpuSpfSolver("a")
    db = solver.build_route_db("a", states, ps)
    assert "fd00::d/128" in db.unicast_routes
    assert "fd00::e/128" not in db.unicast_routes  # unreachable announcer
    assert solver.last_sentinels["unreachable_rows"] >= 1
    assert solver.last_sentinels["saturated_rows"] == 0
    device_stats.unregister_pool("tpu_solver:a")


def test_pipeline_sentinels_kill_switch():
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from tests.test_spf_solver import prefix_db, square_states

    ps = PrefixState()
    ps.update_prefix_database(prefix_db("d", "fd00::d/128"))
    solver = TpuSpfSolver("a", enable_numerical_sentinels=False)
    db = solver.build_route_db("a", square_states(), ps)
    assert "fd00::d/128" in db.unicast_routes
    assert solver.last_sentinels == {}
    device_stats.unregister_pool("tpu_solver:a")


@run_async
async def test_decision_emits_sentinel_anomaly():
    """Decision folds solver sentinels into gauges; an anomalous build
    additionally produces the counter bump, a categorized LogSample,
    and span attributes."""
    from openr_tpu.decision.decision import Decision

    q = ReplicateQueue("sentinel-logs")
    reader = q.get_reader()
    fake = SimpleNamespace(
        solver=SimpleNamespace(
            last_sentinels={"saturated_rows": 2, "unreachable_rows": 0}
        ),
        _log_samples=q,
        node_name="node-s",
    )
    span = SimpleNamespace(attributes={})
    before = counters.get_counter("decision.sentinel.anomalies") or 0
    Decision._emit_sentinels(fake, span)
    assert counters.get_counter("decision.sentinel.saturated_rows") == 2
    assert (
        counters.get_counter("decision.sentinel.anomalies") == before + 1
    )
    assert span.attributes["sentinel_anomaly"] is True
    assert span.attributes["sentinel_saturated_rows"] == 2
    sample = await asyncio.wait_for(reader.get(), 5)
    assert sample.event == "DECISION_SENTINEL_ANOMALY"
    assert sample.values["category"] == "sentinel"
    assert sample.values["saturated_rows"] == 2

    # a clean build publishes gauges but raises no anomaly
    fake.solver.last_sentinels = {
        "saturated_rows": 0, "unreachable_rows": 3,
    }
    span2 = SimpleNamespace(attributes={})
    Decision._emit_sentinels(fake, span2)
    assert (
        counters.get_counter("decision.sentinel.anomalies") == before + 1
    )
    assert span2.attributes == {}
    assert counters.get_counter("decision.sentinel.unreachable_rows") == 3


# -- monitor: drop accounting + category filter ----------------------------

class TestMonitorEventLogs:
    @run_async
    async def test_drop_counting_and_category_filter(self):
        q = ReplicateQueue("logSamples-dp")
        mon = Monitor(
            "node1",
            MonitorConfig(max_event_log_entries=3),
            q.get_reader(),
            interval_s=0.05,
        )
        await mon.start()
        try:
            q.push(LogSample(event="SPF_A", node_name="node1"))
            q.push(LogSample(event="SPF_B", node_name="node1"))
            q.push(LogSample(
                event="OTHER",
                node_name="node1",
                values={"category": "sentinel"},
            ))
            await wait_until(lambda: len(mon.event_logs) == 3)
            before = (
                counters.get_counter("monitor.event_logs.dropped") or 0
            )
            # ring is full: the next two appends evict (and count)
            q.push(LogSample(event="SPF_C", node_name="node1"))
            q.push(LogSample(event="SPF_D", node_name="node1"))
            await wait_until(
                lambda: (
                    counters.get_counter("monitor.event_logs.dropped")
                    or 0
                )
                == before + 2
            )
            # category filter: exact event / dotted prefix / values tag
            logs = await mon.get_event_logs(category="OTHER")
            assert len(logs) == 1
            logs = await mon.get_event_logs(category="sentinel")
            assert len(logs) == 1 and "OTHER" in logs[0]
            logs = await mon.get_event_logs(category="NO_SUCH")
            assert logs == []
            assert len(await mon.get_event_logs()) == 3
        finally:
            await mon.stop()


# -- watchdog: gauge pruning for disappeared readers -----------------------

class TestWatchdogPruning:
    @run_async
    async def test_reader_gauges_pruned_after_removal(self):
        wd = Watchdog(
            "node1",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60,
                           max_memory_mb=100_000),
            crash_handler=lambda reason: None,
        )
        q = ReplicateQueue("prunetest")
        r1 = q.get_reader("r")
        q.get_reader("r2")
        q.push(1)
        wd.watch_queue(q)
        await wd.start()
        base = "messaging.queue.prunetest"
        try:
            await wait_until(
                lambda: counters.get_counter(f"{base}.reader.r.depth")
                == 1
            )
            q.remove_reader(r1)
            # next sweep prunes r's gauges; r2 (shared prefix) survives
            await wait_until(
                lambda: counters.get_counter(f"{base}.reader.r.depth")
                is None
            )
            assert (
                counters.get_counter(f"{base}.reader.r.reads") is None
            )
            assert (
                counters.get_counter(f"{base}.reader.r2.depth")
                is not None
            )
        finally:
            await wd.stop()
            counters.erase_prefix(f"{base}.")


# -- monitor health summary -------------------------------------------------

class TestHealthSummary:
    @run_async
    async def test_health_summary_fields(self):
        q = ReplicateQueue("logSamples-hs")
        mon = Monitor(
            "node-h", MonitorConfig(), q.get_reader(), interval_s=0.05
        )
        wd = Watchdog(
            "node-h",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60,
                           max_memory_mb=100_000),
            crash_handler=lambda reason: None,
        )
        mon.attach_fleet_sources(watchdog=wd)
        await mon.start()
        try:
            card = mon.health_summary()
            assert card["node"] == "node-h"
            assert card["rss_mb"] > 0
            assert card["watchdog_fired"] is None
            assert card["backend"] in ("cpu", "unavailable")
            assert card["hbm_in_use_mb"] is None  # cpu: no accounting
            assert card["ts_ms"] > 0
        finally:
            await mon.stop()
