"""Multi-chip sharding of the route-computation pipeline.

The reference is single-process C++ with no device parallelism; the scale
axis it offers is per-area partitioning (SURVEY §5 long-context analogue).
Here the TPU-native scale story is explicit (SURVEY §2 parallelism
checklist), over the shift-decomposed mirror (ops/edgeplan.py):

  - **batch axis ("dp")**: independent SSSP vantages — whole-fabric RIB
    computation (every node's routes; the any-vantage ctrl API) shards
    roots across devices; zero communication.
  - **graph axis ("tp"/"cp")**: the node dimension of the WEIGHT arrays
    (the memory that scales with LSDB size: shift_w [S, N], residual
    ELL) is sharded across devices. Each relaxation computes the partial
    candidate field contributed by the LOCAL source columns, then
    combines with jax.lax.pmin over the 'graph' axis — the halo exchange
    of this domain. The frontier (dist [D, N]) stays replicated, so a
    relax is: local shifts over a locally-weighted full-width field +
    one pmin collective. This is what lets a 1M+-node LSDB's weight
    state exceed a single chip's HBM while collectives ride ICI.

Both axes compose in one jax.sharding.Mesh('batch', 'graph') via
shard_map. Iteration count is a diameter bound measured on device by the
single-chip pipeline (trips are part of its output), not a blind
n_nodes bound — every shard runs the same fixed trip count, keeping the
mesh in lockstep with no host round-trips.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from openr_tpu.ops.edgeplan import INF32E

INF_E = int(INF32E)
_UNROLL = 8


def make_mesh(n_devices: Optional[int] = None, batch: Optional[int] = None):
    """Factor devices into a ('batch', 'graph') mesh. Prefers a wider
    batch axis (root fan-out is embarrassingly parallel; graph sharding
    pays a pmin per relaxation step)."""
    import jax

    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    if batch is None:
        graph = 1
        # give the graph axis a factor of 2 when we have >= 4 devices so
        # both kinds of sharding are exercised
        if n >= 4 and n % 2 == 0:
            graph = 2
        batch = n // graph
    else:
        graph = n // batch
    assert batch * graph == n, (batch, graph, n)
    from jax.sharding import Mesh

    return Mesh(np.array(devs).reshape(batch, graph), ("batch", "graph"))


@functools.lru_cache(maxsize=8)
def _sharded_fabric_fn(mesh, n_cap: int, s_cap: int, r_cap: int,
                       kr_cap: int, has_res: bool, d_cap: int,
                       p_cap: int, a_cap: int, n_trips: int,
                       lfa: bool = False):
    """shard_mapped whole-fabric pipeline: for each root (sharded over
    'batch'), batched-seed SSSP with graph-axis-sharded weights, then
    best-route selection. Returns (dist[R, N], metric[R, P],
    nh_mask[R, P, D])."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    graph_size = mesh.shape["graph"]
    shard_cols = n_cap // graph_size

    def local_fn(
        deltas,      # [S]            replicated
        shift_w,     # [S, N/g]       node columns sharded over 'graph'
        res_rows,    # [R/g]          residual rows sharded
        res_nbr,     # [R/g, K]
        res_w,       # [R/g, K]
        roots,       # [Rt/b]         roots sharded over 'batch'
        root_nbr,    # [Rt/b, D]
        root_w,      # [Rt/b, D]
        ann_node,    # [P, A]         announcer matrix replicated
        ann_flags,
        path_pref,
        source_pref,
        dist_adv,
        min_nh,      # [P, A]
        v4_blocked,  # [P]
    ):
        my_col0 = jax.lax.axis_index("graph") * shard_cols

        def one_root(root, seeds_nbr, seeds_w):
            # mask root as transit within my local source columns (no
            # column matches when the root lives in another shard)
            local_root = root - my_col0
            col_iota = jnp.arange(shard_cols)
            sw = jnp.where(
                col_iota[None, :] == local_root, INF_E, shift_w
            )
            rw = jnp.where(res_nbr == root, INF_E, res_w)
            valid = seeds_w < INF_E
            seed_idx = jnp.clip(seeds_nbr, 0, n_cap - 1)
            dist0 = jnp.full((d_cap, n_cap), INF_E, jnp.int32)
            dist0 = dist0.at[jnp.arange(d_cap), seed_idx].min(
                jnp.where(valid, 0, INF_E).astype(jnp.int32)
            )

            nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
            rows_c = jnp.clip(res_rows, 0, n_cap - 1)

            def relax(dist):
                # local sources' contribution over the full-width field
                pc = jnp.full_like(dist, INF_E)
                def cls(k, pc):
                    w_full = jax.lax.dynamic_update_slice(
                        jnp.full((n_cap,), INF_E, jnp.int32),
                        sw[k],
                        (my_col0,),
                    )
                    return jnp.minimum(
                        pc, jnp.roll(dist + w_full[None, :], deltas[k], axis=1)
                    )
                pc = jax.lax.fori_loop(0, s_cap, cls, pc)
                if has_res:
                    nd = dist[:, nbr_c]
                    cand = (nd + rw[None]).min(axis=2)
                    pc = pc.at[:, rows_c].min(cand)
                # halo exchange: combine shards' candidates
                pc = jax.lax.pmin(pc, "graph")
                return jnp.minimum(dist, pc)

            def body(i, dist):
                for _ in range(_UNROLL):
                    dist = relax(dist)
                return dist

            dist_d = jax.lax.fori_loop(0, n_trips, body, dist0)
            # convergence verdict: one extra relaxation must be a no-op.
            # Under-iteration (n_trips below the true diameter bound) is
            # thereby detected instead of silently returning too-large
            # distances for distant roots.
            converged = jnp.all(relax(dist_d) == dist_d)
            via = seeds_w[:, None] + dist_d
            dist = jnp.minimum(via.min(axis=0), INF_E).at[root].set(0)

            ann_valid = (ann_flags & 1).astype(bool)
            ann_over = (ann_flags & 2).astype(bool)
            idx = jnp.clip(ann_node, 0, n_cap - 1)
            ann_dist = dist[idx]
            reach = ann_valid & (ann_dist < INF_E)
            neg = -(2**31)
            pp = jnp.where(reach, path_pref, neg)
            s = reach & (pp == pp.max(axis=1, keepdims=True))
            sp = jnp.where(s, source_pref, neg)
            s = s & (sp == sp.max(axis=1, keepdims=True))
            da = jnp.where(s, dist_adv, INF_E)
            s2 = s & (da == da.min(axis=1, keepdims=True))
            nd = s2 & ~ann_over
            s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
            igp = jnp.where(s3, ann_dist, INF_E)
            metric = igp.min(axis=1)
            s4 = s3 & (igp == metric[:, None])
            on_sp = (via == dist[None, :]).T
            nh_mask = jnp.any(s4[:, :, None] & on_sp[idx], axis=1)
            if lfa:
                # rfc5286 alternates, same predicate as the single-chip
                # pipeline (tpu_solver._plan_pipeline): neighbor slot d
                # backs up prefix p iff its own distance to the selected
                # announcers beats detouring back through this root
                d_root = dist_d[:, root]
                ann_nd = dist_d.T[idx]  # [P, A, D]
                nbr_pd = jnp.where(
                    s3[:, :, None], ann_nd, INF_E
                ).min(axis=1)
                link_up = seeds_w < INF_E
                ok_lfa = (
                    link_up[None, :]
                    & ~nh_mask
                    & (nbr_pd < INF_E)
                    & (nbr_pd < d_root[None, :] + metric[:, None])
                )
                alt = jnp.where(
                    ok_lfa, seeds_w[None, :] + nbr_pd, jnp.int32(1 << 30)
                )
                has_lfa = ok_lfa.any(axis=1)
                lfa_slot = jnp.where(
                    has_lfa,
                    jnp.argmin(alt, axis=1).astype(jnp.int32),
                    -1,
                )
                lfa_metric = jnp.where(has_lfa, alt.min(axis=1), 0)
            else:
                lfa_slot = jnp.full((p_cap,), -1, jnp.int32)
                lfa_metric = jnp.zeros((p_cap,), jnp.int32)
            # route-level ok on device (shared with the single-chip
            # compaction) so the host skips its own O(P*A) filter pass
            from openr_tpu.ops.compact import route_ok_device

            ok = route_ok_device(
                metric, s3, nh_mask, ann_node, min_nh, v4_blocked, root
            )
            return (
                dist, metric, s3, nh_mask, lfa_slot, lfa_metric, ok,
                converged,
            )

        return jax.vmap(one_root)(roots, root_nbr, root_w)

    try:
        from jax import shard_map  # jax >= 0.6
        _check_kw = {"check_vma": False}
    except ImportError:  # older jax: experimental module, check_rep kwarg
        from jax.experimental.shard_map import shard_map
        _check_kw = {"check_rep": False}

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(
                P(),                 # deltas
                P(None, "graph"),    # shift_w columns
                P("graph"),          # res_rows
                P("graph", None),    # res_nbr
                P("graph", None),    # res_w
                P("batch"),          # roots
                P("batch", None),    # root_nbr
                P("batch", None),    # root_w
                P(), P(), P(), P(), P(),
                P(),                 # min_nh
                P(),                 # v4_blocked
            ),
            out_specs=(
                P("batch", None),
                P("batch", None),
                P("batch", None, None),
                P("batch", None, None),
                P("batch", None),
                P("batch", None),
                P("batch", None),    # ok
                P("batch"),
            ),
            **_check_kw,
        )
    )


class Unconverged(AssertionError):
    """The fixed trip bound was below the graph's diameter bound."""


def pad_to(arr: np.ndarray, size: int, fill, axis: int = 0) -> np.ndarray:
    if arr.shape[axis] == size:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, size - arr.shape[axis])
    return np.pad(arr, pad, constant_values=fill)


def sharded_fabric_step(mesh, plan, matrix, roots, out_nbr, out_w,
                        n_trips: int, check_convergence: bool = True,
                        lfa: bool = False, block_v4: bool = False,
                        with_ok: bool = False):
    """Run the sharded whole-fabric pipeline.

    plan: ops.edgeplan.EdgePlan; matrix: ops.csr.PrefixMatrix;
    roots [Rt] int32 (padded to a multiple of the batch axis);
    out_nbr/out_w [Rt, D]: per-root out-edge tables; n_trips: diameter
    bound in unrolled trips (take it from the single-chip pipeline's
    measured trip count with 2x slack — one vantage's trip count bounds
    its eccentricity, and another root's can be up to ~2x that). The
    kernel emits a per-root convergence verdict (one extra relaxation
    must be a fixpoint no-op); with check_convergence the verdict is
    asserted host-side (raising Unconverged), so an insufficient bound
    fails loudly — TpuSpfSolver.build_fabric_route_dbs catches it and
    retries with a doubled bound.

    Returns (dist [Rt, N_cap], metric [Rt, P_cap], s3 [Rt, P_cap, A]
    selected-announcer masks, nh_mask [Rt, P_cap, D], lfa_slot
    [Rt, P_cap] (-1 = none; only meaningful with lfa=True), lfa_metric
    [Rt, P_cap]). With with_ok=True a seventh array is appended: the
    device-computed route-level ok mask [Rt, P_cap]
    (ops/compact.route_ok_device with v4 rows blocked per block_v4),
    which ColumnarRib.set_full_arrays consumes directly.
    """
    g = mesh.shape["graph"]
    n_cap = plan.n_cap
    assert n_cap % g == 0, (n_cap, g)
    r_cap = ((plan.res_rows.shape[0] + g - 1) // g) * g
    res_rows = pad_to(plan.res_rows, r_cap, -1)
    res_nbr = pad_to(plan.res_nbr, r_cap, -1)
    res_w = pad_to(plan.res_w, r_cap, INF_E)
    kr_cap = res_nbr.shape[1]
    d_cap = out_nbr.shape[1]
    p_cap, a_cap = matrix.ann_node.shape
    has_res = plan.k_res > 0

    idxm = np.clip(matrix.ann_node, 0, None)
    flags = matrix.ann_valid.astype(np.int32) | (
        plan.node_overloaded[idxm].astype(np.int32) << 1
    )

    v4_blocked = (
        matrix.is_v4 if block_v4 else np.zeros(p_cap, bool)
    )

    fn = _sharded_fabric_fn(
        mesh, n_cap, plan.s_cap, r_cap, kr_cap, has_res, d_cap,
        p_cap, a_cap, n_trips, lfa,
    )
    dist, metric, s3, nh_mask, lfa_slot, lfa_metric, ok, converged = fn(
        plan.deltas, plan.shift_w, res_rows, res_nbr, res_w,
        roots.astype(np.int32), out_nbr.astype(np.int32),
        out_w.astype(np.int32),
        matrix.ann_node, flags, matrix.path_pref, matrix.source_pref,
        matrix.dist_adv,
        matrix.min_nexthop.astype(np.int32), v4_blocked,
    )
    if check_convergence:
        conv = np.asarray(converged)
        if not conv.all():
            raise Unconverged(
                f"sharded SSSP unconverged for roots "
                f"{np.asarray(roots)[~conv].tolist()}: raise n_trips ({n_trips})"
            )
    if with_ok:
        return dist, metric, s3, nh_mask, lfa_slot, lfa_metric, ok
    return dist, metric, s3, nh_mask, lfa_slot, lfa_metric
