from openr_tpu.link_monitor.link_monitor import (  # noqa: F401
    AdjacencyValue,
    LinkMonitor,
    LinkMonitorState,
    get_rtt_metric,
)
