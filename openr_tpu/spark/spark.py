"""Spark actor — neighbor discovery & liveness.

Role of the reference's openr/spark/Spark.{h,cpp}: periodic multicast
hellos carrying a seen-neighbors map (2-way check), unicast handshake
(area/port negotiation), cheap heartbeats once established, and the
5-state neighbor FSM (ref Types.thrift:29, transition table Spark.cpp:97-164):

    IDLE        --hello(any)-->             WARM
    WARM        --hello(sees us)-->         NEGOTIATE   (start handshaking)
    NEGOTIATE   --handshake-->              ESTABLISHED (NEIGHBOR_UP)
    NEGOTIATE   --negotiate timeout-->      WARM
    ESTABLISHED --hello(forgot us)-->       IDLE        (NEIGHBOR_DOWN)
    ESTABLISHED --hello(restarting)-->      RESTART     (NEIGHBOR_RESTARTING)
    ESTABLISHED --heartbeat timeout-->      IDLE        (NEIGHBOR_DOWN)
    RESTART     --hello(sees us)-->         NEGOTIATE   (NEIGHBOR_RESTARTED on est.)
    RESTART     --GR timeout-->             IDLE        (NEIGHBOR_DOWN)

RTT is measured from the 4 send/receive timestamps reflected through the
hello exchange (ref Spark.h:233, updateNeighborRtt) and smoothed by a step
detector (ref StepDetector.h); fast-init mode hellos at a faster cadence
until initial discovery completes (ref Spark.h fastInit). Per-sender packet
rate limiting guards against storms (ref Spark.h:511).

I/O goes through the IoProvider seam (io_provider.py) so tests drive an
in-process latency-aware mesh (MockIoMesh).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from openr_tpu.config import SparkConfig
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.actor import Actor, Timer
from openr_tpu.runtime.counters import counters
from openr_tpu.spark.io_provider import IoProvider, ReceivedPacket
from openr_tpu.types import (
    InterfaceDatabase,
    NeighborEvent,
    NeighborEventType,
    NeighborInitEvent,
    ReflectedNeighborInfo,
    SparkHandshakeMsg,
    SparkHelloMsg,
    SparkHeartbeatMsg,
    SparkNeighState,
    SparkPacket,
)

log = logging.getLogger(__name__)


def _sender_ip(sender_addr: str):
    """Parse the provider's sender address ("ip:port" for UDP; the mock
    mesh uses "node@iface", which is not an IP) -> ip_address | None."""
    import ipaddress

    host, sep, _port = sender_addr.rpartition(":")
    if not sep:
        host = sender_addr
    try:
        return ipaddress.ip_address(host.strip("[]"))
    except ValueError:
        return None


class SparkNeighEvent:
    """ref Types.thrift:37-47."""

    HELLO_RCVD_INFO = 0
    HELLO_RCVD_NO_INFO = 1
    HELLO_RCVD_RESTART = 2
    HEARTBEAT_RCVD = 3
    HANDSHAKE_RCVD = 4
    HEARTBEAT_TIMER_EXPIRE = 5
    NEGOTIATE_TIMER_EXPIRE = 6
    GR_TIMER_EXPIRE = 7
    NEGOTIATION_FAILURE = 8


# state x event -> next state; None = invalid transition
# (exact mirror of the reference table, Spark.cpp:97-164)
_S = SparkNeighState
_STATE_MAP: dict[SparkNeighState, dict[int, SparkNeighState]] = {
    _S.IDLE: {
        SparkNeighEvent.HELLO_RCVD_INFO: _S.WARM,
        SparkNeighEvent.HELLO_RCVD_NO_INFO: _S.WARM,
    },
    _S.WARM: {
        SparkNeighEvent.HELLO_RCVD_INFO: _S.NEGOTIATE,
    },
    _S.NEGOTIATE: {
        SparkNeighEvent.HANDSHAKE_RCVD: _S.ESTABLISHED,
        SparkNeighEvent.NEGOTIATE_TIMER_EXPIRE: _S.WARM,
        SparkNeighEvent.NEGOTIATION_FAILURE: _S.WARM,
    },
    _S.ESTABLISHED: {
        SparkNeighEvent.HELLO_RCVD_NO_INFO: _S.IDLE,
        SparkNeighEvent.HELLO_RCVD_RESTART: _S.RESTART,
        SparkNeighEvent.HEARTBEAT_RCVD: _S.ESTABLISHED,
        SparkNeighEvent.HEARTBEAT_TIMER_EXPIRE: _S.IDLE,
    },
    _S.RESTART: {
        SparkNeighEvent.HELLO_RCVD_INFO: _S.NEGOTIATE,
        SparkNeighEvent.GR_TIMER_EXPIRE: _S.IDLE,
    },
}


def get_next_state(
    state: SparkNeighState, event: int
) -> Optional[SparkNeighState]:
    """ref Spark::getNextState (Spark.h:400)."""
    return _STATE_MAP[state].get(event)


@dataclass
class _NeighborInfo:
    """Per-(iface, neighbor) session (ref SparkNeighbor, Spark.h:245-338)."""

    node_name: str
    if_name: str
    state: SparkNeighState = SparkNeighState.IDLE
    area: str = ""
    their_if_name: str = ""  # from their hellos; advertised as other_if_name
    their_seq_num: int = 0
    # reflection data for their RTT computation
    their_last_sent_ts_us: int = 0
    my_last_rcvd_ts_us: int = 0
    rtt_us: int = 0
    reported_rtt_us: int = 0
    # sliding sample window for the step detector
    rtt_samples: deque = field(default_factory=deque)
    # last message receipt; pre-ESTABLISHED sessions idle past the sweep
    # TTL are reaped (they have no hold timer of their own)
    last_msg_ts: float = 0.0
    hold_time_ms: int = 0
    gr_active: bool = False
    restarted: bool = False  # came back through RESTART
    ctrl_port: int = 0
    kvstore_port: int = 0
    addr_v6: str = ""
    addr_v4: str = ""
    hold_timer: Optional[Timer] = None
    negotiate_timer: Optional[Timer] = None
    gr_timer: Optional[Timer] = None
    handshake_sent: bool = False


class Spark(Actor):
    """ref Spark.h:46."""

    def __init__(
        self,
        node_name: str,
        config: SparkConfig,
        io_provider: IoProvider,
        neighbor_updates_queue: ReplicateQueue,
        resolve_area: Optional[Callable[[str, str], Optional[str]]] = None,
        ctrl_port: int = 0,
        kvstore_port: int = 0,
        interface_updates_queue=None,
    ):
        super().__init__(f"spark:{node_name}")
        self.node_name = node_name
        self.cfg = config
        self.io = io_provider
        self._neighbor_q = neighbor_updates_queue
        self._interface_updates = interface_updates_queue
        # area negotiation hook (role of config AreaConfiguration matchers)
        self._resolve_area = resolve_area or (lambda node, iface: "0")
        self.ctrl_port = ctrl_port
        self.kvstore_port = kvstore_port

        self.interfaces: set[str] = set()
        # (if_name, neighbor_node) -> session
        self.neighbors: dict[tuple[str, str], _NeighborInfo] = {}
        self.my_seq_num = 1
        self._fast_init_until = 0.0
        self._init_event_sent = False
        # per-sender token buckets for rate limiting
        self._rate: dict[str, tuple[float, float]] = {}
        # (iface, node) pairs already warned about for area refusal
        self._refused_logged: set[tuple[str, str]] = set()

    # -- lifecycle ---------------------------------------------------------

    async def on_start(self) -> None:
        self._fast_init_until = (
            time.monotonic() + 4 * self.cfg.fastinit_hello_time_ms / 1e3
        )
        self.add_task(self._recv_loop(), name=f"{self.name}.recv")
        self.add_task(self._hello_loop(), name=f"{self.name}.hello")
        self.add_task(self._heartbeat_loop_task(), name=f"{self.name}.heartbeat")
        if self._interface_updates is not None:
            self.add_task(self._interface_loop(), name=f"{self.name}.ifaces")

    async def on_stop(self) -> None:
        self.io.close()

    def add_interface(self, if_name: str) -> None:
        self.interfaces.add(if_name)

    def remove_interface(self, if_name: str) -> None:
        self.interfaces.discard(if_name)
        for key, nb in list(self.neighbors.items()):
            if nb.if_name == if_name:
                # RESTART sessions are still advertised by LinkMonitor (GR
                # hold) — they need an explicit DOWN too
                if nb.state in (
                    SparkNeighState.ESTABLISHED,
                    SparkNeighState.RESTART,
                ):
                    self._emit(nb, NeighborEventType.NEIGHBOR_DOWN)
                self._drop_neighbor(key)

    async def _interface_loop(self) -> None:
        while True:
            db: InterfaceDatabase = await self._interface_updates.get()
            new_ifs = {i.if_name for i in db.interfaces if i.is_up}
            for gone in self.interfaces - new_ifs:
                self.remove_interface(gone)
            for added in new_ifs - self.interfaces:
                self.add_interface(added)
                # fast-init on new interfaces (ref fastInit semantics)
                self._fast_init_until = max(
                    self._fast_init_until,
                    time.monotonic()
                    + 2 * self.cfg.fastinit_hello_time_ms / 1e3,
                )

    # -- send paths --------------------------------------------------------

    async def _hello_loop(self) -> None:
        while True:
            now = time.monotonic()
            self._sweep_stale_sessions(now)
            fast = now < self._fast_init_until
            await self._send_hellos(solicit=fast)
            if not fast and not self._init_event_sent:
                self._init_event_sent = True
                self._neighbor_q.push(NeighborInitEvent(init_complete=True))
            await asyncio.sleep(
                self.cfg.fastinit_hello_time_ms / 1e3
                if fast
                else self.cfg.hello_time_s
            )

    async def _send_hellos(self, solicit: bool = False) -> None:
        for if_name in list(self.interfaces):
            infos = {}
            for (iface, node), nb in self.neighbors.items():
                if iface != if_name or nb.their_seq_num == 0:
                    continue
                infos[node] = ReflectedNeighborInfo(
                    seq_num=nb.their_seq_num,
                    last_nbr_msg_sent_ts_us=nb.their_last_sent_ts_us,
                    last_my_msg_rcvd_ts_us=nb.my_last_rcvd_ts_us,
                )
            hello = SparkHelloMsg(
                domain_name="",
                node_name=self.node_name,
                if_name=if_name,
                seq_num=self.my_seq_num,
                neighbor_infos=infos,
                solicit_response=solicit,
                sent_ts_us=int(time.monotonic() * 1e6),
            )
            self.my_seq_num += 1
            await self.io.send(if_name, SparkPacket(hello=hello))
            counters.increment("spark.hello.packets_sent")

    async def _send_handshake(
        self, nb: _NeighborInfo, is_adj_established: bool
    ) -> None:
        msg = SparkHandshakeMsg(
            node_name=self.node_name,
            is_adj_established=is_adj_established,
            hold_time_ms=int(self.cfg.hold_time_s * 1e3),
            gr_hold_time_ms=int(self.cfg.graceful_restart_time_s * 1e3),
            openr_ctrl_port=self.ctrl_port,
            kvstore_port=self.kvstore_port,
            area=nb.area,
            neighbor_node_name=nb.node_name,
            transport_address_v6=f"fe80::{self.node_name}",
        )
        await self.io.send(nb.if_name, SparkPacket(handshake=msg))
        counters.increment("spark.handshake.packets_sent")

    async def _heartbeat_loop_task(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.keepalive_time_s)
            sent_ifaces = set()
            for nb in self.neighbors.values():
                if (
                    nb.state == SparkNeighState.ESTABLISHED
                    and nb.if_name not in sent_ifaces
                ):
                    sent_ifaces.add(nb.if_name)
                    await self.io.send(
                        nb.if_name,
                        SparkPacket(
                            heartbeat=SparkHeartbeatMsg(
                                node_name=self.node_name,
                                seq_num=self.my_seq_num,
                            )
                        ),
                    )
                    counters.increment("spark.heartbeat.packets_sent")

    async def send_restarting_hellos(self) -> None:
        """Graceful-restart announcement on shutdown (ref Spark GR)."""
        for if_name in list(self.interfaces):
            hello = SparkHelloMsg(
                domain_name="",
                node_name=self.node_name,
                if_name=if_name,
                seq_num=self.my_seq_num,
                restarting=True,
                sent_ts_us=int(time.monotonic() * 1e6),
            )
            self.my_seq_num += 1
            await self.io.send(if_name, SparkPacket(hello=hello))

    # -- receive path ------------------------------------------------------

    async def _recv_loop(self) -> None:
        while True:
            pkt = await self.io.recv()
            if pkt.from_if_name not in self.interfaces:
                continue
            if not self._rate_limit_ok(pkt.sender_addr):
                counters.increment("spark.packets_rate_limited")
                continue
            try:
                if pkt.packet.hello is not None:
                    self._process_hello(pkt)
                elif pkt.packet.handshake is not None:
                    await self._process_handshake(pkt)
                elif pkt.packet.heartbeat is not None:
                    self._process_heartbeat(pkt)
            except Exception:
                # one malformed/hostile packet must not kill the recv
                # fiber, but it must not vanish either
                counters.increment("spark.packet_process_errors")
                log.exception("%s: error processing packet", self.name)

    def _rate_limit_ok(self, sender: str) -> bool:
        """Token bucket per sender addr (ref Spark.h:511)."""
        rate = float(self.cfg.min_packets_per_sec)
        if rate <= 0:
            return True
        tokens, ts = self._rate.get(sender, (rate, time.monotonic()))
        now = time.monotonic()
        tokens = min(rate, tokens + (now - ts) * rate)
        if tokens < 1.0:
            self._rate[sender] = (tokens, now)
            return False
        self._rate[sender] = (tokens - 1.0, now)
        return True

    def _get_neighbor(
        self, if_name: str, node: str
    ) -> Optional[_NeighborInfo]:
        """None when NO configured area claims (neighbor, interface) —
        the matchers gate admission (ref area negotiation failure), so
        an unclaimed sender must be refused outright, not given an
        adjacency under a phantom '' area that KvStore then rejects."""
        key = (if_name, node)
        nb = self.neighbors.get(key)
        if nb is None:
            area = self._resolve_area(node, if_name)
            if area is None:
                counters.increment("spark.neighbor.no_area_match")
                # refused senders keep helloing and hold no state here —
                # warn once per (iface, node), count every packet
                if key not in self._refused_logged:
                    if len(self._refused_logged) >= 256:
                        self._refused_logged.clear()
                    self._refused_logged.add(key)
                    log.warning(
                        "%s: no area claims neighbor %s on %s — refusing",
                        self.node_name, node, if_name,
                    )
                return None
            nb = self.neighbors[key] = _NeighborInfo(
                node_name=node, if_name=if_name
            )
            nb.area = area
        nb.last_msg_ts = time.monotonic()
        return nb

    def _sweep_stale_sessions(self, now: float) -> None:
        """Age out pre-ESTABLISHED sessions that stopped talking.
        IDLE/WARM/NEGOTIATE entries carry no hold timer, so without this
        a sender spoofing a fresh node_name per packet would grow
        permanent neighbor state; a real neighbor mid-discovery keeps
        its entry alive with every hello and re-forms instantly anyway
        (runs on the hello cadence; ESTABLISHED/RESTART lifetimes belong
        to the hold/GR timers)."""
        ttl = max(self.cfg.hold_time_s, 3 * self.cfg.hello_time_s)
        for key, nb in list(self.neighbors.items()):
            if nb.state in (
                SparkNeighState.ESTABLISHED,
                SparkNeighState.RESTART,
            ):
                continue
            if now - nb.last_msg_ts > ttl:
                self._drop_neighbor(key)
                counters.increment("spark.stale_sessions_swept")

    def _drop_neighbor(self, key: tuple[str, str]) -> None:
        nb = self.neighbors.pop(key, None)
        if nb is None:
            return
        for t in (nb.hold_timer, nb.negotiate_timer, nb.gr_timer):
            if t is not None:
                t.cancel()

    # -- hello processing (ref processHelloMsg Spark.h:135) ----------------

    def _process_hello(self, pkt: ReceivedPacket) -> None:
        hello = pkt.packet.hello
        if not hello.node_name:
            # sanity check (ref sanityCheckMsg): a nameless hello must
            # not create neighbor state — WARM sessions have no hold
            # timer, so a hostile sender could grow permanent entries
            counters.increment("spark.hello.invalid")
            return
        if hello.node_name == self.node_name:
            return  # our own multicast echo
        counters.increment("spark.hello.packets_recv")
        # real-socket providers can't stamp the sender's clock; fall back
        # to the in-packet timestamp for both reflection and RTT math
        if not pkt.sent_ts_us:
            pkt.sent_ts_us = hello.sent_ts_us
        nb = self._get_neighbor(pkt.from_if_name, hello.node_name)
        if nb is None:
            return  # no configured area admits this neighbor
        nb.their_if_name = hello.if_name
        nb.their_seq_num = hello.seq_num
        nb.their_last_sent_ts_us = pkt.sent_ts_us or hello.sent_ts_us
        nb.my_last_rcvd_ts_us = pkt.recv_ts_us

        sees_us = self.node_name in hello.neighbor_infos
        if sees_us:
            self._update_rtt(nb, pkt, hello.neighbor_infos[self.node_name])

        if hello.restarting:
            if nb.state == SparkNeighState.ESTABLISHED:
                self._transition(nb, SparkNeighEvent.HELLO_RCVD_RESTART)
                nb.gr_active = True
                self._emit(nb, NeighborEventType.NEIGHBOR_RESTARTING)
                self._arm_gr_timer(nb)
            return

        if nb.state == SparkNeighState.IDLE:
            self._transition(
                nb,
                SparkNeighEvent.HELLO_RCVD_INFO
                if sees_us
                else SparkNeighEvent.HELLO_RCVD_NO_INFO,
            )
            if hello.solicit_response:
                self.add_task(
                    self._send_hellos(solicit=False),
                    name=f"{self.name}.solicited-hello",
                )
            return
        if nb.state == SparkNeighState.WARM:
            if sees_us:
                self._transition(nb, SparkNeighEvent.HELLO_RCVD_INFO)
                self._start_negotiation(nb)
            return
        if nb.state == SparkNeighState.ESTABLISHED:
            if not sees_us:
                # neighbor forgot us (restarted without GR)
                self._transition(nb, SparkNeighEvent.HELLO_RCVD_NO_INFO)
                self._emit(nb, NeighborEventType.NEIGHBOR_DOWN)
                self._cancel_hold(nb)
            return
        if nb.state == SparkNeighState.RESTART:
            if sees_us:
                nb.restarted = True
                self._transition(nb, SparkNeighEvent.HELLO_RCVD_INFO)
                self._start_negotiation(nb)
            return

    def _update_rtt(
        self, nb: _NeighborInfo, pkt: ReceivedPacket, info: ReflectedNeighborInfo
    ) -> None:
        """4-timestamp RTT (ref Spark.h:233): (t4-t1) - (t3-t2)."""
        t1 = info.last_nbr_msg_sent_ts_us  # when WE sent the reflected msg
        t2 = info.last_my_msg_rcvd_ts_us  # when THEY received it
        t3 = pkt.sent_ts_us  # when they sent this hello
        t4 = pkt.recv_ts_us  # when we received it
        if not (t1 and t2 and t3 and t4):
            return
        rtt = (t4 - t1) - (t3 - t2)
        if rtt <= 0:
            return
        nb.rtt_us = rtt
        # StepDetector (ref StepDetector.h + config knobs
        # OpenrConfig.thrift:223): compare the fast-window MEAN against
        # the last reported value, and report only when the move clears
        # BOTH the relative threshold and the absolute ads_threshold.
        # Raw per-hello RTT jitters by far more than 10% on fast links;
        # advertising every wiggle re-floods the adjacency fabric-wide
        # and churns every node's SPF.
        sd = self.cfg.step_detector_conf
        nb.rtt_samples.append(rtt)
        while len(nb.rtt_samples) > sd.fast_window_size:
            nb.rtt_samples.popleft()
        mean = sum(nb.rtt_samples) / len(nb.rtt_samples)
        if nb.reported_rtt_us == 0:
            nb.reported_rtt_us = int(mean)
            return
        diff = abs(mean - nb.reported_rtt_us)
        # hysteresis per the reference: increases must clear the upper
        # threshold, decreases the (tighter) lower one — worse news needs
        # more evidence than better news reverting
        pct = (
            sd.upper_threshold_pct
            if mean > nb.reported_rtt_us
            else sd.lower_threshold_pct
        )
        if (
            diff * 100 > nb.reported_rtt_us * pct
            and diff >= sd.ads_threshold
            and nb.state == SparkNeighState.ESTABLISHED
        ):
            nb.reported_rtt_us = int(mean)
            self._emit(nb, NeighborEventType.NEIGHBOR_RTT_CHANGE)

    # -- handshake (ref processHandshakeMsg Spark.h:145) -------------------

    def _start_negotiation(self, nb: _NeighborInfo) -> None:
        nb.handshake_sent = True
        self.add_task(
            self._send_handshake(nb, is_adj_established=False),
            name=f"{self.name}.handshake",
        )
        if nb.negotiate_timer is None:
            nb.negotiate_timer = self.make_timer(
                lambda nb=nb: self._on_negotiate_timeout(nb)
            )
        nb.negotiate_timer.schedule(self.cfg.handshake_time_ms / 1e3 * 5)

    def _on_negotiate_timeout(self, nb: _NeighborInfo) -> None:
        if nb.state == SparkNeighState.NEGOTIATE:
            self._transition(nb, SparkNeighEvent.NEGOTIATE_TIMER_EXPIRE)

    async def _process_handshake(self, pkt: ReceivedPacket) -> None:
        msg = pkt.packet.handshake
        if not msg.node_name:
            counters.increment("spark.handshake.invalid")
            return  # sanity: nameless sender must not create state
        if msg.node_name == self.node_name:
            return
        if msg.neighbor_node_name and msg.neighbor_node_name != self.node_name:
            return  # directed at someone else
        counters.increment("spark.handshake.packets_recv")
        nb = self._get_neighbor(pkt.from_if_name, msg.node_name)
        if nb is None:
            return  # no configured area admits this neighbor

        # area validation: both sides must agree (ref area negotiation)
        if msg.area and nb.area and msg.area != nb.area:
            log.warning(
                "%s: area mismatch with %s (%s != %s)",
                self.name,
                nb.node_name,
                msg.area,
                nb.area,
            )
            if nb.state == SparkNeighState.NEGOTIATE:
                self._transition(nb, SparkNeighEvent.NEGOTIATION_FAILURE)
            return

        if not msg.is_adj_established:
            # reply so the peer can also establish (ref handshake reply)
            await self._send_handshake(nb, is_adj_established=True)

        if nb.state != SparkNeighState.NEGOTIATE:
            return
        nb.hold_time_ms = msg.hold_time_ms or int(self.cfg.hold_time_s * 1e3)
        nb.ctrl_port = msg.openr_ctrl_port
        nb.kvstore_port = msg.kvstore_port
        nb.addr_v6 = msg.transport_address_v6
        nb.addr_v4 = msg.transport_address_v4
        # kernel truth beats the message payload: the UDP source address
        # the handshake ARRIVED from is where the neighbor is actually
        # reachable (ref Spark reading the kernel's recvfrom address) —
        # cross-namespace/real-network peering depends on it
        sender_ip = _sender_ip(pkt.sender_addr)
        if sender_ip is not None:
            if sender_ip.version == 4:
                nb.addr_v4 = str(sender_ip)
            else:
                nb.addr_v6 = str(sender_ip)
        self._transition(nb, SparkNeighEvent.HANDSHAKE_RCVD)
        if nb.negotiate_timer is not None:
            nb.negotiate_timer.cancel()
        if nb.gr_timer is not None:
            nb.gr_timer.cancel()
        self._arm_hold_timer(nb)
        self._emit(
            nb,
            NeighborEventType.NEIGHBOR_RESTARTED
            if nb.restarted
            else NeighborEventType.NEIGHBOR_UP,
        )
        nb.restarted = False
        nb.gr_active = False

    # -- heartbeat / hold (ref processHeartbeatMsg Spark.h:146) ------------

    def _process_heartbeat(self, pkt: ReceivedPacket) -> None:
        msg = pkt.packet.heartbeat
        if msg.node_name == self.node_name:
            return
        nb = self.neighbors.get((pkt.from_if_name, msg.node_name))
        if nb is None or nb.state != SparkNeighState.ESTABLISHED:
            return
        self._transition(nb, SparkNeighEvent.HEARTBEAT_RCVD)
        self._arm_hold_timer(nb)

    def _arm_hold_timer(self, nb: _NeighborInfo) -> None:
        if nb.hold_timer is None:
            nb.hold_timer = self.make_timer(
                lambda nb=nb: self._on_hold_timeout(nb)
            )
        hold_s = (nb.hold_time_ms or int(self.cfg.hold_time_s * 1e3)) / 1e3
        nb.hold_timer.schedule(hold_s)

    def _cancel_hold(self, nb: _NeighborInfo) -> None:
        if nb.hold_timer is not None:
            nb.hold_timer.cancel()

    def _on_hold_timeout(self, nb: _NeighborInfo) -> None:
        if nb.state != SparkNeighState.ESTABLISHED:
            return
        self._transition(nb, SparkNeighEvent.HEARTBEAT_TIMER_EXPIRE)
        self._emit(nb, NeighborEventType.NEIGHBOR_DOWN)
        counters.increment("spark.neighbor.hold_expired")

    def _arm_gr_timer(self, nb: _NeighborInfo) -> None:
        if nb.gr_timer is None:
            nb.gr_timer = self.make_timer(lambda nb=nb: self._on_gr_timeout(nb))
        self._cancel_hold(nb)
        nb.gr_timer.schedule(self.cfg.graceful_restart_time_s)

    def _on_gr_timeout(self, nb: _NeighborInfo) -> None:
        if nb.state != SparkNeighState.RESTART:
            return
        self._transition(nb, SparkNeighEvent.GR_TIMER_EXPIRE)
        self._emit(nb, NeighborEventType.NEIGHBOR_DOWN)
        counters.increment("spark.neighbor.gr_expired")

    # -- FSM + event emission ----------------------------------------------

    def _transition(self, nb: _NeighborInfo, event: int) -> None:
        next_state = get_next_state(nb.state, event)
        if next_state is None:
            log.debug(
                "%s: invalid transition %s x %s", self.name, nb.state, event
            )
            return
        if next_state != nb.state:
            log.debug(
                "%s: %s/%s %s -> %s",
                self.name,
                nb.if_name,
                nb.node_name,
                nb.state.name,
                next_state.name,
            )
        nb.state = next_state

    def _emit(self, nb: _NeighborInfo, event_type: NeighborEventType) -> None:
        self._neighbor_q.push(
            NeighborEvent(
                event_type=event_type,
                node_name=nb.node_name,
                if_name=nb.if_name,
                area=nb.area,
                remote_if_name=nb.their_if_name,
                neighbor_addr_v6=nb.addr_v6,
                neighbor_addr_v4=nb.addr_v4,
                ctrl_port=nb.ctrl_port,
                kvstore_port=nb.kvstore_port,
                rtt_us=nb.reported_rtt_us or nb.rtt_us,
            )
        )
        counters.increment(f"spark.neighbor.{event_type.name.lower()}")

    # -- introspection API (ref getNeighbors) ------------------------------

    async def get_neighbors(self) -> list[_NeighborInfo]:
        return list(self.neighbors.values())
