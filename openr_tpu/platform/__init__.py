"""Platform layer: kernel-facing route programming.

Role of the reference's openr/platform/ (NetlinkFibHandler.h:32 serving
thrift FibService over openr/nl/NetlinkProtocolSocket) and the
standalone platform_linux binary (LinuxPlatformMain.cpp): a separate
process owns the dataplane; the daemon's Fib actor programs it through
the FibService seam (fib/fib_service.py) over runtime/rpc.py.

  netlink.py      async rtnetlink client (the openr/nl layer)
  fib_handler.py  FibService RPC server over a dataplane backend
                  (in-memory or netlink) + the daemon-side RemoteFibService
  main.py         standalone platform agent binary
"""

from openr_tpu.platform.fib_handler import (  # noqa: F401
    FibPlatformServer,
    MemoryDataplane,
    RemoteFibService,
    wait_for_fib_service,
)
