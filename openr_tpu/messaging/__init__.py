from openr_tpu.messaging.queue import (  # noqa: F401
    QueueClosedError,
    ReplicateQueue,
    RQueue,
)
