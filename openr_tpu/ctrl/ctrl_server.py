"""Ctrl server — the operator/control API.

Role of the reference's openr/ctrl-server/OpenrCtrlHandler.{h,cpp} +
OpenrThriftCtrlServer (service OpenrCtrl, OpenrCtrl.thrift:246-713): one
server fanning out to every module's async API, plus server-streaming
subscriptions for KvStore and Fib deltas with an initial snapshot
(ref OpenrCtrlHandler.h:351-389). Served over runtime/rpc.py (role of the
thrift server on :2018); the breeze CLI (cli/breeze.py) is the client.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from openr_tpu.messaging import QueueClosedError, ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.rpc import RpcServer, Stream
from openr_tpu.runtime.tracing import tracer
from openr_tpu.serde import from_plain, to_plain
from openr_tpu.types import InitializationEvent, Publication

log = logging.getLogger(__name__)


class CtrlServer(Actor):
    """ref OpenrCtrlHandler.h — fans out to module semifuture APIs."""

    def __init__(
        self,
        node_name: str,
        kvstore=None,
        decision=None,
        fib=None,
        link_monitor=None,
        prefix_manager=None,
        spark=None,
        kvstore_updates_queue: Optional[ReplicateQueue] = None,
        fib_updates_queue: Optional[ReplicateQueue] = None,
        listen_port: int = 0,
        config=None,
        monitor=None,
        persistent_store=None,
    ):
        super().__init__(f"ctrl:{node_name}")
        self.node_name = node_name
        self.kvstore = kvstore
        self.decision = decision
        self.fib = fib
        self.link_monitor = link_monitor
        self.prefix_manager = prefix_manager
        self.spark = spark
        self._kvstore_updates_q = kvstore_updates_queue
        self._fib_updates_q = fib_updates_queue
        self._listen_port = listen_port
        self.config = config
        self.monitor = monitor
        self.persistent_store = persistent_store
        self.server = RpcServer(self.name)
        self.port: int = 0
        self.start_time = time.time()
        # initialization-event introspection (ref getInitializationEvents)
        self.initialization_events: dict[str, float] = {}
        # live-stream bookkeeping (ref getSubscriberInfo,
        # OpenrCtrl.thrift:72-83 + :407)
        self._subscribers: dict[int, dict] = {}
        self._next_subscriber_id = 0

    async def on_start(self) -> None:
        s = self.server
        s.register("openr.version", self._version)
        s.register("openr.initialization_events", self._get_init_events)
        s.register("openr.initialization_converged", self._init_converged)
        s.register("openr.initialization_duration", self._init_duration)
        s.register("openr.my_node_name", self._my_node_name)
        s.register("openr.build_info", self._build_info)
        s.register("monitor.counters", self._counters)
        s.register("monitor.statistics", self._statistics)
        s.register("monitor.traces", self._traces)
        s.register("monitor.traces.export_chrome", self._traces_chrome)
        s.register("monitor.event_logs", self._event_logs)
        s.register("ctrl.monitor.logs", self._event_logs)
        s.register("ctrl.monitor.fleet", self._monitor_fleet)
        s.register("ctrl.monitor.crashes", self._monitor_crashes)
        s.register("ctrl.monitor.slo", self._monitor_slo)
        s.register("ctrl.monitor.boot", self._monitor_boot)
        s.register("ctrl.monitor.dump", self._monitor_dump)
        s.register("ctrl.monitor.bundles", self._monitor_bundles)
        s.register("ctrl.monitor.record", self._monitor_record)
        # fault-injection registry (runtime/faults.py): arm / disarm /
        # inspect chaos drills on the live daemon
        s.register("ctrl.fault.inject", self._fault_inject)
        s.register("ctrl.fault.clear", self._fault_clear)
        s.register("ctrl.fault.list", self._fault_list)
        s.register("monitor.heap_profile.start", self._heap_profile_start)
        s.register("monitor.heap_profile.dump", self._heap_profile_dump)
        # device plane (runtime/device_stats.py + ops/xla_cache.ledger):
        # all of these degrade gracefully on CPU-only hosts
        s.register("ctrl.tpu.profiler.start", self._tpu_profiler_start)
        s.register("ctrl.tpu.profiler.stop", self._tpu_profiler_stop)
        s.register("ctrl.tpu.profiler.status", self._tpu_profiler_status)
        s.register("ctrl.tpu.kernels", self._tpu_kernels)
        s.register("ctrl.tpu.aot", self._tpu_aot)
        s.register("ctrl.tpu.devices", self._tpu_devices)
        s.register("ctrl.store.set", self._store_set)
        s.register("ctrl.store.get", self._store_get)
        s.register("ctrl.store.erase", self._store_erase)
        s.register("ctrl.store.dump", self._store_dump)
        if self.kvstore is not None:
            s.register("ctrl.kvstore.keyvals", self._kv_get)
            s.register("ctrl.kvstore.dump", self._kv_dump)
            s.register("ctrl.kvstore.hashes", self._kv_hashes)
            s.register("ctrl.kvstore.peers", self._kv_peers)
            s.register("ctrl.kvstore.set", self._kv_set)
            s.register("ctrl.kvstore.set_key", self._kv_set_key)
            s.register("ctrl.kvstore.areas", self._kv_area_summary)
            s.register("ctrl.kvstore.long_poll_adj", self._kv_long_poll_adj)
            s.register("ctrl.kvstore.flood_topo", self._kv_flood_topo)
            s.register("ctrl.kvstore.divergence", self._kv_divergence)
        s.register("ctrl.config.dryrun", self._dryrun_config)
        s.register("ctrl.config.get", self._get_config)
        s.register("openr.drain_state", self._drain_state)
        if self.decision is not None:
            s.register("ctrl.decision.routes", self._decision_routes)
            s.register(
                "ctrl.decision.fabric_routes", self._decision_fabric_routes
            )
            s.register("ctrl.decision.adj_dbs", self._decision_adj_dbs)
            s.register(
                "ctrl.decision.adjacencies_filtered",
                self._decision_adjacencies_filtered,
            )
            s.register("ctrl.decision.prefix_dbs", self._decision_prefix_dbs)
            s.register(
                "ctrl.decision.received_routes", self._decision_received
            )
            s.register("ctrl.decision.path", self._decision_path)
            s.register("ctrl.decision.explain", self._decision_explain)
            if self.kvstore is not None:
                s.register(
                    "ctrl.decision.validate", self._decision_validate
                )
            s.register("ctrl.decision.set_rib_policy", self._set_rib_policy)
            s.register("ctrl.decision.get_rib_policy", self._get_rib_policy)
            s.register(
                "ctrl.decision.clear_rib_policy", self._clear_rib_policy
            )
            s.register(
                "ctrl.decision.convergence", self._decision_convergence
            )
            s.register("ctrl.decision.budget", self._decision_budget)
            s.register("ctrl.decision.replay", self._decision_replay)
            s.register("ctrl.decision.overload", self._decision_overload)
            s.register("ctrl.decision.whatif.sweep", self._whatif_sweep)
            s.register("ctrl.decision.whatif.drain", self._whatif_drain)
            s.register(
                "ctrl.decision.whatif.optimize", self._whatif_optimize
            )
        if self.fib is not None:
            s.register("ctrl.fib.routes", self._fib_routes)
            s.register("ctrl.fib.mpls_routes", self._fib_mpls)
            s.register("ctrl.fib.routes_filtered", self._fib_routes_filtered)
            s.register("ctrl.fib.mpls_filtered", self._fib_mpls_filtered)
            s.register("ctrl.fib.perf", self._fib_perf)
            s.register("ctrl.fib.route_detail_db", self._fib_route_detail_db)
            if self.decision is not None:
                s.register("ctrl.fib.validate", self._fib_validate)
        s.register("ctrl.subscriber_info", self._subscriber_info)
        if self.link_monitor is not None:
            s.register("ctrl.lm.links", self._lm_links)
            s.register("ctrl.lm.interfaces", self._lm_interfaces)
            s.register("ctrl.lm.adjacencies", self._lm_adjacencies)
            s.register("ctrl.lm.set_node_overload", self._lm_set_overload)
            s.register("ctrl.lm.set_link_overload", self._lm_set_link_overload)
            s.register("ctrl.lm.set_link_metric", self._lm_set_link_metric)
            s.register("ctrl.lm.set_adj_metric", self._lm_set_adj_metric)
            s.register(
                "ctrl.lm.set_node_metric_increment",
                self._lm_set_node_metric_increment,
            )
            s.register(
                "ctrl.lm.set_link_metric_increment",
                self._lm_set_link_metric_increment,
            )
        if self.spark is not None:
            s.register("ctrl.spark.neighbors", self._spark_neighbors)
            s.register("ctrl.spark.flood_restarting", self._spark_flood_restarting)
        if self.prefix_manager is not None:
            s.register("ctrl.prefixmgr.advertised", self._pm_advertised)
            s.register("ctrl.prefixmgr.prefixes", self._pm_prefixes)
            s.register("ctrl.prefixmgr.prefixes_by_type", self._pm_prefixes_by_type)
            s.register("ctrl.prefixmgr.originated", self._pm_originated)
            s.register("ctrl.prefixmgr.advertise", self._pm_advertise)
            s.register("ctrl.prefixmgr.withdraw", self._pm_withdraw)
            s.register(
                "ctrl.prefixmgr.withdraw_by_type", self._pm_withdraw_by_type
            )
            s.register("ctrl.prefixmgr.sync_by_type", self._pm_sync_by_type)
        if self._kvstore_updates_q is not None:
            s.register("ctrl.kvstore.subscribe", self._subscribe_kvstore)
            self.add_task(
                self._watch_initialization(self._kvstore_updates_q),
                name=f"{self.name}.init-watch-kv",
            )
        if self._fib_updates_q is not None:
            s.register("ctrl.fib.subscribe", self._subscribe_fib)
            s.register("ctrl.fib.subscribe_detail", self._subscribe_fib_detail)
            self.add_task(
                self._watch_initialization(self._fib_updates_q),
                name=f"{self.name}.init-watch-fib",
            )
        ssl_ctx = None
        peer_verifier = None
        if self.config is not None:
            ts = self.config.raw.thrift_server
            if ts.enable_secure_thrift_server:
                from openr_tpu.config import (
                    build_server_ssl_context,
                    make_peer_verifier,
                )

                ssl_ctx = build_server_ssl_context(ts)
                peer_verifier = make_peer_verifier(ts.acceptable_peers)
        self.port = await s.start(
            port=self._listen_port, ssl=ssl_ctx, peer_verifier=peer_verifier
        )

    async def on_stop(self) -> None:
        await self.server.stop()

    # -- misc --------------------------------------------------------------

    async def _version(self) -> dict:
        return {
            "node": self.node_name,
            "version": 1,
            "uptime_s": time.time() - self.start_time,
        }

    async def _counters(self, prefix: str = "") -> dict:
        return counters.get_counters(prefix)

    async def _statistics(self, prefix: str = "") -> dict:
        """ref breeze monitor statistics: multi-window stat view."""
        return counters.get_statistics(prefix)

    async def _traces(
        self,
        limit: int = 20,
        trace_id: Optional[int] = None,
        include_active: bool = False,
    ) -> list:
        """Closed convergence traces (runtime/tracing.py span trees)."""
        return tracer.get_traces(
            limit=limit, trace_id=trace_id, include_active=include_active
        )

    async def _traces_chrome(
        self, trace_id: Optional[int] = None, limit: int = 20
    ) -> dict:
        """Chrome trace-event JSON for chrome://tracing / Perfetto."""
        return tracer.export_chrome(trace_id=trace_id, limit=limit)

    async def _decision_convergence(self, fleet: bool = False) -> dict:
        """Per-event convergence latency: percentile summary over the
        closed-trace ring, the windowed convergence_ms stat, and the
        solver's incremental/full dispatch split (decision.solver.*
        counters — incr.solves ran the seed-from-previous kernel,
        incr.full_fallbacks degraded to a full solve while incremental
        was enabled, full.solves is every cold/full dispatch). With
        fleet=True (breeze decision convergence --fleet) also folds in
        the FLEET view: every node's TTL'd conv-ack ring aggregated
        per origin event."""
        incr_stats = counters.get_statistics(
            "decision.solver.incr"
        )
        device_stats = counters.get_statistics("decision.device")
        out = {
            "summary": tracer.convergence_summary(),
            "stat": counters.get_statistics("convergence_ms").get(
                "convergence_ms", {}
            ),
            "solver": {
                "incremental_solves": counters.get_counter(
                    "decision.solver.incr.solves"
                ) or 0,
                "incremental_full_fallbacks": counters.get_counter(
                    "decision.solver.incr.full_fallbacks"
                ) or 0,
                "full_solves": counters.get_counter(
                    "decision.solver.full.solves"
                ) or 0,
                "cone_frac": incr_stats.get(
                    "decision.solver.incr.cone_frac", {}
                ),
                "changed_rows": incr_stats.get(
                    "decision.solver.incr.changed_rows", {}
                ),
                # executed relaxation work per solve (ops/relax.py
                # ledger): rounds everywhere, bucket_epochs when the
                # bucketed Δ-stepping kernel engaged, halo_exchanges in
                # the multichip tier (one per epoch under bucketed)
                "device_rounds": device_stats.get(
                    "decision.device.rounds", {}
                ),
                "device_bucket_epochs": device_stats.get(
                    "decision.device.bucket_epochs", {}
                ),
                "device_halo_exchanges": device_stats.get(
                    "decision.device.halo_exchanges", {}
                ),
                "device_bytes_downloaded": device_stats.get(
                    "decision.device.bytes_downloaded", {}
                ),
            },
        }
        # device-kernel rows for the LAST solve, whatever its shape —
        # solver.last_timing is refreshed by every device collect
        # (full, incremental seed-from-previous, streamed epoch), so
        # these render after an incremental solve too, where the
        # windowed stats above can have already aged out
        solver = (
            getattr(self.decision, "solver", None)
            if self.decision is not None
            else None
        )
        tm = getattr(solver, "last_timing", None)
        if isinstance(tm, dict) and tm:
            last = {
                k: tm[k]
                for k in ("spf_kernel", "rounds", "bucket_epochs",
                          "halo_exchanges", "incremental",
                          "bytes_uploaded", "bytes_downloaded")
                if tm.get(k) is not None
            }
            # streamed churn epochs: budget use + changed-rows download
            if isinstance(tm.get("stream"), dict):
                last["stream"] = tm["stream"]
            out["solver"]["last_solve"] = last
            # windowed decision.device.* stats age out during idle (the
            # sample ring only answers for the trailing windows) and the
            # rows above render blank — fall back to the last_timing
            # snapshot, same pattern as the kernel rows
            for row, key in (
                ("device_rounds", "rounds"),
                ("device_bucket_epochs", "bucket_epochs"),
                ("device_halo_exchanges", "halo_exchanges"),
                ("device_bytes_downloaded", "bytes_downloaded"),
            ):
                if tm.get(key) is None:
                    continue
                win = out["solver"].get(row) or {}
                if all(
                    not (w or {}).get("count")
                    for w in win.values()
                    if isinstance(w, dict)
                ):
                    out["solver"][row] = {
                        "snapshot": tm[key],
                        "source": "last_timing",
                    }
        if fleet:
            out["fleet"] = await self._fleet_convergence()
        return out

    async def _decision_budget(self, fleet: bool = False) -> dict:
        """Latency-budget waterfall: the per-epoch churn-to-ack budget
        ledger's per-component windows, conservation accounting, and
        p50->p99 tail attribution (runtime/latency_budget.py). With
        fleet=True, joins the fleet conv-ack view so each origin event
        also names the straggler's dominant budget COMPONENT."""
        from openr_tpu.runtime.latency_budget import latency_budget

        out = latency_budget.report()
        out["node"] = self.node_name
        if fleet:
            out["fleet"] = await self._fleet_convergence()
        return out

    async def _fleet_convergence(self, limit: int = 20) -> dict:
        """Aggregate the `monitor:conv-ack:<node>` rings every node
        floods back into KvStore (fib.py stamps fleet_convergence_ms
        when a programmed route's trace carries a remote origin stamp).
        Grouped per origin event: fleet_ms is the LAST FIB ack's
        latency — origin publish → slowest node programmed — and the
        straggler is that node. Percentiles run across events."""
        import json as _json

        from openr_tpu.kvstore.kvstore import CONV_ACK_PREFIX
        from openr_tpu.runtime.counters import _percentile

        events: dict[str, dict] = {}
        reporting: set = set()
        if self.kvstore is not None:
            for area in list(getattr(self.kvstore, "areas", None) or []):
                vals = await self.kvstore.dump_all(area, CONV_ACK_PREFIX)
                for key, val in vals.items():
                    if val.value is None:
                        continue
                    try:
                        ring = _json.loads(val.value.decode())
                    except (ValueError, UnicodeDecodeError):
                        continue
                    reporting.add(key[len(CONV_ACK_PREFIX):])
                    for ack in ring.get("acks", []):
                        ev = events.setdefault(
                            ack.get("event", "?"),
                            {
                                "origin": ack.get("origin", ""),
                                "acks": {},
                                "ts_ms": 0,
                            },
                        )
                        node = ack.get("node", "?")
                        ms = float(ack.get("ms", 0.0))
                        # one node can re-program for the same origin
                        # event (coalesced floods) — keep its slowest ack
                        if ms >= ev["acks"].get(node, 0.0):
                            # the slowest ack's dominant budget component
                            # (fib.py threads it through the conv-ack) —
                            # names the straggler STAGE, not just the node
                            if ack.get("comp"):
                                ev.setdefault("comps", {})[node] = {
                                    "component": ack["comp"],
                                    "ms": float(ack.get("comp_ms", 0.0)),
                                }
                        ev["acks"][node] = max(
                            ev["acks"].get(node, 0.0), ms
                        )
                        ev["ts_ms"] = max(
                            ev["ts_ms"], int(ack.get("ts_ms", 0))
                        )
        rows = []
        for event_id, ev in events.items():
            straggler = max(ev["acks"], key=ev["acks"].get)
            row = {
                "event": event_id,
                "origin": ev["origin"],
                "ts_ms": ev["ts_ms"],
                "fleet_ms": round(ev["acks"][straggler], 3),
                "straggler": straggler,
                "nodes_acked": len(ev["acks"]),
                "acks": {
                    n: round(ms, 3) for n, ms in ev["acks"].items()
                },
            }
            comp = (ev.get("comps") or {}).get(straggler)
            if comp:
                row["straggler_component"] = comp["component"]
                row["straggler_component_ms"] = round(comp["ms"], 3)
            rows.append(row)
        rows.sort(key=lambda r: r["ts_ms"], reverse=True)
        fleet_ms = sorted(r["fleet_ms"] for r in rows)
        return {
            "local_node": self.node_name,
            "nodes_reporting": sorted(reporting),
            "events": rows[: max(1, limit)],
            "event_count": len(rows),
            "fleet_ms": {
                "count": len(fleet_ms),
                "p50": round(_percentile(fleet_ms, 50.0), 3),
                "p95": round(_percentile(fleet_ms, 95.0), 3),
                "p99": round(_percentile(fleet_ms, 99.0), 3),
                "max": fleet_ms[-1] if fleet_ms else 0.0,
            },
            "stat": counters.get_statistics("fleet_convergence_ms").get(
                "fleet_convergence_ms", {}
            ),
        }

    async def _monitor_slo(self) -> dict:
        """SLO burn-rate report (monitor.slo_report)."""
        if self.monitor is None:
            raise RuntimeError("no monitor wired to ctrl")
        return self.monitor.slo_report()

    async def _monitor_boot(self) -> dict:
        """Boot-to-first-RIB phase ledger (runtime/lifecycle.py). Unlike
        the other monitor endpoints this reads the process-global boot
        tracer — it answers even before/without a wired monitor."""
        from openr_tpu.runtime.lifecycle import boot_tracer

        return boot_tracer.report()

    async def _monitor_dump(self, reason: str = "manual") -> dict:
        """Operator-triggered flight-recorder bundle."""
        if self.monitor is None:
            raise RuntimeError("no monitor wired to ctrl")
        return await self.monitor.dump_flight_recorder(reason=reason)

    async def _monitor_bundles(self) -> dict:
        """Flight-recorder bundle listing (disk + memory)."""
        if self.monitor is None:
            raise RuntimeError("no monitor wired to ctrl")
        return await self.monitor.flight_recorder_bundles()

    async def _monitor_record(self, reason: str = "record") -> dict:
        """Operator-requested replayable bundle (inputs annex +
        snapshot re-anchor request)."""
        if self.monitor is None:
            raise RuntimeError("no monitor wired to ctrl")
        return await self.monitor.record_replay_bundle(reason=reason)

    async def _decision_replay(self) -> dict:
        """Input-recorder / RIB-digest status (runtime/replay_log.py)."""
        return await self.decision.replay_status()

    async def _decision_overload(self) -> dict:
        """Overload ladder / flap-damper state (runtime/overload.py)."""
        return await self.decision.overload_report()

    async def _watch_initialization(self, queue: ReplicateQueue) -> None:
        reader = queue.get_reader(f"{self.name}.init")
        try:
            while True:
                item = await reader.get()
                if isinstance(item, InitializationEvent):
                    self.initialization_events[item.name] = time.time()
        except QueueClosedError:
            pass

    async def _get_init_events(self) -> dict:
        return dict(self.initialization_events)

    # the reference's convergence signal (ref initializationConverged):
    # FIB_SYNCED marks the cold-boot pipeline complete end-to-end (the
    # RIB was computed AND programmed)
    _CONVERGENCE_EVENT = "FIB_SYNCED"

    async def _init_converged(self) -> bool:
        return self._CONVERGENCE_EVENT in self.initialization_events

    async def _init_duration(self) -> Optional[float]:
        """ref getInitializationDurationMs; None until converged."""
        ts = self.initialization_events.get(self._CONVERGENCE_EVENT)
        return None if ts is None else (ts - self.start_time) * 1e3

    async def _my_node_name(self) -> str:
        return self.node_name

    async def _build_info(self) -> dict:
        """ref getBuildInfo — platform/package provenance."""
        import platform as _platform

        try:
            from importlib.metadata import version as _pkg_version

            pkg = _pkg_version("openr-tpu")
        # lint: allow(broad-except) uninstalled checkout reports "dev"
        except Exception:
            pkg = "dev"
        return {
            "build_package": "openr_tpu",
            "build_version": pkg,
            "build_platform": _platform.platform(),
            "build_python": _platform.python_version(),
        }

    async def _heap_profile_start(self, frames: int = 1) -> dict:
        """ref MonitorBase::dumpHeapProfile hook (MonitorBase.h:54);
        tracemalloc is process-global, no Monitor actor required."""
        from openr_tpu.runtime.monitor import start_heap_profile

        return start_heap_profile(int(frames))

    async def _heap_profile_dump(
        self, top: int = 25, stop: bool = False
    ) -> dict:
        from openr_tpu.runtime.monitor import dump_heap_profile

        return await dump_heap_profile(int(top), bool(stop))

    async def _monitor_crashes(self) -> list:
        """Last task crashes (runtime/tasks.py ring), newest first."""
        from openr_tpu.runtime.tasks import recent_crashes

        return recent_crashes()

    # -- fault injection (runtime/faults.py) -------------------------------

    async def _fault_inject(
        self,
        site: str,
        probability: float = 0.0,
        every_nth: int = 0,
        one_shot: bool = False,
        window_s: float = 0.0,
        max_fires: int = 0,
        seed: Optional[int] = None,
        delay_ms: float = 0.0,
        rate: float = 0.0,
    ) -> dict:
        from openr_tpu.runtime.faults import registry

        return registry.arm(
            site,
            probability=float(probability),
            every_nth=int(every_nth),
            one_shot=bool(one_shot),
            window_s=float(window_s),
            max_fires=int(max_fires),
            seed=seed if seed is None else int(seed),
            delay_ms=float(delay_ms),
            rate=float(rate),
        )

    async def _fault_clear(self, site: Optional[str] = None) -> dict:
        from openr_tpu.runtime.faults import registry

        return registry.clear(site)

    async def _fault_list(self) -> dict:
        from openr_tpu.runtime.faults import registry

        return registry.list()

    async def _event_logs(self, category: Optional[str] = None) -> list:
        """ref getEventLogs — Monitor's LogSample ring, optionally
        filtered by event category (exact event, dotted prefix, or
        values["category"])."""
        if self.monitor is None:
            return []
        return await self.monitor.get_event_logs(category=category)

    # -- device plane ------------------------------------------------------

    async def _tpu_profiler_start(
        self,
        seconds: Optional[float] = None,
        out_dir: Optional[str] = None,
    ) -> dict:
        """On-demand XLA trace capture from the live daemon. Single-
        flight (the profiler is process-global); `seconds` arms an
        auto-stop so an abandoned capture cannot run forever."""
        from openr_tpu.runtime import device_stats

        try:
            return device_stats.profiler_start(
                out_dir or None,
                float(seconds) if seconds else None,
            )
        except RuntimeError as e:
            return {"ok": False, "error": str(e)}

    async def _tpu_profiler_stop(self) -> dict:
        from openr_tpu.runtime import device_stats

        try:
            return device_stats.profiler_stop()
        except RuntimeError as e:
            return {"ok": False, "error": str(e)}

    async def _tpu_profiler_status(self) -> dict:
        from openr_tpu.runtime import device_stats

        return device_stats.profiler_status()

    async def _tpu_aot(self) -> dict:
        """The persistent AOT executable cache: on-disk entries (kernel,
        signature, size, fingerprint, age) + this process's hit/miss
        ledger. `breeze tpu aot` renders it; a warm boot with misses > 0
        is the first thing the cold-start runbook checks."""
        from openr_tpu.ops.xla_cache import get_aot, retrace

        cache = get_aot()
        return {
            "summary": cache.summary(),
            "entries": cache.entries(),
            "aot_installs": retrace.snapshot().get("aot_installs", 0),
        }

    async def _tpu_devices(self) -> dict:
        """Per-device memory snapshot + live-array census (gauges'
        structured twin). backend="cpu" with bare device entries is the
        graceful no-HBM-accounting answer."""
        from openr_tpu.runtime import device_stats

        return device_stats.export_device_gauges()

    async def _tpu_kernels(self) -> dict:
        """The kernel cost ledger joined with the solver's measured
        exec times: per instrumented executable, compile cost + XLA's
        estimated flops/bytes; per area, the last solve's achieved
        throughput against the kernel that ran it."""
        from openr_tpu.ops.xla_cache import ledger
        from openr_tpu.runtime import device_stats

        kernels = ledger.snapshot()
        solver = (
            getattr(self.decision, "solver", None)
            if self.decision is not None
            else None
        )
        last_timing = getattr(solver, "last_timing", None) or {}
        achieved: list[dict] = []
        for area, stages in (last_timing.get("areas") or {}).items():
            kname = stages.get("kernel")
            exec_ms = stages.get("exec_ms")
            entry = kernels.get(kname)
            if not kname or entry is None or not exec_ms:
                continue
            row = {
                "area": area,
                "kernel": kname,
                "exec_ms": round(exec_ms, 3),
            }
            # exec_ms includes the result pull, so achieved numbers are
            # a lower bound on raw kernel throughput
            flops = entry.get("flops")
            if flops:
                row["estimated_gflops"] = round(flops / 1e9, 6)
                row["achieved_gflops_s"] = round(
                    flops / (exec_ms / 1e3) / 1e9, 3
                )
            nbytes = entry.get("bytes_accessed")
            if nbytes:
                row["achieved_gb_s"] = round(
                    nbytes / (exec_ms / 1e3) / 1e9, 3
                )
            achieved.append(row)
        from openr_tpu.ops.xla_cache import retrace

        return {
            "backend": device_stats.collect_device_stats()["backend"],
            "kernels": kernels,
            "achieved": achieved,
            "last_timing": last_timing,
            "sentinels": getattr(solver, "last_sentinels", None) or {},
            # per-namespace unexpected-recompile counts, cache-class
            # census, and the recent-retrace ring (namespace, kernel,
            # signature delta) — the triage view for a slow warm solve
            "retrace": retrace.snapshot(),
        }

    async def _monitor_fleet(self) -> dict:
        """Every node's TTL'd `monitor:health:<node>` card as flooded
        into KvStore — fleet health from any single node's ctrl port.
        A node missing here either never advertised or let its TTL
        lapse (both triage-worthy)."""
        import json as _json

        nodes: dict[str, dict] = {}
        if self.kvstore is not None:
            for area in list(getattr(self.kvstore, "areas", None) or []):
                vals = await self.kvstore.dump_all(area, "monitor:health:")
                for key, val in vals.items():
                    node = key[len("monitor:health:"):]
                    try:
                        card = _json.loads(val.value.decode())
                    except (ValueError, UnicodeDecodeError):
                        card = {"error": "unparseable health payload"}
                    cur = nodes.get(node)
                    if (
                        cur is None
                        or card.get("ts_ms", 0) > cur.get("ts_ms", 0)
                    ):
                        nodes[node] = card
        return {"local_node": self.node_name, "nodes": nodes}

    # -- persistent config store (ref setConfigKey/getConfigKey/eraseConfigKey,
    # OpenrCtrl.thrift:648-661) -----------------------------------------------

    async def _store_set(self, key: str, value: str) -> dict:
        if self.persistent_store is None:
            raise RuntimeError("no persistent store configured")
        self.persistent_store.store(f"ctrl:{key}", value.encode())
        return {"ok": True}

    async def _store_get(self, key: str) -> Optional[str]:
        if self.persistent_store is None:
            raise RuntimeError("no persistent store configured")
        raw = self.persistent_store.load(f"ctrl:{key}")
        return None if raw is None else raw.decode(errors="replace")

    async def _store_erase(self, key: str) -> dict:
        if self.persistent_store is None:
            raise RuntimeError("no persistent store configured")
        return {"erased": self.persistent_store.erase(f"ctrl:{key}")}

    async def _store_dump(self) -> dict:
        """Read-only inventory of EVERY persistent-store key — daemon
        state (link-monitor drain/overrides, rib-policy, allocator
        index) and ctrl:-namespaced operator keys — with sizes and a
        best-effort text preview (values may be binary serde)."""
        if self.persistent_store is None:
            raise RuntimeError("no persistent store configured")
        out = {}
        for key in sorted(self.persistent_store.keys()):
            raw = self.persistent_store.load(key) or b""
            preview = raw[:200].decode("utf-8", errors="replace")
            out[key] = {"bytes": len(raw), "preview": preview}
        return out

    # -- kvstore -----------------------------------------------------------

    async def _kv_get(self, area: str = "0", keys: Optional[list] = None) -> dict:
        vals = await self.kvstore.get_key_vals(area, keys or [])
        return {k: to_plain(v) for k, v in vals.items()}

    async def _kv_dump(self, area: str = "0", prefix: str = "") -> dict:
        vals = await self.kvstore.dump_all(area, prefix)
        return {k: to_plain(v) for k, v in vals.items()}

    async def _kv_peers(self, area: str = "0") -> dict:
        return {
            name: to_plain(spec)
            for name, spec in self.kvstore.get_peers(area).items()
        }

    async def _kv_set(self, area: str, key: str, value: dict) -> dict:
        from openr_tpu.types import Value

        await self.kvstore.set_key_vals(area, {key: from_plain(value, Value)})
        return {"ok": True}

    async def _kv_set_key(
        self,
        key: str,
        value: str,
        area: str = "0",
        version: Optional[int] = None,
        ttl_ms: Optional[int] = None,
    ) -> dict:
        """Operator key injection with TTL control (ref setKvStoreKeyVals
        with KeySetParams ttl, KvStore.thrift:749): version defaults to
        beating the live value."""
        from openr_tpu.types import TTL_INFINITY, Value

        if version is None:
            live = await self.kvstore.get_key_vals(area, [key])
            version = (live[key].version + 1) if key in live else 1
        val = Value(
            version=version,
            originator_id=f"breeze:{self.node_name}",
            value=value.encode(),
            ttl_ms=TTL_INFINITY if ttl_ms is None else ttl_ms,
        )
        await self.kvstore.set_key_vals(area, {key: val})
        return {"ok": True, "version": version}

    async def _kv_hashes(self, area: str = "0", prefix: str = "") -> dict:
        """Hash-only dump (ref getKvStoreHashFiltered) — the anti-entropy
        comparison view, value payloads stripped."""
        vals = await self.kvstore.dump_hashes(area, prefix)
        return {k: to_plain(v) for k, v in vals.items()}

    async def _kv_area_summary(self) -> dict:
        """ref getKvStoreAreaSummary."""
        return self.kvstore.get_area_summary()

    # -- decision ----------------------------------------------------------

    async def _decision_routes(self, from_node: Optional[str] = None) -> dict:
        db = await self.decision.get_decision_route_db(from_node)
        if db is None:
            return {"unicast": {}, "mpls": {}}
        return {
            "unicast": {p: to_plain(e) for p, e in db.unicast_routes.items()},
            "mpls": {str(l): to_plain(e) for l, e in db.mpls_routes.items()},
        }

    async def _decision_fabric_routes(
        self, from_nodes: Optional[list] = None
    ) -> dict:
        dbs = await self.decision.get_fabric_route_dbs(from_nodes)
        return {
            node: (
                None
                if db is None
                else {
                    "unicast": {
                        p: to_plain(e) for p, e in db.unicast_routes.items()
                    },
                    "mpls": {
                        str(l): to_plain(e)
                        for l, e in db.mpls_routes.items()
                    },
                }
            )
            for node, db in dbs.items()
        }

    async def _decision_adj_dbs(self) -> dict:
        dbs = await self.decision.get_adj_dbs()
        return {
            area: {node: to_plain(db) for node, db in nodes.items()}
            for area, nodes in dbs.items()
        }

    async def _decision_adjacencies_filtered(
        self,
        node_names: Optional[list] = None,
        areas: Optional[list] = None,
    ) -> dict:
        """ref getDecisionAreaAdjacenciesFiltered: adjacency DBs
        restricted to the requested node/area sets."""
        dbs = await self.decision.get_adj_dbs()
        return {
            area: {
                node: to_plain(db)
                for node, db in nodes.items()
                if not node_names or node in node_names
            }
            for area, nodes in dbs.items()
            if not areas or area in areas
        }

    async def _decision_prefix_dbs(self) -> dict:
        """ref getDecisionPrefixDbs: every announcer's prefix entries as
        Decision currently sees them."""
        dbs = await self.decision.get_prefix_dbs()
        return {
            node: {
                area: {p: to_plain(e) for p, e in prefixes.items()}
                for area, prefixes in areas.items()
            }
            for node, areas in dbs.items()
        }

    async def _decision_received(
        self,
        prefixes: Optional[list] = None,
        node: str = "",
        area: str = "",
    ) -> list:
        """ref getReceivedRoutes(Filtered) — ReceivedRouteFilter's
        prefixes / nodeName / areaName axes (OpenrCtrl.thrift:245-253)."""
        want = set(prefixes or [])
        return [
            [pfx, list(node_area), to_plain(entry)]
            for pfx, node_area, entry in await self.decision.get_received_routes()
            if (not want or pfx in want)
            and (not node or node_area[0] == node)
            and (not area or node_area[1] == area)
        ]

    async def _decision_path(
        self, src: str = "", dst: str = "", area: str = "", k: int = 2
    ) -> list:
        """ref `breeze decision path` (clis/decision.py PathCli): up to
        k edge-disjoint paths between two nodes from the live LSDB."""
        return await self.decision.get_paths(
            src or self.node_name, dst, area=area, k=int(k)
        )

    async def _decision_explain(self, prefix: str = "") -> dict:
        """Route provenance (`breeze decision explain`): the originating
        kvstore event, solve epoch and solver kind behind one RIB entry,
        joined with the Fib agent's programmed state for that prefix."""
        if not prefix:
            return {"error": "prefix required"}
        out = await self.decision.explain_route(prefix)
        if self.fib is not None and "error" not in out:
            out["fib"] = await self.fib.get_route_detail(out["prefix"])
        return out

    async def _whatif_sweep(
        self, order: int = 1, area: str = "",
        roots: Optional[list] = None, max_scenarios: int = 0,
        top: int = 0,
    ) -> dict:
        """Batched N-k failure sweep on the resident graph
        (decision/whatif.py): per-scenario partition/stretch verdicts."""
        return await self.decision.whatif_sweep(
            order=int(order), area=area or None, roots=roots,
            max_scenarios=int(max_scenarios), top=int(top),
        )

    async def _whatif_drain(
        self, node: str = "", link: str = "", area: str = "",
        roots: Optional[list] = None, top: int = 10,
    ) -> dict:
        """Drain impact preview for a node or link ('n1|n2')."""
        return await self.decision.whatif_drain(
            node=node, link=link, area=area or None, roots=roots,
            top=int(top),
        )

    async def _whatif_optimize(
        self, demands: Optional[list] = None, area: str = "",
        iters: int = 40, lr: float = 2.0, tau: float = 1.0,
    ) -> dict:
        """Differentiable link-weight TE against a demand matrix
        ([{src, dst, volume}])."""
        return await self.decision.whatif_optimize(
            demands or [], area=area or None, iters=int(iters),
            lr=float(lr), tau=float(tau),
        )

    async def _decision_validate(self) -> dict:
        """ref DecisionValidateCmd (commands/decision.py:434): per area,
        Decision's view of the LSDB must mirror KvStore's keys — report
        node sets present in one but not the other."""
        from openr_tpu.types import parse_adj_key, parse_prefix_key

        out: dict[str, dict] = {}
        adj_dbs = await self.decision.get_adj_dbs()
        prefix_dbs = await self.decision.get_prefix_dbs()
        areas = list(getattr(self.kvstore, "areas", None) or adj_dbs)
        for area in areas:
            kv = await self.kvstore.dump_all(area)
            kv_adj = {
                n for n in (parse_adj_key(key) for key in kv) if n
            }
            kv_prefix = set()
            for key in kv:
                parsed = parse_prefix_key(key)
                if parsed and parsed[1] == area:
                    kv_prefix.add(parsed[0])
            dec_adj = set(adj_dbs.get(area, {}))
            dec_prefix = {
                node
                for node, by_area in prefix_dbs.items()
                if area in by_area
            }
            report = {
                "adj_only_in_kvstore": sorted(kv_adj - dec_adj),
                "adj_only_in_decision": sorted(dec_adj - kv_adj),
                "prefix_only_in_kvstore": sorted(kv_prefix - dec_prefix),
                "prefix_only_in_decision": sorted(dec_prefix - kv_prefix),
            }
            report["ok"] = not any(v for v in report.values())
            out[area] = report
        return out

    async def _fib_validate(self) -> dict:
        """ref FibValidateRoutesCmd (commands/fib.py:216): Decision's
        computed routes vs Fib's programmed state must agree (the Fib
        actor's dirty/retry machinery closes transient gaps — persistent
        deltas mean routes stuck unprogrammed)."""
        dec = await self.decision.get_decision_route_db(None)
        fib_unicast = await self.fib.get_route_db()
        fib_mpls = await self.fib.get_mpls_route_db()
        dec_unicast = dict(dec.unicast_routes) if dec else {}
        dec_mpls = dict(dec.mpls_routes) if dec else {}
        mismatched = sorted(
            p
            for p in set(dec_unicast) & set(fib_unicast)
            if dec_unicast[p].nexthops != fib_unicast[p].nexthops
        )
        report = {
            "unicast_only_in_decision": sorted(
                set(dec_unicast) - set(fib_unicast)
            ),
            "unicast_only_in_fib": sorted(
                set(fib_unicast) - set(dec_unicast)
            ),
            "unicast_nexthop_mismatch": mismatched,
            "mpls_only_in_decision": sorted(
                set(dec_mpls) - set(fib_mpls)
            ),
            "mpls_only_in_fib": sorted(set(fib_mpls) - set(dec_mpls)),
            "fib_synced": self.fib.synced,
        }
        report["ok"] = self.fib.synced and not any(
            v for k, v in report.items() if k not in ("ok", "fib_synced")
        )
        return report

    async def _set_rib_policy(self, policy: dict) -> dict:
        from openr_tpu.decision.rib_policy import RibPolicy

        await self.decision.set_rib_policy(from_plain(policy, RibPolicy))
        return {"ok": True}

    async def _get_rib_policy(self) -> Optional[dict]:
        policy = await self.decision.get_rib_policy()
        if policy is None:
            return None
        out = to_plain(policy)
        out["remaining_ttl_secs"] = policy.remaining_ttl_secs()
        return out

    async def _clear_rib_policy(self) -> dict:
        await self.decision.clear_rib_policy()
        return {"ok": True}

    # -- fib ---------------------------------------------------------------

    async def _fib_routes(self) -> dict:
        routes = await self.fib.get_route_db()
        return {p: to_plain(e) for p, e in routes.items()}

    async def _fib_mpls(self) -> dict:
        routes = await self.fib.get_mpls_route_db()
        return {str(l): to_plain(e) for l, e in routes.items()}

    async def _fib_route_detail_db(self) -> dict:
        """ref getRouteDetailDb (OpenrCtrl.thrift:392): programmed routes
        WITH the selection detail FibService never sees — the winning
        PrefixEntry (best_prefix_entry), best node/area, igp cost, LFA
        backups — in RouteDatabaseDetail shape."""
        return {
            "node": self.node_name,
            "unicast": await self._fib_routes(),
            "mpls": await self._fib_mpls(),
        }

    async def _fib_routes_filtered(self, prefixes: list) -> dict:
        """ref getUnicastRoutesFiltered: exact-prefix selection."""
        routes = await self.fib.get_route_db()
        want = set(prefixes or [])
        return {
            p: to_plain(e) for p, e in routes.items() if p in want
        }

    async def _fib_mpls_filtered(self, labels: list) -> dict:
        """ref getMplsRoutesFiltered."""
        routes = await self.fib.get_mpls_route_db()
        want = {int(x) for x in labels or []}
        return {
            str(l): to_plain(e) for l, e in routes.items() if l in want
        }

    async def _fib_perf(self) -> list:
        return [to_plain(p) for p in await self.fib.get_perf_db()]

    # -- link monitor ------------------------------------------------------

    async def _lm_links(self) -> dict:
        return await self.link_monitor.get_links()

    async def _lm_interfaces(self) -> dict:
        return {
            name: to_plain(info)
            for name, info in (await self.link_monitor.get_interfaces()).items()
        }

    async def _lm_set_overload(self, overloaded: bool) -> dict:
        await self.link_monitor.set_node_overload(overloaded)
        return {"ok": True}

    async def _lm_set_link_overload(self, if_name: str, overloaded: bool) -> dict:
        await self.link_monitor.set_link_overload(if_name, overloaded)
        return {"ok": True}

    async def _lm_set_link_metric(
        self, if_name: str, metric: Optional[int] = None
    ) -> dict:
        await self.link_monitor.set_link_metric(if_name, metric)
        return {"ok": True}

    async def _lm_set_adj_metric(
        self, if_name: str, neighbor: str, metric: Optional[int] = None
    ) -> dict:
        """ref set/unsetAdjacencyMetric (OpenrCtrl.thrift:581-586);
        metric None unsets."""
        await self.link_monitor.set_adjacency_metric(
            if_name, neighbor, metric
        )
        return {"ok": True}

    async def _lm_set_node_metric_increment(self, increment: int = 0) -> dict:
        """ref set/unsetNodeInterfaceMetricIncrement; 0 unsets."""
        await self.link_monitor.set_node_metric_increment(increment)
        return {"ok": True}

    async def _lm_set_link_metric_increment(
        self, if_name: str, increment: int = 0
    ) -> dict:
        """ref set/unsetInterfaceMetricIncrement; 0 unsets."""
        await self.link_monitor.set_link_metric_increment(if_name, increment)
        return {"ok": True}

    async def _lm_adjacencies(self, area: Optional[str] = None) -> list:
        """ref getLinkMonitorAdjacencies(Filtered)."""
        return [
            to_plain(db)
            for db in await self.link_monitor.get_adjacencies(area)
        ]

    # -- spark / prefix manager --------------------------------------------

    async def _spark_neighbors(self) -> list:
        return [
            {
                "node": nb.node_name,
                "if_name": nb.if_name,
                "state": nb.state.name,
                "area": nb.area,
                "rtt_us": nb.rtt_us,
            }
            for nb in await self.spark.get_neighbors()
        ]

    async def _pm_advertised(
        self,
        prefixes: Optional[list] = None,
        ptype: Optional[str] = None,
        area: str = "",
    ) -> dict:
        """ref getAdvertisedRoutes(Filtered) + getAreaAdvertisedRoutes —
        AdvertisedRouteFilter's prefixes / prefixType axes
        (OpenrCtrl.thrift:64-67) plus the destination-area view."""
        want = set(prefixes or [])
        pt = self._parse_prefix_type(ptype) if ptype is not None else None
        if area:
            routes = await self.prefix_manager.get_area_advertised_routes(
                area
            )
        else:
            routes = await self.prefix_manager.get_advertised_routes()
        return {
            p: to_plain(e)
            for p, e in routes.items()
            if (not want or p in want) and (pt is None or e.type == pt)
        }

    async def _pm_prefixes(self) -> dict:
        return {
            p: to_plain(e)
            for p, e in (await self.prefix_manager.get_prefixes()).items()
        }

    async def _pm_prefixes_by_type(self, ptype) -> dict:
        """ref getPrefixesByType."""
        pt = self._parse_prefix_type(ptype)
        return {
            p: to_plain(e)
            for p, e in (await self.prefix_manager.get_prefixes()).items()
            if e.type == pt
        }

    async def _pm_originated(self) -> dict:
        """ref getOriginatedPrefixes: config-originated supernodes with
        their install state."""
        out = {}
        for prefix, st in self.prefix_manager.originated.items():
            out[prefix] = {
                "config": to_plain(st.conf),
                "supporting_prefixes": sorted(st.supporting),
                "advertised": st.advertised,
            }
        return out

    @staticmethod
    def _parse_prefix_type(ptype):
        from openr_tpu.types import PrefixType

        if isinstance(ptype, str):
            return PrefixType[ptype.upper()]
        return PrefixType(ptype)

    def _parse_entries(self, prefixes: list, ptype) -> tuple:
        from openr_tpu.types import PrefixEntry, replace

        pt = self._parse_prefix_type(ptype)
        entries = []
        for p in prefixes:
            if isinstance(p, str):
                entries.append(PrefixEntry(prefix=p, type=pt))
            else:
                e = from_plain(p, PrefixEntry)
                entries.append(replace(e, type=pt))
        return pt, entries

    async def _pm_advertise(
        self, prefixes: list, ptype="BREEZE", dest_areas: Optional[list] = None
    ) -> dict:
        """Operator prefix injection (ref advertisePrefixes,
        OpenrCtrl.thrift:299): entries may be plain prefix strings or
        full PrefixEntry payloads."""
        pt, entries = self._parse_entries(prefixes, ptype)
        self.prefix_manager.advertise_prefixes(
            entries, pt, tuple(dest_areas or ())
        )
        return {"ok": True, "advertised": len(entries)}

    async def _pm_withdraw(self, prefixes: list, ptype="BREEZE") -> dict:
        """ref withdrawPrefixes (OpenrCtrl.thrift:307)."""
        pt, entries = self._parse_entries(prefixes, ptype)
        self.prefix_manager.withdraw_prefixes(entries, pt)
        return {"ok": True, "withdrawn": len(entries)}

    async def _pm_withdraw_by_type(self, ptype) -> dict:
        """ref withdrawPrefixesByType (OpenrCtrl.thrift:314)."""
        self.prefix_manager.withdraw_prefixes_by_type(
            self._parse_prefix_type(ptype)
        )
        return {"ok": True}

    async def _pm_sync_by_type(self, prefixes: list, ptype) -> dict:
        """ref syncPrefixesByType (OpenrCtrl.thrift:323): the given set
        REPLACES everything of that type."""
        pt, entries = self._parse_entries(prefixes, ptype)
        self.prefix_manager.sync_prefixes_by_type(entries, pt)
        return {"ok": True, "synced": len(entries)}

    async def _spark_flood_restarting(self) -> dict:
        """ref floodRestartingMsg: graceful-restart hellos out of every
        interface now (operator-initiated GR prep)."""
        await self.spark.send_restarting_hellos()
        return {"ok": True}

    async def _get_config(self) -> dict:
        """Running config dump (ref getRunningConfig)."""
        if self.config is None:
            return {}
        return to_plain(self.config.raw)

    async def _drain_state(self) -> dict:
        """ref getDrainState: node-level drain plus per-link overrides."""
        if self.link_monitor is None:
            return {}
        st = self.link_monitor.state
        return {
            "is_drained": st.is_overloaded,
            "overloaded_links": sorted(st.overloaded_links),
            "link_metric_overrides": dict(st.link_metric_overrides),
        }

    async def _kv_flood_topo(self, area: str = "0") -> dict:
        """DUAL spanning-tree state (ref getSpmsimFloodTopo-style
        introspection): per-root state/parent/children, the active SPT
        peer set, and whether flooding is tree- or mesh-mode."""
        st = self.kvstore.areas.get(area)
        if st is None or st.dual is None:
            return {"enabled": False}
        spt = st.dual.flood_peers()
        return {
            "enabled": True,
            "mode": "spt" if spt is not None else "full-mesh",
            "flood_peers": sorted(spt) if spt is not None else None,
            "roots": st.dual.status(),
        }

    async def _kv_divergence(self, resolve: bool = True) -> dict:
        """LSDB divergence beacons (`breeze kv divergence`): compare
        peers' advertised digests against our recent local digests; with
        resolve, pull each suspect's key hashes and name the first
        divergent key."""
        return await self.kvstore.divergence_report(resolve=bool(resolve))

    async def _kv_long_poll_adj(
        self,
        area: str = "0",
        snapshot: Optional[dict] = None,
        timeout_s: float = 290.0,
    ) -> dict:
        """Long-poll for adjacency-key changes (ref
        longPollKvStoreAdjArea, OpenrCtrl.thrift:262 + the handler's
        long-poll fiber bookkeeping): `snapshot` maps adj: key ->
        version as the client last saw it; the call returns
        {"changed": true} as soon as any adjacency key in the area is
        new, bumped, or gone relative to the snapshot, or
        {"changed": false} at timeout. An empty snapshot returns
        immediately with the current truth (any adj key counts as
        changed)."""
        from openr_tpu.types import ADJ_DB_MARKER

        snap = {k: int(v) for k, v in (snapshot or {}).items()}

        def changed_vs_snapshot(cur: dict) -> bool:
            for k, ver in cur.items():
                if snap.get(k, -1) < ver:
                    return True
            return any(k not in cur for k in snap)

        def adj_versions(vals: dict) -> dict:
            return {
                k: v.version
                for k, v in vals.items()
                if k.startswith(ADJ_DB_MARKER)
            }

        # Register the reader BEFORE taking the snapshot: a publication
        # landing between dump_all and reader creation would otherwise be
        # missed and the poll sleeps its full timeout (ref installs the
        # kvstore callback before snapshotting for the same reason).
        reader = None
        if self._kvstore_updates_q is not None:
            reader = self._kvstore_updates_q.get_reader(f"{self.name}.longpoll")
        try:
            current = adj_versions(await self.kvstore.dump_all(area))
            if changed_vs_snapshot(current):
                return {"changed": True}
            if reader is None:
                return {"changed": False}
            deadline = time.monotonic() + timeout_s
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"changed": False}
                try:
                    item = await asyncio.wait_for(reader.get(), remaining)
                except asyncio.TimeoutError:
                    return {"changed": False}
                if not isinstance(item, Publication) or item.area != area:
                    continue
                pub_adj = adj_versions(item.key_vals)
                if changed_vs_snapshot({**current, **pub_adj}):
                    return {"changed": True}
                if any(
                    k.startswith(ADJ_DB_MARKER) for k in item.expired_keys
                ):
                    return {"changed": True}
        finally:
            if reader is not None:
                self._kvstore_updates_q.remove_reader(reader)

    async def _dryrun_config(self, config: dict) -> dict:
        """Validate a config payload without applying it (ref
        dryrunConfig, OpenrCtrl.thrift:269-277): returns the parsed,
        defaulted config on success or the validation error."""
        from openr_tpu.config import Config, ConfigError, OpenrConfig

        try:
            cfg = Config(from_plain(config, OpenrConfig))
        except (ConfigError, TypeError, ValueError, KeyError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        return {"ok": True, "config": to_plain(cfg.raw)}

    # -- streaming subscriptions (ref OpenrCtrlHandler.h:351-389) ----------

    def _register_stream(self, stream: Stream, kind: str) -> int:
        """Track a live stream for getSubscriberInfo (ref
        StreamSubscriberInfo, OpenrCtrl.thrift:72-83): every push stamps
        last-sent time and bumps the message count."""
        sid = self._next_subscriber_id
        self._next_subscriber_id += 1
        info = {
            "subscriber_id": sid,
            "type": kind,
            "started": time.time(),
            "last_msg_sent_time": 0.0,
            "total_streamed_msgs": 0,
        }
        self._subscribers[sid] = info
        orig_push = stream.push

        def push(item):
            info["total_streamed_msgs"] += 1
            info["last_msg_sent_time"] = time.time()
            orig_push(item)

        stream.push = push
        return sid

    async def _subscriber_info(self, type: str = "") -> list:
        """ref getSubscriberInfo(type): stats for every live streaming
        subscription, optionally filtered by kind (kvstore / fib /
        fib_detail)."""
        now = time.time()
        return [
            {
                "subscriber_id": i["subscriber_id"],
                "type": i["type"],
                "uptime_ms": int((now - i["started"]) * 1e3),
                "last_msg_sent_time": i["last_msg_sent_time"],
                "total_streamed_msgs": i["total_streamed_msgs"],
            }
            for i in self._subscribers.values()
            if not type or i["type"] == type
        ]

    def _start_subscription(
        self, kind: str, snapshot, queue, reader_suffix: str, on_item
    ) -> Stream:
        """Common tail of every subscribe handler: acquire the queue
        reader (fallible — the producer may have closed the queue),
        register the subscriber, push the pre-serialized snapshot, spawn
        the pump. Every fallible step precedes registration so a failing
        subscribe can't leak a phantom ctrl.subscriber_info entry."""
        stream = Stream()
        reader = queue.get_reader(f"{self.name}.{reader_suffix}")
        sid = self._register_stream(stream, kind)
        if snapshot is not None:
            stream.push(snapshot)
        self.add_task(
            self._pump_subscription(
                stream, reader, queue, lambda item: on_item(stream, item), sid
            ),
            name=f"{self.name}.{kind}-sub",
        )
        return stream

    async def _subscribe_kvstore(self, area: str = "0") -> Stream:
        """Snapshot + live deltas (ref subscribeAndGetKvStoreFiltered)."""
        snapshot = await self.kvstore.dump_all(area)
        payload = {
            "snapshot": {k: to_plain(v) for k, v in snapshot.items()},
            "area": area,
        }

        def on_item(stream, item):
            if isinstance(item, Publication) and item.area == area:
                stream.push({"delta": to_plain(item)})

        return self._start_subscription(
            "kvstore", payload, self._kvstore_updates_q, "sub", on_item
        )

    @staticmethod
    def _fib_delta(stream, item):
        if not isinstance(item, InitializationEvent):
            stream.push({"delta": to_plain(item)})

    async def _subscribe_fib(self) -> Stream:
        """Snapshot + programmed-route deltas (ref subscribeAndGetFib)."""
        payload = None
        if self.fib is not None:
            routes = await self.fib.get_route_db()
            payload = {
                "snapshot": {p: to_plain(e) for p, e in routes.items()}
            }
        return self._start_subscription(
            "fib", payload, self._fib_updates_q, "sub", self._fib_delta
        )

    async def _subscribe_fib_detail(self) -> Stream:
        """ref subscribeAndGetFibDetail (OpenrCtrlCpp.thrift:53-55):
        RouteDatabaseDetail-shaped snapshot (node name + unicast incl.
        best_prefix_entry + mpls) followed by live deltas."""
        payload = None
        if self.fib is not None:
            payload = {"snapshot": await self._fib_route_detail_db()}
        return self._start_subscription(
            "fib_detail", payload, self._fib_updates_q, "subd",
            self._fib_delta,
        )

    async def _pump_subscription(
        self, stream, reader, queue, on_item, sid: Optional[int] = None
    ) -> None:
        """Forward queue items into a stream until it closes. reader.get()
        races stream closure so a disconnected client's queue reader is
        unregistered promptly instead of on the next (possibly never)
        published item."""
        close_wait = asyncio.ensure_future(stream.wait_closed())
        get_t = None
        try:
            while not stream.closed:
                get_t = asyncio.ensure_future(reader.get())
                # mark any exception retrieved up front: the task can be
                # abandoned mid-flight (stream close, or this pump task
                # cancelled at actor stop) and then completed by the
                # queue closing — without this the loop logs "Task
                # exception was never retrieved"
                get_t.add_done_callback(
                    lambda t: t.cancelled() or t.exception()
                )
                await asyncio.wait(
                    {get_t, close_wait}, return_when=asyncio.FIRST_COMPLETED
                )
                if not get_t.done():
                    get_t.cancel()
                    break
                # lint: allow(blocking-call) task is done() — no wait
                on_item(get_t.result())
        except QueueClosedError:
            pass
        finally:
            if get_t is not None and not get_t.done():
                get_t.cancel()
            close_wait.cancel()
            stream.close()
            queue.remove_reader(reader)
            if sid is not None:
                self._subscribers.pop(sid, None)
