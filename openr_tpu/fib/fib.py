"""Fib actor — route programming agent client.

Role of the reference's openr/fib/Fib.{h,cpp}:

  - RouteState snapshot of desired routes + dirtyPrefixes/dirtyLabels retry
    sets (ref Fib.h:224-247) and FSM AWAITING -> SYNCING -> SYNCED
    (ref Fib.h:262-270)
  - first FULL_SYNC from Decision triggers a full syncFib; later updates
    program incrementally (ref processDecisionRouteUpdate, updateRoutes vs
    syncRoutes)
  - programming failures mark routes dirty; a retry fiber reprograms them
    with exponential backoff (ref retryRoutesSignal, Fib.cpp:118,345-430)
  - optional delayed deletes (route_delete_delay_ms)
  - publishes the PROGRAMMED delta on fibRouteUpdatesQueue — the FIB-ACK
    feature PrefixManager redistribution depends on (ref Main.cpp:381-400)
  - keepAlive: poll agent aliveSince; a restart forces full re-sync
    (ref Fib::keepAlive)
  - perf-event convergence log ring (ref PerfDatabase, Types.thrift:598)
"""

from __future__ import annotations

import asyncio
import collections
import enum
import logging
import time
from typing import Optional

from openr_tpu.config import FibConfig
from openr_tpu.decision.columnar_rib import (
    LazyUnicastRoutes,
    _lookup as _lazy_lookup,
)
from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    RibMplsEntry,
    RibUnicastEntry,
    RouteUpdateType,
)
from openr_tpu.fib.fib_service import FibServiceBase, FibUpdateError
from openr_tpu.messaging import RQueue, ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.faults import maybe_fail
from openr_tpu.runtime.latency_budget import latency_budget
from openr_tpu.runtime.lifecycle import boot_tracer
from openr_tpu.runtime.throttle import ExponentialBackoff
from openr_tpu.runtime.tracing import TraceContext, tracer
from openr_tpu.types import (
    InitializationEvent,
    PerfEvents,
    add_perf_event,
    total_perf_duration_ms,
)

log = logging.getLogger(__name__)

CLIENT_ID_OPENR = 786  # ref Platform.thrift FibClient::OPENR


class FibState(enum.IntEnum):
    """ref Fib.h:262-270."""

    AWAITING_UPDATE = 0
    SYNCING = 1
    SYNCED = 2


class RouteState:
    """Desired routes + dirty tracking (ref Fib.h RouteState :224-247)."""

    def __init__(self) -> None:
        self.unicast_routes: dict[str, RibUnicastEntry] = {}
        self.mpls_routes: dict[int, RibMplsEntry] = {}
        self.dirty_prefixes: dict[str, float] = {}  # prefix -> ready-at ts
        self.dirty_labels: dict[int, float] = {}
        self.state = FibState.AWAITING_UPDATE

    def update(self, upd: DecisionRouteUpdate) -> None:
        cols = upd.columns
        if cols is not None and cols.new_mapping is not None:
            # columnar spine: Decision is the sole producer on this
            # queue and delivers in order, so our desired state equals
            # its previous table — swap in the new table's detached
            # lazy snapshot instead of re-keying O(routes) dict slots
            # (and, on the legacy path, forcing the lazy update map)
            self.unicast_routes = cols.new_mapping
        else:
            for prefix, entry in upd.unicast_routes_to_update.items():
                self.unicast_routes[prefix] = entry
            for prefix in upd.unicast_routes_to_delete:
                self.unicast_routes.pop(prefix, None)
        for label, entry in upd.mpls_routes_to_update.items():
            self.mpls_routes[label] = entry
        for label in upd.mpls_routes_to_delete:
            self.mpls_routes.pop(label, None)

    def unicast_route_of(self, prefix: str):
        """Single-route read WITHOUT bulk-forcing a columnar table (the
        dirty-programming path touches O(changed) routes; a plain
        [] would materialize every row of the backing column store)."""
        ur = self.unicast_routes
        if isinstance(ur, LazyUnicastRoutes):
            return _lazy_lookup(ur, prefix)
        return ur.get(prefix)

    def unicast_snapshot(self):
        """Publishable snapshot of the desired unicast table: O(1) for
        a columnar table (detached lazy clone), dict copy otherwise."""
        ur = self.unicast_routes
        if isinstance(ur, LazyUnicastRoutes):
            return ur.snapshot()
        return dict(ur)


class Fib(Actor):
    """ref Fib.h:35."""

    def __init__(
        self,
        node_name: str,
        config: FibConfig,
        fib_service: FibServiceBase,
        route_updates_queue: RQueue,
        fib_route_updates_queue: ReplicateQueue,
        log_sample_queue: Optional[ReplicateQueue] = None,
        retry_initial_backoff_s: float = 0.05,
        retry_max_backoff_s: float = 2.0,
    ):
        super().__init__(f"fib:{node_name}")
        self.node_name = node_name
        self.cfg = config
        self.service = fib_service
        self._route_updates = route_updates_queue
        self._fib_updates_q = fib_route_updates_queue
        self._log_sample_q = log_sample_queue
        self.route_state = RouteState()
        self._retry_backoff = ExponentialBackoff(
            retry_initial_backoff_s, retry_max_backoff_s
        )
        self._retry_signal = None  # asyncio.Event, created on start
        self._agent_alive_since: Optional[float] = None
        self._synced_signalled = False
        self._partial_sync_published = False
        self._pending_perf: Optional[PerfEvents] = None
        # convergence trace awaiting the pass that actually programs
        # (first wins; later ones close as "coalesced", like pending
        # publications do in Decision)
        self._pending_trace: Optional[TraceContext] = None
        # newest Decision solve epoch folded into the pending dirty set
        # (epoch fence attribution: the pass that programs publishes it)
        self._pending_epoch: Optional[int] = None
        # convergence perf-event ring (ref PerfDatabase)
        self.perf_db: collections.deque[PerfEvents] = collections.deque(
            maxlen=32
        )
        # fleet-convergence ack backchannel: set via attach_kvstore so
        # FIB acks for origin-stamped events flood back as TTL'd
        # monitor:conv-ack:<node> keys (None = backchannel off)
        self._kvstore = None

    def attach_kvstore(self, kvstore) -> None:
        self._kvstore = kvstore

    async def on_start(self) -> None:
        self._retry_signal = asyncio.Event()
        # baseline the agent's aliveSince NOW — recording it lazily on the
        # first poll would miss a restart that happens before that poll
        try:
            self._agent_alive_since = await self.service.alive_since()
        # lint: allow(broad-except) agent not up yet is the normal cold
        except Exception:
            pass  # keepalive loop will establish it
        self.add_supervised_task(
            self._route_updates_loop, name=f"{self.name}.updates"
        )
        self.add_supervised_task(self._retry_loop, name=f"{self.name}.retry")
        self.add_supervised_task(
            self._keepalive_loop, name=f"{self.name}.keepalive"
        )

    async def on_fiber_restart(self, task_name: str) -> None:
        """A fiber crash mid-programming leaves the agent's table state
        unknown — force a full re-sync (same recovery as an agent
        restart in the keepalive loop)."""
        if self.route_state.state != FibState.AWAITING_UPDATE:
            self.route_state.state = FibState.SYNCING
        if self._retry_signal is not None:
            self._retry_signal.set()

    # -- main update path (ref processDecisionRouteUpdate) -----------------

    async def _route_updates_loop(self) -> None:
        while True:
            item = await self._route_updates.get()
            if isinstance(item, InitializationEvent):
                continue
            await self.process_decision_route_update(item)

    async def process_decision_route_update(
        self, upd: DecisionRouteUpdate
    ) -> None:
        rs = self.route_state
        ctx = tracer.context_of(upd)
        sp = tracer.start_span(ctx, "fib.diff", node=self.node_name)
        rs.update(upd)
        if upd.solve_epoch is not None:
            self._pending_epoch = upd.solve_epoch
        if upd.perf_events is not None:
            add_perf_event(upd.perf_events, self.node_name, "FIB_RECEIVED")

        if rs.state == FibState.AWAITING_UPDATE:
            tracer.end_span(sp)
            if upd.type != RouteUpdateType.FULL_SYNC:
                # folded into Decision's initial snapshot; not a
                # convergence event of its own
                tracer.end_trace(ctx, status="pre_sync")
                latency_budget.discard_trace(ctx)
                return  # wait for Decision's initial snapshot
            bud = latency_budget.of_trace(ctx)
            if bud is not None:
                bud.advance("payload_apply")
            rs.state = FibState.SYNCING
            await self._sync_routes(upd.perf_events, trace=ctx)
            return

        # SYNCED (or SYNCING retry pending): program incrementally
        now = time.monotonic()
        delete_delay = self.cfg.route_delete_delay_ms / 1e3
        for prefix in upd.unicast_routes_to_update:
            rs.dirty_prefixes[prefix] = now
        for prefix in upd.unicast_routes_to_delete:
            rs.dirty_prefixes[prefix] = now + delete_delay
        for label in upd.mpls_routes_to_update:
            rs.dirty_labels[label] = now
        for label in upd.mpls_routes_to_delete:
            rs.dirty_labels[label] = now + delete_delay
        tracer.end_span(sp)
        bud = latency_budget.of_trace(ctx)
        if bud is not None:
            # queue hop from Decision plus the fib diff / dirty-marking
            bud.advance("payload_apply")
        self._pending_perf = upd.perf_events
        if ctx is not None:
            if self._pending_trace is None:
                self._pending_trace = ctx
            else:
                tracer.end_trace(ctx, status="coalesced")
                latency_budget.discard_trace(ctx)
        self._retry_signal.set()

    # -- full sync (ref syncRoutes) ----------------------------------------

    async def _sync_routes(
        self,
        perf: Optional[PerfEvents] = None,
        trace: Optional[TraceContext] = None,
    ) -> None:
        rs = self.route_state
        if trace is None:
            # retry path: adopt the pending trace so the sync that
            # finally lands closes the right convergence event
            trace, self._pending_trace = self._pending_trace, None
        sp = tracer.start_span(
            trace, "platform.program", node=self.node_name, mode="full_sync"
        )
        t_prog = time.monotonic()
        prog0 = self._service_program_ms()
        # both tables are always attempted — a partial unicast failure must
        # not leave pending MPLS routes unprogrammed (ref syncRoutes covers
        # both with retry)
        failed_p: set = set()
        failed_l: set = set()
        try:
            # chaos seam: a programming failure here must land in the
            # existing retry-with-backoff machinery below
            maybe_fail("fib.program", span=sp)
            batch = None
            if getattr(self.service, "supports_columns", False):
                from openr_tpu.decision.column_delta import (
                    build_column_batch,
                )

                batch = build_column_batch(rs.unicast_routes)
            if batch is not None:
                # columnar spine: the desired table ships as packed
                # arrays — no per-route objects between here and the
                # dataplane's bulk transaction
                counters.increment("fib.column_syncs")
                await self.service.sync_fib_columns(CLIENT_ID_OPENR, batch)
            else:
                await self.service.sync_fib(
                    CLIENT_ID_OPENR, list(rs.unicast_routes.values())
                )
        except FibUpdateError as e:
            failed_p.update(e.failed_prefixes)
            failed_l.update(e.failed_labels)
        except Exception as e:
            log.warning("%s: syncFib failed: %s", self.name, e)
            counters.increment("fib.sync_fib_failure")
            self._end_program(sp, t_prog, ok=False, trace=trace, prog0=prog0)
            self._park_trace(trace)
            self._schedule_retry()
            return
        try:
            await self.service.sync_mpls_fib(
                CLIENT_ID_OPENR, list(rs.mpls_routes.values())
            )
        except FibUpdateError as e:
            failed_p.update(e.failed_prefixes)
            failed_l.update(e.failed_labels)
        except Exception as e:
            log.warning("%s: syncMplsFib failed: %s", self.name, e)
            counters.increment("fib.sync_fib_failure")
            self._end_program(sp, t_prog, ok=False, trace=trace, prog0=prog0)
            self._park_trace(trace)
            # the unicast sync already ran: publish the unicast routes that
            # DID land as an INCREMENTAL delta (additive — it must not
            # claim snapshot completeness while the MPLS table state is
            # unknown), once per failure episode so persistent failures
            # don't re-flood subscribers every backoff tick. State stays
            # SYNCING, so the retry re-runs the full sync including MPLS;
            # no dirty-marking needed (SYNCING retries never take the
            # dirty-route path).
            if not self._partial_sync_published:
                self._partial_sync_published = True
                self._publish_programmed(
                    DecisionRouteUpdate(
                        type=RouteUpdateType.INCREMENTAL,
                        unicast_routes_to_update={
                            p: r
                            for p, r in rs.unicast_routes.items()
                            if p not in failed_p
                        },
                    ),
                    perf,
                )
            self._schedule_retry()
            return
        if failed_p or failed_l:
            # partial: only the failed subset stays dirty; publish ONLY what
            # actually landed (FIB-ACK must never claim unprogrammed routes)
            self._end_program(sp, t_prog, ok=False, trace=trace, prog0=prog0)
            now = time.monotonic()
            for p in failed_p:
                rs.dirty_prefixes[p] = now
            for label in failed_l:
                rs.dirty_labels[label] = now
            self._finish_sync(
                perf,
                unicast={
                    p: r
                    for p, r in rs.unicast_routes.items()
                    if p not in failed_p
                },
                mpls={
                    label: r
                    for label, r in rs.mpls_routes.items()
                    if label not in failed_l
                },
                trace=trace,
            )
            self._schedule_retry()
            return
        self._end_program(sp, t_prog, ok=True, trace=trace, prog0=prog0)
        rs.dirty_prefixes.clear()
        rs.dirty_labels.clear()
        self._retry_backoff.report_success()
        self._finish_sync(
            perf,
            unicast=rs.unicast_snapshot(),
            mpls=dict(rs.mpls_routes),
            trace=trace,
        )

    def _end_program(
        self,
        sp,
        t_prog: float,
        ok: bool,
        trace: Optional[TraceContext] = None,
        prog0: Optional[float] = None,
    ) -> None:
        tracer.end_span(sp, ok=ok)
        counters.add_stat_value(
            "fib.program_ms", (time.monotonic() - t_prog) * 1000.0
        )
        bud = latency_budget.of_trace(trace)
        if bud is None:
            return
        # budget: when the dataplane handlers self-report their write
        # time (RemoteFibService.program_ms_total), split the segment
        # into the netlink write proper vs RPC/ack overhead; otherwise
        # the whole segment is programming
        dp_ms = None
        if prog0 is not None:
            total = getattr(self.service, "program_ms_total", None)
            if total is not None:
                dp_ms = max(0.0, float(total) - prog0)
        if dp_ms is not None:
            bud.advance_split({"program": dp_ms}, primary="ack_rtt")
        else:
            bud.advance("program")

    def _service_program_ms(self) -> Optional[float]:
        total = getattr(self.service, "program_ms_total", None)
        return float(total) if total is not None else None

    def _park_trace(self, trace: Optional[TraceContext]) -> None:
        """Hold the trace for the retry that eventually programs."""
        if trace is None:
            return
        if self._pending_trace is None:
            self._pending_trace = trace
        else:
            tracer.end_trace(trace, status="coalesced")
            latency_budget.discard_trace(trace)

    def _finish_sync(
        self,
        perf: Optional[PerfEvents],
        unicast,  # dict or LazyUnicastRoutes snapshot (columnar spine)
        mpls: dict[int, RibMplsEntry],
        trace: Optional[TraceContext] = None,
    ) -> None:
        rs = self.route_state
        rs.state = FibState.SYNCED
        self._partial_sync_published = False
        counters.increment("fib.full_sync")
        self._publish_programmed(
            DecisionRouteUpdate(
                type=RouteUpdateType.FULL_SYNC,
                unicast_routes_to_update=unicast,
                mpls_routes_to_update=mpls,
                solve_epoch=self._pending_epoch,
            ),
            perf,
            trace=trace,
        )
        if not self._synced_signalled:
            self._synced_signalled = True
            # boot lifecycle: the first programmed RIB closes the boot
            # span tree and stamps boot.first_rib_ms
            boot_tracer.phase_mark(
                "first_fib_program",
                node=self.node_name,
                routes=(
                    len(unicast) if hasattr(unicast, "__len__") else None
                ),
            )
            boot_tracer.complete(node=self.node_name)
            self._fib_updates_q.push(InitializationEvent.FIB_SYNCED)

    # -- dirty-route retry (ref retryRoutes Fib.cpp:345-430) ---------------

    def _schedule_retry(self) -> None:
        self._retry_backoff.report_error()
        counters.increment("fib.route_programming_failure")
        self._retry_signal.set()

    async def _retry_loop(self) -> None:
        while True:
            await self._retry_signal.wait()
            self._retry_signal.clear()
            rs = self.route_state
            # honor backoff after failures
            delay = self._retry_backoff.time_until_retry_s()
            if delay > 0:
                await asyncio.sleep(delay)
            if rs.state == FibState.SYNCING:
                await self._sync_routes()
                continue
            if not rs.dirty_prefixes and not rs.dirty_labels:
                continue
            # wait for the earliest delayed delete to come due
            now = time.monotonic()
            due_in = [
                ts - now
                for ts in list(rs.dirty_prefixes.values())
                + list(rs.dirty_labels.values())
                if ts > now
            ]
            await self._program_dirty_routes()
            if due_in:
                await asyncio.sleep(max(0.01, min(due_in)))
                self._retry_signal.set()

    async def _program_dirty_routes(self) -> None:
        """Program everything due in the dirty sets; failures stay dirty
        (ref updateRoutes + createUpdate from dirty state)."""
        rs = self.route_state
        now = time.monotonic()
        perf = self._pending_perf
        self._pending_perf = None
        ctx = self._pending_trace
        self._pending_trace = None
        sp = tracer.start_span(
            ctx, "platform.program", node=self.node_name, mode="incremental"
        )
        t_prog = now
        prog0 = self._service_program_ms()

        add_prefixes = [
            p
            for p, ts in rs.dirty_prefixes.items()
            if ts <= now and p in rs.unicast_routes
        ]
        del_prefixes = [
            p
            for p, ts in rs.dirty_prefixes.items()
            if ts <= now and p not in rs.unicast_routes
        ]
        add_labels = [
            l
            for l, ts in rs.dirty_labels.items()
            if ts <= now and l in rs.mpls_routes
        ]
        del_labels = [
            l
            for l, ts in rs.dirty_labels.items()
            if ts <= now and l not in rs.mpls_routes
        ]
        programmed = DecisionRouteUpdate(
            type=RouteUpdateType.INCREMENTAL,
            solve_epoch=self._pending_epoch,
        )
        ok = True
        try:
            # chaos seam: everything due stays dirty and retries
            maybe_fail("fib.program", span=sp)
            if add_prefixes:
                await self.service.add_unicast_routes(
                    CLIENT_ID_OPENR,
                    [rs.unicast_route_of(p) for p in add_prefixes],
                )
            for p in add_prefixes:
                rs.dirty_prefixes.pop(p, None)
                programmed.unicast_routes_to_update[p] = (
                    rs.unicast_route_of(p)
                )
        except FibUpdateError as e:
            ok = False
            for p in add_prefixes:
                if p not in e.failed_prefixes:
                    rs.dirty_prefixes.pop(p, None)
                    programmed.unicast_routes_to_update[p] = (
                        rs.unicast_route_of(p)
                    )
        except Exception as e:
            counters.increment("fib.program_error")
            log.warning("%s: add_unicast failed: %s", self.name, e)
            ok = False

        try:
            if del_prefixes:
                await self.service.delete_unicast_routes(
                    CLIENT_ID_OPENR, del_prefixes
                )
            for p in del_prefixes:
                rs.dirty_prefixes.pop(p, None)
                programmed.unicast_routes_to_delete.append(p)
        except FibUpdateError as e:
            # partial failure: successfully-deleted prefixes leave the
            # dirty set and publish their FIB-ACK now; only the failed
            # ones stay dirty for retry (mirrors the add path above)
            ok = False
            for p in del_prefixes:
                if p not in e.failed_prefixes:
                    rs.dirty_prefixes.pop(p, None)
                    programmed.unicast_routes_to_delete.append(p)
        except Exception as e:
            counters.increment("fib.program_error")
            log.warning("%s: delete_unicast failed: %s", self.name, e)
            ok = False

        try:
            if add_labels:
                await self.service.add_mpls_routes(
                    CLIENT_ID_OPENR, [rs.mpls_routes[l] for l in add_labels]
                )
            for l in add_labels:
                rs.dirty_labels.pop(l, None)
                programmed.mpls_routes_to_update[l] = rs.mpls_routes[l]
        except FibUpdateError as e:
            ok = False
            for l in add_labels:
                if l not in e.failed_labels:
                    rs.dirty_labels.pop(l, None)
                    programmed.mpls_routes_to_update[l] = rs.mpls_routes[l]
        except Exception as e:
            counters.increment("fib.program_error")
            log.warning("%s: add_mpls failed: %s", self.name, e)
            ok = False

        try:
            if del_labels:
                await self.service.delete_mpls_routes(CLIENT_ID_OPENR, del_labels)
            for l in del_labels:
                rs.dirty_labels.pop(l, None)
                programmed.mpls_routes_to_delete.append(l)
        except FibUpdateError as e:
            ok = False
            for l in del_labels:
                if l not in e.failed_labels:
                    rs.dirty_labels.pop(l, None)
                    programmed.mpls_routes_to_delete.append(l)
        except Exception as e:
            counters.increment("fib.program_error")
            log.warning("%s: delete_mpls failed: %s", self.name, e)
            ok = False

        self._end_program(sp, t_prog, ok=ok, trace=ctx, prog0=prog0)
        if not programmed.empty():
            self._publish_programmed(programmed, perf, trace=ctx)
        else:
            # nothing landed this pass (backoff / delayed deletes not
            # due): hold the trace for the pass that actually programs
            self._park_trace(ctx)
        if ok:
            self._retry_backoff.report_success()
        else:
            self._schedule_retry()

    # -- programmed-delta publication (FIB-ACK) ----------------------------

    def _publish_programmed(
        self,
        programmed: DecisionRouteUpdate,
        perf: Optional[PerfEvents],
        trace: Optional[TraceContext] = None,
    ) -> None:
        if perf is not None:
            add_perf_event(perf, self.node_name, "FIB_PROGRAMMED")
            programmed.perf_events = perf
            self.perf_db.append(perf)
            duration_ms = total_perf_duration_ms(perf)
            counters.add_stat_value("fib.convergence_time_ms", duration_ms)
            if self._log_sample_q is not None:
                from openr_tpu.runtime.monitor import LogSample

                self._log_sample_q.push(
                    LogSample(
                        event="ROUTE_CONVERGENCE",
                        node_name=self.node_name,
                        values={
                            "duration_ms": duration_ms,
                            "unicast_routes": len(
                                programmed.unicast_routes_to_update
                            ),
                        },
                    )
                )
        counters.increment("fib.routes_programmed")
        if programmed.solve_epoch is not None:
            # the ack attributes to the NEWEST epoch this pass folded
            # in; the gauge makes programmed-epoch monotonicity (the
            # fence property: a stale batch is never programmed)
            # observable from tests and the chaos drill
            counters.set_counter("fib.solve_epoch", programmed.solve_epoch)
            self._pending_epoch = None
        self._fib_updates_q.push(programmed, trace=trace)
        # latency budget: the ack is out — close the epoch's ledger with
        # the tail attributed to ack_rtt, enforcing the conservation
        # invariant; the dominant component rides the conv-ack and the
        # trace so the fleet join can name the straggler STAGE
        budget_row = latency_budget.close_trace(
            trace, status="ok", final_component="ack_rtt"
        )
        top_comp, top_ms = "", 0.0
        if budget_row is not None:
            top_comp = budget_row["top_component"]
            top_ms = budget_row["top_ms"]
        # fleet-convergence ack: a trace stitched to an origin event
        # reports (origin_event_id, this node, origin->ack latency) back
        # through the kvstore backchannel BEFORE the trace closes (the
        # stamp lives on the active trace's root attributes)
        attrs = tracer.root_attributes(trace)
        event_id = attrs.get("origin_event_id")
        if event_id is not None and self._kvstore is not None:
            origin_ts = attrs.get("origin_ts_ms")
            fleet_ms = (
                max(0.0, time.time() * 1000.0 - float(origin_ts))
                if origin_ts is not None
                else 0.0
            )
            counters.add_stat_value("fleet_convergence_ms", fleet_ms)
            try:
                self._kvstore.record_convergence_ack(
                    area=str(attrs.get("area") or "0"),
                    origin_node=str(attrs.get("origin_node") or ""),
                    origin_event_id=str(event_id),
                    fleet_convergence_ms=fleet_ms,
                    component=top_comp,
                    component_ms=top_ms,
                )
            # lint: allow(broad-except) the ack is telemetry — it must
            # never take down route programming
            except Exception:
                counters.increment("fib.conv_ack_failures")
        # programming ack published: the topology event has converged
        end_attrs = {}
        if programmed.solve_epoch is not None:
            end_attrs["solve_epoch"] = programmed.solve_epoch
        if top_comp:
            end_attrs["budget_top"] = top_comp
            end_attrs["budget_top_ms"] = round(top_ms, 3)
        tracer.end_trace(
            trace,
            status="ok",
            routes=len(programmed.unicast_routes_to_update)
            + len(programmed.unicast_routes_to_delete),
            **end_attrs,
        )

    # -- agent liveness (ref Fib::keepAlive) -------------------------------

    async def _keepalive_loop(self) -> None:
        while True:
            await asyncio.sleep(0.2)
            try:
                alive = await self.service.alive_since()
            except Exception:
                # an unreachable agent is a normal transient here; the
                # counter (not a log line every 200 ms) is the signal
                counters.increment("fib.keepalive_failure")
                continue
            if self._agent_alive_since is None:
                self._agent_alive_since = alive
            elif alive != self._agent_alive_since:
                # agent restarted: wipe assumptions, full re-sync
                log.warning("%s: fib agent restarted; re-syncing", self.name)
                self._agent_alive_since = alive
                if self.route_state.state != FibState.AWAITING_UPDATE:
                    self.route_state.state = FibState.SYNCING
                    self._retry_signal.set()

    # -- module API (ref Fib.h:140-180) ------------------------------------

    async def get_route_db(self) -> dict[str, RibUnicastEntry]:
        return dict(self.route_state.unicast_routes)

    async def get_mpls_route_db(self) -> dict[int, RibMplsEntry]:
        return dict(self.route_state.mpls_routes)

    async def get_perf_db(self) -> list[PerfEvents]:
        return list(self.perf_db)

    async def get_route_detail(self, prefix: str) -> dict:
        """Programmed-state view of one prefix — joined into
        ctrl.decision.explain so provenance answers both "which event
        produced this route" and "did it actually land in the agent"."""
        rs = self.route_state
        return {
            "desired": prefix in rs.unicast_routes,
            "dirty": prefix in rs.dirty_prefixes,
            "fib_state": rs.state.name,
            "synced": self.synced,
        }

    @property
    def synced(self) -> bool:
        return (
            self.route_state.state == FibState.SYNCED
            and not self.route_state.dirty_prefixes
            and not self.route_state.dirty_labels
        )
