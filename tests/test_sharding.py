"""Multi-chip sharded fabric-path tests, on the virtual 8-CPU device mesh
(conftest sets xla_force_host_platform_device_count=8).

The sharded pipeline (parallel/sharding.py) computes EVERY vantage's
routes in one pass: roots data-parallel over the 'batch' mesh axis, the
graph's node columns sharded over 'graph' with a pmin halo exchange per
relaxation. TpuSpfSolver.build_fabric_route_dbs wraps it with trip-bound
derivation (measured single-chip trips, convergence-vote verified,
doubling retry) and full route materialization; results must equal the
per-vantage CPU oracle exactly.
"""

import numpy as np

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.parallel.sharding import Unconverged, make_mesh, sharded_fabric_step
from openr_tpu.types import Adjacency, AdjacencyDatabase
from tests.test_tpu_solver import assert_rib_equal


def test_make_mesh_factors_devices():
    mesh = make_mesh(8)
    assert mesh.shape["batch"] * mesh.shape["graph"] == 8
    assert mesh.shape["graph"] == 2  # both axes exercised at >= 4 devices


def fabric_vs_oracle(states, ps, roots, mesh=None, **solver_kw):
    tpu = TpuSpfSolver(roots[0], **solver_kw)
    dbs = tpu.build_fabric_route_dbs(roots, states, ps, mesh=mesh)
    for root in roots:
        cpu_db = SpfSolver(root, **solver_kw).build_route_db(root, states, ps)
        if cpu_db is None:
            assert dbs[root] is None, root
            continue
        assert_rib_equal(cpu_db, dbs[root], f"fabric vantage {root}")
    return tpu, dbs


def test_fabric_route_dbs_grid_all_vantage_parity():
    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    roots = [db.this_node_name for db in adj_dbs[::7]]  # 10 vantages
    tpu, dbs = fabric_vs_oracle(states, ps, roots, mesh=make_mesh(8))
    assert len(dbs) == len(roots)


def test_fabric_route_dbs_with_lfa():
    """LFA backups computed on the sharded path match the oracle."""
    adj_dbs, prefix_dbs = topologies.grid(6)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    roots = ["node-0-0", "node-2-3", "node-5-5"]
    # parity incl. lfa_nexthops is asserted inside fabric_vs_oracle
    fabric_vs_oracle(states, ps, roots, enable_lfa=True)


def test_fabric_route_dbs_drained_and_churn():
    adj_dbs, prefix_dbs = topologies.random_mesh(30, seed=3)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    ls = states["0"]
    victim = next(d for d in adj_dbs if d.this_node_name == "node-7")
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="node-7",
            adjacencies=victim.adjacencies,
            is_overloaded=True,
            area="0",
        )
    )
    roots = ["node-0", "node-7", "node-15"]
    tpu, _ = fabric_vs_oracle(states, ps, roots)
    # metric churn, then the same solver instance recomputes correctly
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="node-3",
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": 9})
                for a in next(
                    d for d in adj_dbs if d.this_node_name == "node-3"
                ).adjacencies
            ),
            area="0",
        )
    )
    dbs = tpu.build_fabric_route_dbs(roots, states, ps)
    for root in roots:
        cpu_db = SpfSolver(root).build_route_db(root, states, ps)
        assert_rib_equal(cpu_db, dbs[root], f"after churn {root}")


def test_fabric_unknown_root_returns_none():
    adj_dbs, prefix_dbs = topologies.grid(4)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-0-0")
    dbs = tpu.build_fabric_route_dbs(
        ["node-0-0", "not-a-node"], states, ps
    )
    assert dbs["not-a-node"] is None
    assert dbs["node-0-0"] is not None


def test_fabric_trip_bound_retry_from_cold_solver():
    """A fresh solver has no measured trip count (last_trips == 0); the
    seed bound is tiny and the convergence vote must drive the doubling
    retry to a correct result on a high-diameter graph."""
    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-0-0")
    assert tpu.last_trips == 0
    dbs = tpu.build_fabric_route_dbs(["node-0-0", "node-7-7"], states, ps)
    cpu_db = SpfSolver("node-0-0").build_route_db("node-0-0", states, ps)
    assert_rib_equal(cpu_db, dbs["node-0-0"], "retry path")


def test_sharded_step_unconverged_raises():
    """Directly under-bound the trip count: the kernel's convergence
    vote must raise instead of returning too-large distances."""
    from openr_tpu.ops.csr import build_prefix_matrix
    from openr_tpu.ops.edgeplan import INF32E, build_plan

    adj_dbs, prefix_dbs = topologies.grid(10, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    ls = states["0"]
    plan = build_plan(ls)
    matrix = build_prefix_matrix(ps, plan.node_index, "0")
    mesh = make_mesh(4)
    batch = mesh.shape["batch"]
    roots_names = [plan.node_names[0]] * batch
    roots = np.array([plan.node_index[n] for n in roots_names], np.int32)
    outs = [plan.out_links(ls, n) for n in roots_names]
    d_cap = max(o[0].shape[0] for o in outs)
    out_nbr = np.full((batch, d_cap), -1, np.int32)
    out_w = np.full((batch, d_cap), int(INF32E), np.int32)
    for i, (nbr, w, _l) in enumerate(outs):
        out_nbr[i, : nbr.shape[0]] = nbr
        out_w[i, : w.shape[0]] = w
    try:
        sharded_fabric_step(mesh, plan, matrix, roots, out_nbr, out_w, 1)
    except Unconverged:
        pass
    else:  # pragma: no cover
        raise AssertionError("expected Unconverged for a 1-trip bound")


def test_fabric_matches_single_chip_solver():
    """The sharded path and the single-chip resident pipeline are two
    implementations of the same function."""
    adj_dbs, prefix_dbs = topologies.grid(6)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    single = TpuSpfSolver("node-3-3")
    single_db = single.build_route_db("node-3-3", states, ps)
    fabric = TpuSpfSolver("node-3-3")
    dbs = fabric.build_fabric_route_dbs(["node-3-3"], states, ps)
    assert_rib_equal(single_db, dbs["node-3-3"], "single vs fabric")
