#!/usr/bin/env python3
"""Thin shim — the checker moved into the lint framework.

The real implementation is `tools/lint/metric_names.py`, run as part
of `python -m tools.lint` (see docs/StaticAnalysis.md). This path is
kept so existing docs, muscle memory, and any out-of-tree CI config
keep working; it preserves the old CLI, exit-code contract, and the
`collect(package_dir)` / `check(counters, stats)` module API.

Usage: python tools/check_metric_names.py [package_dir]
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.lint import metric_names as _mn  # noqa: E402
from tools.lint.core import Project  # noqa: E402
from openr_tpu.runtime.metrics_export import (  # noqa: E402
    is_valid_metric_name,
    normalize_metric_name,
)

STAT_SUFFIXES = _mn.STAT_SUFFIXES


def collect(package_dir) -> tuple[dict, dict, list]:
    """Old API: walk `package_dir` -> ({counter name: "file:line"},
    same for stats, parse-error strings)."""
    rel = Path(package_dir).resolve().relative_to(REPO_ROOT).as_posix()
    project = Project(REPO_ROOT, [rel])
    counters, stats = _mn.collect(project)

    def sites(bucket: dict) -> dict:
        return {
            name: f"{r}:{line}" for name, (r, line, _scope) in bucket.items()
        }

    return sites(counters), sites(stats), list(project.parse_errors)


def check(counter_names: dict, stat_names: dict) -> list:
    """Old API: name -> site maps in, error strings out."""
    errors: list[str] = []
    # exposition family -> (raw name, site); stats expand to their
    # derived families so `a.b` (stat) vs `a.b_max` (counter) is caught
    families: dict[str, tuple[str, str]] = {}

    def claim(family: str, raw: str, site: str) -> None:
        if not is_valid_metric_name(family):
            errors.append(
                f"{site}: metric {raw!r} normalizes to invalid "
                f"exposition identifier {family!r}"
            )
            return
        prev = families.get(family)
        if prev is not None and prev[0] != raw:
            errors.append(
                f"{site}: metric {raw!r} and {prev[0]!r} ({prev[1]}) "
                f"collide — both normalize to {family!r}"
            )
            return
        families.setdefault(family, (raw, site))

    for raw, site in sorted(counter_names.items()):
        claim(normalize_metric_name(raw), raw, site)
    for raw, site in sorted(stat_names.items()):
        base = normalize_metric_name(raw)
        for suffix in STAT_SUFFIXES:
            claim(base + suffix, raw, site)
    return errors


def main(argv: list[str]) -> int:
    package = "openr_tpu"
    if len(argv) > 1:
        package = Path(argv[1]).resolve().relative_to(REPO_ROOT).as_posix()
    project = Project(REPO_ROOT, [package])
    findings = _mn.run(project)
    for err in project.parse_errors:
        print(f"check_metric_names: {err}", file=sys.stderr)
    for fd in findings:
        print(f"check_metric_names: {fd.render()}", file=sys.stderr)
    if findings or project.parse_errors:
        return 1
    counters, stats = _mn.collect(project)
    print(
        f"check_metric_names: OK — {len(counters)} counter and "
        f"{len(stats)} stat families normalize cleanly"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
