"""Batched what-if sweeps over the resident shift-decomposed mirror.

The live solver already keeps each area's graph on device (deltas /
shift_w / residual ELL, decision/tpu_solver.py). A what-if scenario —
a failed link, a drained node, a metric change — is a handful of
directed-edge weight overrides on top of that mirror. This module vmaps
the delta-stepping SSSP over a BATCH of such sparse overlays: the graph
arrays ride in once per dispatch as shared operands (no re-upload), each
lane scatters its own overrides into a private copy on device, and the
per-scenario verdicts (unreachable pairs, max metric stretch, partition
flag) reduce on device so the host pulls O(batch) ints, not O(batch*N)
planes.

Lane 0 of every batch is the identity overlay: the baseline distance
plane every other lane is judged against. That keeps the whole sweep —
baseline included — in ONE device dispatch, and follows Bounded
Dijkstra (arXiv:1903.00436) in spirit: each perturbed solve is measured
as a stretch against the baseline plane computed in the same launch.

The TE half (`te_step`) is the differentiable variant per "Fast Traffic
Engineering by Gradient Descent" (arXiv:2209.10380): the same
relaxation in float32 with a softmin (-tau*logsumexp) combine, so
per-demand path costs are differentiable in the link-weight vector and
`jax.grad` of the total cost yields per-link traffic fractions (the
classic shortest-path sensitivity identity).

Executables here live in their own `whatif` bounded-cache namespace so
interactive sweeps can never evict the live solver's compiled
pipelines (ops/xla_cache.py).
"""

from __future__ import annotations

import numpy as np

from openr_tpu.ops import relax as relax_ops
from openr_tpu.ops.edgeplan import INF32E
from openr_tpu.ops.xla_cache import bounded_jit_cache, instrument_jit

INF_E = int(INF32E)

# fused relaxations per while_loop trip — owned by ops/relax.py so sweep
# trip counts stay comparable with the solver's last_trips
_UNROLL = relax_ops.UNROLL

# "unreachable" in the float TE surrogate: finite so logsumexp grads
# never see inf-inf (which poisons reverse-mode with NaNs), huge enough
# that exp(-_BIG_F/tau) underflows to exactly 0 for any sane tau
_BIG_F = np.float32(1.0e9)


def sweep_max_trips(n_cap: int) -> int:
    """Worst-case while_loop trips for a sweep SSSP — same bound as the
    live pipeline (a failure can only lengthen paths, never beyond the
    n-node chain the pipeline already bounds)."""
    return relax_ops.max_trips(n_cap)


def _make_sweep(b, r, es_cap, er_cap, n_cap, s_cap, r_cap, kr_cap,
                has_res, max_trips, return_dist, kernel="sync",
                delta_exp=0):
    import jax
    import jax.numpy as jnp

    def kernel(deltas, shift_w, res_rows, res_nbr, res_w, roots,
               sh_idx, sh_val, rs_idx, rs_val):
        if has_res:
            nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
            rows_c = jnp.clip(res_rows, 0, n_cap - 1)

        def one(si, sv, ri, rv):
            # per-lane weight planes: the shared resident mirror with
            # this scenario's overrides scattered in. Pad entries carry
            # an out-of-range index and drop on scatter, so every lane
            # ships the same fixed-size overlay regardless of how many
            # edges its scenario touches.
            sw = (
                shift_w.reshape(-1)
                .at[si].set(sv, mode="drop")
                .reshape(s_cap, n_cap)
            )
            if has_res:
                rw = (
                    res_w.reshape(-1)
                    .at[ri].set(rv, mode="drop")
                    .reshape(r_cap, kr_cap)
                )

            residual = (rows_c, nbr_c, rw) if has_res else None
            relax = relax_ops.make_relax(
                deltas, s_cap, lambda k: sw[k], residual=residual
            )

            dist0 = jnp.full((r, n_cap), INF_E, jnp.int32)
            dist0 = dist0.at[
                jnp.arange(r), jnp.clip(roots, 0, n_cap - 1)
            ].set(0)

            if kernel == "bucketed":
                dist, trips, rounds = relax_ops.run_bucketed(
                    relax, dist0, deltas, sw, lambda k: sw[k],
                    n_cap, s_cap, delta_exp,
                )
            else:
                dist, trips, rounds = relax_ops.run_sync(
                    relax, dist0, max_trips
                )
            return dist, trips, rounds

        dist_all, trips_all, rounds_all = jax.vmap(one)(
            sh_idx, sh_val, rs_idx, rs_val
        )
        # lane 0 is the identity overlay: the baseline every other lane
        # is judged against. `valid` masks pad columns and nodes the
        # baseline itself cannot reach — a failure is only charged for
        # pairs it newly disconnects.
        base = dist_all[0]  # [R, N]
        valid = base < INF_E
        unreachable = (valid[None] & (dist_all >= INF_E)).sum(axis=(1, 2))
        reach = valid[None] & (dist_all < INF_E)
        stretch = jnp.where(reach, dist_all - base[None], 0).max(axis=(1, 2))
        changed = (valid[None] & (dist_all != base[None])).sum(axis=(1, 2))
        # rounds rides LAST so whatif.collect's fixed [:4] / [4] parses
        # stay valid whether or not the dist plane is pulled
        if return_dist:
            return (unreachable, stretch, changed, trips_all.max(),
                    dist_all, rounds_all.max())
        return (unreachable, stretch, changed, trips_all.max(),
                rounds_all.max())

    return kernel


@bounded_jit_cache(namespace="whatif")
def sweep_batch(b, r, es_cap, er_cap, n_cap, s_cap, r_cap, kr_cap,
                has_res, max_trips, return_dist, kernel="sync",
                delta_exp=0):
    """-> (kernel name, instrumented executable) for a sweep of `b`
    scenario lanes x `r` vantage roots over an [n_cap] mirror. Each lane
    carries es_cap shift-slot and er_cap residual-slot overrides (flat
    indices into the raveled planes, same addressing as drain_dirty)."""
    import jax

    kern = _make_sweep(
        b, r, es_cap, er_cap, n_cap, s_cap, r_cap, kr_cap,
        has_res, max_trips, return_dist, kernel, delta_exp,
    )
    name = (
        f"sweep[b={b},r={r},n={n_cap},s={s_cap}"
        + (",res" if has_res else "")
        + (",dist" if return_dist else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    aot_key = repr((
        "sweep", b, r, es_cap, er_cap, n_cap, s_cap, r_cap, kr_cap,
        has_res, max_trips, return_dist, kernel, delta_exp,
    ))
    return name, instrument_jit(name, jax.jit(kern), aot_key=aot_key)


# -- differentiable TE (softmin surrogate, arXiv:2209.10380) ---------------


def _make_te(n_links, n_srcs, n_dem, es_cap, er_cap, n_cap, s_cap,
             r_cap, kr_cap, has_res, trips):
    import jax
    import jax.numpy as jnp

    def fn(theta, deltas, res_rows, res_nbr,
           sh_idx, sh_link, rs_idx, rs_link,
           srcs, dem_row, dem_dst, dem_vol, tau, tau_util):
        def softmin2(a, b):
            return -tau * jnp.logaddexp(-a / tau, -b / tau)

        if has_res:
            nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
            rows_c = jnp.clip(res_rows, 0, n_cap - 1)
            pad_row = (res_rows < 0)[:, None]

        def total_cost(th):
            # float planes: _BIG_F everywhere a directed edge is absent
            # or administratively down, theta[link] at every live slot —
            # so one scalar per link drives both directions
            swf = (
                jnp.full((s_cap * n_cap,), _BIG_F, jnp.float32)
                .at[sh_idx].set(th[sh_link], mode="drop")
                .reshape(s_cap, n_cap)
            )
            if has_res:
                rwf = (
                    jnp.full((r_cap * kr_cap,), _BIG_F, jnp.float32)
                    .at[rs_idx].set(th[rs_link], mode="drop")
                    .reshape(r_cap, kr_cap)
                )
                rwf = jnp.where(pad_row, _BIG_F, rwf)

            def one_src(s):
                d0 = (
                    jnp.full((n_cap,), _BIG_F, jnp.float32)
                    .at[jnp.clip(s, 0, n_cap - 1)].set(0.0)
                )

                def trip(d, _):
                    def cls(acc, kx):
                        delta, w = kx
                        return softmin2(acc, jnp.roll(d + w, delta)), None
                    acc, _ = jax.lax.scan(cls, d, (deltas, swf))
                    if has_res:
                        nd = d[nbr_c]  # [rows, K]
                        cand = -tau * jax.nn.logsumexp(
                            -(nd + rwf) / tau, axis=1
                        )
                        acc = acc.at[rows_c].min(cand)
                    return jnp.minimum(acc, d), None

                d, _ = jax.lax.scan(trip, d0, None, length=trips)
                return d

            dists = jax.vmap(one_src)(srcs)  # [S, N]
            cost = dists[dem_row, dem_dst]  # [D]
            return (dem_vol * cost).sum()

        # shortest-path sensitivity: d(total_cost)/d(theta_l) is the
        # (softmin-weighted) demand volume crossing link l — the link's
        # predicted utilization under this weight vector
        util = jax.grad(total_cost)(theta)

        def loss_fn(th):
            u = jax.grad(total_cost)(th)
            return tau_util * jax.nn.logsumexp(u / tau_util)

        loss, grad = jax.value_and_grad(loss_fn)(theta)
        return loss, grad, util, total_cost(theta)

    return fn


@bounded_jit_cache(namespace="whatif")
def te_step(n_links, n_srcs, n_dem, es_cap, er_cap, n_cap, s_cap,
            r_cap, kr_cap, has_res, trips):
    """-> (name, executable) computing one gradient-descent step of the
    softmin TE surrogate: (soft-max-utilization loss, its gradient in
    the per-link weight vector, per-link utilization, total path cost).
    `trips` is static — reverse-mode AD needs the relaxation as a fixed
    scan, so callers bound it by the measured baseline trip count."""
    import jax

    fn = _make_te(
        n_links, n_srcs, n_dem, es_cap, er_cap, n_cap, s_cap,
        r_cap, kr_cap, has_res, trips,
    )
    name = (
        f"te_step[l={n_links},s={n_srcs},d={n_dem},n={n_cap},t={trips}"
        + (",res" if has_res else "")
        + "]"
    )
    aot_key = repr((
        "te", n_links, n_srcs, n_dem, es_cap, er_cap, n_cap, s_cap,
        r_cap, kr_cap, has_res, trips,
    ))
    return name, instrument_jit(name, jax.jit(fn), aot_key=aot_key)
