"""Process-wide overload control: admission, flap damping, brownout.

The platform survives *faults* (supervised restart, CPU failover,
flight recorder, deterministic replay) but faults are discrete;
*overload* is sustained. A pathological flapping adjacency or a churn
storm past the streaming pipeline's capacity grows the dispatch queue
without bound, monopolizes solves, and burns the ack-p99 SLO with no
mechanism to shed, damp, or degrade. This module is that mechanism —
one controller per node, three cooperating pieces:

- **state ladder** — an explicit, observable overload state
  ``ok -> backpressure -> brownout -> shedding`` driven by the
  pending-solve queue depth, HBM pressure (device_stats gauges),
  host RSS, and active SLO burn. Upshifts are immediate (pressure is
  now); downshifts step one rung at a time and only after a dwell
  period with every signal below its *clear* watermark — hysteresis,
  so a borderline load can't strobe the ladder. Every transition runs
  the registered callback (Decision emits an ``OVERLOAD_STATE_CHANGE``
  LogSample; the Monitor's trigger table freezes a flight-recorder
  bundle) and restamps the closed ``overload.*`` gauge family.

- **admission control** — ``admit(cls)`` schedules work by priority
  class: live convergence always runs; TE/what-if is rejected from
  brownout up (the generalization of the ad-hoc what-if deferral);
  background probes (kvstore flood probes, digest anti-entropy) are
  deferred from backpressure up. ``coalesce_ms()`` widens the dispatch
  fiber's coalescing window with queue depth and ladder level — deeper
  queue, bigger batches, bounded by ``overload_coalesce_max_ms``.
  ``shed()`` answers whether a new solve request should fold into the
  held overflow batch instead of growing the queue past the watermark.

- **flap damping** — :class:`FlapDamper`, RFC 2439 transplanted from
  BGP route flap damping onto LSDB keys: each ingest *change* of an
  (area, key) adds a fixed penalty to that key's figure of merit, the
  figure decays exponentially with a half-life, and a key whose figure
  crosses the suppress threshold stops perturbing the LSDB — its
  latest value is *held*, not dropped — until decay brings it under
  the reuse threshold, at which point the held value re-ingests
  through the normal path (no stale-route window: the LSDB converges
  to the key's final state the moment it calms down). One flapping
  adjacency is contained while the rest of the LSDB converges at full
  speed.

Decay is computed lazily from the last-touch monotonic timestamp —
no timer per key — and the clock is injectable (tests drive virtual
time). A clock that reads *backwards* (paused process, test reuse)
decays nothing rather than inflating penalties: monotonicity is
enforced, not assumed.

Brownout rungs beyond admission control are enacted by the owners of
the machinery: Decision consults ``streaming_allowed()`` before
deferring an epoch finish behind the stream fence and
``multichip_allowed()`` to pin the solver to the single-chip tier
(decision/tpu_solver.py honors ``force_single_chip``). Each rung is a
query, not a command, so a rung reverses the instant the ladder does.

One controller per node, looked up by node name (``get_controller``)
— same per-node registry idiom as the replay recorder: in-process
multi-node emulations keep their controllers separate, production
daemons have exactly one.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from openr_tpu.runtime.counters import counters

# the ladder, in escalation order; list index == numeric level
OVERLOAD_STATES = ("ok", "backpressure", "brownout", "shedding")

OK, BACKPRESSURE, BROWNOUT, SHEDDING = range(4)

# closed vocabulary of the overload.* counter family — restamped via
# set_counter(f"overload.{field}", ...) on every evaluation;
# tools/lint/metric_names.py expands this list for collision checking
# (keep the two in sync by importing, never copying)
OVERLOAD_COUNTER_FIELDS = (
    "state",             # numeric ladder level (0..3)
    "brownout",          # 1 while level >= brownout (gauge_duration SLO source)
    "transitions",       # ladder transitions since start
    "queue_depth",       # last observed pending-solve queue depth
    "damped_keys",       # keys currently suppressed
    "suppressed_events", # ingest events withheld by damping
    "released_keys",     # suppressions lifted after decay
    "shed_epochs",       # solve requests folded into the overflow batch
    "rejected_whatif",   # what-if admissions rejected by the ladder
    "deferred_probes",   # background probes deferred by the ladder
)

# admission priority classes, strongest first
PRIORITY_CLASSES = ("live", "whatif", "probe")


class FlapDamper:
    """RFC 2439-style per-key exponential flap damping (see module
    docstring). Keys are (area, key) pairs; time is whatever the
    injected clock says, in seconds."""

    def __init__(
        self,
        half_life_s: float = 60.0,
        penalty: float = 1.0,
        suppress_threshold: float = 3.0,
        reuse_threshold: float = 1.0,
        max_penalty: float = 12.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        if half_life_s <= 0:
            raise ValueError("half_life_s must be positive")
        if not 0 < reuse_threshold < suppress_threshold <= max_penalty:
            raise ValueError(
                "thresholds must satisfy 0 < reuse < suppress <= max"
            )
        self.half_life_s = float(half_life_s)
        self.penalty = float(penalty)
        self.suppress_threshold = float(suppress_threshold)
        self.reuse_threshold = float(reuse_threshold)
        self.max_penalty = float(max_penalty)
        self._clock = clock or time.monotonic
        # (area, key) -> [figure, last_t, suppressed, held_event]
        self._keys: dict[tuple, list] = {}
        self.suppressed_events = 0
        self.released_keys = 0

    def _decayed(self, rec: list, now: float) -> float:
        """Figure of merit decayed to `now`. A backwards clock decays
        nothing (monotonicity enforced, never negative exponents)."""
        dt = now - rec[1]
        if dt <= 0.0:
            return rec[0]
        return rec[0] * (0.5 ** (dt / self.half_life_s))

    def record_change(self, area: str, key: str) -> bool:
        """One ingest change of (area, key): decay, add the penalty,
        maybe cross into suppression. Returns True when the key is
        suppressed AFTER this event (the caller withholds the event
        from the LSDB and parks it via `hold`)."""
        now = self._clock()
        rec = self._keys.get((area, key))
        if rec is None:
            rec = [0.0, now, False, None]
            self._keys[(area, key)] = rec
        figure = min(self._decayed(rec, now) + self.penalty,
                     self.max_penalty)
        rec[0] = figure
        rec[1] = max(rec[1], now)
        if not rec[2] and figure >= self.suppress_threshold:
            rec[2] = True
            counters.increment("overload.damper.suppressions")
        if rec[2]:
            self.suppressed_events += 1
        return rec[2]

    def is_suppressed(self, area: str, key: str) -> bool:
        rec = self._keys.get((area, key))
        return bool(rec and rec[2])

    def hold(self, area: str, key: str, event) -> None:
        """Park the LATEST withheld event for a suppressed key (latest
        wins) so release can re-ingest the key's final state."""
        rec = self._keys.get((area, key))
        if rec is not None and rec[2]:
            rec[3] = event

    def releasable(self) -> list[tuple]:
        """Suppressed keys whose figure has decayed below the reuse
        threshold: [(area, key, held_event)]. Clears the suppression —
        the caller MUST re-ingest each held event (or the key's state
        stays at its last pre-suppression value until the next change)."""
        now = self._clock()
        out = []
        for (area, key), rec in list(self._keys.items()):
            figure = self._decayed(rec, now)
            if rec[2] and figure <= self.reuse_threshold:
                out.append((area, key, rec[3]))
                self.released_keys += 1
                del self._keys[(area, key)]
            elif not rec[2] and figure < self.penalty * 0.01:
                del self._keys[(area, key)]  # fully calmed: forget
        return out

    def damped_count(self) -> int:
        return sum(1 for rec in self._keys.values() if rec[2])

    def figure_of_merit(self, area: str, key: str) -> float:
        rec = self._keys.get((area, key))
        return 0.0 if rec is None else self._decayed(rec, self._clock())

    def report(self) -> dict:
        now = self._clock()
        suppressed = {
            f"{area}/{key}": round(self._decayed(rec, now), 3)
            for (area, key), rec in self._keys.items()
            if rec[2]
        }
        return {
            "half_life_s": self.half_life_s,
            "suppress_threshold": self.suppress_threshold,
            "reuse_threshold": self.reuse_threshold,
            "tracked_keys": len(self._keys),
            "damped_keys": len(suppressed),
            "suppressed": suppressed,
            "suppressed_events": self.suppressed_events,
            "released_keys": self.released_keys,
        }


class OverloadController:
    """Per-node overload state ladder + admission control (see module
    docstring)."""

    def __init__(
        self,
        node_name: str,
        queue_watermark: int = 8,
        coalesce_max_ms: int = 250,
        hbm_high_frac: float = 0.9,
        hbm_clear_frac: float = 0.75,
        rss_high_mb: float = 0.0,
        rss_clear_mb: float = 0.0,
        dwell_s: float = 5.0,
        damper: Optional[FlapDamper] = None,
        clock: Optional[Callable[[], float]] = None,
        on_transition: Optional[Callable] = None,
    ):
        if queue_watermark < 1:
            raise ValueError("queue_watermark must be >= 1")
        self.node_name = node_name
        self.queue_watermark = int(queue_watermark)
        self.coalesce_max_ms = int(coalesce_max_ms)
        self.hbm_high_frac = float(hbm_high_frac)
        self.hbm_clear_frac = float(hbm_clear_frac)
        self.rss_high_mb = float(rss_high_mb)
        self.rss_clear_mb = float(rss_clear_mb)
        self.dwell_s = float(dwell_s)
        self.damper = damper if damper is not None else FlapDamper()
        self._clock = clock or time.monotonic
        self.on_transition = on_transition
        self.level = OK
        self._since = self._clock()
        self.transitions = 0
        # cached signals (partial observers each feed what they see)
        self._depth = 0
        self._hbm_frac: Optional[float] = None
        self._rss_mb: Optional[float] = None
        self._slo_burning = False
        self.shed_epochs = 0
        self.rejected_whatif = 0
        self.deferred_probes = 0
        self._history: list[dict] = []

    # -- signals ------------------------------------------------------

    def observe(
        self,
        queue_depth: Optional[int] = None,
        hbm_frac: Optional[float] = None,
        rss_mb: Optional[float] = None,
        slo_burning: Optional[bool] = None,
    ) -> int:
        """Feed whichever signals this observer sees (Decision's
        dispatch fiber feeds depth; the Monitor tick feeds memory and
        SLO burn — same event loop, so no locking), then re-evaluate
        the ladder. Returns the post-evaluation level."""
        if queue_depth is not None:
            self._depth = int(queue_depth)
        if hbm_frac is not None:
            self._hbm_frac = float(hbm_frac)
        if rss_mb is not None:
            self._rss_mb = float(rss_mb)
        if slo_burning is not None:
            self._slo_burning = bool(slo_burning)
        return self.evaluate()

    def _mem_high(self) -> bool:
        if self._hbm_frac is not None and self._hbm_frac >= self.hbm_high_frac:
            return True
        return bool(
            self.rss_high_mb > 0
            and self._rss_mb is not None
            and self._rss_mb >= self.rss_high_mb
        )

    def _mem_clear(self) -> bool:
        """Memory below the CLEAR watermarks (hysteresis band)."""
        if self._hbm_frac is not None and self._hbm_frac > self.hbm_clear_frac:
            return False
        if (
            self.rss_high_mb > 0
            and self._rss_mb is not None
            and self._rss_mb > (self.rss_clear_mb or self.rss_high_mb)
        ):
            return False
        return True

    def _target(self) -> int:
        """Escalation target from the current signals (the watermark
        side of the hysteresis band — upshifts key off this)."""
        wm = self.queue_watermark
        mem_high = self._mem_high()
        if self._depth >= 2 * wm or (mem_high and self._depth >= wm):
            return SHEDDING
        if self._depth >= wm or mem_high:
            return BROWNOUT
        if self._depth >= max(1, wm // 2) or self._slo_burning:
            return BACKPRESSURE
        return OK

    def _clear_target(self) -> int:
        """De-escalation target: every signal must sit below its clear
        watermark before a rung releases (the other side of the band)."""
        wm = self.queue_watermark
        if not self._mem_clear() or self._depth >= wm:
            return max(BROWNOUT, min(self._target(), self.level))
        if self._depth >= max(1, wm // 4) or self._slo_burning:
            return BACKPRESSURE
        return OK

    def evaluate(self) -> int:
        """One ladder step: upshift immediately to the escalation
        target; downshift one rung only after `dwell_s` at the current
        level with the clear target below it."""
        now = self._clock()
        target = self._target()
        if target > self.level:
            self._transition(target, now)
        elif (
            self.level > OK
            and (now - self._since) >= self.dwell_s
            and self._clear_target() < self.level
        ):
            self._transition(self.level - 1, now)
        self._export()
        return self.level

    def _transition(self, new_level: int, now: float) -> None:
        old = self.level
        self.level = new_level
        self._since = now
        self.transitions += 1
        entry = {
            "t": now,
            "from": OVERLOAD_STATES[old],
            "to": OVERLOAD_STATES[new_level],
            "queue_depth": self._depth,
            "hbm_frac": self._hbm_frac,
            "rss_mb": self._rss_mb,
            "slo_burning": self._slo_burning,
        }
        self._history.append(entry)
        del self._history[:-32]
        if self.on_transition is not None:
            try:
                self.on_transition(entry)
            # lint: allow(broad-except) observer failure must not wedge
            # the ladder — control beats telemetry under overload
            except Exception:
                counters.increment("overload.transition_hook_errors")

    # -- queries the pipeline consults --------------------------------

    @property
    def state(self) -> str:
        return OVERLOAD_STATES[self.level]

    def admit(self, priority: str) -> bool:
        """Admission by priority class: live convergence always runs;
        what-if from brownout up and probes from backpressure up are
        turned away (counted — rejection is an answer, not a drop)."""
        if priority == "live" or self.level == OK:
            return True
        if priority == "whatif":
            if self.level >= BROWNOUT:
                self.rejected_whatif += 1
                self._export()
                return False
            return True
        if priority == "probe":
            self.deferred_probes += 1
            self._export()
            return False
        return True

    def coalesce_ms(self, base_ms: int) -> float:
        """Adaptive coalescing window for the dispatch fiber: the
        configured base in steady state, widened with ladder level and
        queue depth under pressure, capped at coalesce_max_ms. A zero
        base widens from a 1 ms seed so backpressure can engage even
        where coalescing was configured off."""
        if self.level == OK:
            return float(base_ms)
        seed = float(base_ms) if base_ms > 0 else 1.0
        scale = 1.0 + self.level + self._depth / float(self.queue_watermark)
        return min(seed * scale, float(self.coalesce_max_ms))

    def shed(self, queue_depth: int) -> bool:
        """Should a new solve request fold into the held overflow batch
        instead of growing the queue? Only in shedding, and only while
        the queue sits at/over the watermark — depth stays bounded."""
        if self.still_shedding(queue_depth):
            self.shed_epochs += 1
            self._export()
            return True
        return False

    def still_shedding(self, queue_depth: int) -> bool:
        """Passive form of `shed` (no counting): is the held overflow
        batch still better off waiting? The dispatch fiber flushes the
        batch back onto the queue the moment this goes False."""
        return (
            self.level >= SHEDDING and queue_depth >= self.queue_watermark
        )

    def streaming_allowed(self) -> bool:
        """Brownout rung: drop the streaming overlap (epoch finishes
        deferred behind the stream fence) back to the simple path."""
        return self.level < BROWNOUT

    def multichip_allowed(self) -> bool:
        """Deepest rung before shedding-only: pin the solver to the
        single-chip tier, releasing the mesh's HBM."""
        return self.level < SHEDDING

    # -- export -------------------------------------------------------

    def _export(self) -> None:
        for field, value in (
            ("state", self.level),
            ("brownout", 1 if self.level >= BROWNOUT else 0),
            ("transitions", self.transitions),
            ("queue_depth", self._depth),
            ("damped_keys", self.damper.damped_count()),
            ("suppressed_events", self.damper.suppressed_events),
            ("released_keys", self.damper.released_keys),
            ("shed_epochs", self.shed_epochs),
            ("rejected_whatif", self.rejected_whatif),
            ("deferred_probes", self.deferred_probes),
        ):
            counters.set_counter(f"overload.{field}", value)

    def report(self) -> dict:
        """`breeze decision overload` / ctrl payload."""
        now = self._clock()
        return {
            "node": self.node_name,
            "state": self.state,
            "level": self.level,
            "since_s": round(now - self._since, 3),
            "queue_watermark": self.queue_watermark,
            "queue_depth": self._depth,
            "hbm_frac": self._hbm_frac,
            "rss_mb": self._rss_mb,
            "slo_burning": self._slo_burning,
            "transitions": self.transitions,
            "shed_epochs": self.shed_epochs,
            "rejected_whatif": self.rejected_whatif,
            "deferred_probes": self.deferred_probes,
            "coalesce_max_ms": self.coalesce_max_ms,
            "dwell_s": self.dwell_s,
            "streaming_allowed": self.streaming_allowed(),
            "multichip_allowed": self.multichip_allowed(),
            "damper": self.damper.report(),
            "history": [
                {**h, "t": round(h["t"], 3)} for h in self._history[-10:]
            ],
        }


# -- per-node registry (Monitor/kvstore/ctrl lookup path) ---------------

_registry: dict[str, OverloadController] = {}


def register(controller: OverloadController) -> OverloadController:
    """Install `controller` as its node's controller (latest wins —
    test harnesses rebuild Decisions under one node name)."""
    _registry[controller.node_name] = controller
    return controller


def get_controller(node_name: str) -> Optional[OverloadController]:
    return _registry.get(node_name)


def unregister(node_name: str) -> None:
    _registry.pop(node_name, None)
