from openr_tpu.spark.io_provider import (  # noqa: F401
    IoProvider,
    MockIoMesh,
    MockIoProvider,
    UdpIoProvider,
)
from openr_tpu.spark.spark import Spark, SparkNeighEvent, get_next_state  # noqa: F401
