from openr_tpu.allocators.range_allocator import (  # noqa: F401
    ALLOC_PREFIX_MARKER,
    PrefixAllocator,
    RangeAllocator,
)
