"""Chaos drills — fault-injected system tests (ISSUE 4 acceptance).

Full node stacks on a MockIoMesh, with faults armed through the same
registry `breeze fault inject` drives in production:

  - kill-the-TPU: arm `solver.exec` mid-convergence on a 3-node topology;
    routes must keep converging through the CPU fallback, the node must
    report degraded (gauge + fleet health + trace stamp), and the device
    must be promoted back once the fault clears.
  - decision fiber crash: arm `decision.ingest`; the supervisor must
    restart the fiber within budget and the pipeline must keep working.
  - spark graceful restart: a restarting node's routes must be held
    through the GR window and flushed when it expires.

Marked slow (out of the tier-1 lane) + chaos (the CI chaos lane).
"""

import asyncio
import contextlib

import pytest

from openr_tpu.config import DecisionConfig, MonitorConfig, SparkConfig
from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.faults import registry
from openr_tpu.runtime.monitor import Monitor
from openr_tpu.runtime.openr_wrapper import OpenrWrapper
from openr_tpu.runtime.tracing import tracer
from openr_tpu.spark import MockIoMesh
from openr_tpu.types import Value
from tests.conftest import run_async

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

CONVERGENCE_S = 20.0


async def start_mesh(names, links, **wrapper_kwargs):
    """test_system.start_mesh, plus per-node wrapper kwargs (solver
    backend, probe-tuned decision config, spark GR timers)."""
    mesh = MockIoMesh()
    kv_ports: dict[str, int] = {}
    nodes = {
        n: OpenrWrapper(n, mesh.provider(n), kv_ports, **wrapper_kwargs)
        for n in names
    }
    for a, if_a, b, if_b in links:
        mesh.connect(a, if_a, b, if_b)
    ifaces = {n: [] for n in names}
    for a, if_a, b, if_b in links:
        ifaces[a].append(if_a)
        ifaces[b].append(if_b)
    for n, w in nodes.items():
        await w.start(*ifaces[n])
    return mesh, nodes


async def stop_all(nodes):
    for w in nodes.values():
        with contextlib.suppress(Exception):
            await w.stop()


def loopback(i: int) -> str:
    return f"10.0.0.{i + 1}/32"


def _counter(key):
    return counters.get_counter(key) or 0


def _degraded_trace_closed():
    return any(
        t["spans"][0]["attributes"].get("degraded") is True
        and t["status"] == "ok"
        for t in tracer.get_traces(limit=500)
    )


class TestKillTheTpuDrill:
    @run_async
    async def test_solver_failover_mid_convergence(self):
        """Triangle a-b-c on the TPU backend; the device 'dies' (armed
        solver.exec) right before a topology change."""
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                solver_probe_initial_backoff_s=0.2,
                solver_probe_max_backoff_s=0.5,
            ),
        )
        mon = Monitor(
            "node-0",
            MonitorConfig(),
            nodes["node-0"].log_sample_queue.get_reader("drill"),
        )
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def converged():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(3) if j != i}
                    if set(nodes[n].fib_routes) != expect:
                        return False
                return True

            await wait_until(converged, timeout_s=CONVERGENCE_S)
            failovers0 = _counter("decision.solver.failovers")
            promotions0 = _counter("decision.solver.promotions")

            # the device dies mid-flight...
            registry.arm("solver.exec")
            # ...and then the topology changes: cut node-0 <-> node-2
            mesh.disconnect("node-0", "if-02", "node-2", "if-20")

            def rerouted_degraded():
                entry = nodes["node-0"].fib_routes.get(loopback(2))
                if entry is None:
                    return False
                via_b = {
                    nh.neighbor_node_name for nh in entry.nexthops
                } == {"node-1"}
                return via_b and _counter("decision.solver.degraded") == 1

            # routes converge anyway — carried by the CPU oracle
            await wait_until(rerouted_degraded, timeout_s=CONVERGENCE_S)
            assert _counter("decision.solver.failovers") > failovers0
            # the node reports degraded in fleet health...
            assert mon.health_summary()["solver_degraded"] is True
            # ...and the convergence trace closed stamped degraded=true
            await wait_until(_degraded_trace_closed, timeout_s=CONVERGENCE_S)
            # probes keep failing while the fault is armed
            await wait_until(
                lambda: _counter("decision.solver.probe_failures") >= 1,
                timeout_s=CONVERGENCE_S,
            )
            assert _counter("decision.solver.degraded") == 1

            # the device heals: clear the fault, probes promote it back
            registry.clear("solver.exec")
            await wait_until(
                lambda: _counter("decision.solver.degraded") == 0
                and _counter("decision.solver.promotions") > promotions0,
                timeout_s=CONVERGENCE_S,
            )
            assert mon.health_summary()["solver_degraded"] is False

            # the promoted pipeline still routes fresh state end to end
            nodes["node-2"].advertise_prefix("10.77.0.0/24")
            await wait_until(
                lambda: "10.77.0.0/24" in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
        finally:
            registry.clear()
            counters.set_counter("decision.solver.degraded", 0)
            await stop_all(nodes)


class TestSloBurnFlightRecorderDrill:
    @run_async
    async def test_failover_trips_slo_burn_and_flight_recorder(self):
        """ISSUE 11 drill: an armed solver.exec fault mid-convergence
        must (1) auto-trigger a flight-recorder bundle attributed to the
        failover (DECISION_SOLVER_DEGRADED), (2) burn the
        solver_degraded_s SLO into an alert through the Monitor's
        metrics loop, and (3) freeze a post-mortem bundle whose trace
        ring holds the degraded-mode convergence roots."""
        import json
        import os
        import tempfile

        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)
        rec_dir = tempfile.mkdtemp(prefix="openr-tpu-flightrec-drill-")
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                solver_probe_initial_backoff_s=5.0,
                solver_probe_max_backoff_s=5.0,
            ),
        )
        mon = Monitor(
            "node-0",
            MonitorConfig(
                # drill-scale SLO: degraded for >1s starts breaching,
                # a half-burned 2s window alerts — so the whole state
                # machine runs in seconds instead of operator-minutes
                slos={
                    "solver_degraded_s": {
                        "kind": "gauge_duration",
                        "source": "decision.solver.degraded",
                        "threshold": 1.0,
                        "fast_window_s": 2.0,
                        "slow_window_s": 4.0,
                    }
                },
                slo_fast_window_s=2.0,
                slo_slow_window_s=4.0,
                flight_recorder_dir=rec_dir,
                flight_recorder_ring=64,
                flight_recorder_min_interval_s=0.0,
            ),
            nodes["node-0"].log_sample_queue.get_reader("slo-drill"),
            interval_s=0.1,
        )
        alerts_key = "monitor.slo.solver_degraded_s.alerts"
        alerts0 = _counter(alerts_key)
        await mon.start()
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def converged():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(3) if j != i}
                    if set(nodes[n].fib_routes) != expect:
                        return False
                return True

            await wait_until(converged, timeout_s=CONVERGENCE_S)

            # the device dies, then the topology changes
            registry.arm("solver.exec")
            mesh.disconnect("node-0", "if-02", "node-2", "if-20")
            await wait_until(
                lambda: _counter("decision.solver.degraded") == 1
                and loopback(2) in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )

            # (1) the failover LogSample auto-triggered a bundle that
            # NAMES the failover in its trigger attribution
            await wait_until(
                lambda: any(
                    b["reason"] == "solver_failover"
                    for b in mon.flight_recorder.bundles
                ),
                timeout_s=CONVERGENCE_S,
            )
            fo = next(
                b
                for b in mon.flight_recorder.bundles
                if b["reason"] == "solver_failover"
            )
            with open(os.path.join(fo["path"], "bundle.json")) as f:
                fo_doc = json.load(f)
            assert fo_doc["schema"] == "openr-tpu-flight-recorder/1"
            assert fo_doc["trigger"]["reason"] == "solver_failover"
            assert (
                fo_doc["trigger"]["detail"]["event"]
                == "DECISION_SOLVER_DEGRADED"
            ), fo_doc["trigger"]
            assert os.path.exists(os.path.join(fo["path"], "trace.json"))

            # the degraded-mode reroute closes its stamped trace before
            # the SLO window can fill
            await wait_until(_degraded_trace_closed, timeout_s=CONVERGENCE_S)

            # (2) the sustained degraded gauge burns the SLO: the engine
            # raises the alert, logs it, and counts it
            await wait_until(
                lambda: _counter(alerts_key) > alerts0,
                timeout_s=CONVERGENCE_S,
            )
            rep = mon.slo_report()
            assert rep["enabled"] is True
            state = rep["slos"]["solver_degraded_s"]["state"]
            assert state in ("fast_burn", "sustained_burn"), rep
            assert any(
                s.event == "SLO_BURN_ALERT"
                and s.values.get("slo") == "solver_degraded_s"
                for s in mon.event_logs
            ), [s.event for s in mon.event_logs]
            assert _counter("monitor.slo.solver_degraded_s.burning") >= 1

            # (3) the burn auto-froze a bundle whose trace ring holds
            # the degraded convergence roots and whose SLO annex shows
            # the burning objective
            await wait_until(
                lambda: any(
                    b["reason"].startswith("slo_burn:")
                    for b in mon.flight_recorder.bundles
                ),
                timeout_s=CONVERGENCE_S,
            )
            sb = next(
                b
                for b in mon.flight_recorder.bundles
                if b["reason"].startswith("slo_burn:")
            )
            with open(os.path.join(sb["path"], "bundle.json")) as f:
                sb_doc = json.load(f)
            assert sb_doc["trigger"]["reason"] == (
                "slo_burn:solver_degraded_s"
            )
            assert any(
                t["spans"][0]["attributes"].get("degraded") is True
                for t in sb_doc["traces"]
            ), [t["spans"][0]["attributes"] for t in sb_doc["traces"]]
            assert (
                sb_doc["slo"]["slos"]["solver_degraded_s"]["state"]
                != "ok"
            ), sb_doc["slo"]
            # the bundle carries the lead-up: counter history ticks and
            # the noted anomaly events
            assert len(sb_doc["counter_history"]) >= 1
            assert _counter("monitor.flight_recorder.triggers") >= 2
        finally:
            registry.clear()
            counters.set_counter("decision.solver.degraded", 0)
            with contextlib.suppress(Exception):
                await mon.stop()
            await stop_all(nodes)


class TestIncrementalSolverFailoverDrill:
    @run_async
    async def test_fault_during_incremental_solve_fails_over(self):
        """ISSUE 7 drill: a warm solver on the incremental (seed-from-
        previous) path takes an armed solver.exec fault mid-churn. The
        failover must carry the event to the CPU oracle with NO stale-
        route window — the fib lands on the post-churn next-hop set —
        and after the device heals, churn re-engages the incremental
        path. Engagement is driven by pumping prefix events (the
        wrapper's own adjacency re-origination makes any single
        topology event race the root-signature gate)."""
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)
        # 4-node ring: node-0 reaches node-2 via ECMP {node-1, node-3},
        # and the 1<->2 edge is NOT one of node-0's root links, so its
        # churn is exactly the incremental path's home turf
        names = [f"node-{i}" for i in range(4)]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-23", "node-3", "if-32"),
            ("node-3", "if-30", "node-0", "if-03"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                incremental_spf=True,
                solver_probe_initial_backoff_s=0.2,
                solver_probe_max_backoff_s=0.5,
            ),
        )
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def nh_set(pfx):
                entry = nodes["node-0"].fib_routes.get(pfx)
                if entry is None:
                    return set()
                return {nh.neighbor_node_name for nh in entry.nexthops}

            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-1", "node-3"},
                timeout_s=CONVERGENCE_S,
            )

            async def pump_incremental(tag):
                """Flap the (non-root-for-node-0) 1<->2 link until an
                incremental solve lands; leaves the link connected.
                Each half waits for fib convergence, so a pass also
                proves the warm path kept routing correct."""
                incr0 = _counter("decision.solver.incr.solves")
                for _ in range(10):
                    mesh.disconnect(
                        "node-1", "if-12", "node-2", "if-21"
                    )
                    await wait_until(
                        lambda: nh_set(loopback(2)) == {"node-3"},
                        timeout_s=CONVERGENCE_S,
                    )
                    mesh.connect("node-1", "if-12", "node-2", "if-21")
                    await wait_until(
                        lambda: nh_set(loopback(2))
                        == {"node-1", "node-3"},
                        timeout_s=CONVERGENCE_S,
                    )
                    if (
                        _counter("decision.solver.incr.solves") > incr0
                    ):
                        return
                raise AssertionError(
                    f"incremental path never engaged ({tag})"
                )

            # healthy churn first: the warm solvers must take the
            # seed-from-previous path
            await pump_incremental(0)

            # topology churn away from node-0's root links
            mesh.disconnect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-3"},
                timeout_s=CONVERGENCE_S,
            )

            # the device dies; the link comes back. The solve for this
            # event would be incremental — the armed fault must push it
            # to the CPU oracle, which lands the restored ECMP set
            # directly (no window serving the stale single-path route)
            failovers0 = _counter("decision.solver.failovers")
            promotions0 = _counter("decision.solver.promotions")
            registry.arm("solver.exec")
            mesh.connect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-1", "node-3"}
                and _counter("decision.solver.degraded") == 1,
                timeout_s=CONVERGENCE_S,
            )
            assert _counter("decision.solver.failovers") > failovers0

            # heal: probes promote the device back, and the next churn
            # runs incremental again off a freshly seeded plane
            registry.clear("solver.exec")
            await wait_until(
                lambda: _counter("decision.solver.degraded") == 0
                and _counter("decision.solver.promotions") > promotions0,
                timeout_s=CONVERGENCE_S,
            )
            await pump_incremental(1)
        finally:
            registry.clear()
            counters.set_counter("decision.solver.degraded", 0)
            await stop_all(nodes)


class TestMultichipSolverFailoverDrill:
    @run_async
    async def test_fault_during_multichip_solve_fails_over(self):
        """Multichip capacity-tier drill: with the tier forced on
        (threshold below the 4-node ring's n_cap, 8 virtual devices),
        an armed solver.exec fault lands on a sharded solve mid-churn.
        The failover must carry the event to the CPU oracle with NO
        stale-route window — the fib lands directly on the post-churn
        ECMP set — and after the device heals, the probe canary must
        re-promote the node back onto the multichip path (the tier's
        dispatch counter advances on post-heal churn)."""
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)
        names = [f"node-{i}" for i in range(4)]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-23", "node-3", "if-32"),
            ("node-3", "if-30", "node-0", "if-03"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                multichip_n_cap_threshold=2,
                solver_probe_initial_backoff_s=0.2,
                solver_probe_max_backoff_s=0.5,
            ),
        )
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def nh_set(pfx):
                entry = nodes["node-0"].fib_routes.get(pfx)
                if entry is None:
                    return set()
                return {nh.neighbor_node_name for nh in entry.nexthops}

            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-1", "node-3"},
                timeout_s=CONVERGENCE_S,
            )
            # the tier must actually be live before the drill means
            # anything: the initial convergence solves were sharded
            assert _counter("decision.solver.multichip.engaged") > 0
            assert _counter("decision.solver.multichip.dispatches") > 0

            # topology churn away from node-0's root links
            mesh.disconnect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-3"},
                timeout_s=CONVERGENCE_S,
            )

            # the device dies; the link comes back. The solve for this
            # event would run through the multichip tier — the armed
            # fault must push it to the CPU oracle, which lands the
            # restored ECMP set directly (no window serving the stale
            # single-path route)
            failovers0 = _counter("decision.solver.failovers")
            promotions0 = _counter("decision.solver.promotions")
            registry.arm("solver.exec")
            mesh.connect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-1", "node-3"}
                and _counter("decision.solver.degraded") == 1,
                timeout_s=CONVERGENCE_S,
            )
            assert _counter("decision.solver.failovers") > failovers0

            # heal: the probe canary promotes the device back and churn
            # dispatches through the multichip tier again
            registry.clear("solver.exec")
            await wait_until(
                lambda: _counter("decision.solver.degraded") == 0
                and _counter("decision.solver.promotions") > promotions0,
                timeout_s=CONVERGENCE_S,
            )
            mc_disp0 = _counter("decision.solver.multichip.dispatches")
            mesh.disconnect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-3"}
                and _counter("decision.solver.multichip.dispatches")
                > mc_disp0,
                timeout_s=CONVERGENCE_S,
            )
            mesh.connect("node-1", "if-12", "node-2", "if-21")
            await wait_until(
                lambda: nh_set(loopback(2)) == {"node-1", "node-3"},
                timeout_s=CONVERGENCE_S,
            )
        finally:
            registry.clear()
            counters.set_counter("decision.solver.degraded", 0)
            await stop_all(nodes)


class TestBucketedKernelFailoverDrill:
    @run_async
    async def test_fault_during_bucketed_solve_fails_over(self):
        """Δ-stepping drill: a 10-ring is the smallest live topology
        whose plan forms shift classes (build_plan's usefulness floor
        is 8 edges per delta), so the bucketed kernel actually engages
        (delta_exp > 0) instead of silently falling back to sync. An
        armed solver.exec fault lands on a bucketed solve mid-churn:
        the failover must carry the event to the CPU oracle with NO
        stale-route window — the fib lands directly on the post-churn
        ECMP set — and after the device heals, churn runs bucketed
        epochs again (the decision.device.bucket_epochs stat advances
        post-heal)."""
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)
        n = 10
        names = [f"node-{i}" for i in range(n)]
        links = [
            (
                f"node-{i}", f"if-{i}{(i + 1) % n}",
                f"node-{(i + 1) % n}", f"if-{(i + 1) % n}{i}",
            )
            for i in range(n)
        ]

        def epoch_count():
            return (
                counters.get_counters("decision.device.bucket_epochs")
                .get("decision.device.bucket_epochs.count.60", 0)
            )

        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                spf_kernel="bucketed",
                solver_probe_initial_backoff_s=0.2,
                solver_probe_max_backoff_s=0.5,
            ),
        )
        try:
            for i, nm in enumerate(names):
                nodes[nm].advertise_prefix(loopback(i))

            def nh_set(pfx):
                entry = nodes["node-0"].fib_routes.get(pfx)
                if entry is None:
                    return set()
                return {nh.neighbor_node_name for nh in entry.nexthops}

            # node-5 is diametrically opposite node-0: 5 hops either
            # way around the ring -> ECMP over both ring neighbors
            await wait_until(
                lambda: nh_set(loopback(5)) == {"node-1", "node-9"},
                timeout_s=CONVERGENCE_S,
            )
            # the drill is meaningless unless the Δ-stepping kernel is
            # actually live: the convergence solves ran bucket epochs
            assert epoch_count() > 0, "bucketed kernel never engaged"

            # churn away from node-0's root links: cutting 4<->5 leaves
            # only the counter-clockwise path
            mesh.disconnect("node-4", "if-45", "node-5", "if-54")
            await wait_until(
                lambda: nh_set(loopback(5)) == {"node-9"},
                timeout_s=CONVERGENCE_S,
            )

            # the device dies; the link comes back. The solve for this
            # event would run bucketed epochs — the armed fault must
            # push it to the CPU oracle, which lands the restored ECMP
            # set directly (no window serving the stale single-path
            # route)
            failovers0 = _counter("decision.solver.failovers")
            promotions0 = _counter("decision.solver.promotions")
            registry.arm("solver.exec")
            mesh.connect("node-4", "if-45", "node-5", "if-54")
            await wait_until(
                lambda: nh_set(loopback(5)) == {"node-1", "node-9"}
                and _counter("decision.solver.degraded") == 1,
                timeout_s=CONVERGENCE_S,
            )
            assert _counter("decision.solver.failovers") > failovers0

            # heal: probes promote the device back and post-heal churn
            # runs bucket epochs again
            registry.clear("solver.exec")
            await wait_until(
                lambda: _counter("decision.solver.degraded") == 0
                and _counter("decision.solver.promotions") > promotions0,
                timeout_s=CONVERGENCE_S,
            )
            epochs0 = epoch_count()
            mesh.disconnect("node-4", "if-45", "node-5", "if-54")
            await wait_until(
                lambda: nh_set(loopback(5)) == {"node-9"}
                and epoch_count() > epochs0,
                timeout_s=CONVERGENCE_S,
            )
            mesh.connect("node-4", "if-45", "node-5", "if-54")
            await wait_until(
                lambda: nh_set(loopback(5)) == {"node-1", "node-9"},
                timeout_s=CONVERGENCE_S,
            )
        finally:
            registry.clear()
            counters.set_counter("decision.solver.degraded", 0)
            await stop_all(nodes)


class TestDecisionFiberCrashDrill:
    @run_async
    async def test_supervisor_restarts_crashed_ingest_fiber(self):
        registry.clear()
        names = ["node-0", "node-1"]
        links = [("node-0", "if-01", "node-1", "if-10")]
        mesh, nodes = await start_mesh(names, links)
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(
                lambda: loopback(1) in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            restarts0 = _counter("runtime.supervisor.restarts")

            # next two publications popped by a Decision ingest fiber
            # (either node — the registry is process-global) kill it
            registry.arm("decision.ingest", every_nth=1, max_fires=2)
            kv = nodes["node-0"].kvstore
            area = next(iter(kv.areas))
            for i in range(2):
                await kv.set_key_vals(
                    area,
                    {
                        f"chaos:junk-{i}": Value(
                            version=1,
                            originator_id="node-0",
                            value=b"x",
                            ttl_ms=-1,
                            ttl_version=0,
                            hash=None,
                        )
                    },
                )
                await asyncio.sleep(0.05)

            # both crashes restarted within the (default 3) budget
            await wait_until(
                lambda: _counter("runtime.supervisor.restarts")
                >= restarts0 + 2
                and not registry.list()["armed"],
                timeout_s=CONVERGENCE_S,
            )
            from openr_tpu.runtime.tasks import recent_crashes

            assert any(
                c["task"].startswith("decision:")
                and "injected fault" in c["error"]
                for c in recent_crashes()
            )

            # the restarted fiber still ingests: a fresh prefix converges
            nodes["node-1"].advertise_prefix("10.99.0.0/24")
            await wait_until(
                lambda: "10.99.0.0/24" in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
        finally:
            registry.clear()
            await stop_all(nodes)


class TestDispatchFiberKillDrill:
    @run_async
    async def test_supervisor_restarts_crashed_dispatch_fiber(self):
        """Async-dispatch mesh (ISSUE 5): kill the dedicated dispatch
        fiber mid-solve via the solver.dispatch seam. The supervisor
        must restart it, on_fiber_restart must force a full rebuild (the
        crashed fiber died holding a coalesced pending snapshot), and
        fresh topology state must keep converging end to end."""
        registry.clear()
        names = ["node-0", "node-1"]
        links = [("node-0", "if-01", "node-1", "if-10")]
        mesh, nodes = await start_mesh(
            names,
            links,
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
                async_dispatch=True,
            ),
        )
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(
                lambda: loopback(1) in nodes["node-0"].fib_routes
                and loopback(0) in nodes["node-1"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            assert _counter("decision.dispatch.solves") >= 1
            restarts0 = _counter("runtime.supervisor.restarts")

            # the next two solves popped by a dispatch fiber (either
            # node — the registry is process-global) kill it
            registry.arm("solver.dispatch", every_nth=1, max_fires=2)
            nodes["node-1"].advertise_prefix("10.88.0.0/24")

            await wait_until(
                lambda: _counter("runtime.supervisor.restarts")
                >= restarts0 + 2
                and not registry.list()["armed"],
                timeout_s=CONVERGENCE_S,
            )
            from openr_tpu.runtime.tasks import recent_crashes

            assert any(
                c["task"].startswith("decision:")
                and c["task"].endswith(".dispatch")
                and "injected fault" in c["error"]
                for c in recent_crashes()
            )

            # the restarted fiber's forced full rebuild recovers the
            # snapshot lost in the crash...
            await wait_until(
                lambda: "10.88.0.0/24" in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            # ...and keeps solving fresh state
            nodes["node-0"].advertise_prefix("10.89.0.0/24")
            await wait_until(
                lambda: "10.89.0.0/24" in nodes["node-1"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
        finally:
            registry.clear()
            await stop_all(nodes)


class TestSparkGracefulRestartDrill:
    @run_async
    async def test_routes_held_through_gr_window_then_flushed(self):
        registry.clear()
        names = ["node-0", "node-1"]
        links = [("node-0", "if-01", "node-1", "if-10")]
        mesh, nodes = await start_mesh(
            names,
            links,
            spark_config=SparkConfig(
                hello_time_s=0.08,
                fastinit_hello_time_ms=20,
                keepalive_time_s=0.05,
                hold_time_s=0.4,
                graceful_restart_time_s=2.5,
                handshake_time_ms=40,
                min_packets_per_sec=0,
            ),
        )
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(
                lambda: loopback(0) in nodes["node-1"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            gr_expired0 = _counter("spark.neighbor.gr_expired")

            # node-0 announces a graceful restart, then goes dark
            await nodes["node-0"].spark.send_restarting_hellos()
            await nodes["node-0"].stop()

            # well past hold_time (0.4s) but inside the GR window (2.5s):
            # node-1 must still hold node-0's route
            await asyncio.sleep(1.0)
            assert loopback(0) in nodes["node-1"].fib_routes

            # node-0 never comes back: GR expiry flushes the route
            await wait_until(
                lambda: loopback(0) not in nodes["node-1"].fib_routes,
                timeout_s=10,
            )
            assert _counter("spark.neighbor.gr_expired") > gr_expired0
        finally:
            registry.clear()
            await stop_all(nodes)


class TestPerfRegressionDrill:
    @run_async
    async def test_latency_fault_trips_baseline_drift(self):
        """ISSUE 14 drill: an armed solver.exec LATENCY fault (delay_ms)
        inflates decision.spf_ms while routing keeps converging — no
        failover, no route loss, just a slower kernel. The
        baseline_drift SLO must compare the live window against the
        pre-seeded perf-ledger baseline, burn into an alert, and freeze
        a perf_regression bundle whose ledger delta shows
        baseline-vs-live."""
        import json
        import os
        import tempfile

        from openr_tpu.runtime import perf_ledger
        from openr_tpu.runtime.perf_ledger import PerfLedger

        registry.clear()
        ledger_dir = tempfile.mkdtemp(prefix="openr-tpu-perf-drill-")
        rec_dir = tempfile.mkdtemp(prefix="openr-tpu-flightrec-perf-")
        # the baseline a healthy fleet accreted before this "restart":
        # p95 solve latency ~5ms
        seed = PerfLedger(ledger_dir)
        for _ in range(8):
            seed.record(
                "solve", {"device_ms": 5.0}, signature="live", variant="live"
            )
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            decision_config=DecisionConfig(
                debounce_min_ms=5, debounce_max_ms=25
            ),
        )
        mon = Monitor(
            "node-0",
            MonitorConfig(
                slos={
                    "solve_drift": {
                        "kind": "baseline_drift",
                        "source": "decision.spf_ms",
                        "threshold": 1.5,
                        "min_count": 2,
                        # drill-scale: no cold-start exclusion (the mesh
                        # converges before the fault arms) and 2s/4s
                        # burn windows so the machine runs in seconds
                        "warmup_s": 0.0,
                        "fast_window_s": 2.0,
                        "slow_window_s": 4.0,
                    }
                },
                slo_fast_window_s=2.0,
                slo_slow_window_s=4.0,
                perf_ledger_dir=ledger_dir,
                flight_recorder_dir=rec_dir,
                flight_recorder_ring=64,
                flight_recorder_min_interval_s=0.0,
            ),
            nodes["node-0"].log_sample_queue.get_reader("perf-drill"),
            interval_s=0.1,
        )
        await mon.start()
        stop_churn = asyncio.Event()

        async def churn():
            """Flap a link-metric override: a link-ATTRIBUTE change
            forces full rebuilds (the incremental path has no
            solver.exec site), keeping decision.spf_ms measuring the
            delayed solves; the topology itself never changes, so
            routing stays converged throughout."""
            flip = False
            while not stop_churn.is_set():
                flip = not flip
                await nodes["node-0"].link_monitor.set_link_metric(
                    "if-01", 10 if flip else None
                )
                await asyncio.sleep(0.15)

        churn_task = None
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def converged():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(3) if j != i}
                    if not expect <= set(nodes[n].fib_routes):
                        return False
                return True

            await wait_until(converged, timeout_s=CONVERGENCE_S)
            failovers0 = _counter("decision.solver.failovers")

            # every solve now pays +40ms — slower, NOT broken
            registry.arm("solver.exec", delay_ms=40.0)
            churn_task = asyncio.ensure_future(churn())

            # the latency fault actually fires (and never raises)
            await wait_until(
                lambda: _counter("runtime.fault.solver.exec.delayed") > 0,
                timeout_s=CONVERGENCE_S,
            )
            # the drift SLO burns and the monitor freezes a
            # perf_regression bundle (NOT a generic slo_burn)
            await wait_until(
                lambda: any(
                    b["reason"] == "perf_regression"
                    for b in mon.flight_recorder.bundles
                ),
                timeout_s=CONVERGENCE_S,
            )
            rep = mon.slo_report()["slos"]["solve_drift"]
            assert rep["state"] in ("fast_burn", "sustained_burn"), rep
            assert rep["baseline"] == 5.0
            assert rep["live"] > rep["baseline"]

            pr = next(
                b
                for b in mon.flight_recorder.bundles
                if b["reason"] == "perf_regression"
            )
            with open(os.path.join(pr["path"], "bundle.json")) as f:
                doc = json.load(f)
            assert doc["trigger"]["reason"] == "perf_regression"
            assert doc["trigger"]["detail"]["kind"] == "baseline_drift"
            delta = doc["perf_ledger_delta"]
            assert delta["slo"] == "solve_drift"
            assert delta["baseline"] == 5.0
            assert delta["live"] > 5.0
            assert delta["ratio"] > 1.5
            assert delta["threshold"] == 1.5
            # the bundled ledger snapshot holds the live-solve key the
            # baseline came from
            assert any(
                k.startswith("solve|live|live|")
                for k in delta["ledger"]["keys"]
            ), list(delta["ledger"]["keys"])
            assert doc["slo"]["slos"]["solve_drift"]["state"] != "ok"

            # the whole time: a PERF regression, not an availability
            # event — no failover, no degraded mode, routes intact
            assert _counter("decision.solver.failovers") == failovers0
            assert _counter("decision.solver.degraded") == 0
            assert converged()
        finally:
            registry.clear()
            stop_churn.set()
            if churn_task is not None:
                with contextlib.suppress(Exception):
                    await churn_task
            with contextlib.suppress(Exception):
                await mon.stop()
            await stop_all(nodes)
            perf_ledger.configure("")


class TestDeviceRetraceFlightRecorderDrill:
    @run_async
    async def test_injected_cache_fork_trips_retrace_bundle(self):
        """ISSUE 15 drill: an injected cache-class fork — the live jit
        executables dropped out from under a warm mesh — must be caught
        by the retrace sentinel on the next solve: the recompile is
        attributed (namespace + signature delta), surfaced as a
        DEVICE_RETRACE LogSample, and freezes a flight-recorder bundle,
        while routing reconverges without a blip. All three nodes run in
        one process and share the module-global factory caches, so the
        process-global event queue may be drained by ANY node's Decision
        — the drill monitors every node and asserts the bundle lands
        somewhere, which is exactly the per-process production shape."""
        import json
        import os
        import tempfile

        from openr_tpu.decision import tpu_solver as ts
        from openr_tpu.ops.xla_cache import retrace

        def _clear_factories():
            # the injection: python-level caches drop their executables
            # WITHOUT the eviction path's retrace.forget() — the next
            # dispatch re-jits a kernel the sentinel considers warm
            for fn in (
                ts._jitted_pipeline, ts._jitted_sssp_batch,
                ts._plan_pipeline, ts._fused_pipeline,
                ts._instrumented_pipeline, ts._instrumented_fused,
                ts._scatter_jit,
            ):
                fn.cache_clear()

        def _retraces():
            return sum(
                counters.get_counters("xla_cache.retraces.").values()
            )

        registry.clear()
        _clear_factories()
        retrace.reset()  # initial convergence compiles = clean warmup
        rec_root = tempfile.mkdtemp(prefix="openr-tpu-retrace-drill-")
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        mesh, nodes = await start_mesh(
            names,
            links,
            solver_backend="tpu",
            decision_config=DecisionConfig(
                debounce_min_ms=5,
                debounce_max_ms=25,
            ),
        )
        mons = {}
        for n in names:
            mons[n] = Monitor(
                n,
                MonitorConfig(
                    flight_recorder_dir=os.path.join(rec_root, n),
                    flight_recorder_min_interval_s=0.0,
                ),
                nodes[n].log_sample_queue.get_reader("retrace-drill"),
                interval_s=0.1,
            )
            await mons[n].start()

        def _bundles(reason):
            return [
                b
                for mon in mons.values()
                for b in mon.flight_recorder.bundles
                if b["reason"] == reason
            ]

        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def converged():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(3) if j != i}
                    if set(nodes[n].fib_routes) != expect:
                        return False
                return True

            await wait_until(converged, timeout_s=CONVERGENCE_S)
            await asyncio.sleep(0.3)  # let trailing rebuilds settle
            retraces0 = _retraces()
            bundles0 = len(_bundles("device_retrace"))

            # INJECT the fork, then change the topology: the rebuild's
            # re-jit of a supposedly-warm kernel is the retrace
            _clear_factories()
            mesh.disconnect("node-0", "if-02", "node-2", "if-20")

            await wait_until(
                lambda: _retraces() > retraces0, timeout_s=CONVERGENCE_S
            )
            await wait_until(
                lambda: len(_bundles("device_retrace")) > bundles0,
                timeout_s=CONVERGENCE_S,
            )
            fo = _bundles("device_retrace")[-1]
            with open(os.path.join(fo["path"], "bundle.json")) as f:
                doc = json.load(f)
            assert doc["trigger"]["reason"] == "device_retrace"
            assert doc["trigger"]["detail"]["event"] == "DEVICE_RETRACE"
            # the attribution carries the namespace and signature delta
            # the operator triages from (docs/Operations.md)
            assert "namespace" in doc["trigger"]["detail"]
            assert "signature_delta" in doc["trigger"]["detail"]

            # the whole time: a telemetry event, not an availability
            # event — routing reconverged through node-1
            await wait_until(converged, timeout_s=CONVERGENCE_S)
            assert _counter("decision.solver.degraded") == 0
        finally:
            registry.clear()
            for mon in mons.values():
                with contextlib.suppress(Exception):
                    await mon.stop()
            await stop_all(nodes)


class TestWarmCacheRestartDrill:
    @run_async
    async def test_decision_restart_mid_churn_recovers_without_compile(self):
        """ISSUE 20 drill: a Decision restart mid-churn with a warm AOT
        cache must recover WITHOUT recompiling — every executable the
        reconvergence dispatches is deserialized from disk. The cold
        generation converges and absorbs a link flap (populating the
        cache), then the whole stack is stopped mid-churn and the
        in-memory half of a process restart is simulated
        (clear_all_jit_caches + jax.clear_caches); a fresh generation
        on the same disk cache must reconverge with zero in-scope XLA
        compiles, zero cache misses, and no sentinel events."""
        import shutil
        import tempfile

        import jax

        from openr_tpu.ops.xla_cache import (
            baker,
            clear_all_jit_caches,
            configure_aot,
            retrace,
        )

        registry.clear()
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        dcfg = DecisionConfig(debounce_min_ms=5, debounce_max_ms=25)
        cache_dir = tempfile.mkdtemp(prefix="openr-tpu-aot-drill-")
        aot = configure_aot(cache_dir)
        aot.reset_stats()
        baker.reset()
        # the cold generation's compiles are warmup, not retraces
        clear_all_jit_caches()
        retrace.reset()

        def converged(nodes):
            def check():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(3) if j != i}
                    if set(nodes[n].fib_routes) != expect:
                        return False
                return True

            return check

        nodes = {}
        try:
            # -- cold generation: converge + flap = cache population
            mesh, nodes = await start_mesh(
                names, links, solver_backend="tpu", decision_config=dcfg
            )
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(converged(nodes), timeout_s=CONVERGENCE_S)
            # churn: cut a link and reconverge through the long way
            mesh.disconnect("node-0", "if-02", "node-2", "if-20")

            def rerouted_via_b():
                entry = nodes["node-0"].fib_routes.get(loopback(2))
                return entry is not None and {
                    nh.neighbor_node_name for nh in entry.nexthops
                } == {"node-1"}

            await wait_until(rerouted_via_b, timeout_s=CONVERGENCE_S)
            assert aot.summary()["writes"] >= 1, aot.summary()
            # mid-churn: fresh state is in flight when the stack dies
            nodes["node-2"].advertise_prefix("10.99.0.0/24")
            await stop_all(nodes)

            # -- the restart: drop every piece of in-memory compiled
            # state a process exit would drop; the disk cache survives
            clear_all_jit_caches()
            jax.clear_caches()
            retrace.reset()
            aot.reset_stats()
            pre = aot.preload()  # the aot_load boot phase
            assert pre["loaded"] >= 1, pre
            scoped0 = _counter("xla_cache.scoped_compiles")

            # -- warm generation: same fabric, same churn shape
            mesh, nodes = await start_mesh(
                names, links, solver_backend="tpu", decision_config=dcfg
            )
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(converged(nodes), timeout_s=CONVERGENCE_S)
            # supervised recovery keeps absorbing churn, still warm
            nodes["node-2"].advertise_prefix("10.99.0.0/24")
            await wait_until(
                lambda: "10.99.0.0/24" in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            await asyncio.sleep(0.3)  # let trailing rebuilds settle

            s = aot.summary()
            assert s["hits"] >= 1, s
            assert s["misses"] == 0, s  # every install came from disk
            assert s["hit_rate"] == 1.0, s
            # the sentinel's census proves no XLA compile fired inside
            # any solver scope, and nothing paged
            assert _counter("xla_cache.scoped_compiles") == scoped0
            snap = retrace.snapshot()
            assert sum(snap["retraces"].values()) == 0, snap
            assert snap["aot_installs"] >= 1, snap
        finally:
            registry.clear()
            await stop_all(nodes)
            configure_aot("off")
            retrace.reset()
            shutil.rmtree(cache_dir, ignore_errors=True)
