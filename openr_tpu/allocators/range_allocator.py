"""Distributed unique-index allocation over KvStore.

Role of the reference's openr/allocators/RangeAllocator{-inl.h,.h} (:22)
and PrefixAllocator.{h,cpp} (:35): claim a unique index from a range by
proposing a KvStore key `<prefix><idx>` valued with our node name; the
CRDT merge picks a single winner per key network-wide. Losing the merge
(another node's value survives) triggers a re-roll with backoff; holding
the key uncontested for a settle period confirms the claim. PrefixAllocator
derives the node's prefix from a seed prefix + the allocated index and
advertises it via a PrefixEvent (ref SEEDED mode).
"""

from __future__ import annotations

import hashlib
import logging
from typing import Callable, Optional

from openr_tpu.kvstore.kvstore import KvStore
from openr_tpu.messaging import RQueue, ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.throttle import ExponentialBackoff
from openr_tpu.types import (
    KeyValueRequest,
    KeyValueRequestType,
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixType,
    Publication,
    parse_prefix,
)

log = logging.getLogger(__name__)

ALLOC_PREFIX_MARKER = "allocprefix:"  # ref Constants::kPrefixAllocMarker


class RangeAllocator(Actor):
    """ref RangeAllocator.h:22."""

    def __init__(
        self,
        node_name: str,
        kvstore: KvStore,
        kvstore_updates_reader: RQueue,
        callback: Callable[[int], None],
        range_start: int,
        range_end: int,  # inclusive
        area: str = "0",
        key_marker: str = ALLOC_PREFIX_MARKER,
        settle_s: float = 0.1,
        backoff_initial_s: float = 0.02,
        backoff_max_s: float = 1.0,
    ):
        super().__init__(f"range-allocator:{node_name}")
        assert range_end >= range_start
        self.node_name = node_name
        self.kvstore = kvstore
        self._updates = kvstore_updates_reader
        self._callback = callback
        self.range_start = range_start
        self.range_end = range_end
        self.area = area
        self.key_marker = key_marker
        self.settle_s = settle_s
        self.my_value = node_name.encode()
        self.current_index: Optional[int] = None
        self.allocated_index: Optional[int] = None
        self._attempt = 0
        self._backoff = ExponentialBackoff(backoff_initial_s, backoff_max_s)
        self._settle_timer = None

    async def on_start(self) -> None:
        self._settle_timer = self.make_timer(self._on_settled)
        self.add_task(self._watch_loop(), name=f"{self.name}.watch")
        self._try_allocate()

    def _key(self, idx: int) -> str:
        return f"{self.key_marker}{idx}"

    def _pick_index(self) -> int:
        """Deterministic pseudo-random probe sequence per node
        (ref initial value hash of node name)."""
        span = self.range_end - self.range_start + 1
        h = hashlib.blake2b(
            f"{self.node_name}:{self._attempt}".encode(), digest_size=8
        )
        return self.range_start + int.from_bytes(h.digest(), "little") % span

    def _try_allocate(self) -> None:
        span = self.range_end - self.range_start + 1
        st = self.kvstore.areas[self.area]
        # probe from the hash position for a key not owned by someone else
        for probe in range(span):
            self._attempt += 1
            idx = self._pick_index()
            key = self._key(idx)
            existing = st.kv.get(key)
            if existing is not None and existing.value != self.my_value:
                continue  # taken by another node
            self.current_index = idx
            self.kvstore.process_key_value_request(
                KeyValueRequest(
                    request_type=KeyValueRequestType.PERSIST,
                    area=self.area,
                    key=key,
                    value=self.my_value,
                )
            )
            counters.increment("range_allocator.proposals")
            self._settle_timer.schedule(self.settle_s)
            return
        log.warning("%s: range exhausted; retrying with backoff", self.name)
        self._backoff.report_error()
        self.schedule(
            max(0.01, self._backoff.time_until_retry_s()), self._try_allocate
        )

    def _on_settled(self) -> None:
        """Held the key uncontested for settle_s: claim confirmed."""
        if self.current_index is None:
            return
        st = self.kvstore.areas[self.area]
        live = st.kv.get(self._key(self.current_index))
        if live is None or live.value != self.my_value:
            self._lost()
            return
        if self.allocated_index != self.current_index:
            self.allocated_index = self.current_index
            counters.increment("range_allocator.allocations")
            self._callback(self.allocated_index)

    def _lost(self) -> None:
        """Our claim was beaten — withdraw it and re-roll elsewhere
        (ref collision detection on merge). A proper CLEAR is required:
        KvStore's override protection has likely already re-persisted our
        value at a bumped version, and only a tombstone stops that ghost
        claim from winning network-wide and blocking the index."""
        if self.current_index is not None:
            self.kvstore.process_key_value_request(
                KeyValueRequest(
                    request_type=KeyValueRequestType.CLEAR,
                    area=self.area,
                    key=self._key(self.current_index),
                )
            )
        self.current_index = None
        if self.allocated_index is not None:
            self.allocated_index = None
        counters.increment("range_allocator.collisions")
        self._backoff.report_error()
        self.schedule(
            max(0.01, self._backoff.time_until_retry_s()), self._try_allocate
        )

    async def _watch_loop(self) -> None:
        while True:
            item = await self._updates.get()
            if not isinstance(item, Publication):
                continue
            if self.current_index is None:
                continue
            key = self._key(self.current_index)
            if key in item.expired_keys:
                continue  # our refresh defends it
            val = item.key_vals.get(key)
            if val is None or val.value is None:
                continue
            if val.value != self.my_value:
                self._lost()


class _LoopbackAddressMixin:
    """Shared 'write the derived address to the interface' behavior
    (ref PrefixAllocator applying the loopback address via netlink)."""

    loopback_iface: str = ""
    set_loopback_address: bool = False
    assigned_address: Optional[str] = None
    _addr_lock = None  # serializes assign/remove (rapid reassignments)

    def _address_lock(self):
        import asyncio as _asyncio

        if self._addr_lock is None:
            self._addr_lock = _asyncio.Lock()
        return self._addr_lock

    def _maybe_assign_address(self, allocated_prefix: str) -> None:
        if not (self.set_loopback_address and self.loopback_iface):
            return
        self.add_task(
            self._assign_address(allocated_prefix),
            name=f"{self.name}.assign-addr",
        )

    def _maybe_remove_address(self) -> None:
        """Withdrawal: the prefix (and its derived address) now belongs
        to nobody or to another node — answering on it would be an
        address conflict."""
        if not (self.set_loopback_address and self.loopback_iface):
            return
        self.add_task(
            self._remove_address(), name=f"{self.name}.remove-addr"
        )

    async def _remove_address(self) -> None:
        import socket as _socket

        from openr_tpu.platform.netlink import NetlinkRouteSocket

        import errno as _errno

        async with self._address_lock():
            if not self.assigned_address:
                return
            try:
                # an interface that no longer exists took its addresses
                # with it — the removal goal is already met
                ifindex = _socket.if_nametoindex(self.loopback_iface)
            except OSError:
                self.assigned_address = None
                return
            nl = NetlinkRouteSocket()
            try:
                nl.open()
                await nl.del_addr(ifindex, self.assigned_address)
                log.info(
                    "%s: removed %s from %s",
                    self.name, self.assigned_address, self.loopback_iface,
                )
            except OSError as e:
                # ENOENT/EADDRNOTAVAIL = already gone, which is the goal;
                # anything else means the conflicting address is STILL
                # INSTALLED — keep assigned_address so a later removal can
                # retry, and say so
                if e.errno not in (_errno.ENOENT, _errno.EADDRNOTAVAIL):
                    log.warning(
                        "%s: failed to remove %s from %s (%s) — address "
                        "remains installed",
                        self.name, self.assigned_address,
                        self.loopback_iface, e,
                    )
                    return
            finally:
                nl.close()
            self.assigned_address = None

    async def _assign_address(self, allocated_prefix: str) -> None:
        """Best-effort: install the allocation's first host address on
        the loopback interface, REMOVING the previous allocation's
        address first — a lost index now belongs to another node, and
        answering for its prefix would be an address conflict (ref
        PrefixAllocator.cpp syncIfaceAddrs removes stale addrs).
        Needs CAP_NET_ADMIN; failure logs and moves on — advertising the
        prefix does not depend on the local address."""
        import socket as _socket

        from openr_tpu.platform.netlink import NetlinkRouteSocket

        net = parse_prefix(allocated_prefix)
        host = net.network_address + (1 if net.num_addresses > 1 else 0)
        addr = f"{host}/{net.prefixlen}"
        async with self._address_lock():
            nl = NetlinkRouteSocket()
            try:
                nl.open()
                ifindex = _socket.if_nametoindex(self.loopback_iface)
                if self.assigned_address and self.assigned_address != addr:
                    try:
                        await nl.del_addr(ifindex, self.assigned_address)
                    except OSError:
                        pass  # already gone
                await nl.add_addr(ifindex, addr)
                self.assigned_address = addr
                log.info(
                    "%s: assigned %s to %s",
                    self.name, addr, self.loopback_iface,
                )
            except OSError as e:
                log.warning(
                    "%s: could not assign %s to %s: %s",
                    self.name, addr, self.loopback_iface, e,
                )
            finally:
                nl.close()


class PrefixAllocator(_LoopbackAddressMixin, Actor):
    """Derive the node's prefix from (seed prefix, allocated index) and
    advertise it (ref PrefixAllocator.h:35, SEEDED mode)."""

    def __init__(
        self,
        node_name: str,
        kvstore: KvStore,
        kvstore_updates_reader: RQueue,
        prefix_updates_queue: ReplicateQueue,
        seed_prefix: str,
        allocate_prefix_len: int,
        area: str = "0",
        loopback_iface: str = "",
        set_loopback_address: bool = False,
        **allocator_kwargs,
    ):
        super().__init__(f"prefix-allocator:{node_name}")
        self.node_name = node_name
        self.seed = parse_prefix(seed_prefix)
        self.alloc_len = allocate_prefix_len
        assert self.seed.prefixlen < self.alloc_len <= self.seed.max_prefixlen, (
            f"allocation length must be in ({self.seed.prefixlen}, "
            f"{self.seed.max_prefixlen}]"
        )
        n_subnets = 1 << (self.alloc_len - self.seed.prefixlen)
        self._prefix_q = prefix_updates_queue
        self.allocated_prefix: Optional[str] = None
        self.loopback_iface = loopback_iface
        self.set_loopback_address = set_loopback_address
        self.range_allocator = RangeAllocator(
            node_name,
            kvstore,
            kvstore_updates_reader,
            self._on_allocated,
            range_start=0,
            range_end=n_subnets - 1,
            area=area,
            **allocator_kwargs,
        )

    async def on_start(self) -> None:
        await self.range_allocator.start()

    async def on_stop(self) -> None:
        await self.range_allocator.stop()

    def _on_allocated(self, index: int) -> None:
        subnet_bits = self.alloc_len - self.seed.prefixlen
        host_bits = self.seed.max_prefixlen - self.alloc_len
        base = int(self.seed.network_address)
        addr = base + (index << host_bits)
        net = parse_prefix(
            f"{self.seed.network_address.__class__(addr)}/{self.alloc_len}"
        )
        self.allocated_prefix = str(net)
        log.info(
            "%s: allocated index %d -> %s (of %d subnets)",
            self.name,
            index,
            self.allocated_prefix,
            1 << subnet_bits,
        )
        self._prefix_q.push(
            PrefixEvent(
                event_type=PrefixEventType.SYNC_PREFIXES_BY_TYPE,
                type=PrefixType.PREFIX_ALLOCATOR,
                prefixes=[
                    PrefixEntry(
                        prefix=self.allocated_prefix,
                        type=PrefixType.PREFIX_ALLOCATOR,
                    )
                ],
            )
        )
        self._maybe_assign_address(self.allocated_prefix)
        counters.increment("prefix_allocator.allocations")


STATIC_ALLOC_KEY = "e2e-network-allocations"  # ref kStaticPrefixAllocParamKey


class StaticPrefixAllocator(_LoopbackAddressMixin, Actor):
    """STATIC allocation mode (ref PrefixAllocator.h:88-101
    staticAllocation / processStaticPrefixAllocUpdate): a central
    controller publishes the `e2e-network-allocations` KvStore key —
    JSON {node_name: prefix} — and each node advertises (and optionally
    installs) whatever the controller assigned it. Changes re-sync; a
    removed assignment withdraws."""

    def __init__(
        self,
        node_name: str,
        kvstore: KvStore,
        kvstore_updates_reader: RQueue,
        prefix_updates_queue: ReplicateQueue,
        area: str = "0",
        loopback_iface: str = "",
        set_loopback_address: bool = False,
    ):
        super().__init__(f"static-prefix-allocator:{node_name}")
        self.node_name = node_name
        self.kvstore = kvstore
        self._updates = kvstore_updates_reader
        self._prefix_q = prefix_updates_queue
        self.area = area
        self.allocated_prefix: Optional[str] = None
        self.loopback_iface = loopback_iface
        self.set_loopback_address = set_loopback_address

    async def on_start(self) -> None:
        # initial read: the key may predate us
        vals = await self.kvstore.get_key_vals(
            self.area, [STATIC_ALLOC_KEY]
        )
        val = vals.get(STATIC_ALLOC_KEY)
        if val is not None:
            self._apply(val.value)
        self.add_task(self._watch(), name=f"{self.name}.watch")

    async def _watch(self) -> None:
        while True:
            pub = await self._updates.get()
            if not isinstance(pub, Publication) or pub.area != self.area:
                continue
            val = pub.key_vals.get(STATIC_ALLOC_KEY)
            if val is not None:
                # ttl-only refreshes carry value=None (engine merge
                # update_ttl) — they are NOT withdrawals
                if val.value is not None:
                    self._apply(val.value)
            elif STATIC_ALLOC_KEY in pub.expired_keys:
                self._apply(None)

    def _apply(self, raw: Optional[bytes]) -> None:
        import json

        assigned: Optional[str] = None
        if raw:
            try:
                allocations = json.loads(raw)
                assigned = allocations.get(self.node_name)
                if assigned is not None:
                    assigned = str(parse_prefix(assigned))
            except (ValueError, TypeError, AttributeError):
                log.warning(
                    "%s: malformed %s payload", self.name, STATIC_ALLOC_KEY
                )
                return  # keep the last good assignment
        if assigned == self.allocated_prefix:
            return
        self.allocated_prefix = assigned
        entries = (
            [
                PrefixEntry(
                    prefix=assigned, type=PrefixType.PREFIX_ALLOCATOR
                )
            ]
            if assigned
            else []
        )
        self._prefix_q.push(
            PrefixEvent(
                event_type=PrefixEventType.SYNC_PREFIXES_BY_TYPE,
                type=PrefixType.PREFIX_ALLOCATOR,
                prefixes=entries,
            )
        )
        if assigned:
            log.info("%s: static allocation %s", self.name, assigned)
            self._maybe_assign_address(assigned)
            counters.increment("prefix_allocator.static_allocations")
        else:
            log.info("%s: static allocation withdrawn", self.name)
            self._maybe_remove_address()
