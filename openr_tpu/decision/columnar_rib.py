"""Columnar, lazily-materialized RIB.

BENCH_r05 showed a cold 100k-prefix rebuild spends 70% of its wall time
constructing `RibUnicastEntry` Python objects in `_build_entries` — for
routes most consumers never look at individually. This module keeps the
solver's packed device outputs (metric, selected-announcer words,
next-hop words, LFA slots) as numpy COLUMNS keyed by prefix-matrix row,
and builds entry objects only at consumption boundaries:

  - the Fib unicast diff (`fast_unicast_diff`): a journal of changed
    row-sets turns the diff into compare-only-what-the-device-says-
    changed — O(changed) entry builds instead of O(P);
  - `RibPolicy.apply_policy` / RPC serialization / CLI dumps: these
    iterate the mapping, which materializes in one bulk pass.

Three cooperating pieces:

  `ColumnarRib`   one (area, vantage)'s live column store. Mutated in
                  place by the solver (full scatter on cold rebuild,
                  row patches on steady-state deltas). Copy-on-write:
                  before a mutation, the column bundle is copied iff a
                  live `RibView` still references it, so snapshots stay
                  valid at ~2 MB/flap cost.
  `RibView`       an immutable snapshot (cols bundle + epoch) of a
                  ColumnarRib. A CURRENT view delegates to the crib's
                  shared materialization cache; a STALE view rebuilds
                  rows on demand from its retained bundle.
  `LazyUnicastRoutes`
                  the MutableMapping that DecisionRouteDb carries:
                  host-built `base` routes shadowed by per-area views,
                  with `overrides`/`deleted` capturing post-build
                  mutations (statics, RibPolicy edits) without forcing.

Entry identity is preserved exactly: `build_entries` below is the
former `tpu_solver._build_entries` loop, moved verbatim so columnar and
eager materialization are byte-identical (asserted by the property test
in tests/test_columnar_rib.py).
"""

from __future__ import annotations

import weakref
from collections.abc import MutableMapping
from typing import Optional

import numpy as np

from openr_tpu.decision.rib import NextHop, RibUnicastEntry
from openr_tpu.decision.spf_solver import select_best_node_area
from openr_tpu.ops.edgeplan import INF32E
from openr_tpu.runtime.counters import counters

INF_E = int(INF32E)
_entry_new = object.__new__

# journal records retained per crib; an older snapshot falls back to the
# full per-entry compare (bounded memory, not bounded correctness)
_JOURNAL_MAX = 256


# fields the fast-construction loop in build_entries always sets itself
_ENTRY_SET_FIELDS = frozenset(
    {
        "prefix", "nexthops", "best_prefix_entry", "best_node_area",
        "igp_cost", "lfa_nexthops",
    }
)


def _entry_defaults() -> tuple[dict, list]:
    """(plain defaults, per-entry default factories) of RibUnicastEntry,
    derived from the dataclass itself so the fast constructor below
    cannot silently desynchronize when a defaulted field is added to the
    schema. Factory-defaulted fields the loop does not overwrite are
    CALLED PER ENTRY — sharing one factory product across all entries
    would alias a future mutable default."""
    import dataclasses

    plain = {}
    factories = []
    for f in dataclasses.fields(RibUnicastEntry):
        if f.default is not dataclasses.MISSING:
            plain[f.name] = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
            if f.name in _ENTRY_SET_FIELDS:
                plain[f.name] = None  # placeholder; always overwritten
            else:
                factories.append((f.name, f.default_factory))  # type: ignore[misc]
    return plain, factories


_ENTRY_DEFAULTS, _ENTRY_FACTORIES = _entry_defaults()


def unpack_words(words: np.ndarray, x: int) -> np.ndarray:
    """host inverse of the device's _pack_words: int32 [R, W] -> bool
    [R, x].

    Bit extraction runs through np.unpackbits over the low two bytes of
    each little-endian word (C speed) — the shift-and-mask formulation
    materialized a [R, W, 16] int32 temporary and cost ~0.3s per 100k-row
    full pull."""
    r, wn = words.shape
    if r == 0 or wn == 0:
        return np.zeros((r, x), bool)
    low2 = (
        np.ascontiguousarray(words.astype("<i4"))
        .view(np.uint8)
        .reshape(r, wn, 4)[:, :, :2]
    )
    bits = np.unpackbits(
        np.ascontiguousarray(low2).reshape(r, wn * 2),
        axis=1,
        bitorder="little",
    )
    return bits[:, :x].astype(bool)


def pack_words_host(bits: np.ndarray) -> np.ndarray:
    """host companion of the device's _pack_words: bool [R, x] -> int32
    [R, ceil(x/16)], 16 bits per little-endian word. Used by the sharded
    fabric path, whose kernel returns unpacked masks."""
    r, x = bits.shape
    w = -(-max(x, 1) // 16)
    pad = w * 16 - x
    if pad:
        bits = np.concatenate([bits, np.zeros((r, pad), bool)], axis=1)
    by = np.packbits(
        bits.astype(np.uint8), axis=1, bitorder="little"
    )  # [R, 2w]
    out = np.zeros((r, w, 4), np.uint8)
    out[:, :, :2] = by.reshape(r, w, 2)
    return np.ascontiguousarray(out).view("<i4").reshape(r, w).astype(np.int32)


def route_ok_rows(matrix, root_idx: int, rows, met, s3, nh,
                  block_v4: bool) -> np.ndarray:
    """Vectorized route-level filter (the host mirror of the device ok
    predicate in tpu_solver._plan_pipeline). met/s3/nh are indexed
    0..len(rows); `rows` (array or slice) indexes the matrix arrays."""
    ok = s3.any(axis=1) & (met < INF_E)
    if block_v4:
        ok &= ~matrix.is_v4[rows]
    ok &= ~(s3 & (matrix.ann_node[rows] == root_idx)).any(axis=1)
    eff_min = np.where(s3, matrix.min_nexthop[rows], -1).max(axis=1)
    nh_count = nh.sum(axis=1)
    ok &= (eff_min <= nh_count) & (nh_count > 0)
    return ok


def build_entries(
    routes: dict, nh_cache: dict, my_node_name: str, matrix, links, rows,
    met, s3, nh, lfa_slot=None, lfa_metric=None, value_rows=None,
    use_v4_allowed: bool = True,
) -> None:
    """Construct RibUnicastEntry for the given matrix rows into `routes`.
    met/s3/nh (and lfa arrays) are indexed by value_rows (delta path) or
    by matrix row (full)."""
    node_areas = matrix.node_areas
    entry_refs = matrix.entry_refs
    prefix_list = matrix.prefix_list
    # row data as Python lists / flat bytes: the loop below runs for
    # every changed route (all ~100k on a cold rebuild) and per-row
    # numpy scalar indexing costs ~10x a list index
    nh_bytes = np.packbits(nh, axis=1).tobytes()
    nh_stride = -(-nh.shape[1] // 8) if len(rows) else 1
    rows_l = rows.tolist()
    vi_l = value_rows.tolist() if value_rows is not None else rows_l
    met_l = met.tolist()
    s3_l = s3.tolist()
    nh_l = nh.tolist()
    lfa_slot_l = lfa_slot.tolist() if lfa_slot is not None else None
    lfa_metric_l = lfa_metric.tolist() if lfa_metric is not None else None
    no_lfa = frozenset()
    n_links = len(links)
    # family-aware next-hop addresses (ref createNextHop): v4
    # prefixes take the link's v4 address unless v4-over-v6 is on.
    # Sliced by row — the delta path calls this for a handful of
    # rows and must not pay an O(P) conversion.
    v4_rows_l = matrix.is_v4[rows].tolist()
    built = 0
    for i, p in enumerate(rows_l):
        vi = vi_l[i]
        row = s3_l[vi]
        nas = node_areas[p]
        sel = [(a, na) for a, na in enumerate(nas) if row[a]]
        if not sel:
            continue
        m = met_l[vi]
        use_v4 = use_v4_allowed and v4_rows_l[i]
        key = (nh_bytes[vi * nh_stride:(vi + 1) * nh_stride], m, use_v4)
        nexthops = nh_cache.get(key)
        if nexthops is None:
            nh_row = nh_l[vi]
            nexthops = frozenset(
                NextHop(
                    address=links[d].nh_from_node(my_node_name, use_v4),
                    if_name=links[d].iface_from_node(my_node_name),
                    metric=m,
                    area=links[d].area,
                    neighbor_node_name=links[d].other_node(my_node_name),
                )
                for d in range(n_links)
                if nh_row[d]
            )
            nh_cache[key] = nexthops
        lfa_nexthops = no_lfa
        if lfa_slot_l is not None:
            d = lfa_slot_l[vi]
            if 0 <= d < n_links:
                alt_m = lfa_metric_l[vi]
                lkey = ("lfa", d, alt_m, use_v4)
                lfa_nexthops = nh_cache.get(lkey)
                if lfa_nexthops is None:
                    lfa_nexthops = frozenset({
                        NextHop(
                            address=links[d].nh_from_node(
                                my_node_name, use_v4
                            ),
                            if_name=links[d].iface_from_node(my_node_name),
                            metric=alt_m,
                            area=links[d].area,
                            neighbor_node_name=links[d].other_node(
                                my_node_name
                            ),
                        )
                    })
                    nh_cache[lkey] = lfa_nexthops
        if len(sel) == 1:
            ba, best = sel[0]
        else:
            best = select_best_node_area(
                {na for _, na in sel}, my_node_name
            )
            ba = next(a for a, na in sel if na == best)
        prefix = prefix_list[p]
        # bypass the dataclass __init__ (per-field object.__setattr__
        # x9) — this loop constructs one entry per route on a cold
        # 100k rebuild; equality/hash read the same attributes either
        # way, and unset fields come from the schema-derived defaults
        entry = _entry_new(RibUnicastEntry)
        d = dict(_ENTRY_DEFAULTS)
        for fname, factory in _ENTRY_FACTORIES:
            d[fname] = factory()
        d["prefix"] = prefix
        d["nexthops"] = nexthops
        d["best_prefix_entry"] = entry_refs[p][ba]
        d["best_node_area"] = best
        d["igp_cost"] = m
        d["lfa_nexthops"] = lfa_nexthops
        entry.__dict__.update(d)
        routes[prefix] = entry
        built += 1
    if built:
        # the zero-objects gate for the columnar spine: any hot path
        # that claims to stay in packed-array land is asserted against
        # this counter standing still
        counters.increment("decision.rib.entries_built", built)


class _Cols:
    """One generation of the packed columns. Treated as immutable once a
    RibView references it (ColumnarRib copies-on-write before mutating a
    referenced bundle)."""

    __slots__ = (
        "met", "s3w", "nhw", "lfa_slot", "lfa_metric", "ok",
        "_key_rows", "_row_of",
    )

    def __init__(self):
        self.met = self.s3w = self.nhw = None
        self.lfa_slot = self.lfa_metric = None
        self.ok = None
        self._key_rows = None  # cached np.flatnonzero(ok)
        self._row_of = None  # cached prefix -> row for ok rows

    def copy(self) -> "_Cols":
        c = _Cols()
        c.met = self.met.copy()
        c.s3w = self.s3w.copy()
        c.nhw = self.nhw.copy()
        if self.lfa_slot is not None:
            c.lfa_slot = self.lfa_slot.copy()
            c.lfa_metric = self.lfa_metric.copy()
        c.ok = self.ok.copy()
        return c

    def key_rows(self) -> np.ndarray:
        if self._key_rows is None:
            self._key_rows = np.flatnonzero(self.ok)
        return self._key_rows


class ColumnarRib:
    """One (area, vantage)'s packed route columns + shared entry cache.

    The solver mutates this in place: `set_full_packed` on a cold
    rebuild (device-compacted ok rows scattered into fresh columns),
    `apply_rows` on steady-state deltas. Every mutation bumps `epoch`
    and journals the changed row set so two RibView snapshots of the
    same crib can diff in O(changed)."""

    def __init__(self, my_node_name: str, matrix, links, root_idx: int,
                 block_v4: bool, use_v4_allowed: bool, lfa: bool):
        self.my_node_name = my_node_name
        self.matrix = matrix
        self.links = links
        self.root_idx = int(root_idx)
        self.block_v4 = block_v4
        self.use_v4_allowed = use_v4_allowed
        self.lfa = lfa
        self.p_n = len(matrix.prefix_list)
        self.cols: Optional[_Cols] = None
        self.epoch = 0
        # oldest epoch the journal can still diff against; reset by
        # set_full_packed and by journal trimming
        self.journal_floor = 0
        # (epoch, rows, exact): `exact` marks a device-exact entry —
        # the row set IS the set of rows whose columns differ from the
        # previous epoch (the streaming pipeline's on-device diff), not
        # a superset a consumer must re-compare
        self.journal: list[tuple[int, np.ndarray, bool]] = []
        self.routes: dict[str, RibUnicastEntry] = {}
        # routes is COMPLETE iff materialized; otherwise it is a partial
        # per-row cache (invalidated row-wise by apply_rows)
        self.materialized = False
        self.nh_cache: dict = {}
        self._views: "weakref.WeakSet[RibView]" = weakref.WeakSet()

    # -- mutation (solver side) -------------------------------------------

    def _cow(self) -> None:
        """Copy the column bundle iff a live view still references it, so
        that view's snapshot survives the coming in-place mutation."""
        c = self.cols
        if c is None:
            return
        if any(v.cols is c for v in self._views):
            self.cols = c.copy()
        else:
            # in-place mutation: the derived caches go stale
            c._key_rows = None
            c._row_of = None

    def set_full_packed(self, rows: np.ndarray, met, s3w, nhw,
                        lfa_slot=None, lfa_metric=None) -> None:
        """Cold rebuild from the device-compacted full buffer: `rows` are
        the ok matrix rows (ascending), the value arrays their gathered
        packed outputs. Non-ok rows keep zero columns — nothing reads
        them (ok=False removes them from every view)."""
        p_n = self.p_n
        keep = rows < p_n
        rows = rows[keep]
        c = _Cols()
        c.met = np.zeros(p_n, np.int32)
        c.s3w = np.zeros((p_n, s3w.shape[1]), np.int32)
        c.nhw = np.zeros((p_n, nhw.shape[1]), np.int32)
        c.met[rows] = met[keep]
        c.s3w[rows] = s3w[keep]
        c.nhw[rows] = nhw[keep]
        if lfa_slot is not None:
            c.lfa_slot = np.full(p_n, -1, np.int32)
            c.lfa_metric = np.zeros(p_n, np.int32)
            c.lfa_slot[rows] = lfa_slot[keep]
            c.lfa_metric[rows] = lfa_metric[keep]
        c.ok = np.zeros(p_n, bool)
        c.ok[rows] = True
        self.cols = c  # old bundle stays with whatever views hold it
        self.epoch += 1
        self.journal_floor = self.epoch
        self.journal = []
        self.routes = {}
        self.materialized = False

    def set_full_arrays(self, met, s3, nh, lfa_slot=None, lfa_metric=None,
                        ok=None) -> None:
        """Cold rebuild from UNPACKED arrays (the sharded fabric path,
        whose kernel returns bool masks + a device-computed ok)."""
        if ok is None:
            ok = route_ok_rows(
                self.matrix, self.root_idx, slice(0, self.p_n),
                met, s3, nh, self.block_v4,
            )
        rows = np.flatnonzero(ok)
        self.set_full_packed(
            rows, met[rows].astype(np.int32),
            pack_words_host(s3[rows]), pack_words_host(nh[rows]),
            None if lfa_slot is None else lfa_slot[rows].astype(np.int32),
            None if lfa_metric is None else lfa_metric[rows].astype(np.int32),
        )

    def apply_rows(self, rows: np.ndarray, met, s3w, nhw,
                   lfa_slot=None, lfa_metric=None, ok=None,
                   exact: bool = False) -> None:
        """Steady-state delta: patch the changed rows in place (after
        copy-on-write if a snapshot is watching). When `ok` is None
        (classic delta payload) the route-level filter is recomputed
        host-side, which costs an unpack of both word planes; a caller
        holding the device route-ok bit (apply_rows_packed) passes it
        in and the unpack only happens if the eager route cache needs
        the masks."""
        rows = np.asarray(rows)
        live = rows < self.p_n
        if not live.all():
            rows = rows[live]
            met = met[live]
            s3w = s3w[live]
            nhw = nhw[live]
            if ok is not None:
                ok = ok[live]
            if lfa_slot is not None:
                lfa_slot = lfa_slot[live]
                lfa_metric = lfa_metric[live]
        if not len(rows):
            return
        self._cow()
        c = self.cols
        a_cap = self.matrix.ann_node.shape[1]
        d_n = len(self.links)
        s3 = nhm = None
        if ok is None:
            s3 = unpack_words(s3w, a_cap)
            nhm = unpack_words(nhw, max(d_n, 1))
            ok = route_ok_rows(
                self.matrix, self.root_idx, rows, met, s3, nhm,
                self.block_v4,
            )
        c.met[rows] = met
        c.s3w[rows] = s3w
        c.nhw[rows] = nhw
        if lfa_slot is not None and c.lfa_slot is not None:
            c.lfa_slot[rows] = lfa_slot
            c.lfa_metric[rows] = lfa_metric
        c.ok[rows] = ok
        c._key_rows = None
        c._row_of = None
        self.epoch += 1
        self.journal.append((self.epoch, np.asarray(rows), exact))
        if len(self.journal) > _JOURNAL_MAX:
            dropped_epoch, _, _ = self.journal.pop(0)
            self.journal_floor = dropped_epoch
        # keep the route cache coherent: eager patch when complete
        # (preserves the seed's O(changed) steady-state cost), row-wise
        # invalidation when partial
        plist = self.matrix.prefix_list
        if self.materialized:
            if s3 is None:
                s3 = unpack_words(s3w, a_cap)
                nhm = unpack_words(nhw, max(d_n, 1))
            for i, r in enumerate(rows.tolist()):
                if not ok[i]:
                    self.routes.pop(plist[r], None)
            keep = np.flatnonzero(ok)
            if len(keep):
                build_entries(
                    self.routes, self.nh_cache, self.my_node_name,
                    self.matrix, self.links, rows[keep], met, s3, nhm,
                    lfa_slot, lfa_metric, value_rows=keep,
                    use_v4_allowed=self.use_v4_allowed,
                )
        elif self.routes:
            for r in rows.tolist():
                self.routes.pop(plist[r], None)

    def apply_rows_packed(self, rows: np.ndarray, met, s3w, nhw, ok,
                          lfa_slot=None, lfa_metric=None) -> None:
        """Streaming-epoch delta (ops/stream.py payload): the device
        route-ok bit arrives with the rows, so the patch is pure column
        writes — no host word-unpack, no route_ok_rows recompute — and
        the journal entry is device-exact: the row set is EXACTLY the
        rows whose columns differ from the previous epoch, which lets
        fast_unicast_column_diff skip its re-compare (exact_since)."""
        self.apply_rows(
            rows, met, s3w, nhw, lfa_slot, lfa_metric,
            ok=np.asarray(ok, bool), exact=True,
        )

    # -- reads (view side) -------------------------------------------------

    def covers(self, epoch: int) -> bool:
        return epoch >= self.journal_floor

    def changed_rows_since(self, epoch: int) -> np.ndarray:
        parts = [r for e, r, _x in self.journal if e > epoch]
        if not parts:
            return np.zeros(0, np.int64)
        return np.unique(np.concatenate(parts))

    def exact_since(self, epoch: int) -> bool:
        """True iff the journal from `epoch` to the tip is ONE
        device-exact entry — the streaming steady state, one epoch per
        solve. The on-device diff is exact against the IMMEDIATELY
        preceding epoch only: across several epochs the union may hold
        rows that changed and changed back, which only a host
        re-compare filters out. When this holds,
        fast_unicast_column_diff consumes changed_rows_since verbatim
        instead of re-comparing the columns."""
        entries = [x for e, _r, x in self.journal if e > epoch]
        return len(entries) == 1 and entries[0]

    def _build_rows_into(self, cols: _Cols, rows: np.ndarray,
                         routes: dict) -> None:
        a_cap = self.matrix.ann_node.shape[1]
        d_n = len(self.links)
        build_entries(
            routes, self.nh_cache, self.my_node_name, self.matrix,
            self.links, rows,
            cols.met[rows],
            unpack_words(cols.s3w[rows], a_cap),
            unpack_words(cols.nhw[rows], max(d_n, 1)),
            None if cols.lfa_slot is None else cols.lfa_slot[rows],
            None if cols.lfa_metric is None else cols.lfa_metric[rows],
            value_rows=np.arange(len(rows)),
            use_v4_allowed=self.use_v4_allowed,
        )

    def materialize(self) -> dict:
        """Bulk-build every ok row (the consumption-boundary path)."""
        if self.materialized:
            return self.routes
        import time as _time

        t0 = _time.perf_counter()
        self.routes = {}
        rows = self.cols.key_rows()
        if len(rows):
            self._build_rows_into(self.cols, rows, self.routes)
        self.materialized = True
        counters.add_stat_value(
            "decision.crib.materialize_ms",
            (_time.perf_counter() - t0) * 1e3,
        )
        return self.routes

    def entry_for_row(self, r: int, bulk: bool = False):
        prefix = self.matrix.prefix_list[r]
        e = self.routes.get(prefix)
        if e is None and not self.materialized:
            if bulk:
                self.materialize()
            else:
                self._build_rows_into(
                    self.cols, np.asarray([r]), self.routes
                )
            e = self.routes.get(prefix)
        return e

    def view(self) -> "RibView":
        return RibView(self)


class RibView:
    """Immutable snapshot of a ColumnarRib. Current (bundle identity
    matches the crib's) -> delegates to the crib's shared cache; stale
    -> rebuilds rows on demand from its own retained bundle."""

    __slots__ = ("crib", "cols", "epoch", "_routes", "_forced",
                 "__weakref__")

    def __init__(self, crib: ColumnarRib):
        self.crib = crib
        self.cols = crib.cols
        self.epoch = crib.epoch
        self._routes: Optional[dict] = None  # own build when stale
        self._forced = False
        crib._views.add(self)

    @property
    def current(self) -> bool:
        return self.cols is self.crib.cols

    def key_rows(self) -> np.ndarray:
        return self.cols.key_rows()

    def prefixes(self) -> list[str]:
        plist = self.crib.matrix.prefix_list
        return [plist[r] for r in self.key_rows().tolist()]

    def _row_of(self, prefix: str):
        c = self.cols
        if c._row_of is None:
            plist = self.crib.matrix.prefix_list
            c._row_of = {plist[r]: r for r in self.key_rows().tolist()}
        return c._row_of.get(prefix)

    def has(self, prefix: str) -> bool:
        return self._row_of(prefix) is not None

    def get(self, prefix: str, bulk: bool = True):
        r = self._row_of(prefix)
        if r is None:
            return None
        if self.current:
            return self.crib.entry_for_row(r, bulk=bulk)
        if self._routes is None:
            self._routes = {}
        e = self._routes.get(prefix)
        if e is None:
            if bulk and not self._forced:
                return self.all_routes().get(prefix)
            self.crib._build_rows_into(
                self.cols, np.asarray([r]), self._routes
            )
            e = self._routes.get(prefix)
        return e

    def all_routes(self) -> dict:
        if self.current:
            return self.crib.materialize()
        if not self._forced:
            routes = {}
            rows = self.key_rows()
            if len(rows):
                self.crib._build_rows_into(self.cols, rows, routes)
            self._routes = routes
            self._forced = True
        return self._routes


class LazyUnicastRoutes(MutableMapping):
    """DecisionRouteDb.unicast_routes when the device path ran: host
    `base` routes shadowed by per-area RibViews, with post-build
    mutations captured in overrides/deleted (so RibPolicy edits and
    static insertions neither force materialization nor break the
    journal diff — mutated keys simply join the diff's candidate set).

    Iteration/len/contains are cheap (ok-mask key sets); values force.
    Equality materializes both sides (dict == LazyUnicastRoutes works
    through the reflected __eq__)."""

    __slots__ = ("base", "segments", "overrides", "deleted",
                 "_merged", "_keys")

    def __init__(self, base=None, segments=()):
        self.base: dict = dict(base) if base else {}
        self.segments: list[RibView] = list(segments)  # later wins
        self.overrides: dict = {}
        self.deleted: set = set()
        self._merged: Optional[dict] = None  # full snapshot once forced
        self._keys: Optional[dict] = None

    # -- reads -------------------------------------------------------------

    def __getitem__(self, k):
        if self._merged is not None:
            return self._merged[k]
        if k in self.deleted:
            raise KeyError(k)
        if k in self.overrides:
            return self.overrides[k]
        for seg in reversed(self.segments):
            e = seg.get(k)
            if e is not None:
                return e
        return self.base[k]

    def __contains__(self, k):
        if self._merged is not None:
            return k in self._merged
        if k in self.deleted:
            return False
        if k in self.overrides:
            return True
        return any(seg.has(k) for seg in self.segments) or k in self.base

    def _key_set(self) -> dict:
        if self._keys is None:
            ks = dict.fromkeys(self.base)
            for seg in self.segments:
                ks.update(dict.fromkeys(seg.prefixes()))
            ks.update(dict.fromkeys(self.overrides))
            for k in self.deleted:
                ks.pop(k, None)
            self._keys = ks
        return self._keys

    def __iter__(self):
        if self._merged is not None:
            return iter(self._merged)
        return iter(self._key_set())

    def __len__(self):
        if self._merged is not None:
            return len(self._merged)
        return len(self._key_set())

    def snapshot(self) -> "LazyUnicastRoutes":
        """Detached copy sharing the column bundles: fresh RibViews pin
        the current generation (copy-on-write protects them from future
        solver patches) while host layers are shallow-copied. O(1) in
        routes — this is how the Fib actor swaps a 100k-route desired
        state without re-keying a dict."""
        segs = []
        for s in self.segments:
            v = RibView(s.crib)
            if v.cols is not s.cols:  # pin s's generation, not the tip
                v.cols = s.cols
                v.epoch = s.epoch
            segs.append(v)
        lz = LazyUnicastRoutes(self.base, segs)
        lz.overrides = dict(self.overrides)
        lz.deleted = set(self.deleted)
        return lz

    def materialized(self) -> dict:
        """Force: one bulk build per segment, then a flat snapshot."""
        if self._merged is None:
            m = dict(self.base)
            for seg in self.segments:
                m.update(seg.all_routes())
            m.update(self.overrides)
            for k in self.deleted:
                m.pop(k, None)
            self._merged = m
        return self._merged

    # -- mutation ----------------------------------------------------------

    def __setitem__(self, k, v):
        self.deleted.discard(k)
        self.overrides[k] = v
        if self._merged is not None:
            self._merged[k] = v
        self._keys = None

    def __delitem__(self, k):
        if k not in self:
            raise KeyError(k)
        self.overrides.pop(k, None)
        self.deleted.add(k)
        if self._merged is not None:
            self._merged.pop(k, None)
        self._keys = None

    # -- comparison --------------------------------------------------------

    def __eq__(self, other):
        if isinstance(other, LazyUnicastRoutes):
            other = other.materialized()
        if isinstance(other, dict):
            return self.materialized() == other
        return NotImplemented

    __hash__ = None  # type: ignore[assignment]

    def __repr__(self):
        n_seg = len(self.segments)
        return (
            f"LazyUnicastRoutes(len={len(self)}, segments={n_seg}, "
            f"base={len(self.base)}, overrides={len(self.overrides)})"
        )


def _lookup(lz: LazyUnicastRoutes, k):
    """Per-key resolution WITHOUT bulk-forcing a segment (the diff only
    touches O(changed) keys; a bulk build would defeat it)."""
    if lz._merged is not None:
        return lz._merged.get(k)
    if k in lz.deleted:
        return None
    v = lz.overrides.get(k)
    if v is not None:
        return v
    for seg in reversed(lz.segments):
        e = seg.get(k, bulk=False)
        if e is not None:
            return e
    return lz.base.get(k)


def fast_unicast_diff(old, new):
    """Vectorized unicast diff between two LazyUnicastRoutes built from
    the SAME cribs: the device already compared every row (the delta
    journal), so only journaled rows + host-touched keys (bases,
    overrides, deletions) need entry-level comparison. Returns
    (to_update dict, to_delete list) or None when ineligible — caller
    falls back to the full per-entry compare."""
    if not (
        isinstance(old, LazyUnicastRoutes)
        and isinstance(new, LazyUnicastRoutes)
    ):
        return None
    if len(old.segments) != len(new.segments):
        return None
    pairs = []
    for so, sn in zip(old.segments, new.segments):
        crib = sn.crib
        if so.crib is not crib:
            return None
        # the new side must be the crib's live tip (so unjournaled rows
        # are provably identical) and the old side within journal reach
        if sn.cols is not crib.cols or sn.epoch != crib.epoch:
            return None
        if not crib.covers(so.epoch):
            return None
        pairs.append((so, crib))

    candidates = (
        set(old.base) | set(new.base)
        | set(old.overrides) | set(new.overrides)
        | old.deleted | new.deleted
    )
    for so, crib in pairs:
        plist = crib.matrix.prefix_list
        p_n = crib.p_n
        for r in crib.changed_rows_since(so.epoch).tolist():
            if r < p_n:
                candidates.add(plist[r])

    to_update: dict = {}
    to_delete: list = []
    for k in candidates:
        nv = _lookup(new, k)
        ov = _lookup(old, k)
        if nv is None:
            if ov is not None:
                to_delete.append(k)
        elif ov is None or ov != nv:
            to_update[k] = nv
    to_delete.sort()
    return to_update, to_delete
