"""Exception-hygiene checker (`broad-except`).

The reference daemon's failure policy is "crash loudly, let the
supervisor restart you" — a swallowed exception is a routing bug that
presents as silence. Every bare `except:` / `except Exception` /
`except BaseException` handler must therefore do at least one of:

  - re-raise (a `raise` anywhere in the handler body — conditional
    re-raise after classification counts),
  - surface the failure on the metrics plane (`counters.increment`,
    `counters.set_counter`, `counters.add_stat_value`, or the
    `record_crash` helper),
  - carry a `# lint: allow(broad-except) <reason>` pragma (or a
    pre-existing `# noqa: BLE001 — reason`) explaining why swallowing
    is the right behavior (teardown paths, best-effort telemetry).

Catching specific exception types is always fine — this checker only
looks at the broad forms.
"""

from __future__ import annotations

import ast

from tools.lint.core import Finding, Project

CODE = "broad-except"

_BROAD = {"Exception", "BaseException"}
_COUNTER_METHODS = {"increment", "set_counter", "add_stat_value"}
_COUNTER_FUNCS = {"record_crash"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = []
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return bool(set(names) & _BROAD)


def _handler_complies(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _COUNTER_METHODS:
                return True
            if isinstance(fn, ast.Name) and fn.id in _COUNTER_FUNCS:
                return True
    return False


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handler_complies(node):
                continue
            caught = (
                "bare except" if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            findings.append(Finding(
                sf.rel, node.lineno, CODE, sf.scope_at(node.lineno),
                "handler",
                f"{caught} swallows without re-raise or counter — "
                f"re-raise, bump a counter, or pragma with a reason",
            ))
    return findings
