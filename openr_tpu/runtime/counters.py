"""Process-wide counters/stats fabric.

Role of fb303 (`fb303::fbData->addStatValue/setCounter`) which the
reference uses everywhere (e.g. decision.spf_ms LinkState.cpp:909,
kvstore thrift counters KvStore.cpp:3263). Flat singleton registry with
counters (set/increment) and stats (windowed sum/count/avg), exported via
the ctrl API and the monitor module.
"""

from __future__ import annotations

import bisect
import collections
import math
import threading
import time
from typing import Optional


class _Stat:
    __slots__ = ("samples",)

    def __init__(self):
        # (ts, value) ring: 4096 most-recent samples; windowed() filters by age
        self.samples: collections.deque = collections.deque(maxlen=4096)

    def add(self, value: float) -> None:
        self.samples.append((time.monotonic(), value))

    def windowed(self, window_s: float = 60.0) -> dict:
        cutoff = time.monotonic() - window_s
        vals = [v for ts, v in self.samples if ts >= cutoff]
        n = len(vals)
        return {
            "count": n,
            "sum": sum(vals),
            "avg": (sum(vals) / n) if n else 0.0,
            "max": max(vals) if vals else 0.0,
        }

    def multi_windowed(self, windows: tuple) -> dict:
        """One pass over the ring bucketing every sample into each
        window it falls in (60s samples are a subset of 600s etc.).
        A window is marked truncated when the ring's eviction horizon
        is newer than its cutoff — the ring holds the 4096 most-recent
        samples, so a high-rate stat cannot honor long windows and must
        SAY so rather than silently undercount."""
        return _aggregate_windows(
            list(self.samples), self.samples.maxlen, windows
        )


def _percentile(sorted_vals: list, q: float) -> float:
    """numpy-style linear interpolation (method="linear") so tests can
    compare against np.percentile bit-for-bit on the same samples."""
    n = len(sorted_vals)
    if not n:
        return 0.0
    idx = (q / 100.0) * (n - 1)
    lo, hi = math.floor(idx), math.ceil(idx)
    if lo == hi:
        return float(sorted_vals[lo])
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _aggregate_windows(samples: list, maxlen: int, windows: tuple) -> dict:
    now = time.monotonic()
    # samples arrive via time.monotonic() so the ring is time-ordered:
    # each window's members are a suffix, found by bisect on the ts
    # column; quantiles then sort just that suffix once per window
    ts_col = [ts for ts, _ in samples]
    vals = [v for _, v in samples]
    full = len(samples) == maxlen
    oldest = ts_col[0] if ts_col else now
    out = {}
    for w in sorted(windows):
        cutoff = now - w
        sub = vals[bisect.bisect_left(ts_col, cutoff):]
        n = len(sub)
        total = sum(sub)
        ordered = sorted(sub)
        out[str(int(w))] = {
            "count": n,
            "sum": total,
            # empty window reports 0.0 (matches windowed()); a window
            # of negative samples reports its true maximum
            "max": ordered[-1] if n else 0.0,
            "avg": (total / n) if n else 0.0,
            "p50": _percentile(ordered, 50.0),
            "p95": _percentile(ordered, 95.0),
            "p99": _percentile(ordered, 99.0),
            "truncated": full and oldest > cutoff,
        }
    return out


class CounterRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._stats: dict[str, _Stat] = {}

    def set_counter(self, key: str, value: float) -> None:
        with self._lock:
            self._counters[key] = value

    def increment(self, key: str, delta: float = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + delta

    def add_stat_value(self, key: str, value: float) -> None:
        with self._lock:
            st = self._stats.get(key)
            if st is None:
                st = self._stats[key] = _Stat()
            st.add(value)

    def get_counter(self, key: str) -> Optional[float]:
        # lock held: solver worker threads increment concurrently and a
        # dict resize mid-read is a torn view on some interpreters
        with self._lock:
            return self._counters.get(key)

    def get_statistics(
        self, prefix: str = "", windows: tuple = (60.0, 600.0, 3600.0)
    ) -> dict[str, dict]:
        """fb303-style multi-window stat view (ref breeze monitor
        statistics): per stat key, count/sum/avg/max over each window.
        Only the sample-ring snapshot happens under the registry lock —
        the aggregation runs outside it, so a statistics poll can't
        stall hot-path add_stat_value/increment calls mid-SPF."""
        with self._lock:
            snap = {
                k: (list(st.samples), st.samples.maxlen)
                for k, st in self._stats.items()
                if k.startswith(prefix)
            }
        return {
            k: _aggregate_windows(samples, maxlen, windows)
            for k, (samples, maxlen) in snap.items()
        }

    def export_snapshot(
        self, windows: tuple = (60.0, 600.0, 3600.0)
    ) -> tuple[dict[str, float], dict[str, dict]]:
        """One consistent (counters, stat-windows) view for exposition
        (runtime/metrics_export.py). Same locking discipline as
        get_statistics: only the ring copy happens under the lock."""
        with self._lock:
            counters_snap = dict(self._counters)
            stat_snap = {
                k: (list(st.samples), st.samples.maxlen)
                for k, st in self._stats.items()
            }
        stats = {
            k: _aggregate_windows(samples, maxlen, windows)
            for k, (samples, maxlen) in stat_snap.items()
        }
        return counters_snap, stats

    def raw_counters(self) -> dict[str, float]:
        """Plain-counter snapshot WITHOUT the windowed stat aggregation
        get_counters folds in — one dict copy under the lock. The cheap
        path for high-frequency samplers (flight-recorder ticks)."""
        with self._lock:
            return dict(self._counters)

    def get_counters(self, prefix: str = "") -> dict[str, float]:
        with self._lock:
            out = {k: v for k, v in self._counters.items() if k.startswith(prefix)}
            for k, st in self._stats.items():
                if k.startswith(prefix):
                    w = st.windowed()
                    out[f"{k}.avg.60"] = w["avg"]
                    out[f"{k}.count.60"] = w["count"]
                    out[f"{k}.sum.60"] = w["sum"]
            return out

    def erase(self, key: str) -> bool:
        """Drop one counter/stat. Returns whether anything existed —
        idempotent, so sweepers can erase speculatively."""
        with self._lock:
            had = self._counters.pop(key, None) is not None
            had = (self._stats.pop(key, None) is not None) or had
            return had

    def erase_prefix(self, prefix: str) -> int:
        """Drop every counter/stat under a prefix; returns the number
        erased. Callers own the trailing-dot discipline: pass
        "q.reader.r." (not "q.reader.r") so reader "r" never swallows
        reader "r2"'s gauges."""
        n = 0
        with self._lock:
            for table in (self._counters, self._stats):
                stale = [k for k in table if k.startswith(prefix)]
                for k in stale:
                    del table[k]
                n += len(stale)
        return n

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._stats.clear()


# the process-wide instance (role of fb303::fbData)
counters = CounterRegistry()
