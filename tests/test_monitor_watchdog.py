"""Monitor + Watchdog actor tests (ref openr/watchdog/Watchdog.h:28-51,
openr/monitor/MonitorBase.h:32)."""

import asyncio
import time

from openr_tpu.config import MonitorConfig, WatchdogConfig
from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.monitor import LogSample, Monitor, Watchdog
from tests.conftest import run_async


class TestMonitor:
    @run_async
    async def test_event_log_retention(self):
        q = ReplicateQueue("logSamples")
        mon = Monitor(
            "node1",
            MonitorConfig(max_event_log_entries=3),
            q.get_reader(),
            interval_s=0.05,
        )
        await mon.start()
        try:
            for i in range(5):
                q.push(LogSample(event=f"EVENT_{i}", node_name="node1"))
            await wait_until(lambda: len(mon.event_logs) == 3)
            logs = await mon.get_event_logs()
            # ring: only the last 3 retained
            assert '"event": "EVENT_4"' in logs[-1]
            assert all("EVENT_0" not in line for line in logs)
        finally:
            await mon.stop()

    @run_async
    async def test_process_gauges_exported(self):
        q = ReplicateQueue("logSamples")
        mon = Monitor("node1", MonitorConfig(), q.get_reader(), interval_s=0.02)
        await mon.start()
        try:
            await wait_until(
                lambda: counters.get_counter("process.memory.rss_mb") is not None
            )
            assert counters.get_counter("process.memory.rss_mb") > 0
            assert counters.get_counter("process.uptime_s") is not None
            # the live gauge and the high-water mark are distinct
            # counters; current can never (meaningfully) exceed peak
            max_rss = counters.get_counter("process.memory.max_rss_mb")
            assert max_rss is not None and max_rss > 0
            assert (
                counters.get_counter("process.memory.rss_mb")
                <= max_rss * 1.05
            )
        finally:
            await mon.stop()

    def test_current_rss_is_live_not_peak(self):
        """ru_maxrss is a high-water mark; the live gauge must come
        from /proc/self/statm and sit at or under the peak."""
        from openr_tpu.runtime.monitor import current_rss_mb, rss_mb

        cur, peak = current_rss_mb(), rss_mb()
        assert cur > 0 and peak > 0
        # small slop: the peak snapshot races the current read
        assert cur <= peak * 1.05, (cur, peak)


class TestWatchdog:
    @run_async
    async def test_fires_on_stalled_actor(self):
        fired = []
        wd = Watchdog(
            "node1",
            # ceiling high enough that suite-wide RSS can't trip it —
            # this test is about stall detection; the memory ceiling
            # has its own test below
            WatchdogConfig(interval_s=0.05, thread_timeout_s=0.2,
                           max_memory_mb=100_000),
            crash_handler=fired.append,
        )
        victim = Actor("victim")
        await victim.start()
        await wd.start()
        try:
            await asyncio.sleep(0.2)
            assert not fired  # healthy heartbeat
            wd.watch_actor(victim)
            # simulate a stall: stop the heartbeat task but keep watching
            await victim.stop()
            victim.last_alive_ts = time.monotonic() - 10
            await wait_until(lambda: fired, timeout_s=3)
            assert "victim" in fired[0]
            assert wd.fired is not None
        finally:
            await wd.stop()

    @run_async
    async def test_memory_ceiling(self):
        fired = []
        wd = Watchdog(
            "node1",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60, max_memory_mb=1),
            crash_handler=fired.append,
        )
        await wd.start()
        try:
            await wait_until(lambda: fired, timeout_s=3)
            assert "memory" in fired[0]
        finally:
            await wd.stop()

    @run_async
    async def test_queue_depth_counters(self):
        wd = Watchdog(
            "node1",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60,
                           max_memory_mb=100_000),
            crash_handler=lambda reason: None,
        )
        q = ReplicateQueue("testq")
        reader = q.get_reader("r")
        for _ in range(7):
            q.push(1)
        wd.watch_queue(q)
        await wd.start()
        try:
            await wait_until(
                lambda: counters.get_counter("messaging.queue.testq.max_depth")
                == 7
            )
            # per-reader visibility: a wedged reader (depth growing,
            # reads flat) must be observable from the counter fabric
            base = "messaging.queue.testq"
            assert counters.get_counter(f"{base}.replicas") == 1
            assert counters.get_counter(f"{base}.reader.r.depth") == 7
            assert counters.get_counter(f"{base}.reader.r.reads") == 0
            for _ in range(3):
                await reader.get()
            await wait_until(
                lambda: counters.get_counter(f"{base}.reader.r.reads") == 3
            )
            assert counters.get_counter(f"{base}.reader.r.depth") == 4
        finally:
            await wd.stop()


def test_stat_multi_windowed_single_pass():
    """fb303-style multi-window view: nesting (60 within 600 within
    3600), exact aggregates, and the truncation flag when the sample
    ring cannot honor a long window."""
    from openr_tpu.runtime.counters import _Stat

    s = _Stat()
    for i in range(10):
        s.add(float(i))
    out = s.multi_windowed((60.0, 600.0, 3600.0))
    for w in ("60", "600", "3600"):
        assert out[w]["count"] == 10
        assert out[w]["max"] == 9.0
        assert abs(out[w]["avg"] - 4.5) < 1e-9
        assert out[w]["truncated"] is False
    # overflow the ring: long windows flag truncation, a tiny window
    # (whose cutoff is newer than the eviction horizon) does not
    for _ in range(5000):
        s.add(1.0)
    out = s.multi_windowed((0.0, 3600.0))
    assert out["3600"]["truncated"] is True
    assert out["3600"]["count"] == 4096  # ring capacity, not a lie
