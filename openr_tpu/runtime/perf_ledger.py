"""Persistent perf-baseline ledger (ISSUE 14).

The BENCH_r01..r05 trajectory and the kernel cost ledger
(ops/xla_cache.KernelLedger) are write-only snapshots: nothing persists
per-kernel / per-stage baselines across runs, so a perf regression is
only caught by a human diffing bench JSONs. This module is the
measurement substrate: a small JSON store of timing observations keyed

  <kernel> | <capacity signature> | <variant> | <jax/XLA fingerprint>

 - kernel               what ran ("solve[lsdb100k]", "prewarm", a jit name)
 - capacity signature   the padded shape class ("n100489", "live")
 - variant              spf_kernel / namespace ("bucketed", "sync", "incr")
 - fingerprint          jax+jaxlib versions + backend — a toolchain bump
                        starts a fresh baseline instead of comparing
                        across compilers

Producers append observations (compile_ms, device_ms, rounds,
bucket_epochs, bytes_uploaded, peak_hbm_mb, ...): bench.py after each
config, tools/prewarm.py per bake, the live Monitor from its metrics
windows, and ops/xla_cache.KernelLedger per recorded compile. Consumers
read rolling quantile baselines: the ``baseline_drift`` SLO kind
(runtime/monitor.SloEngine) compares live window quantiles against the
stored quantile, and ``tools/perf_diff.py`` renders verdicts.

The store is OFF by default ("" dir — lookups return None, records
no-op) so tests and control-plane-only processes never touch disk;
``monitor_config.perf_ledger_dir`` / $OPENR_TPU_PERF_LEDGER /
``--perf-ledger-dir`` opt in. Writes are atomic (tmp + rename) and the
per-key observation window is bounded (rolling baseline, not an
ever-growing log).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Optional

from openr_tpu.runtime.counters import _percentile, counters

log = logging.getLogger(__name__)

ENV_DIR = "OPENR_TPU_PERF_LEDGER"
LEDGER_FILE = "perf_ledger.json"
# rolling window: enough history for a stable p95, bounded on disk
MAX_OBSERVATIONS = 64
_QUANTILES = ("p50", "p95", "p99")


def default_dir() -> str:
    """$OPENR_TPU_PERF_LEDGER, else the user cache — for the OFFLINE
    tools (prewarm, bench --perf-ledger) that want persistence without
    config plumbing. The daemon only persists via an explicit knob."""
    return os.environ.get(ENV_DIR, "") or os.path.join(
        os.path.expanduser("~"), ".cache", "openr_tpu", "perf"
    )


def fingerprint() -> str:
    """Toolchain identity a baseline is valid under. Passive on jax
    (device_stats._jax discipline): reads versions only if something
    already imported it, so a control-plane process stays light."""
    from openr_tpu.runtime import device_stats

    jax = device_stats._jax(allow_import=False)
    if jax is None:
        return "nojax"
    jaxlib = sys.modules.get("jaxlib")
    try:
        backend = jax.default_backend()
    # lint: allow(broad-except) backend probe is best-effort identity
    except Exception:
        backend = "unknown"
    return (
        f"jax{getattr(jax, '__version__', '?')}"
        f"+jaxlib{getattr(jaxlib, '__version__', '?')}"
        f"+{backend}"
    )


class PerfLedger:
    """One JSON file of keyed observation windows + quantile baselines."""

    def __init__(self, dir_path: str = ""):
        self.dir = dir_path or ""
        self._lock = threading.Lock()
        self._data: Optional[dict] = None  # lazy: {key: {"observations": []}}

    @property
    def enabled(self) -> bool:
        return bool(self.dir)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, LEDGER_FILE) if self.dir else ""

    @staticmethod
    def key(
        kernel: str,
        signature: str = "",
        variant: str = "",
        fp: Optional[str] = None,
    ) -> str:
        return "|".join(
            (kernel, signature, variant, fp if fp is not None else fingerprint())
        )

    # -- storage -----------------------------------------------------------

    def _load(self) -> dict:
        """Caller holds the lock."""
        if self._data is not None:
            return self._data
        self._data = {}
        if not self.enabled:
            return self._data
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and isinstance(doc.get("entries"), dict):
                self._data = doc["entries"]
        except FileNotFoundError:
            pass
        except (OSError, ValueError):
            # a torn/corrupt ledger must not wedge the daemon: start
            # fresh and make the loss visible
            counters.increment("perf.ledger.load_errors")
            log.warning("perf ledger %s unreadable — starting fresh", self.path)
        return self._data

    def _save(self) -> None:
        """Caller holds the lock. Atomic: tmp + rename."""
        if not self.enabled:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"schema": "openr-tpu-perf-ledger/1", "entries": self._data},
                    f,
                    indent=1,
                    sort_keys=True,
                )
            os.replace(tmp, self.path)
        except OSError:
            counters.increment("perf.ledger.write_errors")
            log.warning("perf ledger write failed", exc_info=True)

    # -- producers ---------------------------------------------------------

    def record(
        self,
        kernel: str,
        metrics: dict,
        signature: str = "",
        variant: str = "",
        fp: Optional[str] = None,
    ) -> None:
        """Append one observation (numeric fields only) to the key's
        rolling window. No-op while disabled."""
        if not self.enabled:
            return
        obs = {
            k: float(v)
            for k, v in (metrics or {}).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        if not obs:
            return
        obs["ts_ms"] = int(time.time() * 1000)
        with self._lock:
            data = self._load()
            entry = data.setdefault(
                self.key(kernel, signature, variant, fp), {"observations": []}
            )
            entry["observations"] = (
                entry.get("observations", []) + [obs]
            )[-MAX_OBSERVATIONS:]
            self._save()
        counters.increment("perf.ledger.records")
        counters.set_counter("perf.ledger.keys", len(data))

    # -- consumers ---------------------------------------------------------

    def observations(
        self,
        kernel: str,
        signature: str = "",
        variant: str = "",
        fp: Optional[str] = None,
    ) -> list[dict]:
        with self._lock:
            entry = self._load().get(self.key(kernel, signature, variant, fp))
            return list(entry.get("observations", [])) if entry else []

    def baseline(
        self,
        kernel: str,
        metric: str,
        signature: str = "",
        variant: str = "",
        quantile: str = "p95",
        fp: Optional[str] = None,
    ) -> Optional[float]:
        """Rolling quantile of one metric over the key's stored window;
        None when the key (or the metric) has no history — the "no
        baseline never breaches" contract the drift SLO leans on."""
        vals = sorted(
            o[metric]
            for o in self.observations(kernel, signature, variant, fp)
            if isinstance(o.get(metric), (int, float))
        )
        if not vals:
            return None
        q = float(quantile.lstrip("p")) if quantile.startswith("p") else 50.0
        return _percentile(vals, q)

    def baselines(
        self,
        kernel: str,
        signature: str = "",
        variant: str = "",
        fp: Optional[str] = None,
    ) -> dict:
        """Per-metric quantile summary for one key (perf_diff, bundles)."""
        obs = self.observations(kernel, signature, variant, fp)
        metrics: dict[str, list] = {}
        for o in obs:
            for k, v in o.items():
                if k != "ts_ms" and isinstance(v, (int, float)):
                    metrics.setdefault(k, []).append(float(v))
        out = {}
        for k, vals in metrics.items():
            vals.sort()
            out[k] = {
                "count": len(vals),
                **{q: round(_percentile(vals, float(q[1:])), 3)
                   for q in _QUANTILES},
            }
        return out

    def prewarm_summary(self) -> dict:
        """Attribution for the boot tracer's `prewarm` phase: what the
        offline bake (tools/prewarm.py) paid per namespace, read back
        from the ledger instead of re-paying it at daemon start."""
        total_ms, namespaces = 0.0, {}
        with self._lock:
            data = self._load()
        for key, entry in data.items():
            kernel, _, variant, _ = (key.split("|") + [""] * 4)[:4]
            if kernel != "prewarm":
                continue
            obs = entry.get("observations") or []
            if not obs:
                continue
            last = obs[-1].get("bake_ms")
            if isinstance(last, (int, float)):
                namespaces[variant] = round(
                    namespaces.get(variant, 0.0) + last, 1
                )
                total_ms += last
        return {"baked_ms": round(total_ms, 1), "namespaces": namespaces}

    def snapshot(self) -> dict:
        """Bundle/report payload: every key's count + quantiles (no raw
        observation dump — bundles stay bounded)."""
        with self._lock:
            data = {k: dict(v) for k, v in self._load().items()}
        out = {}
        for key, entry in data.items():
            obs = entry.get("observations") or []
            metrics: dict[str, list] = {}
            for o in obs:
                for k, v in o.items():
                    if k != "ts_ms" and isinstance(v, (int, float)):
                        metrics.setdefault(k, []).append(float(v))
            out[key] = {
                "count": len(obs),
                "metrics": {
                    k: {
                        q: round(_percentile(sorted(vals), float(q[1:])), 3)
                        for q in _QUANTILES
                    }
                    for k, vals in metrics.items()
                },
            }
        return {
            "dir": self.dir,
            "fingerprint": fingerprint(),
            "keys": out,
        }


# -- process singleton (the tracer/counters pattern) -------------------------

_ledger = PerfLedger("")


def configure(dir_path: str) -> PerfLedger:
    """Point the process ledger at a directory ("" disables). Idempotent
    for a repeated identical dir; repointing drops the cached data."""
    global _ledger
    if dir_path != _ledger.dir:
        _ledger = PerfLedger(dir_path)
    return _ledger


def get_ledger() -> PerfLedger:
    return _ledger
