"""Pure KvStore merge/diff logic — the CRDT core, no I/O.

Role of the reference's openr/kvstore/KvStoreUtil.{h,cpp}:
  - merge_key_values: last-writer-wins merge (KvStoreUtil.cpp:42-210) —
    higher version, then originator id, then value bytes; equal triples
    retain the higher ttl_version (TTL refresh without data change).
  - compare_values (KvStoreUtil.cpp:215-249).
  - dump_difference: the 3-way full-sync delta computation
    (KvStoreUtil.cpp:339-379).
  - dump_all / dump_hashes with prefix+originator filters
    (KvStoreUtil.cpp:385-430).

TTL bookkeeping (countdown queue, ref KvStore.h:652-656 + cleanupTtlCountdownQueue)
lives here too since it is pure given a clock.
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field
from typing import Optional

from openr_tpu.types import (
    FilterOperator,
    Publication,
    TTL_INFINITY,
    Value,
    compute_hash,
)


@dataclass
class MergeStats:
    """Why keys did not merge (ref KvStoreNoMergeReasonStats)."""

    no_matched_key: int = 0
    invalid_ttl: int = 0
    old_version: int = 0
    no_need_to_update: int = 0
    val_updates: int = 0
    ttl_updates: int = 0


@dataclass
class KvStoreFilters:
    """Key-prefix and originator-id match (ref KvStoreUtil.cpp:252-299).

    OR: match if any prefix matches OR any originator matches.
    AND: both must match. Empty term lists match everything for that term.
    """

    key_prefixes: tuple[str, ...] = ()
    originator_ids: frozenset[str] = frozenset()
    operator: FilterOperator = FilterOperator.OR

    def key_match(self, key: str, value: Value) -> bool:
        key_ok = not self.key_prefixes or any(
            key.startswith(p) for p in self.key_prefixes
        )
        orig_ok = not self.originator_ids or value.originator_id in self.originator_ids
        if self.operator == FilterOperator.AND:
            return key_ok and orig_ok
        # OR: but an empty term list shouldn't make everything match when
        # the other term is restrictive — OR over *present* terms.
        if not self.key_prefixes and not self.originator_ids:
            return True
        if not self.key_prefixes:
            return orig_ok
        if not self.originator_ids:
            return key_ok
        return key_ok or orig_ok


def merge_key_values(
    kv: dict[str, Value],
    key_vals: dict[str, Value],
    filters: Optional[KvStoreFilters] = None,
    stats: Optional[MergeStats] = None,
) -> dict[str, Value]:
    """Merge `key_vals` into `kv` in place; return the accepted updates
    (the received values) to publish/flood. Exact reference semantics
    (KvStoreUtil.cpp:42-210)."""
    updates: dict[str, Value] = {}
    st = stats if stats is not None else MergeStats()

    for key, value in key_vals.items():
        if filters is not None and not filters.key_match(key, value):
            st.no_matched_key += 1
            continue
        # TTL must be infinite or positive
        if value.ttl_ms != TTL_INFINITY and value.ttl_ms <= 0:
            st.invalid_ttl += 1
            continue
        # versions start at 1 (ref "versions must start at 1"); a version-0
        # value would tie my_version=0 for a missing key and fall into the
        # originator compare against no local entry
        if value.version < 1:
            st.old_version += 1
            continue

        mine = kv.get(key)
        my_version = mine.version if mine is not None else 0
        if value.version < my_version:
            st.old_version += 1
            continue

        update_all = False
        update_ttl = False
        if value.value is not None:
            if value.version > my_version:
                update_all = True
            elif value.originator_id > mine.originator_id:
                update_all = True
            elif value.originator_id == mine.originator_id:
                # Same version+originator: deterministically let the higher
                # value win so re-incarnated stores converge.
                if mine.value is None or value.value > mine.value:
                    update_all = True
                elif value.value == mine.value:
                    if value.ttl_version > mine.ttl_version:
                        update_ttl = True
        elif (
            mine is not None
            and value.version == mine.version
            and value.originator_id == mine.originator_id
            and value.ttl_version > mine.ttl_version
        ):
            # hash-only TTL refresh
            update_ttl = True

        if not update_all and not update_ttl:
            st.no_need_to_update += 1
            continue

        if update_all:
            st.val_updates += 1
            new_value = Value(
                version=value.version,
                originator_id=value.originator_id,
                value=value.value,
                ttl_ms=value.ttl_ms,
                ttl_version=value.ttl_version,
                hash=value.hash
                if value.hash is not None
                else compute_hash(value.version, value.originator_id, value.value),
                # the winning value's origin stamp rides the merge verbatim
                origin_node=value.origin_node,
                origin_event_id=value.origin_event_id,
                origin_ts_ms=value.origin_ts_ms,
            )
            kv[key] = new_value
        else:  # update_ttl
            st.ttl_updates += 1
            assert mine is not None
            mine.ttl_ms = value.ttl_ms
            mine.ttl_version = value.ttl_version

        updates[key] = value
    return updates


def compare_values(v1: Value, v2: Value) -> int:
    """1 if v1 better, -1 if v2 better, 0 equal, -2 unknown
    (ref KvStoreUtil.cpp:215-249)."""
    if v1.version != v2.version:
        return 1 if v1.version > v2.version else -1
    if v1.originator_id != v2.originator_id:
        return 1 if v1.originator_id > v2.originator_id else -1
    if v1.hash is not None and v2.hash is not None and v1.hash == v2.hash:
        if v1.ttl_version != v2.ttl_version:
            return 1 if v1.ttl_version > v2.ttl_version else -1
        return 0
    if v1.value is not None and v2.value is not None:
        if v1.value > v2.value:
            return 1
        if v1.value < v2.value:
            return -1
        return 0
    return -2  # a value is missing; can't tell


def dump_difference(
    area: str,
    my_key_vals: dict[str, Value],
    req_key_vals: dict[str, Value],
) -> Publication:
    """3-way full-sync delta (ref KvStoreUtil.cpp:339-379): return my full
    values where mine is better/unknown, and list the keys where the
    requester's copy is better/unknown (it should send those back)."""
    pub = Publication(area=area)
    for key, my_val in my_key_vals.items():
        req_val = req_key_vals.get(key)
        if req_val is None:
            pub.key_vals[key] = my_val
            continue
        rc = compare_values(my_val, req_val)
        if rc in (1, -2):
            pub.key_vals[key] = my_val
        if rc in (-1, -2):
            pub.to_be_updated_keys.append(key)
    for key in req_key_vals:
        if key not in my_key_vals:
            pub.to_be_updated_keys.append(key)
    return pub


def dump_all_with_filters(
    area: str,
    kv: dict[str, Value],
    filters: Optional[KvStoreFilters] = None,
    do_not_publish_value: bool = False,
) -> Publication:
    """ref KvStoreUtil.cpp:385-408."""
    pub = Publication(area=area)
    for key, val in kv.items():
        if filters is not None and not filters.key_match(key, val):
            continue
        pub.key_vals[key] = _strip_value(val) if do_not_publish_value else val
    return pub


def dump_hash_with_filters(
    area: str,
    kv: dict[str, Value],
    filters: Optional[KvStoreFilters] = None,
) -> Publication:
    """Hash-only dump for delta sync (ref KvStoreUtil.cpp:410-430)."""
    pub = Publication(area=area)
    for key, val in kv.items():
        if filters is not None and not filters.key_match(key, val):
            continue
        pub.key_vals[key] = _strip_value(val)
    return pub


def _strip_value(val: Value) -> Value:
    return Value(
        version=val.version,
        originator_id=val.originator_id,
        value=None,
        ttl_ms=val.ttl_ms,
        ttl_version=val.ttl_version,
        hash=val.hash,
        origin_node=val.origin_node,
        origin_event_id=val.origin_event_id,
        origin_ts_ms=val.origin_ts_ms,
    )


# ---------------------------------------------------------------------------
# TTL countdown (ref KvStore.h:652-656, cleanupTtlCountdownQueue)
# ---------------------------------------------------------------------------

@dataclass(order=True)
class _TtlEntry:
    expiry: float
    key: str = field(compare=False)
    version: int = field(compare=False)
    originator_id: str = field(compare=False)
    ttl_version: int = field(compare=False)


class TtlCountdownQueue:
    """Min-heap of key expiries with lazy invalidation: an entry only kills
    the key if (version, originator, ttl_version) still match the live
    value — a refresh or newer write strands the stale entry."""

    def __init__(self) -> None:
        self._heap: list[_TtlEntry] = []

    def track(self, key: str, value: Value, now: Optional[float] = None) -> None:
        if value.ttl_ms == TTL_INFINITY:
            return
        now = time.monotonic() if now is None else now
        heapq.heappush(
            self._heap,
            _TtlEntry(
                expiry=now + value.ttl_ms / 1e3,
                key=key,
                version=value.version,
                originator_id=value.originator_id,
                ttl_version=value.ttl_version,
            ),
        )

    def next_expiry_in_s(self, now: Optional[float] = None) -> Optional[float]:
        if not self._heap:
            return None
        now = time.monotonic() if now is None else now
        return max(0.0, self._heap[0].expiry - now)

    def expire(
        self, kv: dict[str, Value], now: Optional[float] = None
    ) -> list[str]:
        """Pop due entries; delete matching live keys from `kv`; return the
        expired key names."""
        now = time.monotonic() if now is None else now
        expired: list[str] = []
        while self._heap and self._heap[0].expiry <= now:
            entry = heapq.heappop(self._heap)
            live = kv.get(entry.key)
            if (
                live is not None
                and live.version == entry.version
                and live.originator_id == entry.originator_id
                and live.ttl_version == entry.ttl_version
            ):
                del kv[entry.key]
                expired.append(entry.key)
        return expired

    def __len__(self) -> int:
        return len(self._heap)
