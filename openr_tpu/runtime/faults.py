"""Deterministic fault-injection registry — the chaos-drill seam.

Role of the fault-injection tooling a training/inference platform uses to
validate preemption and device-loss handling: recovery paths (supervised
fiber restart, TPU->CPU solver failover, KvStore peer resync, FIB retry)
only count as working if they can be *driven* on demand, reproducibly.

Named sites call ``maybe_fail("site")`` on their hot path. When nothing is
armed the check is a single dict lookup on an empty dict — near-zero cost.
Arming a site attaches a schedule:

  - probability  p in (0, 1]: fire on each check with probability p,
    drawn from a PRNG seeded by (registry seed, site) — the firing
    pattern is identical for identical seeds and check sequences
  - every_nth    fire on every Nth check of the site
  - one_shot     fire on the first check, then disarm
  - window_s     schedule stays armed for this long after arming
  - max_fires    disarm after this many firings
  - delay_ms     instead of raising, a firing SLEEPS this long and
    returns — a latency fault, not a loss fault. Used by the perf
    drills: routing keeps converging while the site's wall-clock
    inflates, which is exactly the regression shape the
    ``baseline_drift`` SLO must catch
  - rate         target firings per second: a token bucket (capacity
    one) paces firings at the target rate no matter how often the
    site is checked — a *calibrated sustained storm*, not a per-call
    coin flip. The overload chaos drills key off this: "500 events/s
    at decision.ingest for 60 s" is `rate=500, window_s=60`

Schedules come from ``config.py`` (fault_injection_config, armed at daemon
startup) or at runtime via the ``ctrl.fault.{inject,clear,list}`` endpoints
(``breeze fault ...``). Every firing bumps ``runtime.fault.<site>.fired``
and, when the caller passes the active trace span, stamps
``fault_injected=<site>`` onto it.
"""

from __future__ import annotations

import time
from random import Random
from typing import Optional

from openr_tpu.runtime.counters import counters

# The sites wired into the codebase today (the registry itself accepts any
# name — new sites need only a maybe_fail() call).
KNOWN_SITES = (
    "rpc.send",  # RpcClient.request, before the frame is written
    "kvstore.flood",  # KvStore._flood_to_peer, before the peer RPC
    "fib.program",  # Fib sync/incremental programming, before the service call
    "solver.exec",  # Decision primary SPF execution + TPU device dispatch
    "solver.dispatch",  # Decision._dispatch_loop, before the async solve
    "queue.push",  # ReplicateQueue.push fan-out
    "decision.ingest",  # Decision._kvstore_loop, after the queue read
    "solver.whatif",  # WhatIfEngine sweep/drain/optimize entry + dispatch
)


class FaultInjected(ConnectionError):
    """Raised by an armed site. Subclasses ConnectionError so transport
    call sites treat it exactly like the I/O failure it simulates."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site!r}")
        self.site = site


class FaultSchedule:
    """One armed site: schedule parameters + firing state."""

    def __init__(
        self,
        site: str,
        probability: float = 0.0,
        every_nth: int = 0,
        window_s: float = 0.0,
        max_fires: int = 0,
        seed: int = 0,
        delay_ms: float = 0.0,
        rate: float = 0.0,
    ):
        self.site = site
        self.probability = probability
        self.every_nth = every_nth
        self.window_s = window_s
        self.max_fires = max_fires
        self.seed = seed
        self.delay_ms = delay_ms
        self.rate = rate
        self.checks = 0
        self.fires = 0
        self.armed_at = time.monotonic()
        # rate pacing: token bucket, capacity one token (no burst debt
        # accumulates across a quiet stretch — the drill stays paced)
        self._rate_tokens = 1.0 if rate > 0 else 0.0
        self._rate_last = self.armed_at
        # string seeding hashes via sha512 — stable across processes,
        # unlike hash() which is salted per interpreter
        self.rng = Random(f"{seed}/{site}")

    def describe(self) -> dict:
        d = {
            "site": self.site,
            "probability": self.probability,
            "every_nth": self.every_nth,
            "window_s": self.window_s,
            "max_fires": self.max_fires,
            "seed": self.seed,
            "delay_ms": self.delay_ms,
            "rate": self.rate,
            "checks": self.checks,
            "fires": self.fires,
        }
        if self.window_s:
            d["remaining_window_s"] = max(
                0.0, self.window_s - (time.monotonic() - self.armed_at)
            )
        return d


class FaultRegistry:
    """Process-global armed-site table (one daemon = one registry)."""

    def __init__(self):
        self.seed = 0
        self._armed: dict[str, FaultSchedule] = {}

    # -- arming / clearing -------------------------------------------------

    def configure(self, cfg) -> None:
        """Apply a config.FaultInjectionConfig at startup."""
        self.seed = int(cfg.seed)
        self.clear()
        if not cfg.enable_fault_injection:
            return
        for sched in cfg.schedules:
            self.arm(**dict(sched))

    def arm(
        self,
        site: str,
        probability: float = 0.0,
        every_nth: int = 0,
        one_shot: bool = False,
        window_s: float = 0.0,
        max_fires: int = 0,
        seed: Optional[int] = None,
        delay_ms: float = 0.0,
        rate: float = 0.0,
    ) -> dict:
        if not site:
            raise ValueError("fault site name must be non-empty")
        probability = float(probability)
        if not 0.0 <= probability <= 1.0:
            raise ValueError(f"probability {probability} not in [0, 1]")
        if int(every_nth) < 0 or int(max_fires) < 0 or float(window_s) < 0:
            raise ValueError("every_nth/max_fires/window_s must be >= 0")
        if float(delay_ms) < 0:
            raise ValueError("delay_ms must be >= 0")
        if float(rate) < 0:
            raise ValueError("rate must be >= 0")
        if float(rate) > 0 and (probability > 0 or int(every_nth) > 0):
            raise ValueError(
                "rate is its own schedule: combine with window_s/"
                "max_fires/delay_ms, not probability/every_nth"
            )
        if one_shot:
            max_fires = 1
        self._armed[site] = FaultSchedule(
            site,
            probability=probability,
            every_nth=int(every_nth),
            window_s=float(window_s),
            max_fires=int(max_fires),
            seed=self.seed if seed is None else int(seed),
            delay_ms=float(delay_ms),
            rate=float(rate),
        )
        counters.increment("runtime.fault.armed")
        return self._armed[site].describe()

    def clear(self, site: Optional[str] = None) -> dict:
        """Disarm one site, or every site when site is None."""
        if site is None:
            cleared = sorted(self._armed)
            self._armed.clear()
        else:
            cleared = [site] if self._armed.pop(site, None) is not None else []
        return {"cleared": cleared}

    def list(self) -> dict:
        return {
            "seed": self.seed,
            "known_sites": list(KNOWN_SITES),
            "armed": [s.describe() for s in self._armed.values()],
        }

    # -- the hook ----------------------------------------------------------

    def maybe_fail(self, site: str, span=None) -> None:
        """Hot-path check: raises FaultInjected when the site's schedule
        fires. `span` (a tracing Span, optional) is stamped with the
        firing for trace-level attribution."""
        sched = self._armed.get(site)
        if sched is None:
            return
        self._check(sched, span)

    def _check(self, s: FaultSchedule, span) -> None:
        if s.window_s and (time.monotonic() - s.armed_at) > s.window_s:
            self._armed.pop(s.site, None)
            return
        s.checks += 1
        if s.every_nth > 0:
            fire = (s.checks % s.every_nth) == 0
        elif s.probability > 0.0:
            fire = s.rng.random() < s.probability
        elif s.rate > 0.0:
            now = time.monotonic()
            s._rate_tokens = min(
                1.0, s._rate_tokens + (now - s._rate_last) * s.rate
            )
            s._rate_last = now
            fire = s._rate_tokens >= 1.0
            if fire:
                s._rate_tokens -= 1.0
        else:
            fire = True  # unconditional schedule (window/one-shot style)
        if not fire:
            return
        s.fires += 1
        counters.increment(f"runtime.fault.{s.site}.fired")
        counters.increment("runtime.fault.fired")
        if s.max_fires and s.fires >= s.max_fires:
            self._armed.pop(s.site, None)
        if span is not None and hasattr(span, "attributes"):
            span.attributes["fault_injected"] = s.site
        if s.delay_ms > 0.0:
            # latency fault: the site succeeds, just slower
            counters.increment(f"runtime.fault.{s.site}.delayed")
            time.sleep(s.delay_ms / 1e3)
            return
        raise FaultInjected(s.site)


registry = FaultRegistry()


def maybe_fail(site: str, span=None) -> None:
    """Module-level hook; see FaultRegistry.maybe_fail."""
    registry.maybe_fail(site, span)
