"""Zero-copy columnar RIB→FIB spine parity (ISSUE 12 tentpole).

The packed column delta must be a drop-in for the per-route object
path at every stage it replaced:

  - fast_unicast_column_diff == the brute-force per-entry compare on
    randomized topologies through churn, overrides, and withdrawals
    (the legacy fast_unicast_diff + full compare stay in-tree as the
    oracle);
  - RouteColumnBatch decodes to exactly the entries the lazy RIB
    materializes (prefix set, metrics, next-hop groups);
  - the columnar dataplane programmer produces the same kernel op
    sequence, _metric record, and _stale make-before-break ledger as
    the per-route walk, including under injected failures;
  - ProvenanceLedger's bulk layer stamping answers get/pop exactly
    like the per-prefix RouteProvenance dict it replaced;
  - sync_fib_columns round-trips the packed arrays over the RPC
    boundary and reports partial failures as FibUpdateError.
"""

import dataclasses

import numpy as np
import pytest

from openr_tpu.decision.column_delta import (
    build_column_batch,
    fast_unicast_column_diff,
)
from openr_tpu.decision.columnar_rib import LazyUnicastRoutes
from openr_tpu.decision.rib import ProvenanceLedger, RouteProvenance
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.serde import to_plain
from openr_tpu.types import Adjacency, AdjacencyDatabase
from tests.conftest import run_async


def _flap(states, adj_dbs, node, metric):
    victim = next(d for d in adj_dbs if d.this_node_name == node)
    states["0"].update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=node,
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": metric})
                for a in victim.adjacencies
            ),
            area="0",
        )
    )


def _withdraw(states, node):
    states["0"].update_adjacency_database(
        AdjacencyDatabase(this_node_name=node, adjacencies=(), area="0")
    )


# -- diff parity -----------------------------------------------------------


@pytest.mark.parametrize("seed,kw", [(3, {}), (21, {}),
                                     (42, {"enable_lfa": True})])
def test_column_diff_matches_brute_force_through_churn(seed, kw):
    """Property: for random topologies under metric churn, overrides,
    and node withdrawals, the packed column diff produces exactly the
    update/delete sets of the brute-force per-entry compare."""
    rng = np.random.default_rng(seed)
    adj_dbs, prefix_dbs = topologies.random_mesh(26, seed=seed)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    tpu = TpuSpfSolver(me, **kw)
    db_old = tpu.build_route_db(me, states, ps)
    assert isinstance(db_old.unicast_routes, LazyUnicastRoutes)

    # cold: empty -> full table
    delta = fast_unicast_column_diff({}, db_old.unicast_routes)
    assert delta is not None and delta.full
    cold_mat = dict(db_old.unicast_routes)
    assert dict(delta.lazy_map()) == cold_mat
    assert delta.deletes == []

    engaged = 0
    for step in range(5):
        victim = f"node-{int(rng.integers(1, 26))}"
        if step == 3:
            _withdraw(states, victim)
        else:
            _flap(states, adj_dbs, victim, metric=int(rng.integers(2, 40)))
        db_new = tpu.build_route_db(me, states, ps)
        if step == 2:
            # host-side override (static-route merge shape): the diff
            # must route it through the entry-compare path
            pfx = next(iter(dict(db_new.unicast_routes)))
            db_new.unicast_routes[pfx] = dataclasses.replace(
                db_new.unicast_routes[pfx], igp_cost=777_777
            )
        upd = db_old.calculate_update(db_new)
        old_mat = dict(db_old.unicast_routes)
        new_mat = dict(db_new.unicast_routes)
        brute_update = {
            p: e for p, e in new_mat.items()
            if p not in old_mat or old_mat[p] != e
        }
        brute_dels = sorted(p for p in old_mat if p not in new_mat)
        ctx = f"seed={seed} step={step} victim={victim}"
        assert dict(upd.unicast_routes_to_update) == brute_update, ctx
        assert sorted(upd.unicast_routes_to_delete) == brute_dels, ctx
        if upd.columns is not None:
            engaged += 1
            assert len(upd.unicast_routes_to_update) == len(brute_update)
            assert set(upd.unicast_routes_to_update) == set(brute_update)
        db_old = db_new
    assert engaged >= 3, f"columnar diff engaged only {engaged}/5 steps"


def test_column_diff_snapshot_isolated_from_later_churn():
    """The new_mapping a delta carries must keep answering with its own
    generation even after the solver patches the live columns (Fib
    holds it as programmed-state across later solves)."""
    adj_dbs, prefix_dbs = topologies.random_mesh(22, seed=11)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    tpu = TpuSpfSolver(me)
    db1 = tpu.build_route_db(me, states, ps)
    delta = fast_unicast_column_diff({}, db1.unicast_routes)
    snap = delta.new_mapping
    before = dict(snap)
    _flap(states, adj_dbs, "node-3", metric=37)
    tpu.build_route_db(me, states, ps)
    assert dict(snap) == before


# -- batch decode parity ---------------------------------------------------


def test_column_batch_matches_materialized_entries():
    """RouteColumnBatch must decode to exactly what the lazy RIB
    materializes: same prefixes, same metric, same next-hop group."""
    adj_dbs, prefix_dbs = topologies.random_mesh(24, seed=8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    db = TpuSpfSolver(me).build_route_db(me, states, ps)
    # one override rides the batch's object-path extra lane
    pfx = next(iter(dict(db.unicast_routes)))
    db.unicast_routes[pfx] = dataclasses.replace(
        db.unicast_routes[pfx], igp_cost=424_242
    )
    batch = build_column_batch(db.unicast_routes)
    assert batch is not None
    mat = dict(db.unicast_routes)
    decoded = batch.as_route_dicts()
    assert decoded.keys() == mat.keys()
    for p, entry in mat.items():
        d = decoded[p]
        assert d["igp_cost"] == entry.igp_cost, p
        want = sorted(
            (nh.address, nh.if_name, nh.weight, nh.metric)
            for nh in entry.nexthops
        )
        got = sorted(
            (nh["address"], nh["if_name"], nh["weight"], nh["metric"])
            for nh in d["nexthops"]
        )
        assert got == want, p
    # wire round trip is loss-free
    import json

    wired = batch.__class__.from_wire(
        json.loads(json.dumps(batch.to_wire()))
    )
    assert wired.as_route_dicts() == decoded


# -- dataplane programmer parity -------------------------------------------


class _ScriptedNetlink:
    """Records kernel mutations in order; fails specific
    (op, prefix, metric) calls with an errno."""

    def __init__(self, fail=()):
        self.ops: list[tuple[str, str, int]] = []
        self.fail = dict(fail)

    async def _do(self, op, r):
        self.ops.append((op, r.prefix, r.metric))
        eno = self.fail.get((op, r.prefix, r.metric))
        if eno is not None:
            import os

            raise OSError(eno, os.strerror(eno))

    async def add_route(self, r):
        await self._do("add", r)

    async def delete_route(self, r):
        await self._do("del", r)


def _scripted_dataplane(fake):
    from openr_tpu.platform.fib_handler import NetlinkDataplane

    dp = NetlinkDataplane.__new__(NetlinkDataplane)
    dp.table = 254
    dp.nl = fake
    dp._opened = True
    dp.mpls = {}
    dp._metric = {}
    dp._stale = {}
    dp.mpls_kernel = False
    return dp


def _per_prefix_ops(fake):
    seq: dict[str, list[tuple[str, int]]] = {}
    for op, p, m in fake.ops:
        seq.setdefault(p, []).append((op, m))
    return seq


@pytest.mark.parametrize("seed", [5, 19])
def test_columnar_programmer_matches_object_walk(seed):
    """Randomized churn + injected kernel failures: add_unicast_columns
    must leave the SAME _metric record, _stale make-before-break
    ledger, failed set, and per-prefix kernel op sequence as the
    per-route object walk driven with identical inputs."""
    import asyncio
    import errno

    rng = np.random.default_rng(seed)
    adj_dbs, prefix_dbs = topologies.random_mesh(22, seed=seed)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    tpu = TpuSpfSolver(me)
    fake_obj = _ScriptedNetlink()
    fake_col = _ScriptedNetlink()
    dp_obj = _scripted_dataplane(fake_obj)
    dp_col = _scripted_dataplane(fake_col)

    async def step(db, fail):
        fake_obj.fail = dict(fail)
        fake_col.fail = dict(fail)
        routes = {p: to_plain(e) for p, e in dict(db.unicast_routes).items()}
        batch = build_column_batch(db.unicast_routes)
        assert batch is not None
        f_obj = await dp_obj.add_unicast(routes)
        f_col = await dp_col.add_unicast_columns(batch)
        return f_obj, f_col

    for i in range(4):
        if i:
            victim = f"node-{int(rng.integers(1, 22))}"
            _flap(states, adj_dbs, victim, metric=int(rng.integers(2, 40)))
        db = tpu.build_route_db(me, states, ps)
        if i == 2:
            # an override exercises the batch's extra (object) lane
            pfx = next(iter(dict(db.unicast_routes)))
            db.unicast_routes[pfx] = dataclasses.replace(
                db.unicast_routes[pfx], igp_cost=999_999
            )
        fail = {}
        if i >= 1:
            # fail a random add and a random old-metric cleanup delete
            mat = dict(db.unicast_routes)
            sample = sorted(mat)[: max(1, len(mat) // 8)]
            for p in sample[: len(sample) // 2]:
                fail[("add", p, mat[p].igp_cost)] = errno.ENOBUFS
            for p in sample[len(sample) // 2:]:
                old = dp_obj._metric.get(p)
                if old is not None and old != mat[p].igp_cost:
                    fail[("del", p, old)] = errno.EBUSY
        f_obj, f_col = asyncio.run(step(db, fail))
        ctx = f"seed={seed} step={i}"
        assert sorted(f_obj) == sorted(f_col), ctx
        assert dp_obj._metric == dp_col._metric, ctx
        assert dp_obj._stale == dp_col._stale, ctx
        assert _per_prefix_ops(fake_obj) == _per_prefix_ops(fake_col), ctx


# -- provenance ledger parity ----------------------------------------------


def test_provenance_ledger_matches_per_prefix_dict():
    """Randomized op sequence: the layered ledger must answer get/pop
    exactly like the plain per-prefix dict it replaced, including under
    layer folding (> _LAYER_MAX coexisting bulk stamps)."""
    rng = np.random.default_rng(0)
    prefixes = [f"10.0.{i}.0/24" for i in range(48)]
    ledger = ProvenanceLedger()
    mirror: dict[str, RouteProvenance] = {}
    ingest_tags: dict[str, tuple] = {}
    for step in range(1, 160):
        op = int(rng.integers(0, 10))
        if op < 3:  # explicit per-prefix stamp
            p = prefixes[int(rng.integers(0, len(prefixes)))]
            prov = RouteProvenance(
                kv_key=f"k{step}", originator=f"n{step}", area="0",
                solve_epoch=step, solver_kind="full", ts_ms=step,
            )
            ledger[p] = prov
            mirror[p] = prov
        elif op < 5:  # delete
            p = prefixes[int(rng.integers(0, len(prefixes)))]
            assert ledger.pop(p, None) == mirror.pop(p, None), step
        else:  # bulk layer (what a columnar build stamps)
            k = int(rng.integers(2, len(prefixes)))
            members = {
                prefixes[j]: None
                for j in rng.choice(len(prefixes), size=k, replace=False)
            }
            tags = {
                p: (f"t{step}", f"o{step}", "0")
                for p in list(members)[:: 2]
            }
            topo = (f"topo{step}", "origin", "0") if op >= 8 else None
            ingest = None
            if topo is None and ingest_tags:
                ingest = dict(ingest_tags)
            ledger.stamp_layer(
                dict(members), dict(tags), topo, ingest, step, "full", step
            )
            for p in members:
                tag = (
                    tags.get(p) or topo
                    or (ingest.get(p) if ingest else None)
                    or ("", "", "")
                )
                mirror[p] = RouteProvenance(
                    kv_key=tag[0], originator=tag[1], area=tag[2],
                    solve_epoch=step, solver_kind="full", ts_ms=step,
                )
            ingest_tags.update(tags)
        for p in prefixes:
            assert ledger.get(p) == mirror.get(p), (step, p)


# -- RPC boundary ----------------------------------------------------------


@run_async
async def test_sync_fib_columns_rpc_roundtrip():
    """Packed column sync across the real RPC boundary: the platform
    agent's table must match the batch, and per-prefix failures must
    come back as FibUpdateError (same contract as sync_fib)."""
    from openr_tpu.fib.fib_service import FibUpdateError
    from openr_tpu.platform.fib_handler import (
        FibPlatformServer,
        MemoryDataplane,
        RemoteFibService,
    )

    adj_dbs, prefix_dbs = topologies.random_mesh(18, seed=4)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    db = TpuSpfSolver("node-0").build_route_db("node-0", states, ps)
    batch = build_column_batch(db.unicast_routes)
    assert batch is not None

    dp = MemoryDataplane()
    server = FibPlatformServer(dp)
    port = await server.start()
    svc = RemoteFibService("127.0.0.1", port)
    try:
        assert svc.supports_columns
        await svc.sync_fib_columns(786, batch)
        table = await svc.get_route_table()
        want = batch.as_route_dicts()
        assert set(table["unicast"]) == set(want)
        some = next(iter(want))
        assert table["unicast"][some]["igp_cost"] == want[some]["igp_cost"]

        victim = sorted(want)[0]
        dp.fail_prefixes.add(victim)
        with pytest.raises(FibUpdateError) as ei:
            await svc.sync_fib_columns(786, batch)
        assert ei.value.failed_prefixes == [victim]
    finally:
        await svc.close()
        await server.stop()
