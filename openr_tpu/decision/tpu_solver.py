"""TPU route-computation backend — the project's differentiator.

Replaces the reference's per-root memoized Dijkstra + per-prefix scalar
loops (openr/decision/LinkState.cpp:836-911 runSpf + SpfSolver.cpp:460-646
buildRouteDb) with one fused, jit-compiled pipeline over the ops/csr.py
array mirror:

  1. SSSP: frontier-synchronous Bellman-Ford as a fixpoint of
         dist'[v] = min(dist[v], min_k dist[in_nbr[v,k]] + in_w[v,k])
     under lax.while_loop — dense [N_cap, K_cap] gather + min-reduce,
     no scatter, static shapes. Overloaded-node transit drain is the same
     mask the reference applies in its relax step (root exempt).
  2. First-hop ("next hop") extraction: boolean fixpoint over the shortest-
     path DAG seeded at the root's out-edge slots — matches runSpf's ECMP
     `>=` accumulation (dist[u]+w == dist[v] predicate,
     LinkState.cpp:885-901).
  3. Best-route selection: vectorized lexicographic selection over the
     prefix x announcer matrix in the reference's order (path_preference
     desc, source_preference desc, advertised distance asc —
     LsdbUtil.cpp:842), drained-announcer filter with all-drained
     fallback (SpfSolver.cpp:709-731), then min-IGP-metric announcer set
     and the union of their first-hop masks.

The memoize-per-root-on-demand strategy is deliberately replaced by
compute-everything-batched: one TPU launch produces the full RIB's
next-hop structure; roots batch via vmap for whole-fabric computation.

Scope (round 2): single-area LSDBs with IP/SP_ECMP prefixes run on
device; KSP2 / UCMP / SR_MPLS / prepend-label prefixes and multi-area
LSDBs fall back to the CPU oracle (decision/spf_solver.py) per prefix —
behavior is identical by construction and enforced by differential tests
(tests/test_tpu_solver.py). MPLS label routes are host-built (they are
O(adjacent links), not hot).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, NextHop, RibUnicastEntry
from openr_tpu.decision.spf_solver import SpfSolver, select_best_node_area
from openr_tpu.ops.csr import (
    INF32,
    EllGraph,
    PrefixMatrix,
    build_ell,
    build_prefix_matrix,
)
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    parse_prefix,
)

INF = int(INF32)
_NEG = -(2**31)


# ---------------------------------------------------------------------------
# jitted kernels (pure functions of arrays; shapes static per capacity class)
# ---------------------------------------------------------------------------

def _sssp_kernel(in_nbr, in_w, in_up, node_over, root):
    """dist[v] fixpoint; int32 [N_cap]."""
    import jax
    import jax.numpy as jnp

    n = in_nbr.shape[0]
    dist0 = jnp.full((n,), INF, jnp.int32).at[root].set(0)
    # a source node may relax its out-edges iff it is the root or not
    # overloaded (transit drain, ref LinkState.cpp:858-866)
    usable = in_up & (in_nbr >= 0) & ((in_nbr == root) | ~node_over[in_nbr])

    def body(state):
        dist, _ = state
        nbr_dist = dist[in_nbr]  # [N, K] gather
        cand = jnp.where(
            usable & (nbr_dist < INF), nbr_dist + in_w, INF
        ).min(axis=1)
        new = jnp.minimum(dist, cand)
        return new, jnp.any(new != dist)

    dist, _ = jax.lax.while_loop(lambda s: s[1], body, (dist0, jnp.bool_(True)))
    return dist


def _next_hop_kernel(in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up):
    """First-hop slot masks nh[v, d]: root's out-edge slot d lies on a
    shortest path to v. bool [N_cap, D_cap]."""
    import jax
    import jax.numpy as jnp

    n, _ = in_nbr.shape
    d_cap = root_nbr.shape[0]
    # seed: slot d reaches its neighbor iff that direct edge achieves the
    # neighbor's shortest distance (ref: direct neighbor adds itself)
    slot_ok = (root_nbr >= 0) & root_up & (dist[jnp.clip(root_nbr, 0, n - 1)] == root_w)
    seed = jnp.zeros((n, d_cap), bool).at[
        jnp.where(root_nbr >= 0, root_nbr, n), jnp.arange(d_cap)
    ].set(slot_ok, mode="drop")
    # propagate over shortest-path in-edges from non-root, non-overloaded
    # parents (root's contribution is exactly the seed)
    ok_parent = (
        in_up
        & (in_nbr >= 0)
        & (in_nbr != root)
        & ~node_over[in_nbr]
        & (dist[in_nbr] < INF)
        & (dist[in_nbr] + in_w == dist[:, None])
    )

    def body(state):
        nh, _ = state
        prop = jnp.any(ok_parent[:, :, None] & nh[in_nbr], axis=1)
        new = seed | prop
        return new, jnp.any(new != nh)

    nh, _ = jax.lax.while_loop(lambda s: s[1], body, (seed, jnp.bool_(True)))
    return nh


def _select_metric_kernel(dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Vectorized per-prefix best-route selection (no next-hop union):
    returns (igp_metric[P], s3[P,A] post-drain selected set, s4[P,A]
    min-IGP subset, idx clipped announcer indices). Shared by the
    single-chip pipeline and the sharded step so the selection semantics
    (incl. the all-drained fallback, SpfSolver.cpp:709-731) exist once."""
    import jax.numpy as jnp

    n = dist.shape[0]
    idx = jnp.clip(ann_node, 0, n - 1)
    ann_dist = dist[idx]
    reach = ann_valid & (ann_dist < INF)
    pp = jnp.where(reach, path_pref, _NEG)
    s = reach & (pp == pp.max(axis=1, keepdims=True))
    sp = jnp.where(s, source_pref, _NEG)
    s = s & (sp == sp.max(axis=1, keepdims=True))
    da = jnp.where(s, dist_adv, INF)
    s2 = s & (da == da.min(axis=1, keepdims=True))
    # drained-announcer filter; keep unfiltered when all drained
    nd = s2 & ~node_over[idx]
    s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
    igp = jnp.where(s3, ann_dist, INF)
    metric = igp.min(axis=1)
    s4 = s3 & (igp == metric[:, None])
    return metric, s3, s4, idx


def _select_kernel(dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Selection + next-hop union.

    Returns (igp_metric[P], selected[P,A] (post-drain set S3),
    nh_mask[P,D], has_route[P])."""
    import jax.numpy as jnp

    metric, s3, s4, idx = _select_metric_kernel(
        dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
    )
    nh_mask = jnp.any(s4[:, :, None] & nh[idx], axis=1)
    has_route = s3.any(axis=1) & (metric < INF)
    return metric, s3, nh_mask, has_route


@functools.lru_cache(maxsize=None)
def _jitted_pipeline():
    """Build the fused jit once (lazy so importing this module doesn't pull
    in jax)."""
    import jax

    def pipeline(
        in_nbr, in_w, in_up, node_over,
        root, root_nbr, root_w, root_up,
        ann_node, ann_valid, path_pref, source_pref, dist_adv,
    ):
        dist = _sssp_kernel(in_nbr, in_w, in_up, node_over, root)
        nh = _next_hop_kernel(
            in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up
        )
        metric, s3, nh_mask, has_route = _select_kernel(
            dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
        )
        return dist, metric, s3, nh_mask, has_route

    return jax.jit(pipeline)


@functools.lru_cache(maxsize=None)
def _jitted_sssp_batch():
    """vmapped multi-root SSSP (whole-fabric / benchmark path)."""
    import jax

    return jax.jit(
        jax.vmap(_sssp_kernel, in_axes=(None, None, None, None, 0))
    )


def sssp_all_pairs(graph: EllGraph, roots: Optional[np.ndarray] = None):
    """Batched SSSP from many roots — [R, N_cap] int32 distances."""
    import jax

    if roots is None:
        roots = np.arange(graph.n_nodes, dtype=np.int32)
    fn = _jitted_sssp_batch()
    args = jax.device_put(
        [
            graph.in_nbr,
            graph.in_w,
            graph.in_up,
            graph.node_overloaded,
            roots.astype(np.int32),
        ]
    )
    return fn(*args)


# ---------------------------------------------------------------------------
# solver
# ---------------------------------------------------------------------------

def _fast_path_eligible(entries) -> bool:
    """Device fast path covers IP + SP_ECMP announcements without prepend
    labels; anything else routes through the CPU oracle."""
    for entry in entries.values():
        if (
            entry.forwarding_type != PrefixForwardingType.IP
            or entry.forwarding_algorithm != PrefixForwardingAlgorithm.SP_ECMP
            or entry.prepend_label is not None
        ):
            return False
    return True


class TpuSpfSolver:
    """Drop-in replacement for SpfSolver.build_route_db with the hot path
    on device. Differentially tested against the CPU oracle."""

    def __init__(self, my_node_name: str, **solver_kwargs):
        self.my_node_name = my_node_name
        self.cpu = SpfSolver(my_node_name, **solver_kwargs)
        self._mirrors: dict[str, tuple[int, EllGraph]] = {}
        # resident device copies, keyed on the generation counters so
        # steady-state recomputes ship only what changed
        self._dev_graph: dict[str, tuple[int, tuple]] = {}
        self._dev_matrix: dict[str, tuple] = {}
        self._partition = None  # (ps.generation, fast, slow)
        self._nh_set_cache: dict = {}
        self.last_device_stats: dict = {}

    # static-route passthroughs keep Decision actor backend-agnostic
    def update_static_unicast_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_unicast_routes(to_update, to_delete)

    def update_static_mpls_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_mpls_routes(to_update, to_delete)

    def create_route_for_prefix_or_get_static(
        self, my_node_name, area_link_states, prefix_state, prefix
    ):
        """Incremental per-prefix path (Decision's changed-prefix rebuild):
        single-prefix work has no batch to amortize a device launch over,
        so it delegates to the CPU oracle. The resident SPF tensors keep
        serving the full-rebuild path."""
        return self.cpu.create_route_for_prefix_or_get_static(
            my_node_name, area_link_states, prefix_state, prefix
        )

    @property
    def static_unicast_routes(self):
        return self.cpu.static_unicast_routes

    @property
    def static_mpls_routes(self):
        return self.cpu.static_mpls_routes

    def mirror(self, link_state: LinkState) -> EllGraph:
        """Device mirror, refreshed when the LinkState generation moves."""
        cached = self._mirrors.get(link_state.area)
        if cached is not None and cached[0] == link_state.generation:
            return cached[1]
        prev = cached[1] if cached is not None else None
        graph = build_ell(
            link_state,
            n_cap=prev.n_cap if prev else 0,
            k_cap=prev.k_cap if prev else 0,
        )
        self._mirrors[link_state.area] = (link_state.generation, graph)
        return graph

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        # multi-area: selection must be global across areas — CPU path
        # (single-area is the device-accelerated deployment this round)
        if len(area_link_states) != 1:
            return self.cpu.build_route_db(
                my_node_name, area_link_states, prefix_state
            )
        area, link_state = next(iter(area_link_states.items()))
        if not link_state.has_node(my_node_name):
            return None

        if self._partition is not None and self._partition[0] == prefix_state.generation:
            fast, slow = self._partition[1], self._partition[2]
        else:
            fast, slow = [], []
            for prefix, entries in prefix_state.prefixes().items():
                (fast if _fast_path_eligible(entries) else slow).append(prefix)
            self._partition = (prefix_state.generation, fast, slow)

        route_db = DecisionRouteDb()
        if fast:
            self._solve_fast(
                my_node_name, area, link_state, prefix_state, fast, route_db
            )

        # CPU oracle path for irregular prefixes + statics + MPLS
        self.cpu.best_routes_cache.clear()
        for prefix in slow:
            route = self.cpu.create_route_for_prefix(
                my_node_name, area_link_states, prefix_state, prefix
            )
            if route is not None:
                route_db.add_unicast_route(route)
        for prefix, entry in self.cpu.static_unicast_routes.items():
            if prefix not in route_db.unicast_routes:
                route_db.add_unicast_route(entry)
        if self.cpu.enable_node_segment_label:
            for entry in self.cpu._node_label_routes(
                my_node_name, area_link_states
            ).values():
                route_db.add_mpls_route(entry)
        if self.cpu.enable_adjacency_labels:
            for entry in self.cpu._adj_label_routes(my_node_name, area_link_states):
                route_db.add_mpls_route(entry)
        for entry in self.cpu.static_mpls_routes.values():
            route_db.add_mpls_route(entry)
        return route_db

    def _solve_fast(
        self,
        my_node_name: str,
        area: str,
        link_state: LinkState,
        prefix_state: PrefixState,
        prefixes: list[str],
        route_db: DecisionRouteDb,
    ) -> None:
        import jax

        graph = self.mirror(link_state)
        root_idx = graph.node_index[my_node_name]

        # graph device arrays: resident across solves, refreshed per
        # generation in ONE batched transfer (round trips dominate on
        # tunneled devices). Keyed per vantage node too — build_route_db
        # serves any-vantage queries (ctrl API), and the root's out-edge
        # table is root-specific.
        gkey = (area, my_node_name)
        cached = self._dev_graph.get(gkey)
        if cached is None or cached[0] != link_state.generation:
            root_nbr, root_w, root_up, links = graph.out_table(root_idx)
            dev = jax.device_put(
                [
                    graph.in_nbr,
                    graph.in_w,
                    graph.in_up,
                    graph.node_overloaded,
                    np.int32(root_idx),
                    root_nbr,
                    root_w,
                    root_up,
                ]
            )
            self._dev_graph[gkey] = (link_state.generation, (dev, links))
            self._nh_set_cache.clear()  # link objects changed
        dev_graph, links = self._dev_graph[gkey][1]

        # announcer matrix: resident across solves, refreshed on either
        # prefix churn OR topology churn (node_index is baked into the
        # announcer indices, and topology changes can renumber nodes)
        mkey = (prefix_state.generation, link_state.generation)
        mcached = self._dev_matrix.get(area)
        if mcached is None or mcached[0] != mkey:
            matrix = build_prefix_matrix(
                prefix_state, graph.node_index, area, prefixes
            )
            dev_m = jax.device_put(
                [
                    matrix.ann_node,
                    matrix.ann_valid,
                    matrix.path_pref,
                    matrix.source_pref,
                    matrix.dist_adv,
                ]
            )
            self._dev_matrix[area] = (mkey, matrix, dev_m)
        _, matrix, dev_matrix = self._dev_matrix[area]

        pipeline = _jitted_pipeline()
        dist, metric, s3, nh_mask, has_route = pipeline(*dev_graph, *dev_matrix)
        # ONE batched device->host fetch (dist stays on device — the route
        # structure doesn't need it)
        metric_np, s3_np, nh_np, has_np = jax.device_get(
            (metric, s3, nh_mask, has_route)
        )
        self.last_device_stats = {
            "n_cap": graph.n_cap,
            "k_cap": graph.k_cap,
            "n_prefixes": len(matrix.prefix_list),
        }

        self._materialize(
            my_node_name,
            prefix_state,
            matrix,
            links,
            root_idx,
            metric_np,
            s3_np,
            nh_np,
            has_np,
            route_db,
        )

    def _materialize(
        self,
        my_node_name: str,
        prefix_state: PrefixState,
        matrix: PrefixMatrix,
        links: list,
        root_idx: int,
        metric: np.ndarray,
        s3: np.ndarray,
        nh_mask: np.ndarray,
        has_route: np.ndarray,
        route_db: DecisionRouteDb,
    ) -> None:
        """Host materialization of device outputs into RibUnicastEntry.

        All route-level filters run vectorized over numpy; the Python loop
        only constructs entries for surviving rows, with next-hop sets
        memoized per (slot pattern, metric) — route fan-outs repeat heavily
        across prefixes, so the cache collapses most construction cost.
        """
        p_n = len(matrix.prefix_list)
        ok = has_route[:p_n].copy()
        # v4 gate
        if not (self.cpu.enable_v4 or self.cpu.v4_over_v6_nexthop):
            ok &= ~matrix.is_v4[:p_n]
        s3n = s3[:p_n]
        # self-advertised skip (fast path has no prepend labels)
        ok &= ~(s3n & (matrix.ann_node[:p_n] == root_idx)).any(axis=1)
        # min-nexthop threshold: max over selected announcers vs nh count
        eff_min = np.where(s3n, matrix.min_nexthop[:p_n], -1).max(axis=1)
        nh_count = nh_mask[:p_n].sum(axis=1)
        ok &= (eff_min <= nh_count) & (nh_count > 0)

        d_range = range(nh_mask.shape[1])
        nh_cache = self._nh_set_cache
        for p in np.flatnonzero(ok):
            prefix = matrix.prefix_list[p]
            row = s3n[p]
            selected = [
                na for a, na in enumerate(matrix.node_areas[p]) if row[a]
            ]
            if not selected:
                continue
            m = int(metric[p])
            bits = tuple(d for d in d_range if nh_mask[p, d])
            # keyed per vantage: slot indices are root-relative
            key = (my_node_name, bits, m)
            nexthops = nh_cache.get(key)
            if nexthops is None:
                nexthops = frozenset(
                    NextHop(
                        address=links[d].nh_v6_from_node(my_node_name),
                        if_name=links[d].iface_from_node(my_node_name),
                        metric=m,
                        area=links[d].area,
                        neighbor_node_name=links[d].other_node(my_node_name),
                    )
                    for d in bits
                )
                nh_cache[key] = nexthops
            best = (
                selected[0]
                if len(selected) == 1
                else select_best_node_area(set(selected), my_node_name)
            )
            entries = prefix_state.entries_for(prefix)
            route_db.add_unicast_route(
                RibUnicastEntry(
                    prefix=prefix,
                    nexthops=nexthops,
                    best_prefix_entry=entries[best],
                    best_node_area=best,
                    igp_cost=m,
                )
            )
