"""Shift-decomposed device mirror of a LinkState graph — the TPU-native
relaxation structure.

Why not plain gather: XLA lowers per-element gathers on TPU to a scalar
loop (~300M elem/s measured on v5e — 3.6 ms per relaxation at 131k
nodes), which busts the <50 ms full-rebuild budget by itself. Rolls,
shifts and elementwise min/add are VPU-vectorized and ~1000x faster. So
the mirror decomposes the directed edge set into

  1. **shift classes**: all edges u -> u+delta for a fixed index delta
     form one class; the relaxation contribution of a class is
     `roll(dist + w_class, delta)` — two vector ops and a roll, no
     gather. Grids/tori decompose perfectly (4 classes); fat-trees and
     hierarchical fabrics mostly (pods/planes are index-affine under
     natural-sorted node numbering); arbitrary graphs partially.
  2. **residual ELL**: leftover edges in padded in-neighbor lists,
     relaxed with the (slow but correct) gather path. The decomposer
     keeps this small by construction.

Effective weights fold every vantage-INDEPENDENT usability rule on the
host: link down, source-node transit drain (overload). The root-as-
transit exclusion is vantage-specific and applied ON DEVICE (mask one
column), so a single resident graph serves every vantage — any-vantage
ctrl queries and the whole-fabric path reuse the same buffers.

INF discipline: INF32E = 2^29 and all real weights <= 2^28, so
`dist + w` never exceeds 2^30 and int32 relaxation needs NO overflow
masks: `new = min(dist, roll(dist + w, delta))` is exact because any sum
involving an INF stays >= INF and dist is pinned <= INF.

Delta maintenance: LinkState's bounded changelog (link_state.py
events_since) is applied as index writes into the class/residual arrays
(metric flap = one int32 store), with the dirty entries shipped to the
device as a scatter update instead of a full re-upload. Node-set changes
trigger a rebuild (rare).

Replaces the role of the reference's LinkState graph walk in runSpf
(openr/decision/LinkState.cpp:836-911) as the data structure the hot
loop runs on.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from openr_tpu.decision.link_state import Link, LinkState

# effectively-infinite metric; 2^29 so dist+w <= 2^30 < int32 max with no
# saturation logic anywhere in the kernels
INF32E = np.int32(1 << 29)
MAX_METRIC = int(1 << 28)

_NAT_RE = re.compile(r"(\d+)")


def natural_key(name: str):
    """Numeric-aware sort key: node-10-2 orders after node-2-3. Index
    locality under this ordering is what makes shift classes dense for
    generated and real-world (rsw001.p002-style) names alike."""
    return tuple(
        int(tok) if tok.isdigit() else tok for tok in _NAT_RE.split(name)
    )


def _next_pow2(n: int, floor: int = 1) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@dataclass
class EdgePlan:
    """Host arrays + bookkeeping; ships to device as-is."""

    n_nodes: int
    n_cap: int
    s_cap: int  # shift-class slots (padded; unused classes have delta 0, all-INF weights)
    deltas: np.ndarray  # int32 [s_cap]
    shift_w: np.ndarray  # int32 [s_cap, n_cap]; w of edge v -> v+deltas[k]
    # residual ELL is ROW-COMPACT: only destination nodes with irregular
    # in-edges occupy a row (hierarchical fabrics have few such nodes), so
    # the slow gather scales with real residual edges, not n_cap
    k_res: int  # real max residual in-degree (0 = no residual path)
    res_rows: np.ndarray  # int32 [r_cap]; destination node of each row, -1 pad
    res_nbr: np.ndarray  # int32 [r_cap, k_cap]; source node, -1 pad
    res_w: np.ndarray  # int32 [r_cap, k_cap]
    node_overloaded: np.ndarray  # bool [n_cap]
    node_names: list
    node_index: dict
    # (link_key, src_name) -> ("s", k, u_idx) | ("r", row, col)
    edge_loc: dict = field(default_factory=dict)
    # occupancy (a slot with INF weight may still be owned by a down link)
    _shift_occ: Optional[np.ndarray] = None  # bool [s_cap, n_cap]
    _res_row_of: dict = field(default_factory=dict)  # v_idx -> row
    _res_fill: Optional[np.ndarray] = None  # int32 [r_cap] cols used per row
    _res_nrows: int = 0
    # delta-update state
    synced_generation: int = -1
    needs_rebuild: bool = False
    # dirty entries since last device sync: lists of flat indices/values
    dirty_shift: list = field(default_factory=list)  # (k, u, w)
    dirty_res: list = field(default_factory=list)  # (v, col, w)
    dirty_res_nbr: bool = False  # residual nbr indices changed (new slots)
    # bumped when node index mapping changes (matrix cache key)
    index_version: int = 0

    # -- host-side out-edge view (per-vantage, cheap) ----------------------

    def out_links(self, link_state: LinkState, root: str):
        """Root's out-edge slots: (nbr_idx[d], w_eff[d], links[d]) in
        deterministic sorted-Link order. Built per call — O(degree)."""
        links = link_state.ordered_links_from_node(root)
        nbr = np.full(max(_next_pow2(len(links), 4), 4), -1, np.int32)
        w = np.full(nbr.shape[0], INF32E, np.int32)
        out = []
        for d, link in enumerate(links[: nbr.shape[0]]):
            other = link.other_node(root)
            nbr[d] = self.node_index[other]
            w[d] = (
                min(link.metric_from_node(root), MAX_METRIC)
                if link.is_up()
                else INF32E
            )
            out.append(link)
        return nbr, w, out


def _effective_w(link: Link, src: str, overloaded_src: bool) -> int:
    if not link.is_up() or overloaded_src:
        return int(INF32E)
    return min(link.metric_from_node(src), MAX_METRIC)


def build_plan(
    link_state: LinkState,
    n_cap: int = 0,
    s_max: int = 64,
    min_class_frac: float = 1 / 128,
    prev: Optional[EdgePlan] = None,
) -> EdgePlan:
    """Full build: natural-order the nodes, histogram index deltas, keep
    the top classes, spill the rest to the residual ELL."""
    names = sorted(link_state.get_adjacency_databases().keys(), key=natural_key)
    index = {n: i for i, n in enumerate(names)}
    n = len(names)
    if prev is not None:
        n_cap = max(n_cap, prev.n_cap)
    n_cap = max(n_cap, _next_pow2(max(n, 1), 8))

    # directed edge extraction (one tight pass; full builds are rare —
    # steady-state churn goes through apply_events)
    links_sorted = sorted(link_state.all_links())
    e2 = len(links_sorted) * 2
    src = np.empty(e2, np.int32)
    dst = np.empty(e2, np.int32)
    w = np.empty(e2, np.int32)
    overload = link_state.is_node_overloaded
    node_over = np.zeros(n_cap, bool)
    for i, nm in enumerate(names):
        node_over[i] = overload(nm)
    for e, link in enumerate(links_sorted):
        i1, i2 = index[link.n1], index[link.n2]
        src[2 * e] = i1
        dst[2 * e] = i2
        w[2 * e] = _effective_w(link, link.n1, node_over[i1])
        src[2 * e + 1] = i2
        dst[2 * e + 1] = i1
        w[2 * e + 1] = _effective_w(link, link.n2, node_over[i2])

    delta = dst - src
    # class selection: most-populous deltas, subject to a usefulness floor
    if e2:
        vals, counts = np.unique(delta, return_counts=True)
        order = np.argsort(-counts)
        floor = max(8, int(e2 * min_class_frac))
        chosen = [int(vals[o]) for o in order[:s_max] if counts[o] >= floor]
    else:
        chosen = []
    s_cap = _next_pow2(max(len(chosen), 1), 4)
    if prev is not None:
        s_cap = max(s_cap, prev.s_cap)
    deltas = np.zeros(s_cap, np.int32)
    deltas[: len(chosen)] = chosen
    class_of = {d: k for k, d in enumerate(chosen)}

    shift_w = np.full((s_cap, n_cap), INF32E, np.int32)
    shift_occ = np.zeros((s_cap, n_cap), bool)
    edge_loc: dict = {}
    res_edges: list = []  # (v, u, w, link, src_name)

    for e in range(e2):
        link = links_sorted[e // 2]
        u, v = int(src[e]), int(dst[e])
        src_name = names[u]
        k = class_of.get(int(delta[e]))
        if k is not None and not shift_occ[k, u]:
            shift_occ[k, u] = True
            shift_w[k, u] = w[e]
            edge_loc[(link, src_name)] = ("s", k, u)
        else:
            res_edges.append((v, u, int(w[e]), link, src_name))

    res_count: dict[int, int] = {}
    for v, _u, _w, _l, _s in res_edges:
        res_count[v] = res_count.get(v, 0) + 1
    k_res = max(res_count.values()) if res_count else 0
    k_cap = _next_pow2(max(k_res, 1), 2)
    n_rows = len(res_count)
    r_cap = _next_pow2(max(n_rows, 1), 8)
    if prev is not None and prev.k_res:
        k_cap = max(k_cap, prev.res_nbr.shape[1])
        r_cap = max(r_cap, prev.res_rows.shape[0])
    res_rows = np.full(r_cap, -1, np.int32)
    res_nbr = np.full((r_cap, k_cap), -1, np.int32)
    res_w = np.full((r_cap, k_cap), INF32E, np.int32)
    row_of: dict[int, int] = {}
    for row, v in enumerate(sorted(res_count)):
        res_rows[row] = v
        row_of[v] = row
    fill = np.zeros(r_cap, np.int32)
    for v, u, we, link, src_name in res_edges:
        row = row_of[v]
        col = int(fill[row])
        fill[row] = col + 1
        res_nbr[row, col] = u
        res_w[row, col] = we
        edge_loc[(link, src_name)] = ("r", row, col)

    index_version = 0
    if prev is not None:
        index_version = (
            prev.index_version
            if prev.node_names == names
            else prev.index_version + 1
        )

    return EdgePlan(
        n_nodes=n,
        n_cap=n_cap,
        s_cap=s_cap,
        deltas=deltas,
        shift_w=shift_w,
        k_res=k_res,
        res_rows=res_rows,
        res_nbr=res_nbr,
        res_w=res_w,
        node_overloaded=node_over,
        node_names=names,
        node_index=index,
        edge_loc=edge_loc,
        _shift_occ=shift_occ,
        _res_row_of=row_of,
        _res_fill=fill,
        _res_nrows=n_rows,
        synced_generation=link_state.generation,
        index_version=index_version,
    )


def _set_edge_w(plan: EdgePlan, link: Link, src_name: str, w: int) -> None:
    loc = plan.edge_loc.get((link, src_name))
    if loc is None:
        plan.needs_rebuild = True
        return
    if loc[0] == "s":
        _, k, u = loc
        if plan.shift_w[k, u] != w:
            plan.shift_w[k, u] = w
            plan.dirty_shift.append((k, u, w))
    else:
        _, row, col = loc
        if plan.res_w[row, col] != w:
            plan.res_w[row, col] = w
            plan.dirty_res.append((row, col, w))


def _refresh_link(plan: EdgePlan, link: Link) -> None:
    for src_name in (link.n1, link.n2):
        u = plan.node_index.get(src_name)
        if u is None:
            plan.needs_rebuild = True
            return
        _set_edge_w(
            plan, link, src_name, _effective_w(link, src_name, bool(plan.node_overloaded[u]))
        )


def _add_link(plan: EdgePlan, link: Link) -> None:
    for src_name, dst_name in ((link.n1, link.n2), (link.n2, link.n1)):
        if (link, src_name) in plan.edge_loc:
            _refresh_link(plan, link)
            continue
        u = plan.node_index.get(src_name)
        v = plan.node_index.get(dst_name)
        if u is None or v is None:
            plan.needs_rebuild = True
            return
        w = _effective_w(link, src_name, bool(plan.node_overloaded[u]))
        # try a shift slot first
        d = v - u
        placed = False
        for k in range(plan.s_cap):
            if plan.deltas[k] == d and not plan._shift_occ[k, u]:
                # class 0 slot with delta 0 is a real class only if some
                # chosen delta was 0 — guard: delta-0 self-loops don't occur
                if d == 0:
                    break
                plan._shift_occ[k, u] = True
                plan.edge_loc[(link, src_name)] = ("s", k, u)
                _set_edge_w(plan, link, src_name, w)
                placed = True
                break
        if placed:
            continue
        row = plan._res_row_of.get(v)
        if row is None:
            if plan._res_nrows >= plan.res_rows.shape[0]:
                plan.needs_rebuild = True
                return
            row = plan._res_nrows
            plan._res_nrows = row + 1
            plan._res_row_of[v] = row
            plan.res_rows[row] = v
        col = int(plan._res_fill[row])
        if col >= plan.res_nbr.shape[1]:
            plan.needs_rebuild = True
            return
        plan._res_fill[row] = col + 1
        plan.res_nbr[row, col] = u
        plan.res_w[row, col] = w
        plan.k_res = max(plan.k_res, col + 1)
        plan.edge_loc[(link, src_name)] = ("r", row, col)
        plan.dirty_res.append((row, col, w))
        # res_nbr/res_rows changed too — consumer re-uploads those arrays
        plan.dirty_res_nbr = True


def _remove_link(plan: EdgePlan, link: Link) -> None:
    """Tombstone: weight INF, slot stays owned (a re-added link reuses
    it); residual slots are NOT compacted."""
    for src_name in (link.n1, link.n2):
        _set_edge_w(plan, link, src_name, int(INF32E))


def _node_overload_changed(
    plan: EdgePlan, link_state: LinkState, node: str
) -> None:
    u = plan.node_index.get(node)
    if u is None:
        plan.needs_rebuild = True
        return
    plan.node_overloaded[u] = link_state.is_node_overloaded(node)
    for link in link_state.links_from_node(node):
        _set_edge_w(
            plan, link, node, _effective_w(link, node, bool(plan.node_overloaded[u]))
        )


def apply_events(
    plan: EdgePlan, link_state: LinkState, events: list[tuple]
) -> bool:
    """Apply a changelog slice; returns False when a rebuild is needed."""
    for ev in events:
        kind = ev[0]
        if kind == "nodes":
            plan.needs_rebuild = True
        elif kind == "links":
            for link in ev[1]:
                _refresh_link(plan, link)
        elif kind == "added":
            for link in ev[1]:
                _add_link(plan, link)
        elif kind == "removed":
            for link in ev[1]:
                _remove_link(plan, link)
        elif kind == "overload":
            _node_overload_changed(plan, link_state, ev[1])
        if plan.needs_rebuild:
            return False
    plan.synced_generation = link_state.generation
    return True


def drain_dirty(plan: EdgePlan):
    """Consume pending scatter updates: ((shift_flat_idx, shift_vals),
    (res_flat_idx, res_vals), res_nbr_changed). Flat indices index the
    raveled [s_cap, n_cap] / [n_cap, k_res_cap] device arrays."""
    n_cap = plan.n_cap
    kr = plan.res_nbr.shape[1]
    if plan.dirty_shift:
        s_idx = np.array(
            [k * n_cap + u for k, u, _ in plan.dirty_shift], np.int32
        )
        s_val = np.array([w for _, _, w in plan.dirty_shift], np.int32)
    else:
        s_idx = s_val = None
    if plan.dirty_res:
        r_idx = np.array(
            [row * kr + c for row, c, _ in plan.dirty_res], np.int32
        )
        r_val = np.array([w for _, _, w in plan.dirty_res], np.int32)
    else:
        r_idx = r_val = None
    nbr_changed = plan.dirty_res_nbr
    plan.dirty_shift = []
    plan.dirty_res = []
    plan.dirty_res_nbr = False
    return (s_idx, s_val), (r_idx, r_val), nbr_changed


def sync_plan(
    link_state: LinkState, plan: Optional[EdgePlan], **build_kwargs
) -> EdgePlan:
    """Bring a plan up to date with a LinkState: apply changelog deltas
    when possible, full-rebuild otherwise."""
    if plan is None or plan.needs_rebuild:
        return build_plan(link_state, prev=plan, **build_kwargs)
    if plan.synced_generation == link_state.generation:
        return plan
    events = link_state.events_since(plan.synced_generation)
    if events is None or not apply_events(plan, link_state, events):
        return build_plan(link_state, prev=plan, **build_kwargs)
    return plan
