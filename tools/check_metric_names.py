#!/usr/bin/env python3
"""Lint-time guard for the OpenMetrics exposition endpoint.

`normalize_metric_name` (runtime/metrics_export.py) maps the fabric's
dotted counter names onto Prometheus identifiers by rewriting every
invalid byte to `_`. That mapping is total but not injective — `a.b`
and `a_b` both become `openr_tpu_a_b` — so a collision would make the
endpoint silently drop one family. This checker walks the source for
every counter/stat name the code can emit and fails the lint lane when

  - any name normalizes to an invalid exposition identifier, or
  - two DIFFERENT raw names normalize to the SAME identifier, or
  - a stat's derived families (`<stat>_sum/_count/_max/_truncated`)
    collide with an explicitly-bumped counter.

Dynamic name segments (f-string placeholders like
`kvstore.{node}.sent_messages`) are abstracted to a fixed token — two
call sites with the same shape are one family; runtime-value collisions
are out of static reach and accepted.

Usage: python tools/check_metric_names.py [package_dir]
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from openr_tpu.runtime.metrics_export import (  # noqa: E402
    is_valid_metric_name,
    normalize_metric_name,
)

# CounterRegistry write methods whose first argument names a family
COUNTER_METHODS = {"increment", "set_counter"}
STAT_METHODS = {"add_stat_value"}
# what one stat family expands to in the exposition
STAT_SUFFIXES = ("", "_sum", "_count", "_max", "_truncated")
PLACEHOLDER = "X"


def _name_of(node: ast.AST) -> str | None:
    """First-argument metric name, with f-string fields abstracted."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append(PLACEHOLDER)
        return "".join(parts)
    return None  # computed name (variable); not statically checkable


def collect(package_dir: Path) -> tuple[dict, dict, list]:
    """-> ({raw counter name: site}, {raw stat name: site}, errors)."""
    counter_names: dict[str, str] = {}
    stat_names: dict[str, str] = {}
    errors: list[str] = []
    for path in sorted(package_dir.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            errors.append(f"{path}: unparseable: {e}")
            continue
        rel = path.relative_to(package_dir.parent)
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.args
            ):
                continue
            method = node.func.attr
            if method in COUNTER_METHODS:
                bucket = counter_names
            elif method in STAT_METHODS:
                bucket = stat_names
            else:
                continue
            raw = _name_of(node.args[0])
            if raw is None:
                continue
            bucket.setdefault(raw, f"{rel}:{node.lineno}")
    return counter_names, stat_names, errors


def check(counter_names: dict, stat_names: dict) -> list[str]:
    errors: list[str] = []
    # exposition family -> (raw name, site); stats expand to their
    # derived families so `a.b` (stat) vs `a.b_max` (counter) is caught
    families: dict[str, tuple[str, str]] = {}

    def claim(family: str, raw: str, site: str) -> None:
        if not is_valid_metric_name(family):
            errors.append(
                f"{site}: metric {raw!r} normalizes to invalid "
                f"exposition identifier {family!r}"
            )
            return
        prev = families.get(family)
        if prev is not None and prev[0] != raw:
            errors.append(
                f"{site}: metric {raw!r} collides with {prev[0]!r} "
                f"({prev[1]}) — both normalize to {family!r}"
            )
            return
        families.setdefault(family, (raw, site))

    for raw, site in sorted(counter_names.items()):
        claim(normalize_metric_name(raw), raw, site)
    for raw, site in sorted(stat_names.items()):
        base = normalize_metric_name(raw)
        for suffix in STAT_SUFFIXES:
            claim(base + suffix, raw, site)
    return errors


def main(argv: list[str]) -> int:
    package_dir = Path(argv[1]) if len(argv) > 1 else REPO_ROOT / "openr_tpu"
    counter_names, stat_names, errors = collect(package_dir)
    errors += check(counter_names, stat_names)
    if errors:
        for err in errors:
            print(f"check_metric_names: {err}", file=sys.stderr)
        return 1
    print(
        f"check_metric_names: OK — {len(counter_names)} counter and "
        f"{len(stat_names)} stat families normalize cleanly"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
