from openr_tpu.ctrl.ctrl_server import CtrlServer  # noqa: F401
