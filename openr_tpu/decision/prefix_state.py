"""Global prefix advertisement state.

Role of the reference's openr/decision/PrefixState.{h,cpp}: map
prefix -> PrefixEntries (= map (node, area) -> PrefixEntry), with
update/delete returning the set of changed prefixes so Decision can do
incremental recomputation, plus received-routes dump for the ctrl API.
"""

from __future__ import annotations

from typing import Optional

import functools

from openr_tpu.types import PrefixDatabase, PrefixEntry, parse_prefix

# (node, area) -> advertised entry
PrefixEntries = dict


# unbounded: the LSDB-scale target is ~100k prefixes and an LRU bound
# below the working set thrashes (ip_network parsing is ~25us a miss —
# a 64k bound cost ~2s per 100k-prefix matrix rebuild); entries are
# small interned strings
@functools.lru_cache(maxsize=None)
def canonical_prefix(prefix: str) -> str:
    return str(parse_prefix(prefix))


class PrefixState:
    def __init__(self) -> None:
        self._prefixes: dict[str, PrefixEntries] = {}
        # bumped on every applied change; derived structures (the device
        # announcer matrix, ops/csr.py) key their caches on it
        self.generation = 0

    def prefixes(self) -> dict[str, PrefixEntries]:
        return self._prefixes

    def entries_for(self, prefix: str) -> Optional[PrefixEntries]:
        return self._prefixes.get(canonical_prefix(prefix))

    def update_prefix_database(self, db: PrefixDatabase) -> set[str]:
        """Apply one per-prefix-key database (single entry + tombstone flag,
        ref PrefixState::updatePrefix); returns changed prefixes."""
        node_area = (db.this_node_name, db.area)
        changed: set[str] = set()
        for entry in db.prefix_entries:
            pfx = canonical_prefix(entry.prefix)
            if db.delete_prefix:
                entries = self._prefixes.get(pfx)
                if entries is not None and node_area in entries:
                    del entries[node_area]
                    if not entries:
                        del self._prefixes[pfx]
                    changed.add(pfx)
            else:
                entries = self._prefixes.setdefault(pfx, {})
                if entries.get(node_area) != entry:
                    entries[node_area] = entry
                    changed.add(pfx)
        if changed:
            self.generation += 1
        return changed

    def delete_entries_of(self, node: str, area: str) -> set[str]:
        """Drop every advertisement by (node, area) — key expiry path."""
        node_area = (node, area)
        changed: set[str] = set()
        for pfx in list(self._prefixes):
            entries = self._prefixes[pfx]
            if node_area in entries:
                del entries[node_area]
                if not entries:
                    del self._prefixes[pfx]
                changed.add(pfx)
        if changed:
            self.generation += 1
        return changed

    def received_routes(
        self, prefix_filter: str = "", node_filter: str = ""
    ) -> list[tuple[str, tuple[str, str], PrefixEntry]]:
        """Filtered dump (ref PrefixState::getReceivedRoutesFiltered)."""
        out = []
        for pfx, entries in self._prefixes.items():
            if prefix_filter and pfx != canonical_prefix(prefix_filter):
                continue
            for node_area, entry in entries.items():
                if node_filter and node_area[0] != node_filter:
                    continue
                out.append((pfx, node_area, entry))
        return out
