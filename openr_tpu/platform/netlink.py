"""Async rtnetlink client — the kernel boundary.

Role of the reference's openr/nl/NetlinkProtocolSocket.{h,cpp}: an
asyncio AF_NETLINK/NETLINK_ROUTE socket with sequence-numbered request
pipelining (ack futures, bounded in-flight window — ref h:33-70),
multipart dump parsing, and RTM_NEWROUTE/RTM_DELROUTE/RTM_GETROUTE
message (de)serialization with RTA attributes incl. RTA_MULTIPATH ECMP
next-hop groups (ref NetlinkRouteMessage.cpp). Implemented directly on
the kernel's binary netlink ABI via struct packing — no external
dependencies.

Route add/delete requires CAP_NET_ADMIN; dumps are unprivileged. The
platform FibHandler (fib_handler.py) drives this behind the dataplane
seam; tests gate kernel-mutating cases on capability.
"""

from __future__ import annotations

import asyncio
import ipaddress
import socket
import struct
from dataclasses import dataclass, field
from typing import Optional

# netlink message types / flags (linux/netlink.h)
NLMSG_ERROR = 2
NLMSG_DONE = 3
NLM_F_REQUEST = 0x01
NLM_F_MULTI = 0x02
NLM_F_ACK = 0x04
NLM_F_ROOT = 0x100
NLM_F_MATCH = 0x200
NLM_F_DUMP = NLM_F_ROOT | NLM_F_MATCH
NLM_F_REPLACE = 0x100
NLM_F_EXCL = 0x200
NLM_F_CREATE = 0x400

# rtnetlink (linux/rtnetlink.h)
RTM_NEWLINK = 16
RTM_DELLINK = 17
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_DELADDR = 21
RTM_GETADDR = 22
RTM_NEWROUTE = 24
RTM_DELROUTE = 25
RTM_GETROUTE = 26
RTM_NEWNEIGH = 28
RTM_DELNEIGH = 29
RTM_GETNEIGH = 30
RTM_NEWRULE = 32
RTM_DELRULE = 33
RTM_GETRULE = 34
RTN_UNICAST = 1
RT_SCOPE_UNIVERSE = 0
RT_TABLE_MAIN = 254

RTA_DST = 1
RTA_OIF = 4
RTA_GATEWAY = 5
RTA_PRIORITY = 6
RTA_MULTIPATH = 9
RTA_TABLE = 15
RTA_VIA = 18
RTA_NEWDST = 19
RTA_ENCAP_TYPE = 21
RTA_ENCAP = 22

# MPLS dataplane (linux/mpls.h, linux/lwtunnel.h, linux/mpls_iptunnel.h)
AF_MPLS = 28
LWTUNNEL_ENCAP_MPLS = 1
MPLS_IPTUNNEL_DST = 1

# link attributes (linux/if_link.h) + addr attributes (linux/if_addr.h)
IFLA_IFNAME = 3
IFA_ADDRESS = 1
IFA_LOCAL = 2

# neighbor table (linux/neighbour.h)
NDA_DST = 1
NDA_LLADDR = 2
NUD_INCOMPLETE = 0x01
NUD_REACHABLE = 0x02
NUD_STALE = 0x04
NUD_DELAY = 0x08
NUD_PROBE = 0x10
NUD_FAILED = 0x20
NUD_NOARP = 0x40
NUD_PERMANENT = 0x80

# policy routing rules (linux/fib_rules.h)
FRA_PRIORITY = 6
FRA_FWMARK = 10
FRA_TABLE = 15
FR_ACT_TO_TBL = 1

# interface flags (linux/if.h)
IFF_UP = 0x1
IFF_RUNNING = 0x40
IFF_LOOPBACK = 0x8

# multicast groups for event subscription (linux/rtnetlink.h); rule
# groups have no legacy RTMGRP_ alias — masks are 1 << (RTNLGRP - 1)
RTMGRP_LINK = 0x1
RTMGRP_NEIGH = 0x4
RTMGRP_IPV4_IFADDR = 0x10
RTMGRP_IPV4_RULE = 0x80
RTMGRP_IPV6_IFADDR = 0x100
RTMGRP_IPV6_RULE = 1 << 18  # RTNLGRP_IPV6_RULE (19)

_NLMSGHDR = struct.Struct("=IHHII")  # len, type, flags, seq, pid
_RTMSG = struct.Struct("=BBBBBBBBI")  # family,dst,src,tos,table,proto,scope,type,flags
_IFINFOMSG = struct.Struct("=BBHiII")  # family,pad,type,index,flags,change
_IFADDRMSG = struct.Struct("=BBBBI")  # family,prefixlen,flags,scope,index
_NDMSG = struct.Struct("=BBHiHBB")  # family,pad1,pad2,ifindex,state,flags,type
# fib_rule_hdr: family,dst_len,src_len,tos,table,res1,res2,action,flags —
# byte-for-byte the rtmsg layout, so _RTMSG packs/unpacks it too
_RTA = struct.Struct("=HH")  # len, type
_RTNH = struct.Struct("=HBBi")  # len, flags, hops, ifindex

# protocol id this daemon stamps on its routes (ref kRouteProtoId role)
PROTO_OPENR = 99


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _rta(rta_type: int, payload: bytes) -> bytes:
    length = _RTA.size + len(payload)
    return _RTA.pack(length, rta_type) + payload + b"\0" * (
        _align4(length) - length
    )


@dataclass(frozen=True)
class NlNextHop:
    """One kernel next hop: gateway address and/or output interface.

    out_labels: MPLS labels this hop imposes — on an IP route they
    encode as LWTUNNEL MPLS encap (push); on an AF_MPLS route as
    RTA_NEWDST (swap). Empty on an MPLS route means pop-and-forward
    (PHP) — or pop-and-lookup when there is no gateway either."""

    gateway: Optional[str] = None  # "10.0.0.1" / "fe80::1"
    ifindex: int = 0
    weight: int = 0  # ECMP weight hint (rtnh_hops = weight - 1)
    out_labels: tuple = ()


@dataclass(frozen=True)
class NlMplsRoute:
    """One kernel MPLS label route (ref NetlinkRouteMessage.cpp:618-769
    AF_MPLS encode)."""

    label: int
    nexthops: tuple = ()  # NlNextHop
    protocol: int = PROTO_OPENR


def mpls_supported() -> bool:
    """True when the kernel has the MPLS dataplane loaded
    (mpls_router); programming AF_MPLS routes without it returns
    EAFNOSUPPORT."""
    import os

    return os.path.isdir("/proc/sys/net/mpls")


def _mpls_label_stack(labels: tuple) -> bytes:
    """Label records, 4 bytes each, bottom-of-stack bit on the last
    (linux/mpls.h mpls_label: label<<12 | tc<<9 | bos<<8 | ttl)."""
    out = bytearray()
    for i, label in enumerate(labels):
        bos = 1 if i == len(labels) - 1 else 0
        out += struct.pack(">I", (int(label) << 12) | (bos << 8))
    return bytes(out)


def _rta_via(gateway: str) -> bytes:
    """RTA_VIA payload: u16 address family + raw address bytes."""
    addr = ipaddress.ip_address(gateway)
    family = socket.AF_INET if addr.version == 4 else socket.AF_INET6
    return _rta(RTA_VIA, struct.pack("=H", family) + addr.packed)


def _mpls_encap_attrs(out_labels: tuple) -> bytes:
    """LWTUNNEL MPLS push encap for an IP route's next hop
    (ref NetlinkRouteMessage.cpp encap encode :664)."""
    inner = _rta(MPLS_IPTUNNEL_DST, _mpls_label_stack(out_labels))
    return _rta(RTA_ENCAP_TYPE, struct.pack("=H", LWTUNNEL_ENCAP_MPLS)) + \
        _rta(RTA_ENCAP, inner)


@dataclass
class NlRoute:
    prefix: str
    nexthops: tuple = ()
    metric: int = 0
    table: int = RT_TABLE_MAIN
    protocol: int = PROTO_OPENR

    @property
    def family(self) -> int:
        return (
            socket.AF_INET
            if ipaddress.ip_network(self.prefix, strict=False).version == 4
            else socket.AF_INET6
        )


@dataclass(frozen=True)
class NlLink:
    """One kernel interface (RTM_NEWLINK/DELLINK payload)."""

    ifindex: int
    name: str
    flags: int = 0

    @property
    def is_up(self) -> bool:
        # operationally usable: administratively up AND carrier present
        return bool(self.flags & IFF_UP) and bool(self.flags & IFF_RUNNING)

    @property
    def is_loopback(self) -> bool:
        return bool(self.flags & IFF_LOOPBACK)


@dataclass(frozen=True)
class NlAddr:
    """One kernel interface address (RTM_NEWADDR/DELADDR payload)."""

    ifindex: int
    prefix: str  # "10.0.0.1/24" / "fe80::1/64"
    family: int = socket.AF_INET


@dataclass(frozen=True)
class NlNeighbor:
    """One neighbor-table entry — ARP/NDP cache line (ref fbnl::Neighbor,
    NetlinkTypes.h:466; RTM_NEWNEIGH/DELNEIGH/GETNEIGH carry ndmsg)."""

    ifindex: int
    destination: str  # neighbor's network-layer address
    lladdr: str = ""  # link-layer (MAC) address, "" when unresolved
    state: int = 0  # NUD_* bitmask
    family: int = socket.AF_INET

    @property
    def is_reachable(self) -> bool:
        # a usable entry: confirmed, static, or a no-ARP device
        return bool(self.state & (NUD_REACHABLE | NUD_PERMANENT | NUD_NOARP))


@dataclass(frozen=True)
class NlRule:
    """One policy-routing rule (ref fbnl::Rule, NetlinkTypes.h:609:
    family + FR_ACT_* action + table, optional fwmark/priority)."""

    family: int = socket.AF_INET
    action: int = FR_ACT_TO_TBL
    table: int = RT_TABLE_MAIN
    priority: Optional[int] = None
    fwmark: Optional[int] = None


@dataclass
class _Pending:
    future: asyncio.Future
    dump: bool = False
    results: list = field(default_factory=list)
    parse: Optional[object] = None  # per-dump message parser


class NetlinkRouteSocket:
    """Pipelined rtnetlink requests (ref NetlinkProtocolSocket.h:33-70:
    up to `max_in_flight` un-acked requests, each completing its future
    on ACK/ERROR/DONE). With `groups`, the socket also joins rtnetlink
    multicast groups and surfaces unsolicited kernel events through
    `event_cb(kind, obj)` — kind in {"link", "link_del", "addr",
    "addr_del", "neigh", "neigh_del", "rule", "rule_del"} with
    NlLink/NlAddr/NlNeighbor/NlRule payloads (ref event queue,
    NetlinkProtocolSocket.h:29-31)."""

    def __init__(self, max_in_flight: int = 256, event_cb=None):
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._pending: dict[int, _Pending] = {}
        self._window = asyncio.Semaphore(max_in_flight)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._portid = 0
        self.event_cb = event_cb

    # -- lifecycle ---------------------------------------------------------

    def open(self, groups: int = 0) -> None:
        sock = socket.socket(
            socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
        )
        sock.bind((0, groups))
        sock.setblocking(False)
        self._sock = sock
        # kernel-assigned portid: unicast replies to OUR requests carry
        # it in nlmsg_pid; multicast events carry the originator's pid
        # (0 for the kernel itself). Demultiplexing on it — not on seq —
        # keeps another client's event from colliding with a pending
        # dump's sequence number and truncating it.
        self._portid = sock.getsockname()[0]
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(sock.fileno(), self._on_readable)

    def close(self) -> None:
        if self._sock is not None:
            if self._loop is not None:
                self._loop.remove_reader(self._sock.fileno())
            self._sock.close()
            self._sock = None
        for p in self._pending.values():
            # _complete() releases a window slot per answered request;
            # failing un-answered ones here bypasses it, and without a
            # matching release a close with in-flight requests permanently
            # shrinks the window if the socket is reopened. Already-done
            # futures (answered, not yet reaped by _send) released theirs
            # in _complete — skip them or the slot double-releases.
            if not p.future.done():
                p.future.set_exception(ConnectionError("netlink closed"))
                self._window.release()
            elif p.future.cancelled():
                # timed-out request whose _send finally hasn't run yet:
                # _complete never released its slot, and after we clear
                # _pending the finally's pop comes back empty so IT won't
                # release either — do it here
                self._window.release()
        self._pending.clear()

    # -- request plumbing --------------------------------------------------

    async def _send(self, msg_type: int, flags: int, payload: bytes,
                    dump: bool = False, parse=None) -> list:
        assert self._sock is not None, "open() first"
        await self._window.acquire()
        self._seq += 1
        seq = self._seq
        hdr = _NLMSGHDR.pack(
            _NLMSGHDR.size + len(payload), msg_type, flags, seq, 0
        )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq] = _Pending(fut, dump=dump, parse=parse)
        try:
            self._sock.send(hdr + payload)
        except OSError:
            self._pending.pop(seq, None)
            self._window.release()
            raise
        try:
            return await asyncio.wait_for(fut, 5.0)
        finally:
            # a timed-out request still holds a window slot (_complete
            # releases only for answered requests) — release it here, or
            # lost kernel replies would leak slots until every _send
            # deadlocks in acquire(). wait_for CANCELS the future on
            # timeout (a cancelled future reads as done), so the "did
            # _complete ever run" test is cancelled(), not done().
            if self._pending.pop(seq, None) is not None and fut.cancelled():
                self._window.release()

    def _on_readable(self) -> None:
        assert self._sock is not None
        try:
            data = self._sock.recv(1 << 17)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            # ENOBUFS means the kernel dropped replies — the affected
            # seqs are unknowable, so fail every in-flight request (each
            # failure releases its window slot) rather than letting them
            # all time out against a silently-lost ack
            for seq in list(self._pending):
                self._complete(seq, error=e.errno or 105)
            return
        off = 0
        while off + _NLMSGHDR.size <= len(data):
            mlen, mtype, mflags, seq, _pid = _NLMSGHDR.unpack_from(data, off)
            if mlen < _NLMSGHDR.size:
                break
            body = data[off + _NLMSGHDR.size:off + mlen]
            self._on_msg(mtype, mflags, seq, body, _pid)
            off += _align4(mlen)

    def _complete(self, seq: int, value=None, error: Optional[int] = None):
        p = self._pending.get(seq)
        if p is None or p.future.done():
            return
        self._window.release()
        if error:
            p.future.set_exception(
                OSError(error, f"netlink error {error} (seq {seq})")
            )
        else:
            p.future.set_result(p.results if p.dump else value)

    _EVENT_KINDS = {
        RTM_NEWLINK: "link",
        RTM_DELLINK: "link_del",
        RTM_NEWADDR: "addr",
        RTM_DELADDR: "addr_del",
        RTM_NEWNEIGH: "neigh",
        RTM_DELNEIGH: "neigh_del",
        RTM_NEWRULE: "rule",
        RTM_DELRULE: "rule_del",
    }

    def _on_msg(self, mtype: int, mflags: int, seq: int, body: bytes,
                pid: Optional[int] = None):
        is_reply = pid is None or pid == self._portid
        if not is_reply:
            if self.event_cb is not None:
                kind = self._EVENT_KINDS.get(mtype)
                if kind is not None:
                    obj = _parse_event(kind, body)
                    if obj is not None:
                        self.event_cb(kind, obj)
            return
        if mtype == NLMSG_ERROR:
            (code,) = struct.unpack_from("=i", body)
            self._complete(seq, error=-code if code else None)
        elif mtype == NLMSG_DONE:
            self._complete(seq)
        else:
            p = self._pending.get(seq)
            if p is not None and p.dump:
                parse = p.parse or _parse_route_msg
                parsed = parse(body)
                if parsed is not None:
                    p.results.append(parsed)
                if not (mflags & NLM_F_MULTI):
                    self._complete(seq)
                return
            if p is None and self.event_cb is not None:
                # kernel-originated notification addressed to us
                # (pid == portid happens for our own route changes too)
                kind = self._EVENT_KINDS.get(mtype)
                if kind is None:
                    return
                obj = _parse_event(kind, body)
                if obj is not None:
                    self.event_cb(kind, obj)

    # -- route operations (ref addRoute/deleteRoute/getAllRoutes) ----------

    async def add_route(self, route: NlRoute, replace: bool = True) -> None:
        flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE
        if replace:
            flags |= NLM_F_REPLACE
        await self._send(RTM_NEWROUTE, flags, _build_route_msg(route))

    async def delete_route(self, route: NlRoute) -> None:
        await self._send(
            RTM_DELROUTE,
            NLM_F_REQUEST | NLM_F_ACK,
            _build_route_msg(route, for_delete=True),
        )

    async def get_routes(self, family: int = socket.AF_INET,
                         table: Optional[int] = None,
                         protocol: Optional[int] = None) -> list[NlRoute]:
        rtm = _RTMSG.pack(family, 0, 0, 0, 0, 0, 0, 0, 0)
        routes = await self._send(
            RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, rtm, dump=True
        )
        return [
            r
            for r in routes
            if (table is None or r.table == table)
            and (protocol is None or r.protocol == protocol)
        ]

    # -- MPLS label routes (ref NetlinkRouteMessage.cpp:618-769) -----------

    async def add_mpls_route(
        self, route: NlMplsRoute, replace: bool = True
    ) -> None:
        flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE
        if replace:
            flags |= NLM_F_REPLACE
        await self._send(RTM_NEWROUTE, flags, _build_mpls_route_msg(route))

    async def delete_mpls_route(self, route: NlMplsRoute) -> None:
        await self._send(
            RTM_DELROUTE,
            NLM_F_REQUEST | NLM_F_ACK,
            _build_mpls_route_msg(route, for_delete=True),
        )

    async def get_mpls_routes(
        self, protocol: Optional[int] = None
    ) -> list[NlMplsRoute]:
        rtm = _RTMSG.pack(AF_MPLS, 0, 0, 0, 0, 0, 0, 0, 0)
        routes = await self._send(
            RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, rtm,
            dump=True, parse=_parse_mpls_route_msg,
        )
        return [
            r for r in routes
            if protocol is None or r.protocol == protocol
        ]

    # -- interface addresses (ref addIfAddress/deleteIfAddress) ------------

    async def add_addr(self, ifindex: int, prefix: str) -> None:
        """Assign `addr/len` to an interface (ref NetlinkAddrMessage
        encode; used by the prefix allocator to install the derived
        loopback address)."""
        await self._send(
            RTM_NEWADDR,
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE,
            _build_addr_msg(ifindex, prefix),
        )

    async def del_addr(self, ifindex: int, prefix: str) -> None:
        await self._send(
            RTM_DELADDR,
            NLM_F_REQUEST | NLM_F_ACK,
            _build_addr_msg(ifindex, prefix),
        )

    # -- link/addr discovery (ref getAllLinks/getAllIfAddresses) -----------

    async def get_links(self) -> list[NlLink]:
        payload = _IFINFOMSG.pack(0, 0, 0, 0, 0, 0)
        return await self._send(
            RTM_GETLINK, NLM_F_REQUEST | NLM_F_DUMP, payload,
            dump=True, parse=_parse_link_msg,
        )

    async def get_addrs(self, family: int = 0) -> list[NlAddr]:
        payload = _IFADDRMSG.pack(family, 0, 0, 0, 0)
        return await self._send(
            RTM_GETADDR, NLM_F_REQUEST | NLM_F_DUMP, payload,
            dump=True, parse=_parse_addr_msg,
        )

    # -- neighbor table (ref getAllNeighbors) ------------------------------

    async def get_neighbors(self, family: int = 0) -> list[NlNeighbor]:
        """Dump the ARP/NDP neighbor table (ref
        NetlinkProtocolSocket::getAllNeighbors, h:197-198)."""
        payload = _NDMSG.pack(family, 0, 0, 0, 0, 0, 0)
        return await self._send(
            RTM_GETNEIGH, NLM_F_REQUEST | NLM_F_DUMP, payload,
            dump=True, parse=_parse_neigh_msg,
        )

    # -- policy routing rules (ref addRule/deleteRule/getAllRules) ---------

    async def add_rule(self, rule: NlRule) -> None:
        """Idempotent: NLM_F_EXCL makes the kernel reject a duplicate
        (without it identical fib rules silently stack), and the EEXIST
        that a retry then earns reads as success."""
        import errno as _errno

        try:
            await self._send(
                RTM_NEWRULE,
                NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_EXCL,
                _build_rule_msg(rule),
            )
        except OSError as e:
            if e.errno != _errno.EEXIST:
                raise

    async def delete_rule(self, rule: NlRule) -> None:
        await self._send(
            RTM_DELRULE, NLM_F_REQUEST | NLM_F_ACK, _build_rule_msg(rule)
        )

    async def get_rules(self, family: int = 0) -> list[NlRule]:
        payload = _RTMSG.pack(family, 0, 0, 0, 0, 0, 0, 0, 0)
        return await self._send(
            RTM_GETRULE, NLM_F_REQUEST | NLM_F_DUMP, payload,
            dump=True, parse=_parse_rule_msg,
        )


def native_bulk_available() -> bool:
    """True when the C++ bulk programmer (native/netlink_bulk.cpp, built
    via native/build_native.py) is importable."""
    try:
        import openr_tpu_native  # noqa: F401
    except ImportError:
        return False
    return True


def pack_bulk_routes(routes: list[NlRoute]) -> bytes:
    """Pack NlRoutes into the native module's record format (see
    native/netlink_bulk.cpp header comment).

    Raises ValueError when a gateway's family differs from the route's:
    the native encoder sizes RTA_GATEWAY from the ROUTE family, and a
    truncated v6 gateway on a v4 route would be ACCEPTED by the kernel
    as a garbage v4 gateway (silent black hole) — the caller falls back
    to the per-route path, which reports such routes as failed."""
    out = bytearray()
    for r in routes:
        net = ipaddress.ip_network(r.prefix, strict=False)
        family = socket.AF_INET if net.version == 4 else socket.AF_INET6
        nhs = r.nexthops or (NlNextHop(),)
        if len(nhs) > 255:
            raise ValueError(
                f"{r.prefix}: {len(nhs)} nexthops exceed the bulk "
                "format's u8 count"
            )
        if any(nh.out_labels for nh in nhs):
            # the bulk format carries no MPLS encap — silently dropping
            # the labels would program a black-holing plain-IP route
            raise ValueError(f"{r.prefix}: MPLS encap not bulk-encodable")
        out += struct.pack(
            "<BBBBI", family, net.prefixlen, len(nhs), 0, r.metric
        )
        out += net.network_address.packed.ljust(16, b"\0")
        for nh in nhs:
            gw = b""
            if nh.gateway:
                addr = ipaddress.ip_address(nh.gateway)
                if addr.version != net.version:
                    raise ValueError(
                        f"{r.prefix}: gateway {nh.gateway} family differs "
                        "from route family (bulk path cannot encode it)"
                    )
                gw = addr.packed
            out += struct.pack("<II", nh.ifindex, nh.weight)
            out += gw.ljust(16, b"\0")
    return bytes(out)


def pack_bulk_columns(batch, ifindex_of) -> bytes:
    """Vectorized companion of pack_bulk_routes: encode the native
    record stream straight from a decision.column_delta.RouteColumnBatch
    — one numpy pass per next-hop GROUP (the batch's nh table, bounded
    by node degree), no per-route Python iteration. `ifindex_of`
    resolves interface names (called once per group member, not per
    route).

    Raises ValueError under exactly the conditions pack_bulk_routes
    does (cross-family gateway, >255 next hops) so the caller's
    fall-back-to-per-route semantics are identical. Columns never carry
    MPLS encap, so that clause has no columnar counterpart."""
    import numpy as np

    if not len(batch.prefixes):
        return b""
    fam = batch.family
    gid = batch.nh_gid
    chunks = []
    for g, nhs in enumerate(batch.nh_groups):
        sel = np.flatnonzero(gid == g)
        if not len(sel):
            continue
        k = max(len(nhs), 1)
        if k > 255:
            raise ValueError(
                f"{batch.prefixes[int(sel[0])]}: {k} nexthops exceed "
                "the bulk format's u8 count"
            )
        nh_block = bytearray()
        gw_fams = []
        for nh in nhs:
            address = (nh.get("address") or "").split("%", 1)[0]
            gw = b""
            if address:
                a = ipaddress.ip_address(address)
                gw_fams.append(
                    socket.AF_INET if a.version == 4 else socket.AF_INET6
                )
                gw = a.packed
            nh_block += struct.pack(
                "<II",
                ifindex_of(nh.get("if_name") or ""),
                int(nh.get("weight") or 0),
            )
            nh_block += gw.ljust(16, b"\0")
        if not nhs:
            nh_block += struct.pack("<II", 0, 0) + b"\0" * 16
        for gf in gw_fams:
            bad = fam[sel] != gf
            if bad.any():
                i = int(sel[int(np.flatnonzero(bad)[0])])
                raise ValueError(
                    f"{batch.prefixes[i]}: gateway family differs "
                    "from route family (bulk path cannot encode it)"
                )
        rec = np.zeros((len(sel), 24 + 24 * k), np.uint8)
        rec[:, 0] = fam[sel]
        rec[:, 1] = batch.plen[sel]
        rec[:, 2] = k
        rec[:, 4:8] = (
            batch.metric[sel].astype("<u4").view(np.uint8).reshape(-1, 4)
        )
        rec[:, 8:24] = batch.addr[sel]
        rec[:, 24:] = np.frombuffer(bytes(nh_block), np.uint8)
        chunks.append(rec.tobytes())
    return b"".join(chunks)


def bulk_route_op(
    op: int, table: int, protocol: int, routes: list[NlRoute]
) -> tuple[int, int]:
    """(ok, err) — whole pipeline (encode, pipelined send, ack harvest)
    in C++ (role of openr/nl's native fast path; measured ~150k routes/s
    vs the reference's stated 100k < 2s, NetlinkProtocolSocket.h:69-70).
    op: 0 = add/replace, 1 = delete."""
    import openr_tpu_native

    return openr_tpu_native.bulk_route_op(
        op, table, protocol, pack_bulk_routes(routes)
    )


def _build_route_msg(route: NlRoute, for_delete: bool = False) -> bytes:
    net = ipaddress.ip_network(route.prefix, strict=False)
    family = socket.AF_INET if net.version == 4 else socket.AF_INET6
    table = route.table if route.table < 256 else RT_TABLE_MAIN
    rtm = _RTMSG.pack(
        family,
        net.prefixlen,
        0,
        0,
        table,
        route.protocol,
        RT_SCOPE_UNIVERSE,
        RTN_UNICAST,
        0,
    )
    attrs = [_rta(RTA_DST, net.network_address.packed)]
    if route.table >= 256:
        attrs.append(_rta(RTA_TABLE, struct.pack("=I", route.table)))
    if route.metric:
        attrs.append(_rta(RTA_PRIORITY, struct.pack("=I", route.metric)))
    nhs = route.nexthops
    if not for_delete and nhs:
        if len(nhs) == 1:
            nh = nhs[0]
            if nh.out_labels:
                # MPLS push: LWTUNNEL encap rides the route level for a
                # single next hop (ref NetlinkRouteMessage.cpp:664)
                attrs.append(_mpls_encap_attrs(nh.out_labels))
            if nh.gateway:
                attrs.append(
                    _rta(
                        RTA_GATEWAY,
                        ipaddress.ip_address(nh.gateway).packed,
                    )
                )
            if nh.ifindex:
                attrs.append(_rta(RTA_OIF, struct.pack("=i", nh.ifindex)))
        else:
            # ECMP group: rtnexthop records, each with nested RTAs
            blob = b""
            for nh in nhs:
                nested = b""
                if nh.out_labels:
                    nested += _mpls_encap_attrs(nh.out_labels)
                if nh.gateway:
                    nested += _rta(
                        RTA_GATEWAY, ipaddress.ip_address(nh.gateway).packed
                    )
                rtnh_len = _RTNH.size + len(nested)
                blob += _RTNH.pack(
                    rtnh_len, 0, max(nh.weight - 1, 0), nh.ifindex
                ) + nested
            attrs.append(_rta(RTA_MULTIPATH, blob))
    return rtm + b"".join(attrs)


def _mpls_nh_attrs(nh: NlNextHop) -> bytes:
    """Per-nexthop attributes of an AF_MPLS route: RTA_VIA (gateway),
    RTA_NEWDST (outgoing label stack — swap); neither means pop."""
    nested = b""
    if nh.out_labels:
        nested += _rta(RTA_NEWDST, _mpls_label_stack(nh.out_labels))
    if nh.gateway:
        nested += _rta_via(nh.gateway)
    return nested


def _build_mpls_route_msg(
    route: NlMplsRoute, for_delete: bool = False
) -> bytes:
    """AF_MPLS label route (ref NetlinkRouteMessage.cpp:618-769):
    dst = the incoming label (20-bit dst_len); per-nexthop RTA_NEWDST
    swaps, RTA_VIA gateways; label-only nexthop (dev only) = pop."""
    rtm = _RTMSG.pack(
        AF_MPLS,
        20,  # label bits
        0,
        0,
        0,  # MPLS routes live in the platform label table, not an RT table
        route.protocol,
        RT_SCOPE_UNIVERSE,
        RTN_UNICAST,
        0,
    )
    attrs = [_rta(RTA_DST, _mpls_label_stack((route.label,)))]
    nhs = route.nexthops
    if not for_delete and nhs:
        if len(nhs) == 1:
            nh = nhs[0]
            attrs.append(_mpls_nh_attrs(nh))
            if nh.ifindex:
                attrs.append(_rta(RTA_OIF, struct.pack("=i", nh.ifindex)))
        else:
            blob = b""
            for nh in nhs:
                nested = _mpls_nh_attrs(nh)
                rtnh_len = _RTNH.size + len(nested)
                blob += _RTNH.pack(
                    rtnh_len, 0, max(nh.weight - 1, 0), nh.ifindex
                ) + nested
            attrs.append(_rta(RTA_MULTIPATH, blob))
    return rtm + b"".join(attrs)


def _parse_route_msg(body: bytes) -> Optional[NlRoute]:
    if len(body) < _RTMSG.size:
        return None
    family, dst_len, _src, _tos, table, proto, _scope, rtype, _flags = (
        _RTMSG.unpack_from(body)
    )
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None
    dst = None
    metric = 0
    nexthops: list[NlNextHop] = []
    gateway = None
    oif = 0
    off = _RTMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == RTA_DST:
            dst = payload
        elif atype == RTA_PRIORITY and len(payload) >= 4:
            (metric,) = struct.unpack("=I", payload[:4])
        elif atype == RTA_TABLE and len(payload) >= 4:
            (table,) = struct.unpack("=I", payload[:4])
        elif atype == RTA_GATEWAY:
            gateway = str(ipaddress.ip_address(payload))
        elif atype == RTA_OIF and len(payload) >= 4:
            (oif,) = struct.unpack("=i", payload[:4])
        elif atype == RTA_MULTIPATH:
            noff = 0
            while noff + _RTNH.size <= len(payload):
                rtnh_len, _f, hops, ifindex = _RTNH.unpack_from(payload, noff)
                if rtnh_len < _RTNH.size:
                    break
                gw = None
                aoff = noff + _RTNH.size
                while aoff + _RTA.size <= noff + rtnh_len:
                    nlen, ntype = _RTA.unpack_from(payload, aoff)
                    if nlen < _RTA.size:
                        break
                    if ntype == RTA_GATEWAY:
                        gw = str(
                            ipaddress.ip_address(
                                payload[aoff + _RTA.size:aoff + nlen]
                            )
                        )
                    aoff += _align4(nlen)
                nexthops.append(
                    NlNextHop(gateway=gw, ifindex=ifindex, weight=hops + 1)
                )
                noff += _align4(rtnh_len)
        off += _align4(alen)
    if gateway or oif:
        nexthops.append(NlNextHop(gateway=gateway, ifindex=oif))
    if dst is None:
        addr = "0.0.0.0" if family == socket.AF_INET else "::"
    else:
        addr = str(ipaddress.ip_address(dst))
    return NlRoute(
        prefix=f"{addr}/{dst_len}",
        nexthops=tuple(nexthops),
        metric=metric,
        table=table,
        protocol=proto,
    )


def _decode_label_stack(payload: bytes) -> tuple:
    labels = []
    for off in range(0, len(payload) - 3, 4):
        (word,) = struct.unpack_from(">I", payload, off)
        labels.append(word >> 12)
        if word & (1 << 8):  # bottom of stack
            break
    return tuple(labels)


def _parse_mpls_nh_attrs(payload: bytes, start: int, end: int):
    gateway = None
    out_labels: tuple = ()
    off = start
    while off + _RTA.size <= end:
        alen, atype = _RTA.unpack_from(payload, off)
        if alen < _RTA.size:
            break
        data = payload[off + _RTA.size:off + alen]
        if atype == RTA_VIA and len(data) > 2:
            gateway = str(ipaddress.ip_address(data[2:]))
        elif atype == RTA_NEWDST:
            out_labels = _decode_label_stack(data)
        off += _align4(alen)
    return gateway, out_labels


def _parse_mpls_route_msg(body: bytes) -> Optional[NlMplsRoute]:
    if len(body) < _RTMSG.size:
        return None
    family, _dl, _src, _tos, _table, proto, _scope, rtype, _flags = (
        _RTMSG.unpack_from(body)
    )
    if family != AF_MPLS or rtype != RTN_UNICAST:
        return None
    label = None
    nexthops: list[NlNextHop] = []
    top_gw, top_labels, top_oif = None, (), 0
    off = _RTMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == RTA_DST:
            stack = _decode_label_stack(payload)
            label = stack[0] if stack else None
        elif atype == RTA_VIA and len(payload) > 2:
            top_gw = str(ipaddress.ip_address(payload[2:]))
        elif atype == RTA_NEWDST:
            top_labels = _decode_label_stack(payload)
        elif atype == RTA_OIF and len(payload) >= 4:
            (top_oif,) = struct.unpack("=i", payload[:4])
        elif atype == RTA_MULTIPATH:
            noff = 0
            while noff + _RTNH.size <= len(payload):
                rtnh_len, _f, hops, ifindex = _RTNH.unpack_from(
                    payload, noff
                )
                if rtnh_len < _RTNH.size:
                    break
                gw, labels = _parse_mpls_nh_attrs(
                    payload, noff + _RTNH.size, noff + rtnh_len
                )
                nexthops.append(
                    NlNextHop(
                        gateway=gw, ifindex=ifindex,
                        weight=hops + 1, out_labels=labels,
                    )
                )
                noff += _align4(rtnh_len)
        off += _align4(alen)
    if label is None:
        return None
    if not nexthops and (top_gw or top_oif or top_labels):
        nexthops.append(
            NlNextHop(
                gateway=top_gw, ifindex=top_oif, out_labels=top_labels
            )
        )
    return NlMplsRoute(
        label=label, nexthops=tuple(nexthops), protocol=proto
    )


def _build_addr_msg(ifindex: int, prefix: str) -> bytes:
    iface = ipaddress.ip_interface(prefix)
    family = socket.AF_INET if iface.version == 4 else socket.AF_INET6
    hdr = _IFADDRMSG.pack(family, iface.network.prefixlen, 0, 0, ifindex)
    packed = iface.ip.packed
    return hdr + _rta(IFA_LOCAL, packed) + _rta(IFA_ADDRESS, packed)


def _parse_link_msg(body: bytes) -> Optional[NlLink]:
    """RTM_NEWLINK/DELLINK -> NlLink (ref NetlinkLinkMessage parsing)."""
    if len(body) < _IFINFOMSG.size:
        return None
    _fam, _pad, _typ, index, flags, _change = _IFINFOMSG.unpack_from(body)
    name = ""
    off = _IFINFOMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        if atype == IFLA_IFNAME:
            name = body[off + _RTA.size:off + alen].rstrip(b"\0").decode(
                errors="replace"
            )
        off += _align4(alen)
    return NlLink(ifindex=index, name=name, flags=flags)


def _parse_addr_msg(body: bytes) -> Optional[NlAddr]:
    """RTM_NEWADDR/DELADDR -> NlAddr (ref NetlinkAddrMessage parsing).

    IFA_ADDRESS is the peer on pointopoint links; IFA_LOCAL, when
    present, is the interface's own address and wins."""
    if len(body) < _IFADDRMSG.size:
        return None
    family, prefixlen, _flags, _scope, index = _IFADDRMSG.unpack_from(body)
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None
    address = local = None
    off = _IFADDRMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == IFA_ADDRESS:
            address = payload
        elif atype == IFA_LOCAL:
            local = payload
        off += _align4(alen)
    raw = local if local is not None else address
    if raw is None:
        return None
    try:
        addr = ipaddress.ip_address(raw)
    except ValueError:
        return None
    return NlAddr(
        ifindex=index, prefix=f"{addr}/{prefixlen}", family=family
    )


def _parse_neigh_msg(body: bytes) -> Optional[NlNeighbor]:
    """RTM_NEWNEIGH/DELNEIGH -> NlNeighbor (ref NetlinkNeighborMessage
    parsing: ndmsg + NDA_DST / NDA_LLADDR attributes)."""
    if len(body) < _NDMSG.size:
        return None
    family, _p1, _p2, ifindex, state, _flags, _typ = _NDMSG.unpack_from(body)
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None
    dst = lladdr = None
    off = _NDMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == NDA_DST:
            dst = payload
        elif atype == NDA_LLADDR:
            lladdr = payload
        off += _align4(alen)
    if dst is None:
        return None
    try:
        destination = str(ipaddress.ip_address(dst))
    except ValueError:
        return None
    mac = ":".join(f"{b:02x}" for b in lladdr) if lladdr else ""
    return NlNeighbor(
        ifindex=ifindex, destination=destination, lladdr=mac,
        state=state, family=family,
    )


def _build_rule_msg(rule: NlRule) -> bytes:
    """NlRule -> fib_rule_hdr + FRA attributes (ref NetlinkRuleMessage::
    addRule/addRuleAttributes). Tables above the u8 header field go in
    FRA_TABLE, mirroring the kernel's (and the reference's) convention."""
    table8 = rule.table if rule.table < 256 else 0
    body = _RTMSG.pack(
        rule.family, 0, 0, 0, table8, 0, 0, rule.action, 0
    )
    if rule.table >= 256:
        body += _rta(FRA_TABLE, struct.pack("=I", rule.table))
    if rule.priority is not None:
        body += _rta(FRA_PRIORITY, struct.pack("=I", rule.priority))
    if rule.fwmark is not None:
        body += _rta(FRA_FWMARK, struct.pack("=I", rule.fwmark))
    return body


def _parse_rule_msg(body: bytes) -> Optional[NlRule]:
    """RTM_NEWRULE/DELRULE -> NlRule (ref NetlinkRuleMessage::parseMessage)."""
    if len(body) < _RTMSG.size:
        return None
    family, _dl, _sl, _tos, table, _r1, _r2, action, _flags = (
        _RTMSG.unpack_from(body)
    )
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None
    priority = fwmark = None
    full_table = table
    off = _RTMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == FRA_TABLE and len(payload) >= 4:
            (full_table,) = struct.unpack_from("=I", payload)
        elif atype == FRA_PRIORITY and len(payload) >= 4:
            (priority,) = struct.unpack_from("=I", payload)
        elif atype == FRA_FWMARK and len(payload) >= 4:
            (fwmark,) = struct.unpack_from("=I", payload)
        off += _align4(alen)
    return NlRule(
        family=family, action=action, table=full_table,
        priority=priority, fwmark=fwmark,
    )


_EVENT_PARSE = {
    "link": _parse_link_msg,
    "link_del": _parse_link_msg,
    "addr": _parse_addr_msg,
    "addr_del": _parse_addr_msg,
    "neigh": _parse_neigh_msg,
    "neigh_del": _parse_neigh_msg,
    "rule": _parse_rule_msg,
    "rule_del": _parse_rule_msg,
}


def _parse_event(kind: str, body: bytes):
    """Decode one unsolicited kernel notification (ref NetlinkEvent
    variant: Link/IfAddress/Neighbor/Rule, NetlinkProtocolSocket.h:29-31)."""
    return _EVENT_PARSE[kind](body)
