"""Bucketed Δ-stepping kernel (ops/relax.py, ISSUE 13).

Unit tests of the shared round ledger plus randomized churn parity of
the bucketed kernel against BOTH the synchronous kernel and the CPU
oracle on every engagement path — full, incremental, multichip, and
what-if — on mesh5 / grid4 / fat_tree. The contract under test is the
module's one promise: sync and bucketed reach the identical int32
fixpoint bit-for-bit, so Δ steers performance only, never results.
"""

import numpy as np
import pytest

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.ops import relax as relax_ops
from tests.test_incremental_spf import _Churn
from tests.test_tpu_solver import assert_rib_equal

AREA = "0"

FABRICS = [
    (lambda: topologies.full_mesh(5), "node-0"),
    (lambda: topologies.grid(4, node_labels=False), "node-1-1"),
    (lambda: topologies.fat_tree(pods=2, planes=2), "rsw-0-0"),
]
FABRIC_IDS = ["mesh5", "grid4", "fat_tree"]


# -- round ledger units ----------------------------------------------------


def test_round_ledger_units():
    # sync trip bound: ceil(n/UNROLL) + 2 slack, floor of 2
    assert relax_ops.max_trips(1) == 3
    assert relax_ops.max_trips(64) == 64 // relax_ops.UNROLL + 2
    assert relax_ops.max_trips(100) > relax_ops.max_trips(10)
    # shared fixpoint bound (consumed by ops/ucmp.py)
    assert relax_ops.fixpoint_bound(64) == 66
    # rung-doubling depth: 2^depth covers n_cap, clamped to [4, 16]
    assert relax_ops.ladder_depth(2) == 4
    assert relax_ops.ladder_depth(64) == 7
    assert relax_ops.ladder_depth(1 << 20) == 16


def test_derive_delta_exp_boundaries():
    INF = relax_ops.INF_E
    # no shift classes at all -> ineligible
    assert relax_ops.derive_delta_exp(
        np.zeros(4, np.int32), np.full((4, 8), INF, np.int32)
    ) == 0
    assert relax_ops.derive_delta_exp(
        np.zeros(0, np.int32), np.zeros((0, 8), np.int32)
    ) == 0
    # all-INF weights (occupied classes, no live edges) -> ineligible
    deltas = np.array([1, -1, 0, 0], np.int32)
    assert relax_ops.derive_delta_exp(
        deltas, np.full((4, 8), INF, np.int32)
    ) == 0
    # uniform metrics: Δ = pow2 ceiling of the one weight -> EVERY edge
    # classifies light (one bucket, ladder covers the whole graph)
    w = np.full((4, 8), INF, np.int32)
    w[0, :] = 10
    e = relax_ops.derive_delta_exp(deltas, w)
    assert e == 4  # 2^4 = 16 >= 10
    assert (1 << e) >= 10
    # max spread: p75 tracks the bulk, capped at 2^28
    w[0, :] = 1
    w[1, :] = 1 << 27
    e = relax_ops.derive_delta_exp(deltas, w)
    assert 1 <= e <= 28
    # weight exactly 1 -> smallest usable exponent, still eligible
    w = np.full((4, 8), INF, np.int32)
    w[0, :] = 1
    assert relax_ops.derive_delta_exp(deltas, w) == 1


def test_plan_delta_exp_sticky_across_rebuilds():
    """build_plan keeps the previous usable exponent so metric churn
    never flips the (kernel, delta_exp) jit-cache class."""
    from openr_tpu.ops.edgeplan import build_plan

    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, _ = topologies.build_states(adj_dbs, prefix_dbs)
    plan = build_plan(states[AREA])
    assert plan.delta_exp > 0
    churn = _Churn(adj_dbs, states, AREA)
    churn.set_metric("node-0-0", "node-0-1", 100000)
    plan2 = build_plan(states[AREA], prev=plan)
    assert plan2.delta_exp == plan.delta_exp


# -- solver-level parity helpers -------------------------------------------


def _trio(me, states, ps, **tpu_kw):
    cpu = SpfSolver(me)
    sync = TpuSpfSolver(me, spf_kernel="sync", **tpu_kw)
    buck = TpuSpfSolver(me, spf_kernel="bucketed", **tpu_kw)

    def solve(ctx):
        cpu_db = cpu.build_route_db(me, states, ps)
        s_db = sync.build_route_db(me, states, ps)
        b_db = buck.build_route_db(me, states, ps)
        assert_rib_equal(cpu_db, b_db, f"{ctx}: bucketed vs oracle")
        assert_rib_equal(cpu_db, s_db, f"{ctx}: sync vs oracle")
        # bit-identical promise: both kernels produce the same RIB
        assert b_db.unicast_routes == s_db.unicast_routes, ctx
        assert b_db.mpls_routes == s_db.mpls_routes, ctx
        return buck.last_device_stats

    return solve, buck


def _random_churn(solve, churn, seed, rounds=6):
    rng = np.random.default_rng(seed)
    metrics = (1, 3, 50, 100000)
    edges = churn.edges()
    down = None
    for i in range(rounds):
        if down is not None and rng.integers(2) == 0:
            u, v, su, sv = down
            churn.link_up(u, v, su, sv)
            ctx = f"round{i + 1}: up {u}<->{v}"
            down = None
        elif down is None and rng.integers(4) == 0:
            u, v = edges[rng.integers(len(edges))]
            down = (u, v, churn.dbs[u], churn.dbs[v])
            churn.link_down(u, v)
            ctx = f"round{i + 1}: down {u}<->{v}"
        else:
            u, v = edges[rng.integers(len(edges))]
            m = int(metrics[rng.integers(len(metrics))])
            churn.set_metric(u, v, m)
            ctx = f"round{i + 1}: metric {u}<->{v}={m}"
        solve(ctx)


# -- full path --------------------------------------------------------------


@pytest.mark.parametrize("gen,me", FABRICS, ids=FABRIC_IDS)
def test_full_path_churn_parity(gen, me):
    from openr_tpu.ops.edgeplan import build_plan

    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    # the eligibility ladder is part of the contract: plans with live
    # shift classes (grid4) derive a usable Δ and engage bucketed;
    # all-residual plans (mesh5, this fat_tree) derive 0 and the solver
    # falls back to sync automatically — exactness either way
    expect = (
        "bucketed" if build_plan(states[AREA]).delta_exp > 0 else "sync"
    )
    solve, buck = _trio(me, states, ps)
    st = solve("cold")
    assert st.get("spf_kernel") == expect, (expect, st)
    if expect == "bucketed":
        assert int(st.get("bucket_epochs") or 0) > 0, st
    else:
        assert int(st.get("bucket_epochs") or 0) == 0, st
    assert int(st.get("rounds") or 0) > 0, st
    _random_churn(solve, _Churn(adj_dbs, states, AREA), seed=13)


def test_full_path_uniform_and_max_spread_metrics():
    """Δ-quantization boundaries: uniform metrics put every edge in one
    light bucket (ladder does all the work); max-spread metrics push the
    flapped edges heavy (handoff relax does). Both must stay exact."""
    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    solve, _ = _trio("node-1-1", states, ps)
    solve("uniform")
    churn = _Churn(adj_dbs, states, AREA)
    # max spread: a few edges near MAX_METRIC, the rest at 1
    churn.set_metric("node-0-0", "node-0-1", 100_000_000)
    churn.set_metric("node-2-2", "node-3-2", 100_000_000)
    churn.set_metric("node-1-0", "node-1-1", 1)
    solve("max-spread")


def test_ineligible_plan_falls_back_to_sync():
    """A 2-node fabric has residual-only edges (no shift classes with
    finite weights survive padding on every topology) — or at minimum a
    plan may derive delta_exp=0; either way the solver must resolve the
    dispatch to the sync kernel and still be exact. Forced here via the
    knob ladder: spf_kernel=sync never reports bucketed stats."""
    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-1-1", spf_kernel="sync")
    cpu_db = SpfSolver("node-1-1").build_route_db("node-1-1", states, ps)
    tpu_db = tpu.build_route_db("node-1-1", states, ps)
    assert_rib_equal(cpu_db, tpu_db, "forced sync")
    st = tpu.last_device_stats
    assert st.get("spf_kernel") == "sync", st
    assert int(st.get("bucket_epochs") or 0) == 0, st


def test_spf_kernel_knob_validation():
    with pytest.raises(ValueError):
        TpuSpfSolver("node-0", spf_kernel="quantum")
    from openr_tpu.config import Config, ConfigError, OpenrConfig

    cfg = OpenrConfig(node_name="n1")
    cfg.decision_config.spf_kernel = "quantum"
    with pytest.raises(ConfigError):
        Config(cfg)
    cfg.decision_config.spf_kernel = "sync"
    Config(cfg)


# -- incremental path -------------------------------------------------------


def test_incremental_path_churn_parity():
    """Warm seed-from-previous solves under the bucketed kernel: same
    trio discipline as test_incremental_spf, with the warm bucketed RIB
    additionally pinned to the warm sync RIB every round."""
    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-1-1"
    cpu = SpfSolver(me)
    sync_i = TpuSpfSolver(me, spf_kernel="sync", incremental_spf=True)
    buck_i = TpuSpfSolver(me, spf_kernel="bucketed", incremental_spf=True)

    engaged = 0

    def solve(ctx):
        nonlocal engaged
        cpu_db = cpu.build_route_db(me, states, ps)
        s_db = sync_i.build_route_db(me, states, ps)
        b_db = buck_i.build_route_db(me, states, ps)
        assert_rib_equal(cpu_db, b_db, f"{ctx}: warm bucketed vs oracle")
        assert b_db.unicast_routes == s_db.unicast_routes, ctx
        st = buck_i.last_device_stats
        if st.get("incremental") and not st.get("fell_back"):
            engaged += 1

    solve("cold")
    churn = _Churn(adj_dbs, states, AREA)
    rng = np.random.default_rng(29)
    edges = [e for e in churn.edges() if me not in e]
    for i in range(6):
        u, v = edges[rng.integers(len(edges))]
        m = int((1, 7, 40, 90000)[rng.integers(4)])
        churn.set_metric(u, v, m)
        solve(f"round{i + 1}: {u}<->{v}={m}")
    # metric-only churn away from the vantage must take the warm lane
    assert engaged >= 3, engaged


# -- multichip path ---------------------------------------------------------


@pytest.mark.parametrize("gen,me", FABRICS, ids=FABRIC_IDS)
def test_multichip_path_churn_parity(gen, me):
    from openr_tpu.ops.edgeplan import build_plan

    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    eligible = build_plan(states[AREA]).delta_exp > 0
    solve, buck = _trio(
        me, states, ps,
        multichip_n_cap_threshold=4, multichip_batch=4,
    )
    st = solve("cold")
    if eligible:
        assert st.get("spf_kernel") == "bucketed", st
        # one pmin per bucket EPOCH: halo count == epoch count
        assert st.get("halo_exchanges") == st.get("bucket_epochs"), st
    else:
        assert st.get("spf_kernel") == "sync", st
        # sync in the multichip tier: one pmin per relaxation round
        assert st.get("halo_exchanges") == st.get("rounds"), st
    assert int(st.get("halo_exchanges") or 0) > 0, st
    tm = buck.last_timing
    assert tm.get("multichip"), tm
    _random_churn(solve, _Churn(adj_dbs, states, AREA), seed=31, rounds=4)


def test_multichip_halo_per_epoch_beats_sync_per_round():
    """The round-proportional traffic claim at test scale: under sync
    the halo count equals the relaxation rounds; under bucketed it
    equals the bucket epochs, which must be strictly fewer."""
    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    kw = dict(multichip_n_cap_threshold=4, multichip_batch=4)
    sync = TpuSpfSolver("node-1-1", spf_kernel="sync", **kw)
    buck = TpuSpfSolver("node-1-1", spf_kernel="bucketed", **kw)
    sync.build_route_db("node-1-1", states, ps)
    buck.build_route_db("node-1-1", states, ps)
    s_st, b_st = sync.last_device_stats, buck.last_device_stats
    assert s_st.get("halo_exchanges") == s_st.get("rounds") > 0, s_st
    assert 0 < b_st["halo_exchanges"] < s_st["halo_exchanges"], (
        s_st, b_st,
    )


# -- what-if path ------------------------------------------------------------


@pytest.mark.parametrize("gen,me", FABRICS, ids=FABRIC_IDS)
def test_whatif_path_sweep_parity(gen, me):
    """The N-1 sweep's verdict rows and returned distance planes must be
    identical under both kernels (the sweep oracle differential lives in
    test_whatif; here the two device kernels are pinned to each other
    bit-for-bit)."""
    from openr_tpu.decision.whatif import WhatIfEngine

    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)

    jobs = {}
    for kern in ("sync", "bucketed"):
        tpu = TpuSpfSolver(me, spf_kernel=kern)
        assert tpu.build_route_db(me, states, ps) is not None
        eng = WhatIfEngine(tpu)
        job = eng.plan_sweep(states, ps, order=1, return_dist=True)
        out = job.run()
        jobs[kern] = (job, out)
    (s_job, s_out), (b_job, b_out) = jobs["sync"], jobs["bucketed"]
    assert s_out["rows"] == b_out["rows"]
    assert s_out["scenarios"] == b_out["scenarios"] > 0
    assert len(s_job.dist_planes) == len(b_job.dist_planes)
    for sp, bp in zip(s_job.dist_planes, b_job.dist_planes):
        np.testing.assert_array_equal(sp, bp)
    # the bucketed sweep actually took the bucketed executable
    assert b_job.rounds > 0


# -- observability ----------------------------------------------------------


def test_rounds_flow_to_stats_and_timing():
    from openr_tpu.runtime.counters import counters

    adj_dbs, prefix_dbs = topologies.grid(4, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-1-1", spf_kernel="bucketed")
    tpu.build_route_db("node-1-1", states, ps)
    tm = tpu.last_timing
    assert tm["spf_kernel"] == "bucketed", tm
    assert tm["rounds"] > 0, tm
    assert tm["bucket_epochs"] > 0, tm
    stats = counters.get_statistics("decision.device")
    assert "decision.device.rounds" in stats, stats
    assert "decision.device.bucket_epochs" in stats, stats
