"""Core message vocabulary for openr_tpu.

Re-expression (not a translation) of the reference wire/IPC schema:
  - adjacency / prefix link-state types: /root/reference/openr/if/Types.thrift
    (Adjacency:98, AdjacencyDatabase:175, PrefixEntry:380, PrefixDatabase:461)
  - kvstore types: /root/reference/openr/if/KvStore.thrift (Value:177,
    Publication:532)
  - spark messages: Types.thrift:821-1003
  - inter-module strong types: openr/common/Types.h, openr/common/LsdbTypes.h
  - perf events: Types.thrift:53-75

Dataclasses here are the single source of truth; serde.py provides the wire
codec; decision/rib.py holds the RIB value types.
"""

from __future__ import annotations

import enum
import ipaddress
import time
from dataclasses import dataclass, field, replace  # noqa: F401  (replace re-exported)
from typing import Optional


# ---------------------------------------------------------------------------
# Network primitives
# ---------------------------------------------------------------------------

def parse_prefix(s: str) -> ipaddress._BaseNetwork:
    return ipaddress.ip_network(s, strict=False)


# ---------------------------------------------------------------------------
# Link-state types (ref Types.thrift)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Adjacency:
    """One directed adjacency advertisement (ref Types.thrift:98-173)."""

    other_node_name: str
    if_name: str
    other_if_name: str = ""
    metric: int = 1
    adj_label: int = 0
    is_overloaded: bool = False
    rtt_us: int = 0
    timestamp_s: int = 0
    # the NEIGHBOR's link addresses — the next hop when forwarding over
    # this adjacency (ref Types.thrift:104-110 nextHopV6/nextHopV4);
    # learned from the Spark handshake's kernel source address
    next_hop_v6: str = ""
    next_hop_v4: str = ""
    weight: int = 1  # UCMP weight of this adj (ref Types.thrift:158)
    # Two-stage cold-boot insertion: adjacency only usable by the *other*
    # node until the restarting node has programmed routes
    # (ref Types.thrift:166, Decision.cpp:567-644).
    adj_only_used_by_other_node: bool = False


@dataclass(frozen=True)
class AdjacencyDatabase:
    """All adjacencies of one node in one area (ref Types.thrift:175-221)."""

    this_node_name: str
    adjacencies: tuple[Adjacency, ...] = ()
    is_overloaded: bool = False  # node drained: no transit traffic
    node_label: int = 0  # segment-routing node label
    area: str = "0"
    # Distinguish a node that is up with no adjacencies from a withdrawal.
    node_metric_increment: int = 0  # soft-drain metric penalty (ref :216)


class PrefixForwardingType(enum.IntEnum):
    """ref Types.thrift:18-27 (OpenrConfig.thrift PrefixForwardingType)."""

    IP = 0
    SR_MPLS = 1


class PrefixForwardingAlgorithm(enum.IntEnum):
    """ref OpenrConfig.thrift:25 — per-prefix route computation algorithm."""

    SP_ECMP = 0
    KSP2_ED_ECMP = 1
    SP_UCMP_ADJ_WEIGHT_PROPAGATION = 3
    SP_UCMP_PREFIX_WEIGHT_PROPAGATION = 4


class PrefixType(enum.IntEnum):
    """Origin of a prefix advertisement (ref Network.thrift PrefixType)."""

    LOOPBACK = 1
    DEFAULT = 2
    BGP = 3
    PREFIX_ALLOCATOR = 4
    BREEZE = 5
    CONFIG = 6
    VIP = 7
    RIB = 8


@dataclass(frozen=True)
class PrefixMetrics:
    """Ranked route-selection metrics, higher wins except distance
    (ref Types.thrift:239-286, compared in SpfSolver.cpp:648-769)."""

    path_preference: int = 1000
    source_preference: int = 100
    # Advertised inter-area hop distance, bumped by PrefixManager on
    # cross-area redistribution; SHORTEST_DISTANCE selection minimizes it
    # (ref Types.thrift:364, LsdbUtil.cpp selectShortestDistance).
    distance: int = 0
    drain_metric: int = 0  # advertised by soft-drained nodes, lower wins


@dataclass(frozen=True)
class PrefixEntry:
    """One prefix advertisement by one node (ref Types.thrift:380-459)."""

    prefix: str  # canonical CIDR string
    type: PrefixType = PrefixType.LOOPBACK
    metrics: PrefixMetrics = field(default_factory=PrefixMetrics)
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    min_nexthop: Optional[int] = None  # drop route if fewer NHs (ref :422)
    prepend_label: Optional[int] = None  # extra MPLS label to push (ref :432)
    weight: Optional[int] = None  # UCMP prefix weight (ref :457)
    tags: tuple[str, ...] = ()
    area_stack: tuple[str, ...] = ()

    def network(self):
        return parse_prefix(self.prefix)


@dataclass(frozen=True)
class PrefixDatabase:
    """All prefixes of one node in one area (ref Types.thrift:461-480).

    The reference advertises per-prefix keys (`prefix:<node>:<area>:<pfx>`,
    LsdbTypes.h:411 PrefixKey); each such key carries a PrefixDatabase with a
    single entry and the deletePrefix tombstone flag.
    """

    this_node_name: str
    prefix_entries: tuple[PrefixEntry, ...] = ()
    area: str = "0"
    delete_prefix: bool = False


# ---------------------------------------------------------------------------
# KvStore types (ref KvStore.thrift)
# ---------------------------------------------------------------------------

TTL_INFINITY = -1  # ref KvStore.thrift Consts


@dataclass
class Value:
    """Versioned CRDT value (ref KvStore.thrift:177-214).

    Merge order: version desc, then originator_id desc, then value bytes
    desc; ttl_version refreshes TTL without data change
    (ref KvStoreUtil.cpp:42-249).
    """

    version: int
    originator_id: str
    value: Optional[bytes] = None  # None => hash-only advertisement
    ttl_ms: int = TTL_INFINITY
    ttl_version: int = 0
    hash: Optional[int] = None
    # Fleet-convergence origin stamp: set once at the originating node's
    # local write and carried unchanged through flood merge so every
    # receiver can attribute its convergence work (and its FIB ack) to the
    # remote origin event. Deliberately EXCLUDED from `hash` — the stamp
    # is telemetry, never merge identity, so it can't flip a merge verdict
    # or perturb full-sync delta detection.
    origin_node: Optional[str] = None
    origin_event_id: Optional[str] = None
    origin_ts_ms: Optional[float] = None  # wall epoch ms at origination

    def __post_init__(self):
        if self.hash is None and self.value is not None:
            self.hash = compute_hash(self.version, self.originator_id, self.value)


def compute_hash(version: int, originator_id: str, value: Optional[bytes]) -> int:
    """Deterministic 64-bit content hash (role of generateHash, LsdbUtil).

    The hash drives full-sync delta detection: a collision silently skips a
    key during sync, so at 100k-key scale a 32-bit hash's birthday bound
    (~2^16 keys) is not acceptable — we use 64 bits.
    """
    import hashlib

    h = hashlib.blake2b(digest_size=8)
    h.update(str(version).encode())
    h.update(b"\x00")
    h.update(originator_id.encode())
    h.update(b"\x00")
    if value is not None:
        h.update(value)
    return int.from_bytes(h.digest(), "little")


@dataclass
class Publication:
    """A batch of changed key/values flooded between stores
    (ref KvStore.thrift:532-560)."""

    key_vals: dict[str, Value] = field(default_factory=dict)
    expired_keys: list[str] = field(default_factory=list)
    # Loop suppression: path of node-ids this publication traversed
    # (ref KvStore.cpp:3155-3290).
    node_ids: list[str] = field(default_factory=list)
    # Keys the sender has a newer hash for than us (full-sync delta request).
    to_be_updated_keys: list[str] = field(default_factory=list)
    area: str = "0"
    # local-process telemetry, stamped by the receiving KvStore when it
    # hands the merged publication to Decision: the monotonic receive
    # time the input black-box recorder (runtime/replay_log.py) logs
    # for each event. Meaningless across hosts — a deserialized value
    # is always overwritten by the local re-stamp before local use.
    recv_t: Optional[float] = None

    def empty(self) -> bool:
        return not self.key_vals and not self.expired_keys


class FilterOperator(enum.IntEnum):
    OR = 1
    AND = 2


@dataclass
class KeyDumpParams:
    """Filtered dump request (ref KvStore.thrift:287-320)."""

    keys: list[str] = field(default_factory=list)  # prefix match terms
    originator_ids: list[str] = field(default_factory=list)
    operator: FilterOperator = FilterOperator.OR
    ignore_ttl: bool = False
    do_not_publish_value: bool = False
    # sender's key->(version, originatorId, hash) map for delta sync
    key_val_hashes: Optional[dict[str, Value]] = None


class KvStorePeerState(enum.IntEnum):
    """Peer sync FSM (ref KvStore.thrift:375, getNextState KvStore.cpp:981)."""

    IDLE = 0
    SYNCING = 1
    INITIALIZED = 2


@dataclass(frozen=True)
class PeerSpec:
    """How to reach a peer's kvstore (ref KvStore.thrift PeerSpec)."""

    peer_addr: str
    ctrl_port: int = 0
    state: KvStorePeerState = KvStorePeerState.IDLE


# ---------------------------------------------------------------------------
# Spark messages (ref Types.thrift:821-1003)
# ---------------------------------------------------------------------------

class SparkNeighState(enum.IntEnum):
    """Neighbor FSM states (ref Types.thrift:29, table Spark.h:463)."""

    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


@dataclass(frozen=True)
class SparkNeighbor:
    node_name: str
    domain_name: str = ""
    hold_time_ms: int = 0
    transport_address_v6: str = ""
    transport_address_v4: str = ""
    openr_ctrl_port: int = 0


@dataclass
class SparkHelloMsg:
    """Periodic multicast hello carrying the seen-neighbor map for the
    2-way connectivity check (ref Types.thrift:821-888)."""

    domain_name: str
    node_name: str
    if_name: str
    seq_num: int
    # neighbor name -> ReflectedNeighborInfo(last seq & timestamps we saw)
    neighbor_infos: dict[str, "ReflectedNeighborInfo"] = field(default_factory=dict)
    version: int = 1
    solicit_response: bool = False  # fast-init: ask for immediate reply
    restarting: bool = False  # graceful-restart signal
    sent_ts_us: int = 0


@dataclass(frozen=True)
class ReflectedNeighborInfo:
    seq_num: int = 0
    last_nbr_msg_sent_ts_us: int = 0
    last_my_msg_rcvd_ts_us: int = 0


@dataclass
class SparkHandshakeMsg:
    """Unicast negotiation after 2-way check (ref Types.thrift:917-960)."""

    node_name: str
    is_adj_established: bool = False
    hold_time_ms: int = 0
    gr_hold_time_ms: int = 0
    transport_address_v6: str = ""
    transport_address_v4: str = ""
    openr_ctrl_port: int = 0
    kvstore_port: int = 0  # peer's kvstore RPC endpoint for LinkMonitor
    area: str = ""  # negotiated area
    neighbor_node_name: str = ""  # directed handshake target


@dataclass
class SparkHeartbeatMsg:
    """Cheap liveness keepalive once ESTABLISHED (ref Types.thrift:890-905)."""

    node_name: str
    seq_num: int
    hold_up_adjacency: bool = False


@dataclass
class SparkPacket:
    """Top-level datagram: exactly one of the three messages."""

    hello: Optional[SparkHelloMsg] = None
    handshake: Optional[SparkHandshakeMsg] = None
    heartbeat: Optional[SparkHeartbeatMsg] = None


# ---------------------------------------------------------------------------
# Inter-module events (ref openr/common/Types.h, LsdbTypes.h)
# ---------------------------------------------------------------------------

class NeighborEventType(enum.IntEnum):
    """ref LsdbTypes.h:76."""

    NEIGHBOR_UP = 1
    NEIGHBOR_DOWN = 2
    NEIGHBOR_RESTARTED = 3
    NEIGHBOR_RTT_CHANGE = 4
    NEIGHBOR_RESTARTING = 5
    NEIGHBOR_ADJ_SYNCED = 6


@dataclass(frozen=True)
class NeighborEvent:
    """Spark -> LinkMonitor (ref LsdbTypes.h:76-160)."""

    event_type: NeighborEventType
    node_name: str
    if_name: str
    area: str
    # the NEIGHBOR's interface name (from its hellos) — required for the
    # bidirectional link verification in LinkState (other_if_name matching)
    remote_if_name: str = ""
    neighbor_addr_v6: str = ""
    neighbor_addr_v4: str = ""
    ctrl_port: int = 0
    kvstore_port: int = 0
    rtt_us: int = 0
    adj_only_used_by_other_node: bool = False


@dataclass(frozen=True)
class NeighborInitEvent:
    """Batched initial neighbor discovery completion signal
    (ref LsdbTypes.h:161)."""

    events: tuple[NeighborEvent, ...] = ()
    init_complete: bool = False


class PrefixEventType(enum.IntEnum):
    ADD_PREFIXES = 1
    WITHDRAW_PREFIXES = 2
    WITHDRAW_PREFIXES_BY_TYPE = 3
    SYNC_PREFIXES_BY_TYPE = 4


@dataclass
class PrefixEvent:
    """Plugin/CLI/LinkMonitor -> PrefixManager (ref LsdbTypes.h:275)."""

    event_type: PrefixEventType
    type: PrefixType
    prefixes: list[PrefixEntry] = field(default_factory=list)
    dest_areas: tuple[str, ...] = ()


@dataclass(frozen=True)
class AreaPeerEvent:
    """LinkMonitor -> KvStore peer add/del for one area
    (ref openr/common/Types.h:49-71)."""

    peers_to_add: dict[str, PeerSpec] = field(default_factory=dict)
    peers_to_del: tuple[str, ...] = ()


# PeerEvent = area -> AreaPeerEvent
PeerEvent = dict


class KeyValueRequestType(enum.IntEnum):
    PERSIST = 1  # advertise + keep refreshed + version-bump-to-win
    SET = 2  # one-shot set
    CLEAR = 3  # unset/erase self-originated key


@dataclass
class KeyValueRequest:
    """Module -> KvStore self-originated key op
    (ref openr/common/Types.h:228)."""

    request_type: KeyValueRequestType
    area: str
    key: str
    value: Optional[bytes] = None
    version: Optional[int] = None
    set_ttl: Optional[int] = None


@dataclass(frozen=True)
class KvStoreSyncEvent:
    """KvStore -> LinkMonitor: initial sync with peer finished
    (ref openr/common/Types.h:237)."""

    node_name: str
    area: str


class InitializationEvent(enum.IntEnum):
    """Cold-boot convergence milestones
    (ref Types.thrift InitializationEvent, docs/Protocol_Guide/Initialization)."""

    INITIALIZING = 0
    AGENT_CONFIGURED = 1
    LINK_DISCOVERED = 2
    NEIGHBOR_DISCOVERED = 3
    KVSTORE_SYNCED = 4
    RIB_COMPUTED = 5
    FIB_SYNCED = 6
    PREFIX_DB_SYNCED = 7
    INITIALIZED = 8


@dataclass(frozen=True)
class InterfaceInfo:
    """One system interface snapshot (ref LsdbTypes.h:313-400)."""

    if_name: str
    is_up: bool
    if_index: int = 0
    networks: tuple[str, ...] = ()  # CIDR strings


@dataclass(frozen=True)
class InterfaceDatabase:
    """LinkMonitor -> Spark interface snapshot (ref LsdbTypes.h:403)."""

    interfaces: tuple[InterfaceInfo, ...] = ()


# ---------------------------------------------------------------------------
# Perf events (ref Types.thrift:53-75, LsdbUtil.h:29-43)
# ---------------------------------------------------------------------------

@dataclass
class PerfEvent:
    node_name: str
    event_descr: str
    unix_ts_ms: int


@dataclass
class PerfEvents:
    events: list[PerfEvent] = field(default_factory=list)


def add_perf_event(perf: PerfEvents, node: str, descr: str) -> None:
    perf.events.append(PerfEvent(node, descr, int(time.time() * 1000)))


def total_perf_duration_ms(perf: PerfEvents) -> int:
    if len(perf.events) < 2:
        return 0
    return perf.events[-1].unix_ts_ms - perf.events[0].unix_ts_ms


# ---------------------------------------------------------------------------
# KvStore key naming (ref LsdbTypes.h:411 PrefixKey, Constants)
# ---------------------------------------------------------------------------

ADJ_DB_MARKER = "adj:"
PREFIX_DB_MARKER = "prefix:"


def adj_key(node: str) -> str:
    return f"{ADJ_DB_MARKER}{node}"


def prefix_key(node: str, area: str, prefix: str) -> str:
    return f"{PREFIX_DB_MARKER}{node}:[{area}]:{prefix}"


def parse_adj_key(key: str) -> Optional[str]:
    if key.startswith(ADJ_DB_MARKER):
        return key[len(ADJ_DB_MARKER):]
    return None


def parse_prefix_key(key: str) -> Optional[tuple[str, str, str]]:
    """-> (node, area, prefix) or None."""
    if not key.startswith(PREFIX_DB_MARKER):
        return None
    rest = key[len(PREFIX_DB_MARKER):]
    try:
        node, rest = rest.split(":[", 1)
        area, prefix = rest.split("]:", 1)
    except ValueError:
        return None
    return node, area, prefix
