"""Deterministic incident replay (ISSUE 18) — recorder + digests + harness.

A RIB is a deterministic function of the ordered LSDB event stream plus
config, so the black-box recorder's promise is exact: a recorded session
must replay through the real Decision ingest path to bit-identical
per-epoch RIB digests, an injected divergence must bisect to its first
divergent epoch, and a chaos drill (mid-flight solver failover) must
record a session that STILL replays bit-identically on the CPU oracle —
the digest is over semantic route content, not solver internals. The
flight recorder's on-disk retention (satellite) is pinned here too.
"""

import json

import pytest

from openr_tpu.config import DecisionConfig, MonitorConfig
from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    NextHop,
    RibUnicastEntry,
)
from openr_tpu.decision.rib_digest import (
    GENESIS,
    as_counter_value,
    delta_digest,
    roll,
)
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.faults import registry
from openr_tpu.runtime.monitor import FlightRecorder
from openr_tpu.types import prefix_key
from tests.conftest import run_async
from tests.test_decision import (
    AREA,
    DecisionHarness,
    adj,
    adj_db_kv,
    prefix_db_kv,
    two_node_mesh,
)
from tools.replay import load_bundle, replay_bundle


def _cnt(key):
    return int(counters.get_counter(key) or 0)


# -- digest unit semantics -------------------------------------------------


def _entry(prefix: str, cost: int, *vias: str) -> RibUnicastEntry:
    return RibUnicastEntry(
        prefix=prefix,
        nexthops=frozenset(
            NextHop(
                address="", if_name=f"if-me-{v}", neighbor_node_name=v
            )
            for v in vias
        ),
        igp_cost=cost,
    )


class TestRibDigest:
    def test_digest_is_order_insensitive_and_content_sensitive(self):
        a = DecisionRouteUpdate(
            unicast_routes_to_update={
                "10.0.0.2/32": _entry("10.0.0.2/32", 3, "b", "c"),
                "10.1.0.0/24": _entry("10.1.0.0/24", 7, "b"),
            },
            unicast_routes_to_delete=["10.9.0.0/24", "10.8.0.0/24"],
        )
        # same content, reversed insertion/delete order: same digest
        b = DecisionRouteUpdate(
            unicast_routes_to_update={
                "10.1.0.0/24": _entry("10.1.0.0/24", 7, "b"),
                "10.0.0.2/32": _entry("10.0.0.2/32", 3, "c", "b"),
            },
            unicast_routes_to_delete=["10.8.0.0/24", "10.9.0.0/24"],
        )
        assert delta_digest(a) == delta_digest(b)
        # a cost change, a nexthop change, and a delete change each move
        # the digest — the divergence signal is content-addressed
        c = DecisionRouteUpdate(
            unicast_routes_to_update={
                "10.0.0.2/32": _entry("10.0.0.2/32", 4, "b", "c"),
                "10.1.0.0/24": _entry("10.1.0.0/24", 7, "b"),
            },
            unicast_routes_to_delete=["10.9.0.0/24", "10.8.0.0/24"],
        )
        assert delta_digest(a) != delta_digest(c)
        d = DecisionRouteUpdate(
            unicast_routes_to_update={
                "10.0.0.2/32": _entry("10.0.0.2/32", 3, "b"),
                "10.1.0.0/24": _entry("10.1.0.0/24", 7, "b"),
            },
            unicast_routes_to_delete=["10.9.0.0/24", "10.8.0.0/24"],
        )
        assert delta_digest(a) != delta_digest(d)
        e = DecisionRouteUpdate(
            unicast_routes_to_update=dict(a.unicast_routes_to_update),
            unicast_routes_to_delete=["10.9.0.0/24"],
        )
        assert delta_digest(a) != delta_digest(e)

    def test_rolling_chain_and_counter_projection(self):
        d1 = delta_digest(DecisionRouteUpdate(
            unicast_routes_to_update={
                "10.0.0.2/32": _entry("10.0.0.2/32", 3, "b")
            },
        ))
        r1 = roll(GENESIS, d1)
        assert r1 != d1 and r1 != GENESIS
        # deterministic and order-dependent: the rolling hash encodes
        # the epoch SEQUENCE, not the multiset of epochs
        assert roll(GENESIS, d1) == r1
        assert roll(r1, d1) != r1
        # the counter projection is gauge-safe: < 2**48 representable
        # exactly in the registry's float64 cells
        v = as_counter_value(d1)
        assert 0 <= v < 2 ** 48
        assert int(float(v)) == v


# -- record -> replay through the real Decision ingest path ----------------


async def _churned_session(h: DecisionHarness, rounds: int = 3):
    """Drive metric flaps + a prefix advertise/withdraw through the
    harness, one awaited route update per epoch; returns the annex."""
    two_node_mesh(h)
    h.synced()
    await h.next_route_update()
    version = 1
    for m in (5, 9, 3)[:rounds]:
        version += 1
        h.publish(
            adj_db_kv("1", [adj("1", "2", metric=m)], version=version),
            adj_db_kv("2", [adj("2", "1", metric=m)], version=version),
        )
        await h.next_route_update()
    h.publish(prefix_db_kv("2", "10.5.0.0/24"))
    await h.next_route_update()
    h.expire(prefix_key("2", AREA, "10.5.0.0/24"))
    await h.next_route_update()
    rec = h.decision._replay
    assert rec is not None, "recorder off despite replay_recorder=True"
    annex = rec.export()
    assert annex is not None and not annex["gap"], annex
    return annex


class TestRecordReplay:
    @run_async
    async def test_recorded_session_replays_bit_identically(self):
        async with DecisionHarness() as h:
            annex = await _churned_session(h)
        # the session stamped digests into the counter fabric
        assert _cnt("decision.rib_digest.epoch") >= 1
        assert _cnt("replay.events") >= 1
        report = replay_bundle({"node": "1", "inputs": annex})
        assert report["status"] == "identical", report
        # anchor epoch is the baseline (not compared); every churn epoch
        # after it is
        assert report["epochs_compared"] >= 4, report

    @run_async
    async def test_injected_divergence_bisects_to_tampered_epoch(self):
        async with DecisionHarness() as h:
            annex = await _churned_session(h)
        bundle = json.loads(json.dumps({"node": "1", "inputs": annex}))
        comparable = [
            e for e in bundle["inputs"]["epochs"]
            if e["cursor"] > bundle["inputs"]["snapshot"]["cursor"]
        ]
        assert len(comparable) >= 3
        victim = comparable[1]
        victim["digest"] = (
            "f" * 16 if victim["digest"] != "f" * 16 else "0" * 16
        )
        report = replay_bundle(bundle)
        assert report["status"] == "diverged", report
        fd = report["first_divergent"]
        assert fd["epoch"] == victim["epoch"], (fd, victim)
        # the bisection hands triage its context: what solved the epoch
        # and which keys fed it
        assert fd["solver_kind"] and fd["spf_kernel"], fd

    @run_async
    async def test_ring_gap_counts_reanchors_and_gapped_annex_refused(
        self,
    ):
        """A ring too small to hold the window back to the snapshot
        anchor counts replay.ring_gaps and SELF-HEALS by re-anchoring a
        fresh snapshot at the next solve — so the final export is
        replayable again, just over a shorter window. A still-gapped
        annex, were one captured mid-hole, is REFUSED by replay: a hole
        silently replayed would be a false divergence verdict."""
        cfg = DecisionConfig(
            debounce_min_ms=5, debounce_max_ms=20,
            replay_ring=4, replay_snapshot_every_epochs=1024,
        )
        gaps0 = _cnt("replay.ring_gaps")
        async with DecisionHarness(config=cfg) as h:
            two_node_mesh(h)
            h.synced()
            await h.next_route_update()
            version = 1
            for m in (5, 9, 3, 8, 2):
                version += 1
                h.publish(
                    adj_db_kv("1", [adj("1", "2", metric=m)],
                              version=version),
                    adj_db_kv("2", [adj("2", "1", metric=m)],
                              version=version),
                )
                await h.next_route_update()
            annex = h.decision._replay.export()
        assert _cnt("replay.ring_gaps") > gaps0
        # self-healed: re-anchored snapshot, replayable shorter window
        assert annex is not None and not annex["gap"], annex
        report = replay_bundle({"node": "1", "inputs": annex})
        assert report["status"] == "identical", report
        # a mid-hole capture (gap flag up) must be refused outright
        gapped = json.loads(json.dumps({"node": "1", "inputs": annex}))
        gapped["inputs"]["gap"] = True
        refused = replay_bundle(gapped)
        assert refused["status"] == "unreplayable", refused


# -- flight-recorder bundle roundtrip + on-disk retention ------------------


class TestFlightRecorderBundles:
    @run_async
    async def test_bundle_inputs_annex_replays_via_load_bundle(self):
        import tempfile

        async with DecisionHarness() as h:
            annex = await _churned_session(h)
        with tempfile.TemporaryDirectory() as td:
            fr = FlightRecorder("1", MonitorConfig(
                flight_recorder_dir=td,
            ))
            record = fr.trigger(
                "drill", {"test": True}, extra={"inputs": annex},
                force=True,
            )
            assert record is not None
            bundle = load_bundle(record["path"])
            report = replay_bundle(bundle)
            assert report["status"] == "identical", report

    def test_on_disk_retention_prunes_to_keep(self):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            cfg = MonitorConfig(
                flight_recorder_dir=td, flight_recorder_keep=2,
                flight_recorder_min_interval_s=0.0,
            )
            fr = FlightRecorder("reten", cfg)
            pruned0 = _cnt("monitor.flight_recorder.pruned")
            paths = []
            for i in range(4):
                rec = fr.trigger(f"r{i}", {}, force=True)
                assert rec is not None
                paths.append(rec["path"])
            listing = fr.list_bundles()
            assert listing["keep"] == 2
            assert len(listing["disk"]) == 2, listing
            assert _cnt("monitor.flight_recorder.pruned") == pruned0 + 2
            kept = {b["path"] for b in listing["disk"]}
            # the newest bundle always survives retention
            assert paths[-1] in kept, (paths, kept)
            assert all(b["replayable"] for b in listing["disk"])
            # the in-memory record ring still remembers all four
            assert len(listing["memory"]) == 4

    def test_keep_zero_is_unbounded(self):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            cfg = MonitorConfig(
                flight_recorder_dir=td, flight_recorder_keep=0,
                flight_recorder_min_interval_s=0.0,
            )
            fr = FlightRecorder("unbnd", cfg)
            for i in range(3):
                assert fr.trigger(f"r{i}", {}, force=True) is not None
            assert len(fr.list_bundles()["disk"]) == 3


# -- chaos drill: failover session replays on the oracle -------------------


@pytest.mark.chaos
class TestFailoverDrillReplay:
    @run_async
    async def test_solver_failover_drill_replays_bit_identically(self):
        """Arm solver.exec so a churn epoch takes the mid-flight
        CPU-failover lane on the TPU backend, keep churning, then
        replay the recorded session on the plain CPU oracle: every
        epoch digest — the failover-cpu one included — must replay
        bit-identically, because the digest fingerprints route CONTENT
        and the failover lane's parity promise says content matches."""
        registry.clear()
        cfg = DecisionConfig(debounce_min_ms=5, debounce_max_ms=20)
        try:
            async with DecisionHarness(backend="tpu", config=cfg) as h:
                two_node_mesh(h)
                h.synced()
                await h.next_route_update()
                registry.arm("solver.exec", every_nth=1, max_fires=1)
                version = 1
                for m in (9, 4, 17):
                    version += 1
                    h.publish(
                        adj_db_kv("1", [adj("1", "2", metric=m)],
                                  version=version),
                        adj_db_kv("2", [adj("2", "1", metric=m)],
                                  version=version),
                    )
                    await h.next_route_update()
                annex = h.decision._replay.export()
        finally:
            registry.clear()
        assert annex is not None and not annex["gap"]
        kinds = {e["solver_kind"] for e in annex["epochs"]}
        assert "failover-cpu" in kinds, kinds
        report = replay_bundle({"node": "1", "inputs": annex})
        assert report["status"] == "identical", report
        assert report["epochs_compared"] >= 2, report
