"""Daemon composition root.

Role of the reference's openr/Main.cpp:161-636: parse+validate the config,
create the replicated queues, start every module in order (watchdog ->
config-store -> monitor -> kvstore -> prefix-manager -> prefix-allocator ->
spark -> link-monitor -> decision -> fib -> ctrl server, ref Main.cpp
start order), run until a stop signal, then tear down in reverse
(ref Main.cpp:592-599).

Interface provisioning: the reference discovers system interfaces over
netlink (a kernel boundary). This daemon takes static interface
declarations — `--interface name[=bind_addr:port]` — served by
UdpIoProvider on loopback/UDP; the netlink-backed provider slots in behind
the same IoProvider seam when running with kernel access.

Run:  python -m openr_tpu.main --config node1.conf --interface if0
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
import time

from openr_tpu.config import Config
from openr_tpu.prefix_manager import OriginatedPrefix
from openr_tpu.runtime.lifecycle import boot_tracer
from openr_tpu.runtime.monitor import Monitor, Watchdog
from openr_tpu.runtime.openr_wrapper import OpenrWrapper
from openr_tpu.runtime.persistent_store import PersistentStore
from openr_tpu.spark.io_provider import UdpIoProvider
from openr_tpu.types import InterfaceInfo

log = logging.getLogger("openr_tpu.main")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="openr_tpu daemon")
    p.add_argument("--config", required=True, help="JSON config file path")
    p.add_argument(
        "--interface",
        action="append",
        default=[],
        metavar="NAME[=ADDR:PORT]",
        help="static interface declaration (repeatable)",
    )
    p.add_argument(
        "--peer",
        action="append",
        default=[],
        metavar="IFACE=ADDR:PORT",
        help="discovery peer endpoint for an interface (repeatable; "
        "loopback stand-in for multicast membership)",
    )
    p.add_argument("--ctrl-port", type=int, default=None)
    p.add_argument(
        "--fib-service",
        default=None,
        metavar="HOST:PORT",
        help="program routes through an out-of-process platform agent "
        "(openr_tpu.platform.main) instead of the in-memory service; "
        "startup blocks until the agent answers aliveSince "
        "(ref waitForFibService, openr/Main.cpp:92-120)",
    )
    p.add_argument(
        "--override_drain_state",
        choices=["drained", "undrained"],
        default=None,
    )
    p.add_argument("--verbose", action="store_true")
    return p.parse_args(argv)


def _build_policy_manager(oc):
    """Config policies dict -> PolicyManager (ref PolicyManager built
    from config areaPolicies, Main.cpp plugin args)."""
    if not oc.policies:
        return None
    from openr_tpu.policy import Policy, PolicyManager
    from openr_tpu.serde import from_plain

    return PolicyManager(
        {
            name: from_plain(p, Policy) if isinstance(p, dict) else p
            for name, p in oc.policies.items()
        }
    )


async def run_daemon(args) -> None:
    # boot lifecycle (runtime/lifecycle.py): t0 is taken BEFORE config
    # load and backdated into begin() once the node name is known, so
    # the span tree covers the whole cold start
    t_boot = time.monotonic()
    cfg = Config.from_file(args.config)
    oc = cfg.raw
    node_name = oc.node_name
    boot_tracer.begin(node_name, start=t_boot)
    boot_tracer.phase_mark("config_load", node=node_name, path=args.config)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    log.info("starting openr_tpu node %s", node_name)

    # -- thread-ownership sentinel (debug; env var seeds the default) -----
    if oc.runtime_config.affinity_checks:
        from openr_tpu.runtime import affinity

        affinity.set_enabled(True)
        log.info("runtime affinity checks enabled")

    # -- fault injection: arm config-declared chaos schedules -------------
    from openr_tpu.runtime.faults import registry as fault_registry

    fault_registry.configure(oc.fault_injection_config)

    # -- device plane: backend init + persistent jit cache (boot phases) --
    backend = oc.decision_config.solver_backend
    if backend != "cpu":
        with boot_tracer.phase(
            "device_init", node=node_name, backend=backend
        ) as ph:
            try:
                import jax

                ph["platform"] = jax.default_backend()
                ph["devices"] = jax.device_count()
            # lint: allow(broad-except) cpu fallback boots without jax
            except Exception as e:
                ph["error"] = str(e)
        with boot_tracer.phase("jit_cache_attach", node=node_name) as ph:
            from openr_tpu.ops.xla_cache import enable_compilation_cache

            # same resolution the solver applies later (idempotent) —
            # attaching here folds the cache-load cost into its own
            # boot phase instead of the first solve's
            ph["cache_dir"] = enable_compilation_cache(
                oc.decision_config.xla_cache_dir or None
            )
        with boot_tracer.phase("aot_load", node=node_name) as ph:
            from openr_tpu.ops.xla_cache import configure_aot

            # deserialize previously compiled executables now, in this
            # attributed phase, so prewarm/first-solve install instead
            # of compiling (ISSUE 20)
            _aot = configure_aot(
                oc.decision_config.aot_cache_dir,
                keep=oc.decision_config.aot_cache_keep,
            )
            ph["cache_dir"] = _aot.dir or None
            if _aot.enabled:
                ph.update(_aot.preload())
            else:
                ph["skipped"] = True
    else:
        boot_tracer.phase_mark(
            "device_init", node=node_name, backend=backend, skipped=True
        )
        boot_tracer.phase_mark("jit_cache_attach", node=node_name, skipped=True)
        boot_tracer.phase_mark("aot_load", node=node_name, skipped=True)

    # prewarm happens offline (tools/prewarm.py); the phase attributes
    # what the bake paid per the perf ledger so the boot report shows
    # whether this start benefits from baked executables
    from openr_tpu.runtime.perf_ledger import configure as configure_perf_ledger

    _perf_ledger = configure_perf_ledger(oc.monitor_config.perf_ledger_dir)
    _pw = _perf_ledger.prewarm_summary()
    boot_tracer.phase_mark(
        "prewarm",
        node=node_name,
        baked_ms=_pw["baked_ms"] or None,
        namespaces=len(_pw["namespaces"]) or None,
    )

    # -- persistent store (ref config-store start, Main.cpp:340) ----------
    store = (
        PersistentStore(oc.persistent_store_path)
        if oc.persistent_store_path
        else None
    )

    # -- spark I/O: UDP provider with static interfaces -------------------
    io = UdpIoProvider(oc.spark_config.neighbor_discovery_port)
    iface_specs = []
    for spec in args.interface:
        name, _, addr = spec.partition("=")
        bind_addr, bind_port = "127.0.0.1", None
        if addr:
            bind_addr, _, port_s = addr.rpartition(":")
            bind_port = int(port_s)
        iface_specs.append((name, bind_addr, bind_port))

    # -- FibService: out-of-process platform agent, if configured ---------
    fib_service = None
    if args.fib_service:
        from openr_tpu.platform import RemoteFibService, wait_for_fib_service

        host, _, port_s = args.fib_service.rpartition(":")
        fib_service = RemoteFibService(host or "127.0.0.1", int(port_s))
        log.info("waiting for FibService at %s ...", args.fib_service)
        await wait_for_fib_service(fib_service)
        log.info("FibService is up")

    kv_ports: dict[str, int] = {}
    originated = [
        OriginatedPrefix(**op) if isinstance(op, dict) else op
        for op in oc.originated_prefixes
    ]
    node = OpenrWrapper(
        node_name,
        io,
        kv_ports,
        areas=[a.area_id for a in oc.areas],
        spark_config=oc.spark_config,
        kvstore_config=oc.kvstore_config,
        decision_config=oc.decision_config,
        fib_config=oc.fib_config,
        fib_service=fib_service,
        lm_config=oc.link_monitor_config,
        originated_prefixes=originated,
        solver_backend=oc.decision_config.solver_backend,
        enable_ctrl=True,
        ctrl_port=(
            args.ctrl_port if args.ctrl_port is not None else oc.openr_ctrl_port
        ),
        persistent_store=store,
        # neighbors publish their kvstore port in the spark handshake;
        # the ADDRESS is kernel truth — the UDP source the handshake
        # arrived from (falls back to loopback for same-host emulation)
        kvstore_port_of=lambda ev: (
            ev.neighbor_addr_v4 or ev.neighbor_addr_v6 or "127.0.0.1",
            ev.kvstore_port,
        ),
        node_label=oc.segment_routing_config.node_segment_label,
        policy_manager=_build_policy_manager(oc),
        origination_policy=oc.origination_policy,
        plugins=oc.plugins,
        running_config=cfg,
        # Spark area negotiation from the per-area regex matchers
        # (ref Config.h:34-110 + Spark area resolution)
        resolve_area=cfg.match_neighbor_area,
        # per-destination-area import policies (ref areaToPolicy_)
        area_policies={
            a.area_id: a.import_policy_name
            for a in oc.areas
            if a.import_policy_name
        },
        # peers connect to the kvstore from OTHER hosts/namespaces —
        # bind the configured listen address. Fail closed: without
        # peer-plane TLS the default stays loopback (an any-address
        # plaintext peer plane invites LSDB injection); an explicit
        # kvstore_config.listen_addr overrides consciously.
        kv_listen_addr=(
            oc.kvstore_config.listen_addr
            or (
                oc.listen_addr
                if oc.kvstore_config.enable_secure_peers
                else "127.0.0.1"
            )
        ),
    )
    def _is_loopback(addr: str) -> bool:
        if addr == "localhost":
            return True
        try:
            import ipaddress as _ip

            return _ip.ip_address(addr).is_loopback
        except ValueError:
            return False

    if (
        oc.kvstore_config.listen_addr
        and not _is_loopback(oc.kvstore_config.listen_addr)
        and not oc.kvstore_config.enable_secure_peers
    ):
        log.warning(
            "kvstore peer plane bound to %s WITHOUT TLS — any on-path "
            "host can inject LSDB state (set enable_secure_peers)",
            oc.kvstore_config.listen_addr,
        )

    # -- bring up interfaces ----------------------------------------------
    iface_infos = []
    for name, bind_addr, bind_port in iface_specs:
        addr = await io.add_interface(name, bind_addr, bind_port)
        log.info("interface %s bound at %s:%d", name, *addr)
        iface_infos.append(InterfaceInfo(if_name=name, is_up=True))
    # kernel interface discovery: rtnetlink dump + live events feed
    # LinkMonitor directly (ref LinkMonitor's netlink subscription,
    # NetlinkProtocolSocket.h:29-31); static --interface stays as the
    # loopback/emulation seam
    iface_mon = None
    if oc.link_monitor_config.enable_netlink_interfaces:
        from openr_tpu.platform.iface_monitor import NetlinkInterfaceMonitor

        iface_mon = NetlinkInterfaceMonitor(
            on_interface=lambda info: node.link_monitor.update_interface(
                info
            ),
            include_regexes=oc.link_monitor_config.include_interface_regexes,
            exclude_regexes=oc.link_monitor_config.exclude_interface_regexes,
        )
    peers_by_iface: dict[str, list[tuple[str, int]]] = {}
    for spec in args.peer:
        iface, _, endpoint = spec.partition("=")
        host, _, port_s = endpoint.rpartition(":")
        peers_by_iface.setdefault(iface, []).append((host, int(port_s)))
    for iface, peers in peers_by_iface.items():
        io.set_peers(iface, peers)

    # -- watchdog + monitor (ref Main.cpp:274-281, :352) ------------------
    watchdog = (
        Watchdog(node_name, oc.watchdog_config) if oc.enable_watchdog else None
    )
    monitor = Monitor(
        node_name,
        oc.monitor_config,
        node.log_sample_queue.get_reader("monitor"),
    )
    node.set_monitor(monitor)  # also wires kvstore for fleet health
    if watchdog is not None:
        monitor.attach_fleet_sources(watchdog=watchdog)

    # -- start (ref start order Main.cpp) ---------------------------------
    if watchdog is not None:
        await watchdog.start()
    await monitor.start()
    await node.start(*[name for name, _, _ in iface_specs])
    for info in iface_infos:
        node.link_monitor.update_interface(info)
    if iface_mon is not None:
        await iface_mon.start()
        log.info(
            "netlink interface discovery: %s",
            ", ".join(sorted(iface_mon.interfaces())) or "(none match)",
        )

    # -- prefix allocator (ref Main.cpp prefix-allocator start) -----------
    allocator = None
    pac = oc.prefix_allocation_config
    if pac is not None:
        from openr_tpu.allocators import (
            PrefixAllocator,
            StaticPrefixAllocator,
        )

        alloc_reader = node.kvstore_updates_queue.get_reader(
            "prefix-allocator"
        )
        common = dict(
            loopback_iface=pac.loopback_interface,
            set_loopback_address=pac.set_loopback_address,
        )
        if pac.prefix_allocation_mode == "STATIC":
            allocator = StaticPrefixAllocator(
                node_name, node.kvstore, alloc_reader,
                node.prefix_updates_queue, **common,
            )
        else:
            allocator = PrefixAllocator(
                node_name, node.kvstore, alloc_reader,
                node.prefix_updates_queue,
                seed_prefix=pac.seed_prefix,
                allocate_prefix_len=pac.allocate_prefix_len,
                **common,
            )
        await allocator.start()
        log.info(
            "prefix allocator started (%s mode)", pac.prefix_allocation_mode
        )
    if args.override_drain_state is not None:
        await node.link_monitor.set_node_overload(
            args.override_drain_state == "drained"
        )
    elif oc.assume_drained:
        await node.link_monitor.set_node_overload(True)

    if watchdog is not None:
        for actor in (
            node.kvstore,
            node.spark,
            node.link_monitor,
            node.decision,
            node.fib,
            node.prefix_manager,
            monitor,
        ):
            watchdog.watch_actor(actor)
        for q in (
            node.kvstore_updates_queue,
            node.route_updates_queue,
            node.fib_updates_queue,
            node.neighbor_updates_queue,
        ):
            watchdog.watch_queue(q)

    log.info(
        "node %s up: ctrl port %d, kvstore port %d",
        node_name,
        node.ctrl.port,
        node.kvstore.port,
    )
    print(f"READY ctrl={node.ctrl.port} kvstore={node.kvstore.port}", flush=True)

    # -- run until signal (ref mainEvb loop + EventBaseStopSignalHandler) -
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    # graceful restart announcement, then reverse teardown
    log.info("stopping node %s", node_name)
    if allocator is not None:
        await allocator.stop()
    if iface_mon is not None:
        iface_mon.close()
    await node.spark.send_restarting_hellos()
    await node.stop()
    await monitor.stop()
    if watchdog is not None:
        await watchdog.stop()
    if store is not None:
        store.close()
    io.close()
    log.info("node %s stopped", node_name)


def main(argv=None) -> None:
    args = parse_args(argv)
    try:
        asyncio.run(run_daemon(args))
    except KeyboardInterrupt:
        sys.exit(130)


if __name__ == "__main__":
    main()
