"""Multi-chip sharded fabric-path tests, on the virtual 8-CPU device mesh
(conftest sets xla_force_host_platform_device_count=8).

The sharded pipeline (parallel/sharding.py) computes EVERY vantage's
routes in one pass: roots data-parallel over the 'batch' mesh axis, the
graph's node columns sharded over 'graph' with a pmin halo exchange per
relaxation. TpuSpfSolver.build_fabric_route_dbs wraps it with trip-bound
derivation (measured single-chip trips, convergence-vote verified,
doubling retry) and full route materialization; results must equal the
per-vantage CPU oracle exactly.
"""

import numpy as np
import pytest

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.parallel.sharding import Unconverged, make_mesh, sharded_fabric_step
from openr_tpu.types import Adjacency, AdjacencyDatabase
from tests.test_tpu_solver import assert_rib_equal


def test_make_mesh_factors_devices():
    mesh = make_mesh(8)
    assert mesh.shape["batch"] * mesh.shape["graph"] == 8
    assert mesh.shape["graph"] == 2  # both axes exercised at >= 4 devices


def fabric_vs_oracle(states, ps, roots, mesh=None, **solver_kw):
    tpu = TpuSpfSolver(roots[0], **solver_kw)
    dbs = tpu.build_fabric_route_dbs(roots, states, ps, mesh=mesh)
    for root in roots:
        cpu_db = SpfSolver(root, **solver_kw).build_route_db(root, states, ps)
        if cpu_db is None:
            assert dbs[root] is None, root
            continue
        assert_rib_equal(cpu_db, dbs[root], f"fabric vantage {root}")
    return tpu, dbs


def test_fabric_route_dbs_grid_all_vantage_parity():
    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    roots = [db.this_node_name for db in adj_dbs[::7]]  # 10 vantages
    tpu, dbs = fabric_vs_oracle(states, ps, roots, mesh=make_mesh(8))
    assert len(dbs) == len(roots)


def test_fabric_route_dbs_with_lfa():
    """LFA backups computed on the sharded path match the oracle."""
    adj_dbs, prefix_dbs = topologies.grid(6)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    roots = ["node-0-0", "node-2-3", "node-5-5"]
    # parity incl. lfa_nexthops is asserted inside fabric_vs_oracle
    fabric_vs_oracle(states, ps, roots, enable_lfa=True)


def test_fabric_route_dbs_drained_and_churn():
    adj_dbs, prefix_dbs = topologies.random_mesh(30, seed=3)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    ls = states["0"]
    victim = next(d for d in adj_dbs if d.this_node_name == "node-7")
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="node-7",
            adjacencies=victim.adjacencies,
            is_overloaded=True,
            area="0",
        )
    )
    roots = ["node-0", "node-7", "node-15"]
    tpu, _ = fabric_vs_oracle(states, ps, roots)
    # metric churn, then the same solver instance recomputes correctly
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="node-3",
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": 9})
                for a in next(
                    d for d in adj_dbs if d.this_node_name == "node-3"
                ).adjacencies
            ),
            area="0",
        )
    )
    dbs = tpu.build_fabric_route_dbs(roots, states, ps)
    for root in roots:
        cpu_db = SpfSolver(root).build_route_db(root, states, ps)
        assert_rib_equal(cpu_db, dbs[root], f"after churn {root}")


def test_fabric_unknown_root_returns_none():
    adj_dbs, prefix_dbs = topologies.grid(4)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-0-0")
    dbs = tpu.build_fabric_route_dbs(
        ["node-0-0", "not-a-node"], states, ps
    )
    assert dbs["not-a-node"] is None
    assert dbs["node-0-0"] is not None


def test_fabric_trip_bound_retry_from_cold_solver():
    """A fresh solver has no measured trip count (last_trips == 0); the
    seed bound is tiny and the convergence vote must drive the doubling
    retry to a correct result on a high-diameter graph."""
    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver("node-0-0")
    assert tpu.last_trips == 0
    dbs = tpu.build_fabric_route_dbs(["node-0-0", "node-7-7"], states, ps)
    cpu_db = SpfSolver("node-0-0").build_route_db("node-0-0", states, ps)
    assert_rib_equal(cpu_db, dbs["node-0-0"], "retry path")


def test_sharded_step_unconverged_raises():
    """Directly under-bound the trip count: the kernel's convergence
    vote must raise instead of returning too-large distances."""
    from openr_tpu.ops.csr import build_prefix_matrix
    from openr_tpu.ops.edgeplan import INF32E, build_plan

    adj_dbs, prefix_dbs = topologies.grid(10, node_labels=False)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    ls = states["0"]
    plan = build_plan(ls)
    matrix = build_prefix_matrix(ps, plan.node_index, "0")
    mesh = make_mesh(4)
    batch = mesh.shape["batch"]
    roots_names = [plan.node_names[0]] * batch
    roots = np.array([plan.node_index[n] for n in roots_names], np.int32)
    outs = [plan.out_links(ls, n) for n in roots_names]
    d_cap = max(o[0].shape[0] for o in outs)
    out_nbr = np.full((batch, d_cap), -1, np.int32)
    out_w = np.full((batch, d_cap), int(INF32E), np.int32)
    for i, (nbr, w, _l) in enumerate(outs):
        out_nbr[i, : nbr.shape[0]] = nbr
        out_w[i, : w.shape[0]] = w
    try:
        sharded_fabric_step(mesh, plan, matrix, roots, out_nbr, out_w, 1)
    except Unconverged:
        pass
    else:  # pragma: no cover
        raise AssertionError("expected Unconverged for a 1-trip bound")


def test_fabric_matches_single_chip_solver():
    """The sharded path and the single-chip resident pipeline are two
    implementations of the same function."""
    adj_dbs, prefix_dbs = topologies.grid(6)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    single = TpuSpfSolver("node-3-3")
    single_db = single.build_route_db("node-3-3", states, ps)
    fabric = TpuSpfSolver("node-3-3")
    dbs = fabric.build_fabric_route_dbs(["node-3-3"], states, ps)
    assert_rib_equal(single_db, dbs["node-3-3"], "single vs fabric")


def test_fabric_non_divisible_graph_axis_pads():
    """A graph axis of 3 does not divide grid(8)'s node capacity (64);
    sharded_fabric_step must pad the node axis up to the mesh
    factorization instead of asserting divisibility, and the padded
    columns must never leak finite distances into the result."""
    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    mesh = make_mesh(6, batch=2)
    assert mesh.shape["graph"] == 3
    roots = ["node-0-0", "node-3-4", "node-7-7"]
    fabric_vs_oracle(states, ps, roots, mesh=mesh)


# -- multichip capacity tier (production single-vantage path) ---------------


def _churn_node(ls, victim, bump):
    """Metric-churn one node's adjacencies through the changelog path
    (generation bump); bump=0 restores the pristine metrics."""
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=victim.this_node_name,
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": a.metric + bump})
                for a in victim.adjacencies
            ),
            area="0",
        )
    )


@pytest.mark.parametrize("incr", [False, True])
def test_multichip_production_path_parity(incr):
    """build_route_db through the multichip capacity tier (threshold
    forced below the graph's n_cap): RIBs bit-identical to BOTH the CPU
    oracle and the single-chip tier — including LFA backups — across
    cold solve, metric churn, restore, link flap, and flap restore, on
    the full-solve and incremental solvers. Tier observability
    (counters, stats, per-shard timings) is asserted alongside."""
    from openr_tpu.runtime.counters import counters

    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    root = adj_dbs[0].this_node_name
    ls = states["0"]
    cpu = SpfSolver(root, enable_lfa=True)
    single = TpuSpfSolver(root, enable_lfa=True, incremental_spf=incr)
    mc = TpuSpfSolver(
        root, enable_lfa=True, incremental_spf=incr,
        multichip_n_cap_threshold=32, multichip_batch=4,
    )
    eng0 = counters.get_counter("decision.solver.multichip.engaged") or 0
    dis0 = counters.get_counter("decision.solver.multichip.dispatches") or 0

    def check(ctx):
        cpu_db = cpu.build_route_db(root, states, ps)
        mc_db = mc.build_route_db(root, states, ps)
        assert_rib_equal(cpu_db, mc_db, f"mc vs oracle: {ctx}")
        assert_rib_equal(
            single.build_route_db(root, states, ps), mc_db,
            f"mc vs single-chip: {ctx}",
        )

    check("cold")
    mc_info = mc.last_timing["multichip"]
    assert mc_info["shards"] == 8
    assert mc_info["batch"] == 4 and mc_info["graph"] == 2
    assert len(mc_info["shard_ms"]) == 8
    assert mc.last_device_stats["multichip"]["shards"] == 8

    _churn_node(ls, adj_dbs[1], 7)
    check("metric churn")
    _churn_node(ls, adj_dbs[1], 0)
    check("restore")
    victim = adj_dbs[5]
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=victim.this_node_name,
            adjacencies=(), area="0",
        )
    )
    check("flap down")
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=victim.this_node_name,
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": 3})
                for a in victim.adjacencies
            ),
            area="0",
        )
    )
    check("flap restore")
    eng1 = counters.get_counter("decision.solver.multichip.engaged") or 0
    dis1 = counters.get_counter("decision.solver.multichip.dispatches") or 0
    assert eng1 >= eng0 + 5, (eng0, eng1)
    assert dis1 >= dis0 + 5, (dis0, dis1)


def test_multichip_tier_stays_off_below_threshold():
    """The same graph under the default threshold (n_cap far below it)
    must never touch the sharded path: no mc stats, no engage ticks."""
    from openr_tpu.runtime.counters import counters

    adj_dbs, prefix_dbs = topologies.grid(8)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    root = adj_dbs[0].this_node_name
    eng0 = counters.get_counter("decision.solver.multichip.engaged") or 0
    tpu = TpuSpfSolver(root)
    cpu_db = SpfSolver(root).build_route_db(root, states, ps)
    assert_rib_equal(
        cpu_db, tpu.build_route_db(root, states, ps), "below threshold"
    )
    assert not tpu.last_timing.get("multichip")
    assert "multichip" not in tpu.last_device_stats
    eng1 = counters.get_counter("decision.solver.multichip.engaged") or 0
    assert eng1 == eng0
