"""Boot-to-first-RIB lifecycle tracer (ISSUE 14).

ROADMAP item 1 gates the cold-start work on "cold-process-to-first-RIB
under 2 s" — but convergence tracing (runtime/tracing.py) only opens a
trace at KvStore ingest, so everything a restarting daemon pays BEFORE
its first LSDB event (config load, jax/device init, persistent-jit-cache
attach, prewarm attribution, the initial full sync, the first
compile-heavy solve) was invisible. This module records that one-shot
timeline:

  config_load -> device_init -> jit_cache_attach -> aot_load
    -> prewarm -> kvstore_initial_sync -> first_solve
    -> first_rib_delta -> first_fib_program

``aot_load`` (ISSUE 20) is the persistent executable-cache preload:
deserializing previously compiled kernels from disk so the prewarm
phase that follows installs them instead of invoking XLA.

``main.run_daemon`` calls ``boot_tracer.begin(node)`` before any actor
spins up; phases are stamped from wherever they actually complete
(main.py for the explicit setup steps, KvStore/Decision/Fib for the
pipeline milestones). The tracer keeps a contiguous cursor, so a
retroactive ``phase_mark`` covers everything since the previous phase
ended — the phases tile the boot wall-clock with no gaps.

Three outputs per boot:

  - gauges: ``boot.phase.<name>_ms`` per phase and the headline
    ``boot.first_rib_ms`` (plus ``boot.complete``), scraped like any
    other counter and recorded as a bench headline (bench.py boot lane)
  - a span tree: one ``boot`` trace whose root carries the node name,
    so ``export_chrome`` lanes it next to the node's convergence
    traces; closed with status="boot" (the whatif pattern) so it never
    pollutes the convergence_ms stat
  - a report: ``ctrl.monitor.boot`` / ``breeze monitor boot`` render
    the phase ledger with per-phase attributes (the first solve's
    compile/device/mat split, the jit-cache dir, prewarm attribution)

Process-global singleton (the ``tracer``/``counters`` pattern): actors
stamp phases without plumbing, and pass their node name so that in
multi-node test processes only the node that ``begin``-ed records.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.tracing import tracer

# Canonical phase order — documentation + the lint expansion for the
# dynamic boot.phase.<name>_ms gauge family (tools/lint/metric_names.py).
BOOT_PHASES = (
    "config_load",
    "device_init",
    "jit_cache_attach",
    "aot_load",
    "prewarm",
    "kvstore_initial_sync",
    "first_solve",
    "first_rib_delta",
    "first_fib_program",
)


class BootTracer:
    """One cold start's phase ledger + span tree. Reusable via reset()
    (tests, bench boot lane); a daemon runs exactly one boot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._node: Optional[str] = None
        self._ctx = None
        self._t0: Optional[float] = None
        self._started_wall_ms = 0
        self._cursor: Optional[float] = None
        self._phases: list[dict] = []
        self._complete = False
        self._first_rib_ms: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def begin(self, node: str, start: Optional[float] = None) -> None:
        """Open the boot timeline. `start` (time.monotonic()) backdates
        the root over work already done (e.g. config load) when the
        caller could only learn the node name from the config."""
        with self._lock:
            if self._node is not None and not self._complete:
                return  # one boot per process; ignore re-entry
            t0 = start if start is not None else time.monotonic()
            self._node = node
            self._t0 = t0
            self._cursor = t0
            self._started_wall_ms = int(
                time.time() * 1000 - (time.monotonic() - t0) * 1000
            )
            self._phases = []
            self._complete = False
            self._first_rib_ms = None
            self._ctx = tracer.start_trace("boot", start=t0, node=node)

    def active(self, node: Optional[str] = None) -> bool:
        """True while a boot is being recorded (begun, not complete) —
        and, when `node` is given, recording THAT node. The cheap guard
        actors use before stamping."""
        if self._node is None or self._complete:
            return False
        return node is None or node == self._node

    def phase_mark(
        self, name: str, node: Optional[str] = None, **attrs
    ) -> None:
        """Record a phase retroactively: it spans from the end of the
        previous phase to now, keeping the boot timeline gapless."""
        now = time.monotonic()
        with self._lock:
            if not self.active(node):
                return
            self._record(name, self._cursor, now, attrs)

    @contextlib.contextmanager
    def phase(self, name: str, node: Optional[str] = None, **attrs):
        """Explicitly timed phase; yields a dict merged into the phase
        attributes at exit (for values only known inside the block)."""
        extra: dict = {}
        start = time.monotonic()
        try:
            yield extra
        finally:
            now = time.monotonic()
            with self._lock:
                if self.active(node):
                    self._record(name, start, now, {**attrs, **extra})

    def complete(self, node: Optional[str] = None, **attrs) -> None:
        """Boot done: the first RIB is programmed. Stamps the headline
        gauge and closes the span tree (status="boot" so the trace
        never lands in the convergence_ms stat)."""
        with self._lock:
            if not self.active(node):
                return
            now = time.monotonic()
            self._complete = True
            self._first_rib_ms = (now - self._t0) * 1e3
            counters.set_counter(
                "boot.first_rib_ms", round(self._first_rib_ms, 3)
            )
            counters.set_counter("boot.complete", 1)
            ctx, self._ctx = self._ctx, None
        if ctx is not None:
            tracer.end_trace(
                ctx,
                status="boot",
                first_rib_ms=round(self._first_rib_ms, 3),
                **attrs,
            )

    def reset(self) -> None:
        """Drop state (tests / bench boot lane). Abandons an unclosed
        trace with an explicit status rather than leaking it active."""
        with self._lock:
            ctx, self._ctx = self._ctx, None
            self._node = None
            self._t0 = None
            self._cursor = None
            self._phases = []
            self._complete = False
            self._first_rib_ms = None
        if ctx is not None:
            tracer.end_trace(ctx, status="boot_abandoned")

    # -- internals ---------------------------------------------------------

    def _record(
        self, name: str, start: float, end: float, attrs: dict
    ) -> None:
        """Caller holds the lock and has already passed the node gate."""
        dur_ms = max(0.0, (end - start) * 1e3)
        self._phases.append(
            {
                "name": name,
                "start_ms": round((start - self._t0) * 1e3, 3),
                "duration_ms": round(dur_ms, 3),
                "attrs": {k: v for k, v in attrs.items() if v is not None},
            }
        )
        self._cursor = max(self._cursor, end)
        counters.set_counter(f"boot.phase.{name}_ms", round(dur_ms, 3))
        tracer.record_span(
            self._ctx, f"boot.{name}", start, end, node=self._node, **attrs
        )

    # -- report ------------------------------------------------------------

    def report(self) -> dict:
        """`ctrl.monitor.boot` / `breeze monitor boot` payload."""
        with self._lock:
            if self._node is None:
                return {"enabled": False, "phases": []}
            return {
                "enabled": True,
                "node": self._node,
                "started_at_ms": self._started_wall_ms,
                "complete": self._complete,
                "first_rib_ms": (
                    round(self._first_rib_ms, 3)
                    if self._first_rib_ms is not None
                    else None
                ),
                "elapsed_ms": round(
                    (time.monotonic() - self._t0) * 1e3, 3
                ),
                "phases": [dict(p) for p in self._phases],
            }


boot_tracer = BootTracer()
