"""ColumnarRib / LazyUnicastRoutes properties (ISSUE 1 tentpole).

The columnar RIB keeps the solver's packed outputs as numpy columns and
builds RibUnicastEntry objects only at consumption boundaries. These
tests pin the load-bearing invariants:

  - materialized-lazily == built-eagerly, byte-identical, on randomized
    topologies through cold rebuilds AND steady-state delta patches
    (the CPU oracle builds every entry eagerly through an independent
    code path);
  - RibView snapshots are isolated from later churn (copy-on-write);
  - fast_unicast_diff (journal-bounded) == the brute-force full
    compare;
  - LazyUnicastRoutes honors MutableMapping semantics without forcing
    surprises.
"""

import numpy as np
import pytest

from openr_tpu.decision.columnar_rib import (
    LazyUnicastRoutes,
    fast_unicast_diff,
)
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.types import Adjacency, AdjacencyDatabase


def _flap(states, adj_dbs, node, metric):
    victim = next(d for d in adj_dbs if d.this_node_name == node)
    states["0"].update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=node,
            adjacencies=tuple(
                Adjacency(**{**a.__dict__, "metric": metric})
                for a in victim.adjacencies
            ),
            area="0",
        )
    )


def _assert_byte_identical(lazy_db, eager_db, context):
    mat = dict(lazy_db.unicast_routes)
    eager = eager_db.unicast_routes
    assert mat.keys() == eager.keys(), context
    for pfx, a in mat.items():
        b = eager[pfx]
        # dataclass __eq__ covers every field; repr pins the byte-level
        # rendering (field order, frozenset contents, defaults)
        assert a == b, f"{context}: {pfx}\n{a}\nvs\n{b}"
        assert sorted(map(repr, a.nexthops)) == sorted(map(repr, b.nexthops))
        assert a.__dict__.keys() == b.__dict__.keys(), (context, pfx)


@pytest.mark.parametrize("seed,kw", [(3, {}), (17, {}),
                                     (42, {"enable_lfa": True})])
def test_columnar_matches_eager_on_randomized_topologies(seed, kw):
    """Property: for random topologies, the lazily-materialized columnar
    RIB is byte-identical to the oracle's eagerly-built entries — cold,
    after a delta patch, and after a full invalidation."""
    rng = np.random.default_rng(seed)
    adj_dbs, prefix_dbs = topologies.random_mesh(28, seed=seed)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    cpu = SpfSolver(me, **kw)
    tpu = TpuSpfSolver(me, **kw)
    tpu_db = tpu.build_route_db(me, states, ps)
    assert isinstance(tpu_db.unicast_routes, LazyUnicastRoutes)
    _assert_byte_identical(tpu_db, cpu.build_route_db(me, states, ps),
                           f"cold seed={seed}")
    # steady-state: a couple of metric flaps exercise the delta patch
    # path (apply_rows) and the journal
    for step in range(3):
        victim = f"node-{int(rng.integers(1, 28))}"
        _flap(states, adj_dbs, victim, metric=int(rng.integers(2, 30)))
        tpu_db = tpu.build_route_db(me, states, ps)
        _assert_byte_identical(
            tpu_db, cpu.build_route_db(me, states, ps),
            f"delta seed={seed} step={step} victim={victim}",
        )


def test_view_snapshots_isolated_from_churn():
    """A RibView snapshot taken before churn must keep answering with
    its own generation's routes (copy-on-write), even while the solver
    patches the live columns underneath."""
    adj_dbs, prefix_dbs = topologies.random_mesh(24, seed=7)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    tpu = TpuSpfSolver(me)
    db1 = tpu.build_route_db(me, states, ps)
    before = dict(db1.unicast_routes)  # force + snapshot
    # drop node-3 entirely: its prefix route must disappear
    states["0"].update_adjacency_database(
        AdjacencyDatabase(this_node_name="node-3", adjacencies=(), area="0")
    )
    db2 = tpu.build_route_db(me, states, ps)
    after = dict(db2.unicast_routes)
    assert before != after, "churn did not change any route"
    # the old db still answers with the old generation
    assert dict(db1.unicast_routes) == before
    # and per-key lookups on the stale view agree with its snapshot
    for pfx in list(before)[:32]:
        assert db1.unicast_routes[pfx] == before[pfx]


def test_fast_unicast_diff_matches_brute_force():
    """The journal-bounded diff must produce exactly the same update set
    as the full per-entry compare."""
    adj_dbs, prefix_dbs = topologies.random_mesh(24, seed=5)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    tpu = TpuSpfSolver(me)
    db1 = tpu.build_route_db(me, states, ps)
    _flap(states, adj_dbs, "node-4", metric=21)
    db2 = tpu.build_route_db(me, states, ps)
    res = fast_unicast_diff(db1.unicast_routes, db2.unicast_routes)
    assert res is not None, "fast path did not engage"
    to_update, dels = res
    old, new = dict(db1.unicast_routes), dict(db2.unicast_routes)
    brute_update = {
        p: e for p, e in new.items()
        if p not in old or old[p] != e
    }
    brute_dels = [p for p in old if p not in new]
    assert to_update == brute_update
    assert sorted(dels) == sorted(brute_dels)
    # the Fib-facing entry point reports the fast path
    upd = db1.calculate_update(db2)
    assert getattr(upd, "fast_diff", False)
    assert upd.unicast_routes_to_update == brute_update
    assert sorted(upd.unicast_routes_to_delete) == sorted(brute_dels)


def test_fast_diff_ineligible_pairs_fall_back():
    """Foreign mappings and unrelated lazies must return None (callers
    then run the full compare)."""
    adj_dbs, prefix_dbs = topologies.random_mesh(20, seed=9)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    db = TpuSpfSolver(me).build_route_db(me, states, ps)
    assert fast_unicast_diff({}, db.unicast_routes) is None
    assert fast_unicast_diff(db.unicast_routes, {}) is None
    # two independent solvers => distinct cribs => ineligible
    other = TpuSpfSolver(me).build_route_db(me, states, ps)
    assert fast_unicast_diff(db.unicast_routes,
                             other.unicast_routes) is None


def test_lazy_mapping_semantics():
    """LazyUnicastRoutes is the dict DecisionRouteDb carries: overrides
    shadow views, deletes hide keys, equality is value-based."""
    adj_dbs, prefix_dbs = topologies.random_mesh(20, seed=13)
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = "node-0"
    lazy = TpuSpfSolver(me).build_route_db(me, states, ps).unicast_routes
    plain = dict(lazy)
    assert len(lazy) == len(plain)
    assert set(lazy) == set(plain)
    assert lazy == plain and plain == dict(lazy)
    pfx = next(iter(plain))
    assert pfx in lazy and lazy[pfx] == plain[pfx]
    assert lazy.get("no-such-prefix/128") is None
    # override shadows the view without changing cardinality
    import dataclasses

    patched = dataclasses.replace(plain[pfx], igp_cost=999_999)
    lazy[pfx] = patched
    assert lazy[pfx] is patched and len(lazy) == len(plain)
    assert lazy != plain
    # delete hides the key
    del lazy[pfx]
    assert pfx not in lazy and len(lazy) == len(plain) - 1
    with pytest.raises(KeyError):
        del lazy["no-such-prefix/128"]
    # re-insert restores
    lazy[pfx] = plain[pfx]
    assert lazy == plain
