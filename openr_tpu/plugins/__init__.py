"""Plugin extension boundary.

Role of the reference's openr/plugin/Plugin.{h,cpp} (:19-44): the
link-time hooks `pluginStart(PluginArgs)` / `vipPluginStart(...)` that
closed-source integrations (BGP speaker, VIP injection) attach to. The
open-source reference ships no-op stubs; the EXTENSION POINT is the
deliverable — queue handles + config, passed to externally-provided
code, started after the core modules and stopped before teardown.

Here plugins are named in config (`plugins: ["pkg.module:factory"]`).
Each factory is called with PluginArgs and returns an object with
`async start()` / `async stop()`. PluginArgs carries the same
capabilities the reference's struct does (Plugin.h PluginArgs: queues +
config):

  prefix_updates_queue   inject/withdraw prefixes (VIP plugin role)
  static_routes_queue    push static routes into Decision (BGP role)
  route_updates_reader() fan-out reader over computed route deltas
  kv_request_queue       persist keys into KvStore

The TPU solver intentionally does NOT live behind this boundary: it is
a Decision backend (decision.make_solver), not a queue-attached
sidecar — plugins extend the CONTROL plane.
"""

from __future__ import annotations

import importlib
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)


@dataclass
class PluginArgs:
    """ref Plugin.h PluginArgs{queues, config, ssl} — minus ssl (the
    RPC layer is plaintext-loopback in this build)."""

    node_name: str
    config: Any = None  # openr_tpu.config.Config when started by main
    prefix_updates_queue: Any = None
    static_routes_queue: Any = None
    kv_request_queue: Any = None
    # factory: call to get a fresh reader over computed route updates
    route_updates_reader: Optional[Callable[[], Any]] = None
    extras: dict = field(default_factory=dict)


def resolve_plugin(spec: str) -> Callable[[PluginArgs], Any]:
    """'package.module:factory' -> callable."""
    mod_name, _, attr = spec.partition(":")
    if not attr:
        attr = "plugin"
    mod = importlib.import_module(mod_name)
    return getattr(mod, attr)


class PluginHost:
    """Owns plugin lifecycles (ref pluginStart/pluginStop call sites in
    Main.cpp:485-509: start after link-monitor, stop before teardown)."""

    def __init__(self, args: PluginArgs, specs: Optional[list[str]] = None):
        self.args = args
        self.specs = list(specs or [])
        self.plugins: list[Any] = []

    async def start(self) -> None:
        for spec in self.specs:
            factory = resolve_plugin(spec)
            plugin = factory(self.args)
            await plugin.start()
            self.plugins.append(plugin)
            log.info("plugin %s started", spec)

    async def stop(self) -> None:
        for plugin in reversed(self.plugins):
            try:
                await plugin.stop()
            except Exception:  # noqa: BLE001 — teardown must not cascade
                log.exception("plugin stop failed")
        self.plugins.clear()
