"""RibPolicy — post-computation route transformation.

Role of the reference's openr/decision/RibPolicy.{h,cpp} (:23-124): an
ordered list of statements, each a matcher (prefix set and/or tag set) plus
an action (per-area / per-neighbor next-hop weights). Decision applies the
policy to the computed unicast RIB before emitting the delta; zero-weight
next hops are removed, and a route whose next hops all drop is deleted.
Policies carry a TTL (validity window) and survive restarts via save/load
with absolute-TTL adjustment (ref Decision.cpp:646-728).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Optional

from openr_tpu.decision.rib import NextHop, RibUnicastEntry


@dataclass
class RibRouteActionWeight:
    """ref OpenrCtrl.thrift RibRouteActionWeight."""

    default_weight: int = 0
    area_to_weight: dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: dict[str, int] = field(default_factory=dict)


@dataclass
class RibPolicyStatement:
    """Match (prefix-list and/or tag-list) -> action
    (ref RibPolicy.h RibPolicyStatement :23-60)."""

    name: str = ""
    prefixes: tuple[str, ...] = ()
    tags: tuple[str, ...] = ()
    action: RibRouteActionWeight = field(default_factory=RibRouteActionWeight)
    counter_id: Optional[str] = None

    def match(self, entry: RibUnicastEntry) -> bool:
        """ref RibPolicyStatement::match — prefix OR tag membership."""
        if self.prefixes and entry.prefix in self.prefixes:
            return True
        if self.tags and entry.best_prefix_entry is not None:
            if set(self.tags) & set(entry.best_prefix_entry.tags):
                return True
        return False

    def apply_action(self, entry: RibUnicastEntry) -> Optional[RibUnicastEntry]:
        """Transform the route's next-hop weights; None if every next hop
        dropped (ref RibPolicyStatement::applyAction)."""
        new_nhs: set[NextHop] = set()
        for nh in entry.nexthops:
            weight = self.action.default_weight
            if nh.area and nh.area in self.action.area_to_weight:
                weight = self.action.area_to_weight[nh.area]
            if (
                nh.neighbor_node_name
                and nh.neighbor_node_name in self.action.neighbor_to_weight
            ):
                weight = self.action.neighbor_to_weight[nh.neighbor_node_name]
            if weight == 0:
                continue  # zero weight removes the next hop
            new_nhs.add(replace(nh, weight=weight))
        if not new_nhs:
            return None
        return replace(
            entry, nexthops=frozenset(new_nhs), counter_id=self.counter_id
        )


@dataclass
class RibPolicy:
    """ref RibPolicy.h RibPolicy :62-124 + OpenrCtrl.thrift RibPolicy:185."""

    statements: tuple[RibPolicyStatement, ...] = ()
    ttl_secs: int = 300
    # absolute validity deadline (monotonic); None = not yet armed
    valid_until: Optional[float] = None

    def arm(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.valid_until = now + self.ttl_secs

    def is_active(self, now: Optional[float] = None) -> bool:
        if self.valid_until is None:
            return False
        now = time.monotonic() if now is None else now
        return now < self.valid_until

    def remaining_ttl_secs(self, now: Optional[float] = None) -> float:
        if self.valid_until is None:
            return 0.0
        now = time.monotonic() if now is None else now
        return max(0.0, self.valid_until - now)

    def match(self, entry: RibUnicastEntry) -> Optional[RibPolicyStatement]:
        for stmt in self.statements:
            if stmt.match(entry):
                return stmt
        return None

    def apply_policy(
        self, unicast_routes: dict[str, RibUnicastEntry]
    ) -> tuple[dict[str, RibUnicastEntry], list[str]]:
        """Transform matching routes in place; returns (changed routes,
        deleted prefixes) (ref RibPolicy::applyPolicy h:100-112)."""
        changed: dict[str, RibUnicastEntry] = {}
        deleted: list[str] = []
        if not self.is_active():
            return changed, deleted
        for prefix, entry in list(unicast_routes.items()):
            stmt = self.match(entry)
            if stmt is None:
                continue
            new_entry = stmt.apply_action(entry)
            if new_entry is None:
                del unicast_routes[prefix]
                deleted.append(prefix)
            elif new_entry != entry:
                unicast_routes[prefix] = new_entry
                changed[prefix] = new_entry
        return changed, deleted
