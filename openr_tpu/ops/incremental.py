"""Incremental device SSSP: seed-from-previous, cone-bounded
re-relaxation (DeltaPath / Bounded-Dijkstra style) on the resident
shift-decomposed mirror.

The full solve relaxes a cold all-INF plane to fixpoint. Relaxation
over non-negative int32 weights is monotone-decreasing and its fixpoint
(with the root-neighbor seeds pinned to 0) is *unique*: starting from
ANY pointwise over-estimate of the true distances it converges to
exactly the cold-solve plane, bit for bit (int32 arithmetic is exact).
That gives the incremental recipe:

  decreases  — the previous plane is already an over-estimate of the
               new distances; just re-relax. The cone that changed is
               small, so the while_loop hits fixpoint in a few trips.
  increases  — the previous plane UNDER-estimates exactly on the
               affected cone; those rows must be re-anchored to INF
               first. A node's distance can only have increased if its
               parent chain (a shortest path under the OLD weights)
               crosses an increased edge, so the affected cone is the
               union of parent-tree subtrees hanging off the head of
               each increased dirty edge. We rebuild the parent plane
               on device from the OLD weights (reconstructed from the
               dirty tuples' pre-write values), seed the subtree roots,
               and propagate descendants to fixpoint.

Zero-weight edges break the subtree argument (equal-distance parent
cycles never reach the increased edge); the host gates incremental off
via EdgePlan.has_zero_w, so every weight seen here is >= 1 and parent
chains strictly decrease the previous distance — a proper forest.

Cone fallback is decided ON DEVICE: when the affected cone exceeds
cone_limit the warm seed is swapped for the cold all-INF seed inside
the same dispatch, degrading to a bit-identical full solve with no
extra host round-trip. Over-invalidation is always safe (INF is an
over-estimate), so every approximation here errs toward correctness.

INF discipline matches the full solver: INF32E = 2^29, weights
<= 2^28, `dist + w` overflow-free in int32. Dirty pad entries use
out-of-range flat indices and are dropped by `mode="drop"` scatters /
validity masks on gathers.
"""

from __future__ import annotations

from openr_tpu.ops import relax as relax_ops

INF_E = 1 << 29  # matches edgeplan.INF32E / tpu_solver.INF_E
_UNROLL = relax_ops.UNROLL  # relax/propagate steps per while_loop trip


def _old_planes(shift_w, res_w, s_dirty_idx, s_dirty_old,
                r_dirty_idx, r_dirty_old, has_res):
    """Reconstruct the previous weight planes from the new resident
    planes + the dirty tuples' pre-write values. Pad entries carry
    out-of-range flat indices and drop."""
    import jax.numpy as jnp

    old_shift = (
        shift_w.ravel()
        .at[s_dirty_idx].set(s_dirty_old, mode="drop")
        .reshape(shift_w.shape)
    )
    if has_res:
        old_res = (
            res_w.ravel()
            .at[r_dirty_idx].set(r_dirty_old, mode="drop")
            .reshape(res_w.shape)
        )
    else:
        old_res = res_w
    return old_shift, old_res


def _parent_plane(deltas, swm_old, res_rows, res_nbr, rwm_old,
                  prev_dist, s_cap, has_res, n_cap, d_cap):
    """Per-lane parent forest [D, N] under the OLD (root-masked)
    weights: par[d, v] = some u with prev[d,u] + w_old(u,v) ==
    prev[d,v], or -1 (seeds and unreachable nodes). Any tight-edge
    parent works for the invalidation argument — the par chain is one
    concrete old shortest path. Guards: prev[u] < INF and w < INF keep
    INF+0 / 0+INF arithmetic from minting spurious tight edges."""
    import jax
    import jax.numpy as jnp

    par = jnp.full((d_cap, n_cap), -1, jnp.int32)
    src = jnp.arange(n_cap, dtype=jnp.int32)

    def cls(k, par):
        dk = deltas[k]
        wk = swm_old[k]
        cand = prev_dist + wk[None, :]
        tgt = jnp.roll(prev_dist, -dk, axis=1)  # tgt[:, u] = prev[:, v]
        hit = (prev_dist < INF_E) & (wk < INF_E)[None, :] & (cand == tgt)
        hit_v = jnp.roll(hit, dk, axis=1)  # hit at child position v
        src_v = jnp.roll(src, dk)[None, :]  # src_v[v] = u
        return jnp.where((par < 0) & hit_v, src_v, par)

    par = jax.lax.fori_loop(0, s_cap, cls, par)

    if has_res:
        nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
        rows_c = jnp.clip(res_rows, 0, n_cap - 1)
        row_valid = res_rows >= 0
        # pad scatter target n_cap drops — a clipped pad row would
        # collide with node 0's real residual row otherwise
        rows_s = jnp.where(row_valid, res_rows, n_cap)
        prev_n = prev_dist[:, nbr_c]  # [D, R, K]
        cand = prev_n + rwm_old[None]
        tgt = prev_dist[:, rows_c][:, :, None]
        hit = (
            (prev_n < INF_E)
            & (rwm_old < INF_E)[None]
            & (cand == tgt)
            & (res_nbr >= 0)[None]
        )  # [D, R, K]
        has = hit.any(axis=2)
        first = jnp.argmax(hit, axis=2)  # first tight slot breaks ties
        nbr_b = jnp.broadcast_to(res_nbr[None], hit.shape)
        pick = jnp.take_along_axis(
            nbr_b, first[:, :, None], axis=2
        )[:, :, 0]  # [D, R]
        cur = par[:, rows_c]
        new = jnp.where((cur < 0) & has & row_valid[None], pick, cur)
        par = par.at[:, rows_s].set(new, mode="drop")
    return par


def incremental_sssp(deltas, shift_w, res_rows, res_nbr, res_w, root,
                     seeds_nbr, seeds_w, prev_dist,
                     s_dirty_idx, s_dirty_old,
                     r_dirty_idx, r_dirty_old, cone_limit,
                     s_cap: int, has_res: bool, n_cap: int, d_cap: int,
                     max_trips: int, kernel: str = "sync",
                     delta_exp: int = 0):
    """Incremental counterpart of tpu_solver._plan_sssp. Same resident
    inputs plus: prev_dist [D, N] (the last solve's per-slot plane),
    consolidated dirty tuples (flat index into the raveled shift /
    residual weight planes + each slot's PRE-drain value; pads are
    out-of-range indices), and cone_limit (dynamic int32 scalar —
    affected-cone budget in node-lanes). `kernel` selects the final
    re-relaxation's implementation (ops/relax.py sync rounds or
    bucketed Δ-stepping) — either way the fixpoint is unique, so the
    output stays bit-identical to the cold solve. Returns
    (dist [D, N], trips, cone, fell_back, rounds)."""
    import jax
    import jax.numpy as jnp

    # root-masked weight planes, new and old
    swm_new = shift_w.at[:, root].set(INF_E)
    old_shift, old_res = _old_planes(
        shift_w, res_w, s_dirty_idx, s_dirty_old,
        r_dirty_idx, r_dirty_old, has_res,
    )
    swm_old = old_shift.at[:, root].set(INF_E)
    if has_res:
        rwm_new = jnp.where(res_nbr == root, INF_E, res_w)
        rwm_old = jnp.where(res_nbr == root, INF_E, old_res)
        nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
        rows_c = jnp.clip(res_rows, 0, n_cap - 1)
        rows_s = jnp.where(res_rows >= 0, res_rows, n_cap)
    else:
        rwm_old = res_w

    par = _parent_plane(
        deltas, swm_old, res_rows, res_nbr, rwm_old, prev_dist,
        s_cap, has_res, n_cap, d_cap,
    )

    # --- classify increased dirty edges + seed the affected cone ---
    aff = jnp.zeros((d_cap, n_cap), jnp.int32)

    ok_s = (s_dirty_idx >= 0) & (s_dirty_idx < s_cap * n_cap)
    sic = jnp.clip(s_dirty_idx, 0, s_cap * n_cap - 1)
    k_j = sic // n_cap
    u_j = sic % n_cap
    # compare ROOT-MASKED values: root-column edges are INF to both
    # solves, so their churn is invisible and must not seed anything
    new_m = swm_new.ravel()[sic]
    old_m = jnp.where(u_j == root, INF_E, s_dirty_old)
    inc_s = ok_s & (new_m > old_m)
    # class-k edge u -> v with v = (u + deltas[k]) % n (roll semantics)
    v_j = (u_j + deltas[k_j]) % n_cap
    pv = par[:, jnp.clip(v_j, 0, n_cap - 1)]  # [D, Sd]
    seed_s = (inc_s[None, :] & (pv == u_j[None, :])).astype(jnp.int32)
    v_sc = jnp.where(ok_s, v_j, n_cap)
    aff = aff.at[:, v_sc].max(seed_s, mode="drop")

    if has_res:
        kr = res_nbr.shape[1]
        lim = res_rows.shape[0] * kr
        ok_r = (r_dirty_idx >= 0) & (r_dirty_idx < lim)
        ric = jnp.clip(r_dirty_idx, 0, lim - 1)
        row_j = ric // kr
        c_j = ric % kr
        ru = res_nbr[row_j, c_j]  # source neighbor
        rv = res_rows[row_j]  # destination node
        new_mr = rwm_new[row_j, c_j]
        old_mr = jnp.where(ru == root, INF_E, r_dirty_old)
        inc_r = ok_r & (new_mr > old_mr) & (ru >= 0) & (rv >= 0)
        pv_r = par[:, jnp.clip(rv, 0, n_cap - 1)]
        seed_r = (inc_r[None, :] & (pv_r == ru[None, :])).astype(
            jnp.int32
        )
        rv_sc = jnp.where(ok_r & (rv >= 0), rv, n_cap)
        aff = aff.at[:, rv_sc].max(seed_r, mode="drop")

    # --- propagate aff to tree descendants (one step = one level) ---
    nodes = jnp.arange(n_cap, dtype=jnp.int32)

    def aff_step(acc):
        def cls(k, a):
            dk = deltas[k]
            childpar = jnp.roll(par, -dk, axis=1)  # par of v at pos u
            is_child = childpar == nodes[None, :]
            contrib = jnp.roll(jnp.where(is_child, a, 0), dk, axis=1)
            return jnp.maximum(a, contrib)

        acc = jax.lax.fori_loop(0, s_cap, cls, acc)
        if has_res:
            is_child = (
                par[:, rows_c][:, :, None] == res_nbr[None]
            ) & (res_nbr >= 0)[None]  # [D, R, K]
            acc_n = acc[:, nbr_c]  # [D, R, K]
            contrib = jnp.where(is_child, acc_n, 0).max(axis=2)
            acc = acc.at[:, rows_s].max(contrib, mode="drop")
        return acc

    def aff_body(state):
        acc, _, t = state
        new = acc
        for _ in range(_UNROLL):
            new = aff_step(new)
        return new, jnp.any(new != acc), t + 1

    def aff_cond(state):
        return state[1] & (state[2] < max_trips)

    aff, _, _ = jax.lax.while_loop(
        aff_cond, aff_body, (aff, jnp.bool_(True), jnp.int32(0))
    )

    cone = aff.sum().astype(jnp.int32)
    fell_back = cone > cone_limit

    # --- seed: warm (re-anchored prev) or cold (full-solve dist0) ---
    valid = seeds_w < INF_E
    seed_idx = jnp.clip(seeds_nbr, 0, n_cap - 1)
    pin = jnp.where(valid, 0, INF_E).astype(jnp.int32)
    lanes = jnp.arange(d_cap)
    warm = jnp.where(aff > 0, INF_E, prev_dist)
    warm = warm.at[lanes, seed_idx].min(pin)
    cold = jnp.full((d_cap, n_cap), INF_E, jnp.int32)
    cold = cold.at[lanes, seed_idx].min(pin)
    dist0 = jnp.where(fell_back, cold, warm)

    # --- relax to fixpoint under the NEW weights (the shared kernel
    # bodies in ops/relax.py; fixpoint uniqueness gives bit-identical
    # output whichever implementation runs)
    residual = (rows_c, nbr_c, rwm_new) if has_res else None
    relax = relax_ops.make_relax(
        deltas, s_cap, lambda k: swm_new[k], residual=residual
    )
    if kernel == "bucketed":
        dist, trips, rounds = relax_ops.run_bucketed(
            relax, dist0, deltas, swm_new, lambda k: swm_new[k],
            n_cap, s_cap, delta_exp,
        )
    else:
        dist, trips, rounds = relax_ops.run_sync(relax, dist0, max_trips)
    return dist, trips, cone, fell_back, rounds


def jit_incremental_sssp(s_cap: int, has_res: bool, n_cap: int,
                         d_cap: int, max_trips: int,
                         kernel: str = "sync", delta_exp: int = 0):
    """Standalone jitted wrapper for unit tests; production composes
    incremental_sssp into the solver pipeline tail instead."""
    import jax
    from functools import partial

    return jax.jit(partial(
        incremental_sssp,
        s_cap=s_cap, has_res=has_res, n_cap=n_cap, d_cap=d_cap,
        max_trips=max_trips, kernel=kernel, delta_exp=delta_exp,
    ))
