"""Sharding-contract checker (`naked-collective`, `undeclared-axis`,
`unconstrained-boundary`, `sharded-axis-roll`).

The multichip tier has exactly one legal shape: collectives run inside
a `shard_map` body against a mesh axis the mesh declares, and every
buffer that crosses the shard_map/GSPMD boundary back into the
replicated pipeline tail is pinned with `with_sharding_constraint`.
Each rule here is a bug-shape the repo has already hit or that XLA
miscompiles silently:

  - `naked-collective`: `lax.pmin/pmax/psum/...`/`axis_index` outside
    a function handed to `shard_map`. Under plain jit there is no
    named axis — at best a trace error at first multichip solve, at
    worst (nested vmap with a colliding axis name) a wrong-answer
    reduction.
  - `undeclared-axis`: a collective naming an axis string the module's
    `Mesh(...)`/`P(...)` specs never declare — a typo'd axis traces
    fine single-chip and explodes only on the multichip fabric.
  - `unconstrained-boundary`: in mesh-aware traced code, a
    `jnp.concatenate` result that is never re-pinned with
    `with_sharding_constraint`. This is the exact PR 13 bug-shape:
    GSPMD re-partitions the short concatenate and emits an
    all-gather per consumer inside the sweep loop; the constraint on
    the inputs does not reach back through the concatenate.
  - `sharded-axis-roll`: `jnp.roll` with a traced (non-constant) shift
    in mesh-aware GSPMD code outside shard_map. A traced shift along a
    sharded axis lowers to an unreduced partial-sum — outputs come
    back multiplied by the orthogonal mesh-axis size (the
    `make_mc_sssp` docstring documents the miscompile; shard_map with
    an explicit `lax.pmin` halo is the fix).

Rules are path-insensitive on purpose: a constraint applied on ANY
path (e.g. only `if mesh is not None`) counts, because the buffer only
crosses a shard boundary when a mesh exists.
"""

from __future__ import annotations

import ast

from tools.lint.core import Finding, Project, SourceFile
from tools.lint.purity import (
    _is_traced_file,
    _ModuleGraph,
    _propagate,
    _terminal_name,
)

CODE_NAKED = "naked-collective"
CODE_AXIS = "undeclared-axis"
CODE_BOUNDARY = "unconstrained-boundary"
CODE_ROLL = "sharded-axis-roll"

_COLLECTIVES = {
    "pmin", "pmax", "psum", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pbroadcast", "axis_index",
}
_SPEC_CALLS = {"Mesh", "P", "PartitionSpec"}


def _shard_scope_spans(g: _ModuleGraph) -> list[tuple[int, int]]:
    """Line spans of defs handed to `shard_map` (nested defs and the
    combine lambdas live inside these spans, so a span test covers the
    whole local-function closure). Name -> ALL same-named def nodes:
    the factories each define their own `local_fn`, and the span set
    must cover every one of them, not just the lexically last."""
    by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(g.sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    spans = []
    for node in ast.walk(g.sf.tree):
        if not isinstance(node, ast.Call):
            continue
        tname = _terminal_name(node.func)
        if tname == "partial" and node.args:
            tname = _terminal_name(node.args[0])
            fargs = node.args[1:]
        else:
            fargs = node.args
        if tname != "shard_map":
            continue
        for arg in fargs:
            aname = _terminal_name(arg)
            for fn in by_name.get(aname or "", ()):
                spans.append((fn.lineno, fn.end_lineno or fn.lineno))
    return spans


def _declared_axes(sf: SourceFile) -> set[str]:
    axes: set[str] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_name(node.func) not in _SPEC_CALLS:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                axes.add(sub.value)
    return axes


def _in_spans(line: int, spans: list[tuple[int, int]]) -> bool:
    return any(lo <= line <= hi for lo, hi in spans)


def _is_jnp_call(node: ast.Call, attr: str) -> bool:
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == attr
        and isinstance(f.value, ast.Name)
        and f.value.id in ("jnp", "jax_numpy")
    )


def _axis_strings(node: ast.Call) -> list[ast.Constant]:
    out = []
    for arg in node.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append(arg)
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis") and isinstance(
            kw.value, ast.Constant
        ) and isinstance(kw.value.value, str):
            out.append(kw.value)
    return out


def _mesh_aware(fn: ast.AST, chain: list) -> bool:
    """The def (or an enclosing factory) threads a `mesh` — only then
    do GSPMD boundary rules apply."""
    for scope in [fn, *chain]:
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                if arg.arg == "mesh":
                    return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == "mesh":
            return True
    return False


def _flag_collectives(
    g: _ModuleGraph, spans: list, axes: set[str],
    findings: list[Finding],
) -> None:
    sf = g.sf
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        tname = _terminal_name(node.func)
        if tname not in _COLLECTIVES:
            continue
        scope = sf.scope_at(node.lineno)
        if not _in_spans(node.lineno, spans):
            findings.append(Finding(
                sf.rel, node.lineno, CODE_NAKED, scope, tname,
                f"`{tname}` outside a shard_map body — there is no "
                f"named mesh axis here; under plain jit this traces "
                f"to an error or, with a colliding vmap axis name, a "
                f"wrong-answer reduction",
            ))
        for axis in _axis_strings(node):
            if axis.value not in axes:
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_AXIS, scope,
                    f"{tname}:{axis.value}",
                    f"`{tname}` names axis {axis.value!r}, which no "
                    f"Mesh(...)/P(...) spec in this module declares — "
                    f"a typo'd axis only fails on the multichip "
                    f"fabric",
                ))


def _flag_boundaries(
    g: _ModuleGraph, spans: list, findings: list[Finding]
) -> None:
    sf = g.sf

    def visit(node: ast.AST, chain: list):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if child.name in g.traced and _mesh_aware(child, chain):
                    _check_def(child)
                visit(child, chain + [child])
            else:
                visit(child, chain)

    def _check_def(fn):
        # names this def ever pins with with_sharding_constraint
        constrained: set[str] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and _terminal_name(node.func) == "with_sharding_constraint"
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                constrained.add(node.args[0].id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                has_concat = any(
                    isinstance(sub, ast.Call)
                    and _is_jnp_call(sub, "concatenate")
                    for sub in ast.walk(node.value)
                )
                if has_concat and tgt not in constrained and not _in_spans(
                    node.lineno, spans
                ):
                    findings.append(Finding(
                        sf.rel, node.lineno, CODE_BOUNDARY,
                        sf.scope_at(node.lineno), tgt,
                        f"`{tgt}` concatenates sharded inputs but is "
                        f"never re-pinned with with_sharding_constraint "
                        f"— GSPMD re-partitions the short concatenate "
                        f"and emits an all-gather per consumer; the "
                        f"constraint on the inputs does not reach back "
                        f"through the concatenate",
                    ))
            elif isinstance(node, ast.Call) and _is_jnp_call(node, "roll"):
                if _in_spans(node.lineno, spans):
                    continue
                shift = node.args[1] if len(node.args) > 1 else None
                if shift is None:
                    continue
                static = isinstance(shift, ast.Constant) or (
                    isinstance(shift, ast.UnaryOp)
                    and isinstance(shift.operand, ast.Constant)
                )
                if not static:
                    findings.append(Finding(
                        sf.rel, node.lineno, CODE_ROLL,
                        sf.scope_at(node.lineno), "roll",
                        "jnp.roll with a traced shift in mesh-aware "
                        "GSPMD code outside shard_map — a traced shift "
                        "along a sharded axis lowers to an unreduced "
                        "partial-sum (outputs multiplied by the "
                        "orthogonal mesh-axis size); move it under "
                        "shard_map with an explicit collective halo",
                    ))

    visit(sf.tree, [])


def run(project: Project) -> list[Finding]:
    graphs = {
        sf.rel: _ModuleGraph(sf)
        for sf in project.files
        if _is_traced_file(sf.rel)
    }
    _propagate(graphs)
    findings: list[Finding] = []
    for g in graphs.values():
        spans = _shard_scope_spans(g)
        axes = _declared_axes(g.sf)
        _flag_collectives(g, spans, axes, findings)
        _flag_boundaries(g, spans, findings)
    seen: set[tuple] = set()
    out = []
    for fd in findings:
        k = (fd.path, fd.line, fd.code, fd.detail)
        if k not in seen:
            seen.add(k)
            out.append(fd)
    return out
