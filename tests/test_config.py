"""Config validation tests (semantics of ref openr/config/tests/ConfigTest.cpp)."""

import pytest

from openr_tpu.config import (
    AreaConfig,
    Config,
    ConfigError,
    OpenrConfig,
)


def _base(**kw) -> OpenrConfig:
    return OpenrConfig(node_name="node1", **kw)


def test_valid_default_config():
    cfg = Config(_base())
    assert cfg.node_name == "node1"
    assert cfg.area_ids() == ["0"]


def test_node_name_required():
    with pytest.raises(ConfigError):
        Config(OpenrConfig())
    with pytest.raises(ConfigError):
        Config(OpenrConfig(node_name="bad name"))


def test_duplicate_areas_rejected():
    with pytest.raises(ConfigError):
        Config(_base(areas=[AreaConfig("a"), AreaConfig("a")]))


def test_spark_timer_validation():
    cfg = _base()
    cfg.spark_config.hold_time_s = 1.0
    cfg.spark_config.keepalive_time_s = 2.0
    with pytest.raises(ConfigError):
        Config(cfg)


def test_decision_debounce_validation():
    cfg = _base()
    cfg.decision_config.debounce_min_ms = 500
    cfg.decision_config.debounce_max_ms = 100
    with pytest.raises(ConfigError):
        Config(cfg)


def test_solver_backend_validation():
    cfg = _base()
    cfg.decision_config.solver_backend = "gpu"
    with pytest.raises(ConfigError):
        Config(cfg)


def test_area_matchers():
    cfg = _base(
        areas=[
            AreaConfig(
                area_id="spine",
                neighbor_regexes=["ssw.*"],
                include_interface_regexes=["eth.*"],
                exclude_interface_regexes=["eth99"],
            ),
            AreaConfig(area_id="pod", neighbor_regexes=["rsw.*"],
                       include_interface_regexes=[".*"]),
        ]
    )
    c = Config(cfg)
    assert c.match_neighbor_area("ssw001", "eth0") == "spine"
    assert c.match_neighbor_area("ssw001", "eth99") is None  # excluded in spine
    assert c.match_neighbor_area("rsw001", "po1") == "pod"
    assert c.match_neighbor_area("unknown", "xe0") is None


def test_json_roundtrip():
    c = Config(_base())
    c2 = Config.from_json(c.dump_json())
    assert c2.node_name == "node1"
    assert c2.raw.spark_config.hold_time_s == c.raw.spark_config.hold_time_s


def test_bad_json():
    with pytest.raises(ConfigError):
        Config.from_json("{not json")
