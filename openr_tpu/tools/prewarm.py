"""openr-tpu-prewarm — bake solver executables into the XLA cache.

The reference daemon cold-starts in milliseconds; ours pays XLA
compilation the first time each capacity class's jit programs run
(~80 s at the 131072-node class on TPU). Those executables are pure
functions of the padded capacity-class shapes, and ops/xla_cache.py
persists them — so this tool runs the solver once per requested class
against a synthetic topology at image-bake / maintenance time, and a
restarting daemon then loads everything from disk (measured: 80.7 s ->
10.4 s first-build at 100k; see docs/Operations.md).

Shapes are what matter, not the topology: a grid sized into the target
class produces the same (n_cap, s_cap, r_cap, ...) paddings the
production LSDB of that class hits, because capacities are pow2-rounded
(ops/edgeplan.py). Classes whose real deployment uses KSP2 or LFA
should prewarm those variants too — they are distinct programs.

Usage:
    openr-tpu-prewarm --nodes 1024 --nodes 100000 --lfa --ksp2
    openr-tpu-prewarm --nodes 50000 --cache-dir /var/cache/openr-xla
"""

from __future__ import annotations

import argparse
import sys
import time


def _grid_side(nodes: int) -> int:
    """Smallest side with side*side >= nodes: rounding DOWN could land
    the synthetic graph in a lower pow2 capacity class than the real
    LSDB pads to (e.g. 66000 -> 256^2=65536 caps at 65536, but the
    production graph caps at 131072 — a different executable)."""
    import math

    return max(2, math.isqrt(max(nodes, 1) - 1) + 1)


def prewarm_class(
    nodes: int, enable_lfa: bool, enable_ksp2: bool, verbose: bool = True
) -> float:
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from openr_tpu.models import topologies
    from openr_tpu.types import (
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
        replace,
    )

    side = _grid_side(nodes)
    adj_dbs, prefix_dbs = topologies.grid(side, node_labels=False)
    if enable_ksp2:
        # a KSP2 sliver compiles the masked-batch programs for the class
        prefix_dbs = [
            replace(
                db,
                prefix_entries=tuple(
                    replace(
                        e,
                        forwarding_type=PrefixForwardingType.SR_MPLS,
                        forwarding_algorithm=(
                            PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        ),
                    )
                    for e in db.prefix_entries
                ),
            )
            if i < 64
            else db
            for i, db in enumerate(prefix_dbs)
        ]
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    me = adj_dbs[len(adj_dbs) // 2].this_node_name
    solver = TpuSpfSolver(me, enable_lfa=enable_lfa)
    t0 = time.perf_counter()
    solver.build_route_db(me, states, ps)
    dt = time.perf_counter() - t0
    if verbose:
        print(
            f"[prewarm] class {side}x{side} ({side * side} nodes)"
            f"{' +lfa' if enable_lfa else ''}"
            f"{' +ksp2' if enable_ksp2 else ''}: {dt:.1f}s"
        )
    return dt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="openr-tpu-prewarm", description=__doc__.split("\n")[0]
    )
    p.add_argument(
        "--nodes", type=int, action="append", required=True,
        help="capacity class to prewarm (LSDB node count); repeatable",
    )
    p.add_argument(
        "--cache-dir", default=None,
        help="XLA cache directory (default: ~/.cache/openr_tpu/xla / "
        "$OPENR_TPU_XLA_CACHE)",
    )
    p.add_argument(
        "--lfa", action="store_true",
        help="also compile the LFA backup-nexthop programs",
    )
    p.add_argument(
        "--ksp2", action="store_true",
        help="also compile the KSP2 masked-batch programs",
    )
    args = p.parse_args(argv)

    from openr_tpu.ops.xla_cache import enable_compilation_cache

    cache = enable_compilation_cache(args.cache_dir)
    if cache is None:
        print("[prewarm] compilation cache DISABLED — nothing to bake",
              file=sys.stderr)
        return 1
    print(f"[prewarm] cache: {cache}")
    total = 0.0
    for n in args.nodes:
        total += prewarm_class(n, enable_lfa=False, enable_ksp2=False)
        if args.lfa:
            total += prewarm_class(n, enable_lfa=True, enable_ksp2=False)
        if args.ksp2:
            total += prewarm_class(n, enable_lfa=False, enable_ksp2=True)
    print(f"[prewarm] done in {total:.1f}s — restarts now load from cache")
    return 0


if __name__ == "__main__":
    sys.exit(main())
