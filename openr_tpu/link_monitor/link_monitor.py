"""LinkMonitor actor — adjacency management.

Role of the reference's openr/link-monitor/LinkMonitor.{h,cpp}:

  - converts Spark neighbor events into adjacencies; RTT -> metric
    (getRttMetric = max(rtt_us/100, 1), ref LinkMonitor.cpp:32) or
    hop-count metric
  - manages KvStore peer sessions via peerUpdatesQueue: NEIGHBOR_UP adds
    the peer, NEIGHBOR_DOWN removes it (ref updateKvStorePeerNeighborUp,
    LinkMonitor.cpp:580)
  - advertises "adj:<node>" into KvStore via kvRequestQueue, throttled
    (ref advertiseAdjacencies LinkMonitor.cpp:700, throttle :145-151);
    adjacency announced only after the peer's initial KvStore sync
    completes (kvStoreEventsQueue gating)
  - graceful restart: NEIGHBOR_RESTARTING holds the adjacency up;
    NEIGHBOR_RESTARTED refreshes it
  - drain/overload state: node overload, per-link overload, link metric
    overrides — persisted via PersistentStore (ref LinkMonitorState,
    Types.thrift:686) and applied to the advertised AdjacencyDatabase
  - interface tracking with link-flap exponential backoff
    (ref LinkMonitor.cpp:112-114); up interfaces propagate to Spark via
    interfaceUpdatesQueue; interface addresses redistribute as prefixes
    via prefixUpdatesQueue (PrefixEvent)
"""

from __future__ import annotations

import logging
import re
import time
from dataclasses import dataclass, field
from typing import Optional

from openr_tpu.config import LinkMonitorConfig
from openr_tpu.messaging import RQueue, ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.persistent_store import PersistentStore
from openr_tpu.runtime.throttle import AsyncThrottle, ExponentialBackoff
from openr_tpu.serde import deserialize, serialize
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    AreaPeerEvent,
    InterfaceDatabase,
    InterfaceInfo,
    KeyValueRequest,
    KeyValueRequestType,
    KvStoreSyncEvent,
    NeighborEvent,
    NeighborEventType,
    NeighborInitEvent,
    PeerSpec,
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixType,
    adj_key,
    replace,
)

log = logging.getLogger(__name__)

_STATE_KEY = "link-monitor-config"  # ref kConfigKey LinkMonitor.cpp:25


def get_rtt_metric(rtt_us: int) -> int:
    """ref LinkMonitor.cpp:32."""
    return max(int(rtt_us / 100), 1)


@dataclass
class AdjacencyValue:
    """Tracked adjacency (ref KvStorePeerValue/AdjacencyValue,
    LinkMonitor.h:68-96)."""

    event: NeighborEvent
    metric: int
    kvstore_synced: bool = False  # announce only after peer's initial sync
    restarting: bool = False  # GR hold: keep advertised


@dataclass
class LinkMonitorState:
    """Persisted drain/override state (ref Types.thrift:686)."""

    is_overloaded: bool = False
    overloaded_links: list[str] = field(default_factory=list)
    link_metric_overrides: dict[str, int] = field(default_factory=dict)
    node_metric_increment: int = 0
    # per-adjacency metric overrides, keyed "if_name|neighbor" (ref
    # setAdjacencyMetric, OpenrCtrl.thrift:581)
    adj_metric_overrides: dict[str, int] = field(default_factory=dict)
    # per-interface hard-drain metric increments (ref
    # setInterfaceMetricIncrement, OpenrCtrl.thrift:568)
    link_metric_increments: dict[str, int] = field(default_factory=dict)


@dataclass
class _InterfaceState:
    info: InterfaceInfo
    backoff: ExponentialBackoff
    active: bool = False  # advertised up (past flap backoff)


class LinkMonitor(Actor):
    """ref LinkMonitor.h:107."""

    def __init__(
        self,
        node_name: str,
        config: LinkMonitorConfig,
        neighbor_updates_queue: RQueue,
        kvstore_events_queue: Optional[RQueue],
        peer_updates_queue: ReplicateQueue,
        kv_request_queue: ReplicateQueue,
        interface_updates_queue: Optional[ReplicateQueue] = None,
        prefix_updates_queue: Optional[ReplicateQueue] = None,
        persistent_store: Optional[PersistentStore] = None,
        node_label: int = 0,
        kvstore_port_of=None,
        advertise_throttle_s: float = 0.005,
    ):
        super().__init__(f"link-monitor:{node_name}")
        self.node_name = node_name
        self.cfg = config
        self._neighbor_updates = neighbor_updates_queue
        self._kvstore_events = kvstore_events_queue
        self._peer_q = peer_updates_queue
        self._kv_request_q = kv_request_queue
        self._interface_q = interface_updates_queue
        self._prefix_q = prefix_updates_queue
        self._store = persistent_store
        self.node_label = node_label
        # hook: map a neighbor event to its kvstore (addr, port) — tests and
        # the composition root wire this to the in-proc stores
        self._kvstore_port_of = kvstore_port_of or (
            lambda ev: ("127.0.0.1", ev.kvstore_port or ev.ctrl_port)
        )

        # (area, neighbor node, if_name) -> AdjacencyValue
        self.adjacencies: dict[tuple[str, str, str], AdjacencyValue] = {}
        # every area we ever advertised into — a vacated area still needs
        # an empty-adjacency-db refresh so stale links don't linger
        self._known_areas: set[str] = {"0"}
        self.state = LinkMonitorState()
        self.interfaces: dict[str, _InterfaceState] = {}
        self._advertise_throttle: Optional[AsyncThrottle] = None
        self._advertise_throttle_s = advertise_throttle_s
        self._redistribute_rx = [
            re.compile(r)
            for r in getattr(config, "redistribute_interface_regexes", [])
        ]

    def _redistributes(self, if_name: str) -> bool:
        if not self._redistribute_rx:
            return True
        return any(rx.fullmatch(if_name) for rx in self._redistribute_rx)

    # -- lifecycle ---------------------------------------------------------

    async def on_start(self) -> None:
        self._load_state()
        self._advertise_throttle = AsyncThrottle(
            self._advertise_throttle_s, self.advertise_adjacencies
        )
        self.add_task(self._neighbor_loop(), name=f"{self.name}.neighbors")
        if self._kvstore_events is not None:
            self.add_task(
                self._kvstore_events_loop(), name=f"{self.name}.kvstore-events"
            )

    def _load_state(self) -> None:
        if self._store is None:
            return
        raw = self._store.load(_STATE_KEY)
        if raw is not None:
            try:
                self.state = deserialize(raw, LinkMonitorState)
            except Exception:
                counters.increment("link_monitor.bad_persisted_state")
                log.exception("%s: bad persisted state; using defaults", self.name)

    def _save_state(self) -> None:
        if self._store is not None:
            self._store.store(_STATE_KEY, serialize(self.state))

    # -- neighbor events (ref processNeighborEvents) -----------------------

    async def _neighbor_loop(self) -> None:
        while True:
            item = await self._neighbor_updates.get()
            if isinstance(item, NeighborInitEvent):
                for ev in item.events:
                    self._handle_neighbor_event(ev)
                continue
            self._handle_neighbor_event(item)

    def _handle_neighbor_event(self, ev: NeighborEvent) -> None:
        key = (ev.area, ev.node_name, ev.if_name)
        if ev.event_type == NeighborEventType.NEIGHBOR_UP:
            metric = (
                get_rtt_metric(ev.rtt_us)
                if self.cfg.use_rtt_metric and ev.rtt_us > 0
                else 1
            )
            new_adj = AdjacencyValue(event=ev, metric=metric)
            # a parallel adjacency to an already-synced peer inherits the
            # sync state: KvStore dedups identical peer specs and will not
            # emit another KvStoreSyncEvent
            if any(
                a == ev.area and n == ev.node_name and adj.kvstore_synced
                for (a, n, _), adj in self.adjacencies.items()
            ):
                new_adj.kvstore_synced = True
            self.adjacencies[key] = new_adj
            self._known_areas.add(ev.area)
            addr, port = self._kvstore_port_of(ev)
            self._peer_q.push(
                {
                    ev.area: AreaPeerEvent(
                        peers_to_add={
                            ev.node_name: PeerSpec(
                                peer_addr=addr, ctrl_port=port
                            )
                        }
                    )
                }
            )
            counters.increment("link_monitor.neighbor_up")
            if self._kvstore_events is None:
                # sync gating disabled (no events queue): announce now
                new_adj.kvstore_synced = True
            if new_adj.kvstore_synced:
                self._advertise_throttled()
        elif ev.event_type == NeighborEventType.NEIGHBOR_RESTARTED:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.restarting = False
                adj.event = ev
                if self.cfg.use_rtt_metric and ev.rtt_us > 0:
                    adj.metric = get_rtt_metric(ev.rtt_us)
            else:
                self._handle_neighbor_event(
                    replace(ev, event_type=NeighborEventType.NEIGHBOR_UP)
                )
                return
            self._advertise_throttled()
        elif ev.event_type == NeighborEventType.NEIGHBOR_RESTARTING:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.restarting = True  # GR: hold adjacency up
            counters.increment("link_monitor.neighbor_restarting")
        elif ev.event_type == NeighborEventType.NEIGHBOR_DOWN:
            if self.adjacencies.pop(key, None) is not None:
                # only drop the KvStore peer session when NO adjacency to
                # this node remains in the area (parallel links)
                if not any(
                    a == ev.area and n == ev.node_name
                    for a, n, _ in self.adjacencies
                ):
                    self._peer_q.push(
                        {ev.area: AreaPeerEvent(peers_to_del=(ev.node_name,))}
                    )
                self._advertise_throttled()
            counters.increment("link_monitor.neighbor_down")
        elif ev.event_type == NeighborEventType.NEIGHBOR_RTT_CHANGE:
            adj = self.adjacencies.get(key)
            if adj is not None and self.cfg.use_rtt_metric:
                new_metric = get_rtt_metric(ev.rtt_us)
                if new_metric != adj.metric:
                    adj.metric = new_metric
                    self._advertise_throttled()

    async def _kvstore_events_loop(self) -> None:
        """Adjacency with a peer becomes announceable once the initial
        full sync with that peer completes (ref kvStoreEventsQueue path)."""
        while True:
            ev: KvStoreSyncEvent = await self._kvstore_events.get()
            changed = False
            for (area, node, _), adj in self.adjacencies.items():
                if node == ev.node_name and area == ev.area:
                    if not adj.kvstore_synced:
                        adj.kvstore_synced = True
                        changed = True
            if changed:
                self._advertise_throttled()

    # -- adjacency advertisement (ref buildAdjacencyDatabase :700) ---------

    def _advertise_throttled(self) -> None:
        if self._advertise_throttle is not None:
            self._advertise_throttle()

    def advertise_adjacencies(self) -> None:
        for area in self._known_areas | {a for a, _, _ in self.adjacencies}:
            db = self.build_adjacency_database(area)
            self._kv_request_q.push(
                KeyValueRequest(
                    request_type=KeyValueRequestType.PERSIST,
                    area=area,
                    key=adj_key(self.node_name),
                    value=serialize(db),
                )
            )
        counters.increment("link_monitor.advertise_adjacencies")

    def build_adjacency_database(self, area: str) -> AdjacencyDatabase:
        adjs = []
        for (a, node, if_name), adj in sorted(self.adjacencies.items()):
            if a != area or not adj.kvstore_synced:
                continue
            ev = adj.event
            # precedence: per-adjacency override > per-link override >
            # measured; per-link increments apply on top (ref
            # LinkMonitor.cpp getLinkMetric semantics)
            metric = self.state.adj_metric_overrides.get(
                f"{if_name}|{node}",
                self.state.link_metric_overrides.get(if_name, adj.metric),
            )
            # soft-drain: node + per-interface increments add on top of
            # the chosen metric (ref LinkMonitor.cpp:1013 — the
            # increment is applied at ADVERTISEMENT, Decision never
            # sees the raw field)
            metric = max(
                1,
                metric
                + self.state.link_metric_increments.get(if_name, 0)
                + self.state.node_metric_increment,
            )
            adjs.append(
                Adjacency(
                    other_node_name=node,
                    if_name=if_name,
                    other_if_name=ev.remote_if_name,
                    metric=metric,
                    is_overloaded=if_name in self.state.overloaded_links,
                    rtt_us=ev.rtt_us,
                    timestamp_s=int(time.time()),
                    adj_only_used_by_other_node=ev.adj_only_used_by_other_node,
                    next_hop_v6=ev.neighbor_addr_v6,
                    next_hop_v4=ev.neighbor_addr_v4,
                )
            )
        return AdjacencyDatabase(
            this_node_name=self.node_name,
            adjacencies=tuple(adjs),
            is_overloaded=self.state.is_overloaded,
            node_label=self.node_label,
            area=area,
            node_metric_increment=self.state.node_metric_increment,
        )

    # -- interface tracking with flap backoff ------------------------------

    def update_interface(self, info: InterfaceInfo) -> None:
        """System interface snapshot (netlink role). Link flaps back off
        exponentially before re-advertising (ref LinkMonitor.cpp:112-114)."""
        st = self.interfaces.get(info.if_name)
        if st is None:
            st = self.interfaces[info.if_name] = _InterfaceState(
                info=info,
                backoff=ExponentialBackoff(
                    self.cfg.linkflap_initial_backoff_ms / 1e3,
                    self.cfg.linkflap_max_backoff_ms / 1e3,
                ),
            )
        was_active = st.active
        if info.is_up and not st.info.is_up:
            # coming up: penalize flapping
            st.backoff.report_error()
        st.info = info
        if info.is_up:
            delay = st.backoff.time_until_retry_s()
            if delay <= 0:
                st.active = True
            else:
                st.active = False
                self.schedule(delay + 0.001, self._interface_retry)
        else:
            st.active = False
        if st.active != was_active:
            self._publish_interfaces()

    def _interface_retry(self) -> None:
        changed = False
        for st in self.interfaces.values():
            if (
                st.info.is_up
                and not st.active
                and st.backoff.time_until_retry_s() <= 0
            ):
                st.active = True
                changed = True
        if changed:
            self._publish_interfaces()

    def _publish_interfaces(self) -> None:
        if self._interface_q is not None:
            self._interface_q.push(
                InterfaceDatabase(
                    interfaces=tuple(
                        st.info
                        for st in self.interfaces.values()
                        if st.active
                    )
                )
            )
        if self._prefix_q is not None:
            # redistribute iface addresses as LOOPBACK prefixes; regexes
            # (ref redistribute_interface_regexes) limit which interfaces
            # qualify — empty means all tracked ones
            entries = [
                PrefixEntry(prefix=net, type=PrefixType.LOOPBACK)
                for st in self.interfaces.values()
                if st.active and self._redistributes(st.info.if_name)
                for net in st.info.networks
            ]
            self._prefix_q.push(
                PrefixEvent(
                    event_type=PrefixEventType.SYNC_PREFIXES_BY_TYPE,
                    type=PrefixType.LOOPBACK,
                    prefixes=entries,
                )
            )

    # -- drain / overload APIs (ref semifuture_setNodeOverload etc.) -------

    async def set_node_overload(self, overloaded: bool) -> None:
        if self.state.is_overloaded != overloaded:
            self.state.is_overloaded = overloaded
            self._save_state()
            self._advertise_throttled()

    async def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        links = set(self.state.overloaded_links)
        before = set(links)
        (links.add if overloaded else links.discard)(if_name)
        if links != before:
            self.state.overloaded_links = sorted(links)
            self._save_state()
            self._advertise_throttled()

    async def set_link_metric(
        self, if_name: str, metric: Optional[int]
    ) -> None:
        if metric is None:
            self.state.link_metric_overrides.pop(if_name, None)
        else:
            self.state.link_metric_overrides[if_name] = metric
        self._save_state()
        self._advertise_throttled()

    async def set_adjacency_metric(
        self, if_name: str, neighbor: str, metric: Optional[int] = None
    ) -> None:
        """Per-adjacency override (ref setAdjacencyMetric/
        unsetAdjacencyMetric, OpenrCtrl.thrift:581-586); None unsets."""
        key = f"{if_name}|{neighbor}"
        if metric is None:
            self.state.adj_metric_overrides.pop(key, None)
        else:
            self.state.adj_metric_overrides[key] = metric
        self._save_state()
        self._advertise_throttled()

    async def set_node_metric_increment(self, increment: int) -> None:
        """Soft-drain penalty advertised in the adjacency DB (ref
        setNodeInterfaceMetricIncrement, OpenrCtrl.thrift:557); 0
        unsets. Negative increments are rejected — they would advertise
        sub-zero path costs fleet-wide (the reference API refuses them
        too)."""
        if increment < 0:
            raise ValueError("metric increment must be >= 0")
        if self.state.node_metric_increment != increment:
            self.state.node_metric_increment = increment
            self._save_state()
            self._advertise_throttled()

    async def set_link_metric_increment(
        self, if_name: str, increment: int
    ) -> None:
        """Per-interface metric increment (ref
        setInterfaceMetricIncrement, OpenrCtrl.thrift:568); 0 unsets;
        negative rejected."""
        if increment < 0:
            raise ValueError("metric increment must be >= 0")
        if increment:
            self.state.link_metric_increments[if_name] = increment
        else:
            self.state.link_metric_increments.pop(if_name, None)
        self._save_state()
        self._advertise_throttled()

    async def get_adjacencies(self, area: Optional[str] = None) -> list:
        """Advertised adjacency DBs (ref getLinkMonitorAdjacencies)."""
        areas = (
            [area]
            if area is not None
            else sorted(
                self._known_areas | {a for a, _, _ in self.adjacencies}
            )
        )
        return [self.build_adjacency_database(a) for a in areas]

    async def get_interfaces(self) -> dict[str, InterfaceInfo]:
        return {name: st.info for name, st in self.interfaces.items()}

    async def get_links(self) -> dict:
        return {
            f"{area}/{node}/{if_name}": {
                "metric": adj.metric,
                "rtt_us": adj.event.rtt_us,
                "synced": adj.kvstore_synced,
                "restarting": adj.restarting,
            }
            for (area, node, if_name), adj in self.adjacencies.items()
        }
