"""What-if engine: batched N-k failure sweeps, drain previews and
differentiable link-weight TE on the solver's resident graph.

The engine is a READ-ONLY consumer of `TpuSpfSolver`'s device state: it
snapshots the live per-area plan arrays (the same `_sync_area` path the
solver's own dispatch uses, so a sweep never re-uploads a graph the
device already holds), expresses each scenario as a handful of flat
slot overrides (identical addressing to `drain_dirty`: shift slot
`k*n_cap+u`, residual slot `row*kr_cap+col`), and ships the whole batch
through ONE vmapped dispatch (ops/sweep.py). Verdicts reduce on device;
the host pulls O(scenarios) ints.

Isolation contract: everything here may fail — an armed `solver.whatif`
fault, an OOM on an oversized batch, a stale snapshot — and none of it
may ever touch the live solver's health. The Decision actor wraps every
entry point, converts failures into `whatif.errors` + an error payload,
and NEVER routes them into the TPU->CPU failover machinery.

Scenario kinds:
  fail        one or more links down (both directed slots -> INF)
  drain_node  every out-edge of a node -> INF (the node still receives:
              its in-edges stand, matching overload/transit-drain
              semantics; as a vantage it would see everything
              unreachable, so drain previews look AT it, not FROM it)
  drain_link  alias of fail for a single link (an operator draining a
              link takes it out of SPF either way)
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Optional

import numpy as np

from openr_tpu.ops.edgeplan import (
    INF32E,
    MAX_METRIC,
    _ensure_edge_loc,
    _next_pow2,
    edge_loc_of,
)
from openr_tpu.ops.sweep import _UNROLL, sweep_batch, sweep_max_trips, te_step
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.faults import maybe_fail
from openr_tpu.runtime.tracing import tracer

log = logging.getLogger(__name__)

INF_E = int(INF32E)

# sweep batch sizing rides the SAME knob as the fused live dispatch
# (decision_config.fuse_n_cap): there it bounds a lane's node capacity,
# here it bounds the sweep's resident distance plane to
# fuse_n_cap * _LANE_ROWS int32 cells per dispatch (~32 MB at the 4096
# default). A grid-1k N-1 sweep (~2k scenarios) fits in one dispatch;
# a 128k-node area batches ~64 scenarios per dispatch.
_LANE_ROWS = 2048

# traces close with this status so what-if round trips never pollute the
# convergence_ms percentile fabric (tracer._finish stamps "ok" only)
_TRACE_STATUS = "whatif"


def _link_name(link) -> str:
    return f"{link.n1}|{link.n2}"


class Scenario:
    """One hypothetical topology: a named set of directed-edge weight
    overrides derived from failed links / a drained node."""

    __slots__ = ("name", "kind", "links", "node")

    def __init__(self, name: str, kind: str, links=(), node: str = ""):
        self.name = name
        self.kind = kind
        self.links = tuple(links)
        self.node = node


class _Chunk:
    """One batched device dispatch: lane 0 is the identity overlay (the
    baseline), lanes 1..n carry scenarios. dispatch() must run where the
    snapshot handles are valid; collect() only blocks on device output
    (executor-safe)."""

    def __init__(self, job: "SweepJob", scenarios: list[Scenario],
                 overlays: list[tuple[list, list]]):
        self.job = job
        self.scenarios = scenarios
        self._overlays = overlays
        self._out = None

    def dispatch(self) -> None:
        maybe_fail("solver.whatif")
        job = self.job
        plan = job.plan
        n_cap, s_cap = plan.n_cap, plan.s_cap
        r_cap = plan.res_rows.shape[0]
        kr_cap = plan.res_nbr.shape[1]
        has_res = plan.k_res > 0
        # fixed-size overlays: pad lanes + slots so batch shapes land in
        # a small set of pow2 buckets (the whatif bounded-cache keys)
        b_pad = _next_pow2(1 + len(self.scenarios), 2)
        es = _next_pow2(
            max([4] + [len(s) for s, _ in self._overlays]), 4
        )
        er = _next_pow2(
            max([4] + [len(r) for _, r in self._overlays]), 4
        )
        # pad slots point one past the raveled plane and drop on scatter
        s_oob = s_cap * n_cap
        r_oob = r_cap * kr_cap
        sh_idx = np.full((b_pad, es), s_oob, np.int32)
        sh_val = np.zeros((b_pad, es), np.int32)
        rs_idx = np.full((b_pad, er), r_oob, np.int32)
        rs_val = np.zeros((b_pad, er), np.int32)
        for i, (s_pairs, r_pairs) in enumerate(self._overlays):
            for j, (flat, val) in enumerate(s_pairs):
                sh_idx[i + 1, j] = flat
                sh_val[i + 1, j] = val
            for j, (flat, val) in enumerate(r_pairs):
                rs_idx[i + 1, j] = flat
                rs_val[i + 1, j] = val
        # kernel eligibility mirrors the live solver's _prep_vantage
        # ladder: bucketed iff the knob asks for it AND the plan derived
        # a usable Δ. The TE baseline forces sync — its measured trips
        # bound the float surrogate's scan length, and only synchronous
        # rounds measure the diameter.
        spf_kernel = getattr(job.engine.solver, "spf_kernel", "sync")
        delta_exp = int(getattr(plan, "delta_exp", 0))
        if (spf_kernel != "bucketed" or delta_exp <= 0
                or getattr(job, "force_sync", False)):
            spf_kernel, delta_exp = "sync", 0
        name, run = sweep_batch(
            b_pad, len(job.roots), es, er, n_cap, s_cap, r_cap, kr_cap,
            has_res, sweep_max_trips(n_cap), job.return_dist,
            spf_kernel, delta_exp,
        )
        with tracer.span(
            job.ctx, "whatif.dispatch", kernel=name,
            scenarios=len(self.scenarios),
        ):
            ad = job.ad
            self._out = run(
                ad.d_deltas, ad.d_shift_w, ad.d_res_rows, ad.d_res_nbr,
                ad.d_res_w, job.roots_dev, sh_idx, sh_val, rs_idx, rs_val,
            )
        counters.increment("whatif.device.batched_dispatches")
        counters.increment(
            "whatif.device.batched_scenarios", len(self.scenarios)
        )

    def collect(self) -> list[dict]:
        unreachable, stretch, changed, trips = (
            np.asarray(x) for x in self._out[:4]
        )
        if self.job.return_dist:
            self.job.dist_planes.append(np.asarray(self._out[4]))
        self.job.trips = max(self.job.trips, int(trips))
        self.job.rounds = max(
            self.job.rounds, int(np.asarray(self._out[-1]))
        )
        self._out = None
        rows = []
        for i, scen in enumerate(self.scenarios, start=1):
            u = int(unreachable[i])
            rows.append({
                "scenario": scen.name,
                "kind": scen.kind,
                "unreachable_pairs": u,
                "max_stretch": int(stretch[i]),
                "changed_nodes": int(changed[i]),
                "partitioned": u > 0,
            })
        return rows


class SweepJob:
    """A planned sweep: scenario enumeration + snapshot done, chunks
    ready to dispatch. `run()` drives everything inline; the Decision
    actor instead walks `chunks` itself so it can yield to live
    convergence work between dispatches."""

    def __init__(self, engine, area, ad, roots, root_names,
                 return_dist, ctx, meta):
        self.engine = engine
        self.area = area
        self.ad = ad
        self.plan = ad.plan
        self.roots = roots
        self.root_names = root_names
        self.roots_dev = None
        self.return_dist = return_dist
        self.ctx = ctx
        self.meta = meta
        self.chunks: list[_Chunk] = []
        self.dist_planes: list[np.ndarray] = []
        self.trips = 0
        self.rounds = 0
        self.force_sync = False
        self._t0 = time.perf_counter()

    def result(self, rows: list[dict]) -> dict:
        rows.sort(
            key=lambda r: (
                r["partitioned"], r["unreachable_pairs"], r["max_stretch"]
            ),
            reverse=True,
        )
        ms = (time.perf_counter() - self._t0) * 1e3
        counters.add_stat_value("whatif.sweep_ms", ms)
        counters.increment("whatif.scenarios", len(rows))
        out = {
            **self.meta,
            "area": self.area,
            "roots": self.root_names,
            "scenarios": len(rows),
            "dispatches": len(self.chunks),
            "partitioned": sum(r["partitioned"] for r in rows),
            "trips": self.trips,
            "sweep_ms": round(ms, 2),
            "rows": rows,
        }
        tracer.end_trace(
            self.ctx, status=_TRACE_STATUS,
            scenarios=len(rows), dispatches=len(self.chunks),
        )
        self.ctx = None
        return out

    def fail(self) -> None:
        tracer.end_trace(self.ctx, status="error")
        self.ctx = None

    def run(self) -> dict:
        try:
            rows = []
            for ch in self.chunks:
                ch.dispatch()
                rows.extend(ch.collect())
            return self.result(rows)
        except Exception:
            self.fail()
            raise


class WhatIfEngine:
    """Scenario planner over a TpuSpfSolver's resident per-area graph
    mirrors. Stateless between calls apart from the solver it reads."""

    def __init__(self, solver, my_node_name: Optional[str] = None):
        self.solver = solver
        self.my_node_name = my_node_name or solver.my_node_name

    # -- snapshot ----------------------------------------------------------

    def _pick_area(self, area, area_link_states) -> str:
        if area:
            if area not in area_link_states:
                raise ValueError(f"unknown area {area!r}")
            return area
        cands = sorted(
            a for a, ls in area_link_states.items()
            if ls.has_node(self.my_node_name)
        ) or sorted(area_link_states)
        if not cands:
            raise ValueError("no areas in the LSDB")
        return cands[0]

    def _snapshot(self, area, area_link_states, prefix_state):
        """Sync the area through the solver's own path (delta scatter
        when the mirror is current — no graph re-upload) and hand back
        its _AreaDev. Must run on the thread that owns the LSDB."""
        solver = self.solver
        fast_by_area, *_ = solver._partition_prefixes(
            prefix_state, area_link_states
        )
        ad = solver._sync_area(
            area, area_link_states[area], prefix_state,
            fast_by_area.get(area, []),
        )
        _ensure_edge_loc(ad.plan)
        return ad

    def _resolve_roots(self, plan, roots) -> tuple[np.ndarray, list[str]]:
        names = list(roots) if roots else [self.my_node_name]
        idx = []
        for n in names:
            i = plan.node_index.get(n)
            if i is None:
                raise ValueError(f"vantage {n!r} not in this area")
            idx.append(i)
        return np.asarray(idx, np.int32), names

    def _batch_cap(self, n_cap: int, r: int) -> int:
        fuse = int(getattr(self.solver, "fuse_n_cap", 4096))
        return max(2, (fuse * _LANE_ROWS) // max(1, n_cap * r))

    # -- overlay construction ---------------------------------------------

    def _fail_directed(self, plan, pairs, link, src) -> bool:
        loc = edge_loc_of(plan, link, src)
        if loc is None:
            return False
        kind, a, b = loc
        if kind == "s":
            pairs[0].append((a * plan.n_cap + b, INF_E))
        else:
            pairs[1].append((a * plan.res_nbr.shape[1] + b, INF_E))
        return True

    def _overlay(self, plan, link_state, scen: Scenario):
        """-> ([(shift_flat, val)], [(res_flat, val)]) or None when a
        touched edge has no slot (mid-rebuild race) — the scenario is
        skipped and counted, never guessed at."""
        pairs: tuple[list, list] = ([], [])
        ok = True
        if scen.kind in ("fail", "drain_link"):
            for link in scen.links:
                ok &= self._fail_directed(plan, pairs, link, link.n1)
                ok &= self._fail_directed(plan, pairs, link, link.n2)
        elif scen.kind == "drain_node":
            for link in link_state.ordered_links_from_node(scen.node):
                if link.is_up():
                    ok &= self._fail_directed(plan, pairs, link, scen.node)
        else:
            raise ValueError(f"unknown scenario kind {scen.kind!r}")
        return pairs if ok else None

    # -- sweeps ------------------------------------------------------------

    def plan_sweep(self, area_link_states, prefix_state, order: int = 1,
                   area: Optional[str] = None, roots=None,
                   max_scenarios: int = 0,
                   return_dist: bool = False) -> SweepJob:
        """Enumerate N-`order` link-failure scenarios and stage them into
        batched dispatches. order=1 sweeps every up link; order=2 sweeps
        every unordered pair (quadratic — cap it with max_scenarios)."""
        maybe_fail("solver.whatif")
        if order not in (1, 2):
            raise ValueError("sweep order must be 1 or 2")
        area = self._pick_area(area, area_link_states)
        link_state = area_link_states[area]
        ctx = tracer.start_trace(
            "whatif.sweep", node=self.my_node_name, area=area, order=order,
        )
        try:
            with tracer.span(ctx, "whatif.snapshot"):
                ad = self._snapshot(area, area_link_states, prefix_state)
            plan = ad.plan
            root_idx, root_names = self._resolve_roots(plan, roots)

            links = [
                ln for ln in link_state.ordered_all_links() if ln.is_up()
            ]
            scens = [
                Scenario(_link_name(ln), "fail", (ln,)) for ln in links
            ]
            if order == 2:
                scens += [
                    Scenario(
                        f"{_link_name(a_)}+{_link_name(b_)}", "fail",
                        (a_, b_),
                    )
                    for a_, b_ in itertools.combinations(links, 2)
                ]
            truncated = 0
            if max_scenarios and len(scens) > max_scenarios:
                truncated = len(scens) - max_scenarios
                scens = scens[:max_scenarios]
                counters.increment("whatif.truncated_scenarios", truncated)

            job = SweepJob(
                self, area, ad, root_idx, root_names, return_dist, ctx,
                meta={"order": order, "truncated": truncated},
            )
            import jax

            job.roots_dev = jax.device_put(root_idx)
            kept: list[Scenario] = []
            overlays: list[tuple[list, list]] = []
            skipped = 0
            for scen in scens:
                ov = self._overlay(plan, link_state, scen)
                if ov is None:
                    skipped += 1
                    continue
                kept.append(scen)
                overlays.append(ov)
            if skipped:
                counters.increment("whatif.skipped_scenarios", skipped)
                job.meta["skipped"] = skipped
            cap = self._batch_cap(plan.n_cap, len(root_idx))
            for i in range(0, max(1, len(kept)), cap):
                job.chunks.append(
                    _Chunk(job, kept[i:i + cap], overlays[i:i + cap])
                )
            counters.increment("whatif.sweeps")
            return job
        except Exception:
            tracer.end_trace(ctx, status="error")
            raise

    def sweep(self, area_link_states, prefix_state, **kw) -> dict:
        return self.plan_sweep(area_link_states, prefix_state, **kw).run()

    # -- drain preview -----------------------------------------------------

    def drain(self, area_link_states, prefix_state,
              node: Optional[str] = None, link: Optional[str] = None,
              area: Optional[str] = None, roots=None,
              top: int = 10) -> dict:
        """Impact preview for draining a node or a link ("n1|n2"), seen
        from the vantage roots: unreachable/stretch verdicts plus the
        top most-affected destinations with before/after metrics."""
        maybe_fail("solver.whatif")
        if bool(node) == bool(link):
            raise ValueError("specify exactly one of node= or link=")
        t0 = time.perf_counter()
        area = self._pick_area(area, area_link_states)
        link_state = area_link_states[area]
        ctx = tracer.start_trace(
            "whatif.drain", node=self.my_node_name, area=area,
            target=node or link,
        )
        try:
            with tracer.span(ctx, "whatif.snapshot"):
                ad = self._snapshot(area, area_link_states, prefix_state)
            plan = ad.plan
            root_idx, root_names = self._resolve_roots(plan, roots)
            if node:
                if not link_state.has_node(node):
                    raise ValueError(f"unknown node {node!r}")
                scen = Scenario(f"drain:{node}", "drain_node", node=node)
            else:
                want = set(link.split("|", 1))
                match = next(
                    (
                        ln for ln in link_state.ordered_all_links()
                        if {ln.n1, ln.n2} == want
                    ),
                    None,
                )
                if match is None:
                    raise ValueError(f"no link {link!r} (want 'n1|n2')")
                scen = Scenario(
                    f"drain:{_link_name(match)}", "drain_link", (match,)
                )
            ov = self._overlay(plan, link_state, scen)
            if ov is None:
                raise RuntimeError(
                    "edge slots not mapped yet (plan mid-rebuild); retry"
                )
            job = SweepJob(
                self, area, ad, root_idx, root_names, True, ctx, meta={},
            )
            import jax

            job.roots_dev = jax.device_put(root_idx)
            chunk = _Chunk(job, [scen], [ov])
            job.chunks.append(chunk)
            chunk.dispatch()
            rows = chunk.collect()
            dist = job.dist_planes[0]  # [B, R, N]
            base, after = dist[0], dist[1]
            impact = []
            n = plan.n_nodes
            for ri, rname in enumerate(root_names):
                b_, a_ = base[ri, :n], after[ri, :n]
                delta = np.where(
                    (b_ < INF_E) & (a_ < INF_E), a_ - b_, 0
                )
                lost = (b_ < INF_E) & (a_ >= INF_E)
                order_ = np.argsort(-(delta + lost * INF_E))[:top]
                for i in order_:
                    if not lost[i] and delta[i] <= 0:
                        break
                    impact.append({
                        "root": rname,
                        "node": plan.node_names[i],
                        "before": int(b_[i]),
                        "after": None if lost[i] else int(a_[i]),
                        "stretch": None if lost[i] else int(delta[i]),
                        "unreachable": bool(lost[i]),
                    })
            ms = (time.perf_counter() - t0) * 1e3
            counters.increment("whatif.drains")
            counters.add_stat_value("whatif.drain_ms", ms)
            out = {
                "area": area,
                "target": node or link,
                "roots": root_names,
                "drain_ms": round(ms, 2),
                **rows[0],
                "impacted": impact,
            }
            tracer.end_trace(ctx, status=_TRACE_STATUS)
            return out
        except Exception:
            tracer.end_trace(ctx, status="error")
            raise

    # -- differentiable TE -------------------------------------------------

    def plan_optimize(self, area_link_states, prefix_state, demands,
                      area: Optional[str] = None, iters: int = 40,
                      lr: float = 2.0, tau: float = 1.0,
                      tau_util: Optional[float] = None) -> "OptimizeJob":
        """Stage a gradient-descent link-weight optimization against an
        operator demand matrix ([{src, dst, volume}]). Planning reads
        the LSDB; the returned job's run() touches only device/host
        arrays, so the actor may push it to an executor."""
        maybe_fail("solver.whatif")
        if not demands:
            raise ValueError("empty demand matrix")
        area = self._pick_area(area, area_link_states)
        link_state = area_link_states[area]
        ctx = tracer.start_trace(
            "whatif.optimize", node=self.my_node_name, area=area,
            demands=len(demands), iters=iters,
        )
        try:
            with tracer.span(ctx, "whatif.snapshot"):
                ad = self._snapshot(area, area_link_states, prefix_state)
            plan = ad.plan
            n_cap = plan.n_cap
            kr_cap = plan.res_nbr.shape[1]

            links = [ln for ln in plan._links_sorted if ln.is_up()]
            if not links:
                raise ValueError("no up links to optimize")
            theta0, sh_idx, sh_link, rs_idx, rs_link = [], [], [], [], []
            link_names = []
            for li, ln in enumerate(links):
                link_names.append(_link_name(ln))
                theta0.append(
                    float(min(ln.metric_from_node(ln.n1), MAX_METRIC))
                )
                for src in (ln.n1, ln.n2):
                    loc = edge_loc_of(plan, ln, src)
                    if loc is None:
                        continue
                    kind, a, b = loc
                    # skip slots the mirror holds at INF (drained src):
                    # the optimizer must not resurrect them
                    if kind == "s":
                        if plan.shift_w[a, b] >= INF_E:
                            continue
                        sh_idx.append(a * n_cap + b)
                        sh_link.append(li)
                    else:
                        if plan.res_w[a, b] >= INF_E:
                            continue
                        rs_idx.append(a * kr_cap + b)
                        rs_link.append(li)

            dem, bad = [], []
            for d in demands:
                si = plan.node_index.get(d["src"])
                di = plan.node_index.get(d["dst"])
                if si is None or di is None or si == di:
                    bad.append(d)
                    continue
                dem.append((si, di, float(d.get("volume", 1.0))))
            if not dem:
                raise ValueError("no resolvable demands in this area")

            # baseline int sweep (identity overlay) for the measured trip
            # bound — the float surrogate's scan length rides the real
            # diameter, per Bounded Dijkstra, instead of a blind n_cap
            base_job = SweepJob(
                self, area, ad,
                np.asarray(sorted({s for s, _, _ in dem}), np.int32),
                [], True, ctx, meta={},
            )
            import jax

            base_job.roots_dev = jax.device_put(base_job.roots)
            base_job.force_sync = True
            base_chunk = _Chunk(base_job, [], [])
            base_job.chunks.append(base_chunk)
            base_chunk.dispatch()
            base_chunk.collect()
            base = base_job.dist_planes[0][0]  # [S, N]
            src_row = {
                int(s): i for i, s in enumerate(base_job.roots)
            }
            reachable = []
            for si, di, vol in dem:
                if base[src_row[si], di] >= INF_E:
                    bad.append({"src_idx": si, "dst_idx": di})
                    continue
                reachable.append((si, di, vol))
            if not reachable:
                raise ValueError("no demand pair is reachable")
            trips = min(256, max(8, base_job.trips * _UNROLL + 2))

            return OptimizeJob(
                self, area, ad, ctx, link_names,
                np.asarray(theta0, np.float32),
                np.asarray(sh_idx, np.int32), np.asarray(sh_link, np.int32),
                np.asarray(rs_idx, np.int32), np.asarray(rs_link, np.int32),
                reachable, src_row, bad, trips,
                iters=int(iters), lr=float(lr), tau=float(tau),
                tau_util=float(tau_util or tau),
            )
        except Exception:
            tracer.end_trace(ctx, status="error")
            raise

    def optimize(self, area_link_states, prefix_state, demands,
                 **kw) -> dict:
        return self.plan_optimize(
            area_link_states, prefix_state, demands, **kw
        ).run()


class OptimizeJob:
    """Gradient-descent loop over the softmin TE surrogate. No LSDB
    access after planning: run() is executor-safe."""

    def __init__(self, engine, area, ad, ctx, link_names, theta0,
                 sh_idx, sh_link, rs_idx, rs_link, demands, src_row,
                 rejected, trips, iters, lr, tau, tau_util):
        self.engine = engine
        self.area = area
        self.ad = ad
        self.ctx = ctx
        self.link_names = link_names
        self.theta0 = theta0
        self.sh = (sh_idx, sh_link)
        self.rs = (rs_idx, rs_link)
        self.demands = demands
        self.src_row = src_row
        self.rejected = rejected
        self.trips = trips
        self.iters = iters
        self.lr = lr
        self.tau = tau
        self.tau_util = tau_util

    def run(self) -> dict:
        t0 = time.perf_counter()
        try:
            plan = self.ad.plan
            n_cap, s_cap = plan.n_cap, plan.s_cap
            r_cap = plan.res_rows.shape[0]
            kr_cap = plan.res_nbr.shape[1]
            has_res = plan.k_res > 0
            L = len(self.theta0)
            l_cap = _next_pow2(L, 4)
            es = _next_pow2(max(1, len(self.sh[0])), 4)
            er = _next_pow2(max(1, len(self.rs[0])), 4)
            srcs = np.asarray(
                sorted({s for s, _, _ in self.demands}), np.int32
            )
            row_of = {int(s): i for i, s in enumerate(srcs)}
            s_cap_d = _next_pow2(len(srcs), 2)
            d_cap = _next_pow2(len(self.demands), 2)

            theta = np.ones(l_cap, np.float32)
            theta[:L] = self.theta0
            sh_idx = np.full(es, s_cap * n_cap, np.int32)
            sh_idx[: len(self.sh[0])] = self.sh[0]
            sh_link = np.zeros(es, np.int32)
            sh_link[: len(self.sh[1])] = self.sh[1]
            rs_idx = np.full(er, r_cap * kr_cap, np.int32)
            rs_idx[: len(self.rs[0])] = self.rs[0]
            rs_link = np.zeros(er, np.int32)
            rs_link[: len(self.rs[1])] = self.rs[1]
            srcs_p = np.zeros(s_cap_d, np.int32)
            srcs_p[: len(srcs)] = srcs
            dem_row = np.zeros(d_cap, np.int32)
            dem_dst = np.zeros(d_cap, np.int32)
            dem_vol = np.zeros(d_cap, np.float32)
            for i, (si, di, vol) in enumerate(self.demands):
                dem_row[i] = row_of[si]
                dem_dst[i] = di
                dem_vol[i] = vol

            name, step = te_step(
                l_cap, s_cap_d, d_cap, es, er, n_cap, s_cap,
                r_cap, kr_cap, has_res, self.trips,
            )
            tau = np.float32(self.tau)
            tau_u = np.float32(self.tau_util)
            ad = self.ad
            util0 = None
            loss_curve = []
            with tracer.span(
                self.ctx, "whatif.gd", kernel=name, iters=self.iters,
            ):
                for it in range(self.iters):
                    loss, grad, util, cost = step(
                        theta, ad.d_deltas, ad.d_res_rows, ad.d_res_nbr,
                        sh_idx, sh_link, rs_idx, rs_link,
                        srcs_p, dem_row, dem_dst, dem_vol, tau, tau_u,
                    )
                    util = np.asarray(util)
                    if util0 is None:
                        util0 = util
                    loss_curve.append(round(float(loss), 4))
                    theta = np.clip(
                        theta - self.lr * np.asarray(grad),
                        1.0, float(MAX_METRIC),
                    ).astype(np.float32)
            # final utilization under the proposed weights
            _, _, util1, _ = step(
                theta, ad.d_deltas, ad.d_res_rows, ad.d_res_nbr,
                sh_idx, sh_link, rs_idx, rs_link,
                srcs_p, dem_row, dem_dst, dem_vol, tau, tau_u,
            )
            util1 = np.asarray(util1)
            before = float(util0[:L].max()) if L else 0.0
            after = float(util1[:L].max()) if L else 0.0
            proposed = np.clip(
                np.rint(theta[:L]), 1, MAX_METRIC
            ).astype(int)
            changes = [
                {
                    "link": self.link_names[i],
                    "metric": int(round(self.theta0[i])),
                    "proposed": int(proposed[i]),
                    "utilization": round(float(util1[i]), 3),
                }
                for i in range(L)
                if int(proposed[i]) != int(round(self.theta0[i]))
            ]
            ms = (time.perf_counter() - t0) * 1e3
            counters.increment("whatif.optimizes")
            counters.add_stat_value("whatif.optimize_ms", ms)
            out = {
                "area": self.area,
                "iters": self.iters,
                "trips": self.trips,
                "tau": self.tau,
                "demands": len(self.demands),
                "rejected_demands": len(self.rejected),
                "max_util_before": round(before, 3),
                "max_util_after": round(after, 3),
                "predicted_max_util_delta": round(after - before, 3),
                "loss_curve": loss_curve,
                "changes": changes,
                "optimize_ms": round(ms, 2),
            }
            tracer.end_trace(self.ctx, status=_TRACE_STATUS)
            self.ctx = None
            return out
        except Exception:
            tracer.end_trace(self.ctx, status="error")
            self.ctx = None
            raise
