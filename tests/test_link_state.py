"""LinkState graph tests — semantics of the reference's
openr/decision/tests/LinkStateTest.cpp: bidirectional link verification,
adjacency diffing, holds, SPF with ECMP + overload drain, k-paths, UCMP."""

from openr_tpu.decision.link_state import HoldableValue, LinkState
from openr_tpu.models import topologies
from openr_tpu.types import Adjacency, AdjacencyDatabase


def adj(me, other, metric=1, **kw):
    return Adjacency(
        other_node_name=other,
        if_name=f"if-{me}-{other}",
        other_if_name=f"if-{other}-{me}",
        metric=metric,
        **kw,
    )


def adj_db(node, adjs, **kw):
    return AdjacencyDatabase(this_node_name=node, adjacencies=tuple(adjs), **kw)


def line_link_state(n=3, metric=1):
    """0 -- 1 -- 2 ... linear chain."""
    ls = LinkState("0")
    names = [f"n{i}" for i in range(n)]
    for i, name in enumerate(names):
        adjs = []
        if i > 0:
            adjs.append(adj(name, names[i - 1], metric))
        if i < n - 1:
            adjs.append(adj(name, names[i + 1], metric))
        ls.update_adjacency_database(adj_db(name, adjs))
    return ls, names


# -- HoldableValue ---------------------------------------------------------

def test_holdable_value_no_hold():
    hv = HoldableValue(10)
    assert hv.update_value(5, 0, 0) is True
    assert hv.value == 5
    assert hv.update_value(5, 0, 0) is False


def test_holdable_value_hold_down_then_decrement():
    hv = HoldableValue(1)
    # metric 1 -> 10 is "bringing down": uses hold_down ttl
    assert hv.update_value(10, 2, 3) is False
    assert hv.value == 1 and hv.has_hold()
    assert hv.decrement_ttl() is False
    assert hv.decrement_ttl() is False
    assert hv.decrement_ttl() is True  # 3rd tick flushes
    assert hv.value == 10 and not hv.has_hold()


def test_holdable_value_bool_direction():
    hv = HoldableValue(False)
    # overload False->True is "down"
    assert hv.update_value(True, 1, 2) is False
    hv.decrement_ttl()
    assert hv.decrement_ttl() is True
    assert hv.value is True


# -- link construction / diffing ------------------------------------------

def test_link_requires_bidirectional_adjacency():
    ls = LinkState("0")
    change = ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
    # b hasn't advertised the reverse adjacency yet: no link, no topology
    assert not change.topology_changed
    assert ls.links_from_node("a") == set()
    change = ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
    assert change.topology_changed
    assert len(ls.links_from_node("a")) == 1
    assert len(change.added_links) == 1


def test_adjacency_diff_metric_and_attribute_changes():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
    # metric change -> topology changed
    change = ls.update_adjacency_database(adj_db("a", [adj("a", "b", metric=5)]))
    assert change.topology_changed
    link = next(iter(ls.links_from_node("a")))
    assert link.metric_from_node("a") == 5
    assert link.metric_from_node("b") == 1
    # adj label change -> attributes only
    change = ls.update_adjacency_database(
        adj_db("a", [adj("a", "b", metric=5, adj_label=50001)])
    )
    assert not change.topology_changed
    assert change.link_attributes_changed
    # node label change flag
    change = ls.update_adjacency_database(
        adj_db("a", [adj("a", "b", metric=5, adj_label=50001)], node_label=105)
    )
    assert change.node_label_changed


def test_link_removal_and_node_delete():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
    change = ls.update_adjacency_database(adj_db("a", []))
    assert change.topology_changed
    assert ls.links_from_node("b") == set()
    change = ls.delete_adjacency_database("b")
    assert change.topology_changed
    assert not ls.has_node("b")


def test_link_overload_makes_link_down():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
    change = ls.update_adjacency_database(
        adj_db("a", [adj("a", "b", is_overloaded=True)])
    )
    assert change.topology_changed
    link = next(iter(ls.links_from_node("a")))
    assert not link.is_up()


def test_metric_hold_up_and_down():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b", metric=10)]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
    # lowering metric = bringing up: held for hold_up_ttl=2 ticks
    change = ls.update_adjacency_database(
        adj_db("a", [adj("a", "b", metric=1)]), hold_up_ttl=2, hold_down_ttl=4
    )
    assert not change.topology_changed
    assert ls.has_holds()
    link = next(iter(ls.links_from_node("a")))
    assert link.metric_from_node("a") == 10  # still reporting old value
    assert not ls.decrement_holds().topology_changed
    assert ls.decrement_holds().topology_changed
    assert link.metric_from_node("a") == 1


# -- SPF -------------------------------------------------------------------

def test_spf_line_metrics_and_next_hops():
    ls, names = line_link_state(4, metric=2)
    res = ls.run_spf("n0")
    assert res["n0"].metric == 0
    assert res["n1"].metric == 2
    assert res["n3"].metric == 6
    assert res["n1"].next_hops == {"n1"}
    assert res["n3"].next_hops == {"n1"}


def test_spf_ecmp_square():
    #   a -- b
    #   |    |     all metric 1: a->d via b and via c (cost 2)
    #   c -- d
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b"), adj("a", "c")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a"), adj("b", "d")]))
    ls.update_adjacency_database(adj_db("c", [adj("c", "a"), adj("c", "d")]))
    ls.update_adjacency_database(adj_db("d", [adj("d", "b"), adj("d", "c")]))
    res = ls.run_spf("a")
    assert res["d"].metric == 2
    assert res["d"].next_hops == {"b", "c"}
    assert len(res["d"].path_links) == 2


def test_spf_overloaded_node_carries_no_transit():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b"), adj("a", "c")]))
    ls.update_adjacency_database(
        adj_db("b", [adj("b", "a"), adj("b", "d")], is_overloaded=True)
    )
    ls.update_adjacency_database(adj_db("c", [adj("c", "a"), adj("c", "d", metric=5)]))
    ls.update_adjacency_database(adj_db("d", [adj("d", "b"), adj("d", "c", metric=5)]))
    res = ls.run_spf("a")
    # b reachable but no transit through b: d costs 1+5 via c, not 2 via b
    assert res["b"].metric == 1
    assert res["d"].metric == 6
    assert res["d"].next_hops == {"c"}
    # overloaded root still routes its own traffic
    res_b = ls.run_spf("b")
    assert res_b["d"].metric == 1


def test_spf_memoization_and_invalidation():
    ls, names = line_link_state(3)
    r1 = ls.get_spf_result("n0")
    assert ls.get_spf_result("n0") is r1  # memo hit
    ls.update_adjacency_database(
        adj_db("n1", [adj("n1", "n0"), adj("n1", "n2", 7)])
    )
    r2 = ls.get_spf_result("n0")
    assert r2 is not r1
    assert r2["n2"].metric == 8


def test_get_metric_a_to_b():
    ls, names = line_link_state(3, metric=3)
    assert ls.get_metric_from_a_to_b("n0", "n2") == 6
    assert ls.get_metric_from_a_to_b("n0", "n0") == 0
    assert ls.get_metric_from_a_to_b("n0", "nx") is None


# -- k shortest (edge-disjoint) paths --------------------------------------

def test_kth_paths_square():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b"), adj("a", "c")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a"), adj("b", "d")]))
    ls.update_adjacency_database(adj_db("c", [adj("c", "a"), adj("c", "d", metric=2)]))
    ls.update_adjacency_database(adj_db("d", [adj("d", "b"), adj("d", "c", metric=2)]))
    p1 = ls.get_kth_paths("a", "d", 1)
    assert len(p1) == 1 and len(p1[0]) == 2  # a-b-d strictly shortest
    p2 = ls.get_kth_paths("a", "d", 2)
    assert len(p2) == 1 and len(p2[0]) == 2  # a-c-d, edge-disjoint
    used = {l for p in p1 for l in p}
    assert all(l not in used for p in p2 for l in p)


def test_kth_paths_ecmp_traces_disjoint():
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("a", [adj("a", "b"), adj("a", "c")]))
    ls.update_adjacency_database(adj_db("b", [adj("b", "a"), adj("b", "d")]))
    ls.update_adjacency_database(adj_db("c", [adj("c", "a"), adj("c", "d")]))
    ls.update_adjacency_database(adj_db("d", [adj("d", "b"), adj("d", "c")]))
    p1 = ls.get_kth_paths("a", "d", 1)
    assert len(p1) == 2  # both equal-cost paths traced from the SPF DAG


# -- UCMP ------------------------------------------------------------------

def test_ucmp_weight_propagation():
    # two leaves with weights 2 and 4 behind a middle node
    #  root -- m -- l1(w2)
    #           \-- l2(w4)
    ls = LinkState("0")
    ls.update_adjacency_database(adj_db("root", [adj("root", "m")]))
    ls.update_adjacency_database(
        adj_db("m", [adj("m", "root"), adj("m", "l1"), adj("m", "l2")])
    )
    ls.update_adjacency_database(adj_db("l1", [adj("l1", "m")]))
    ls.update_adjacency_database(adj_db("l2", [adj("l2", "m")]))
    # equidistant leaves required
    spf = ls.get_spf_result("root")
    res = ls.resolve_ucmp_weights(spf, {"l1": 2, "l2": 4}, use_prefix_weight=True)
    assert res["m"].weight == 6  # sum of leaf prefix weights
    m_links = res["m"].next_hop_links
    weights = sorted(nh.weight for nh in m_links.values())
    assert weights == [1, 2]  # gcd-normalized 2:4
    assert res["root"].weight == 6


def test_ucmp_unequal_leaf_distance_skipped():
    ls, names = line_link_state(3)
    spf = ls.get_spf_result("n0")
    res = ls.resolve_ucmp_weights(spf, {"n1": 1, "n2": 1}, use_prefix_weight=True)
    assert res == {}


# -- generators sanity -----------------------------------------------------

def test_topology_generators_shapes():
    adj_dbs, prefix_dbs = topologies.grid(3)
    assert len(adj_dbs) == 9 and len(prefix_dbs) == 9
    link_states, prefix_state = topologies.build_states(adj_dbs, prefix_dbs)
    ls = link_states["0"]
    assert len(ls.all_links()) == 12  # 2*n*(n-1) grid edges
    res = ls.run_spf("node-0-0")
    assert res["node-2-2"].metric == 4
    assert len(prefix_state.prefixes()) == 9

    adj_dbs, _ = topologies.fat_tree()
    names = {db.this_node_name for db in adj_dbs}
    assert len(names) == 2 * 4 + 2 * 2 + 2 * 4  # ssw + fsw + rsw
    link_states, _ = topologies.build_states(adj_dbs, [])
    ft = link_states["0"]
    # rsw in pod0 reaches rsw in pod1 in 4 hops via fsw-ssw-fsw
    res = ft.run_spf("rsw-0-0")
    assert res["rsw-1-0"].metric == 4
    assert len(res["rsw-1-0"].next_hops) == 2  # both planes ECMP

    adj_dbs, _ = topologies.random_mesh(20, seed=3)
    link_states, _ = topologies.build_states(adj_dbs, [])
    res = link_states["0"].run_spf("node-0")
    assert len(res) == 20  # connected
