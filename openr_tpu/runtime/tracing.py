"""Convergence tracing fabric — spans + trace-context propagation.

Role of the perf-event breadcrumbs the reference threads through
thrift::PerfEvents (Decision.cpp addPerfEvent, Fib.cpp logPerfEvents),
generalised into a proper span tree: one topology event entering
KvStore carries a single trace_id through decision → tpu_solver →
columnar RIB materialization → fib → platform programming ack, and the
closed trace exports as Chrome trace-event JSON (chrome://tracing /
Perfetto).

Design constraints:
- Process-wide singleton (like runtime.counters.counters) because the
  pipeline crosses actor and thread boundaries (the TPU solver's
  "rib-mat" worker thread records materialization spans).
- The queue items (Publication, DecisionRouteUpdate) are mutable
  dataclasses with eq=True — unhashable — so the context rides in a
  side-table keyed by id(item), cleaned up by weakref.finalize. Items
  that are not weakref-able simply don't carry context.
- Opt-out cheap: with tracing disabled start_trace returns None and
  every other entry point takes the None fast path (one attribute
  check); context_of is one dict lookup.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import threading
import time
import weakref
from typing import Any, Optional

from openr_tpu.runtime.counters import counters

# ring of closed traces kept for monitor.traces / export
MAX_CLOSED_TRACES = 256
# safety valve: a trace that never closes (e.g. FIB never acks because
# the platform is down) must not leak — oldest active is force-closed
# with status "evicted" once this many are in flight
MAX_ACTIVE_TRACES = 256
# side-table cap: a stuck consumer (queue reader crashed between push
# and pop) strands contexts whose items never get collected — past this
# many, orphans (contexts of no-longer-active traces) are evicted
# first, then the oldest entries
MAX_TRACE_CONTEXTS = 1024


class Span:
    """One timed stage. start/end are time.monotonic() seconds; the
    tracer's wall-clock anchor maps them to epoch µs at export time."""

    __slots__ = (
        "span_id", "trace_id", "parent_id", "name",
        "start", "end", "attributes", "thread",
    )

    def __init__(
        self,
        span_id: int,
        trace_id: int,
        name: str,
        start: float,
        parent_id: Optional[int] = None,
        attributes: Optional[dict] = None,
        thread: str = "",
    ):
        self.span_id = span_id
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.attributes: dict = attributes or {}
        self.thread = thread

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end is None:
            return None
        return (self.end - self.start) * 1000.0

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "trace_id": self.trace_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_ms": self.duration_ms,
            "attributes": dict(self.attributes),
            "thread": self.thread,
        }


class TraceContext:
    """Lightweight handle that rides through the queues. Only identity
    lives here; span storage is in the tracer so any thread can add."""

    __slots__ = ("trace_id", "root_span_id")

    def __init__(self, trace_id: int, root_span_id: int):
        self.trace_id = trace_id
        self.root_span_id = root_span_id

    def __repr__(self) -> str:  # breeze-friendly
        return f"TraceContext(trace_id={self.trace_id})"


class _Trace:
    __slots__ = ("trace_id", "name", "spans", "status", "started", "ended")

    def __init__(self, trace_id: int, name: str, started: float):
        self.trace_id = trace_id
        self.name = name
        self.spans: list[Span] = []
        self.status = "active"
        self.started = started
        self.ended: Optional[float] = None


class _NullSpan:
    """No-op context manager handed out when tracing is off or the
    context is None — hot paths need no branches beyond `with`."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager wrapping an open Span; closes it on exit."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> "_LiveSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.span.attributes["error"] = repr(exc)
        self._tracer.end_span(self.span)
        return False

    def set(self, **attrs) -> None:
        self.span.attributes.update(attrs)


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self._trace_seq = itertools.count(1)
        self._span_seq = itertools.count(1)
        self._active: dict[int, _Trace] = {}
        self._closed: "list[_Trace]" = []
        # side-table: id(item) -> TraceContext, scrubbed by finalizers
        self._ctx_by_id: dict[int, TraceContext] = {}
        # anchor for monotonic -> wall-clock µs mapping in exports
        self._wall_anchor = time.time()
        self._mono_anchor = time.monotonic()

    # -- config -----------------------------------------------------------

    def configure(self, enabled: bool) -> None:
        self.enabled = enabled

    # -- context propagation (messaging/queue.py) -------------------------

    def attach(self, item: Any, ctx: Optional[TraceContext]) -> bool:
        """Associate ctx with a queue item. Returns False when the item
        cannot carry context (not weakref-able) or ctx is None."""
        if ctx is None:
            return False
        key = id(item)
        try:
            weakref.finalize(item, self._ctx_by_id.pop, key, None)
        except TypeError:
            return False
        self._ctx_by_id[key] = ctx
        if len(self._ctx_by_id) > MAX_TRACE_CONTEXTS:
            self._evict_contexts()
        return True

    def _evict_contexts(self) -> None:
        """Side-table hygiene: drop contexts whose trace already closed
        (the span tree is finished — the entry can only go stale), then
        oldest-first down to the cap. Keeps a wedged consumer from
        growing the table unbounded."""
        evicted = 0
        with self._lock:
            if len(self._ctx_by_id) > MAX_TRACE_CONTEXTS:
                orphans = [
                    k for k, c in self._ctx_by_id.items()
                    if c.trace_id not in self._active
                ]
                for k in orphans:
                    self._ctx_by_id.pop(k, None)
                evicted += len(orphans)
            excess = len(self._ctx_by_id) - MAX_TRACE_CONTEXTS
            if excess > 0:
                for k in list(itertools.islice(self._ctx_by_id, excess)):
                    self._ctx_by_id.pop(k, None)
                evicted += excess
        if evicted:
            counters.increment("tracing.contexts_evicted", evicted)

    def context_of(self, item: Any) -> Optional[TraceContext]:
        """One dict lookup; safe on any object."""
        return self._ctx_by_id.get(id(item))

    def active_context_count(self) -> int:
        return len(self._ctx_by_id)

    def detach(self, item: Any) -> Optional[TraceContext]:
        return self._ctx_by_id.pop(id(item), None)

    # -- span lifecycle ---------------------------------------------------

    def start_trace(
        self, name: str, start: Optional[float] = None, **attributes
    ) -> Optional[TraceContext]:
        """Open a new trace; returns None when tracing is disabled so
        producers can pass the context straight through push(trace=...).
        `start` (time.monotonic()) backdates the root to cover work
        already done when the producer decides the event is traceworthy."""
        if not self.enabled:
            return None
        now = start if start is not None else time.monotonic()
        with self._lock:
            trace_id = next(self._trace_seq)
            span_id = next(self._span_seq)
            root = Span(
                span_id, trace_id, name, now,
                attributes=dict(attributes),
                thread=threading.current_thread().name,
            )
            tr = _Trace(trace_id, name, now)
            tr.spans.append(root)
            self._active[trace_id] = tr
            evicted = None
            if len(self._active) > MAX_ACTIVE_TRACES:
                oldest_id = min(
                    self._active, key=lambda t: self._active[t].started
                )
                evicted = self._active.pop(oldest_id)
        if evicted is not None:
            self._finish(evicted, now, status="evicted")
        return TraceContext(trace_id, span_id)

    def start_span(
        self,
        ctx: Optional[TraceContext],
        name: str,
        parent_id: Optional[int] = None,
        **attributes,
    ) -> Optional[Span]:
        if ctx is None or not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return None
            span = Span(
                next(self._span_seq), ctx.trace_id, name, now,
                parent_id=parent_id or ctx.root_span_id,
                attributes=dict(attributes),
                thread=threading.current_thread().name,
            )
            tr.spans.append(span)
            return span

    def end_span(self, span: Optional[Span], **attributes) -> None:
        if span is None:
            return
        span.end = time.monotonic()
        if attributes:
            span.attributes.update(attributes)

    def span(
        self,
        ctx: Optional[TraceContext],
        name: str,
        parent_id: Optional[int] = None,
        **attributes,
    ):
        """`with tracer.span(ctx, "decision.spf"): ...` — no-op when ctx
        is None / tracing off."""
        sp = self.start_span(ctx, name, parent_id, **attributes)
        if sp is None:
            return _NULL_SPAN
        return _LiveSpan(self, sp)

    def record_span(
        self,
        ctx: Optional[TraceContext],
        name: str,
        start: float,
        end: float,
        parent_id: Optional[int] = None,
        **attributes,
    ) -> Optional[Span]:
        """Retroactively add an already-timed stage (e.g. folding the
        TPU solver's last_timing sync/exec/mat breakdown). start/end are
        time.monotonic() seconds."""
        if ctx is None or not self.enabled:
            return None
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return None
            span = Span(
                next(self._span_seq), ctx.trace_id, name, start,
                parent_id=parent_id or ctx.root_span_id,
                attributes=dict(attributes),
                thread=threading.current_thread().name,
            )
            span.end = end
            tr.spans.append(span)
            return span

    def root_attributes(self, ctx: Optional[TraceContext]) -> dict:
        """Copy of an ACTIVE trace's root-span attributes — how Fib reads
        the origin stamp the KvStore ingress threaded onto the trace.
        Empty dict for None/closed/unknown contexts."""
        if ctx is None or not self.enabled:
            return {}
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return {}
            return dict(tr.spans[0].attributes)

    def trace_start(self, ctx: Optional[TraceContext]) -> Optional[float]:
        """Monotonic start of an ACTIVE trace, or None — anchors the
        latency-budget ledger's ``ingest_wait`` at the KvStore receive
        stamp the ingress passed to start_trace(start=...)."""
        if ctx is None or not self.enabled:
            return None
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            return tr.started if tr is not None else None

    def annotate(self, ctx: Optional[TraceContext], **attributes) -> None:
        """Stamp attributes onto an active trace's root span without
        closing it — e.g. degraded=True when the solver failed over
        mid-flight, so the trace closes carrying the marker."""
        if ctx is None or not self.enabled or not attributes:
            return
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return
            tr.spans[0].attributes.update(attributes)

    def end_trace(
        self, ctx: Optional[TraceContext], status: str = "ok", **attributes
    ) -> None:
        """Close the root span, move the trace to the closed ring, and
        stamp the end-to-end convergence_ms stat (status "ok" only —
        coalesced/no_change closures are not convergence events)."""
        if ctx is None:
            return
        now = time.monotonic()
        with self._lock:
            tr = self._active.pop(ctx.trace_id, None)
        if tr is None:
            return
        if attributes:
            tr.spans[0].attributes.update(attributes)
        self._finish(tr, now, status=status)

    def _finish(self, tr: _Trace, now: float, status: str) -> None:
        root = tr.spans[0]
        if root.end is None:
            root.end = now
        tr.ended = now
        tr.status = status
        root.attributes.setdefault("status", status)
        with self._lock:
            self._closed.append(tr)
            if len(self._closed) > MAX_CLOSED_TRACES:
                del self._closed[: len(self._closed) - MAX_CLOSED_TRACES]
        if status == "ok":
            counters.add_stat_value(
                "convergence_ms", (now - tr.started) * 1000.0
            )
            counters.increment("tracing.traces_closed")
        else:
            counters.increment(f"tracing.traces_{status}")

    # -- introspection (ctrl server / breeze) -----------------------------

    def get_traces(
        self,
        limit: int = 20,
        trace_id: Optional[int] = None,
        include_active: bool = False,
    ) -> list[dict]:
        with self._lock:
            picked: list[_Trace] = list(self._closed)
            if include_active:
                picked += list(self._active.values())
        if trace_id is not None:
            picked = [t for t in picked if t.trace_id == trace_id]
        picked = picked[-max(1, limit):]
        return [
            {
                "trace_id": t.trace_id,
                "name": t.name,
                "status": t.status,
                "duration_ms": (
                    (t.ended - t.started) * 1000.0
                    if t.ended is not None else None
                ),
                "num_spans": len(t.spans),
                "spans": [s.to_dict() for s in t.spans],
            }
            for t in picked
        ]

    def export_chrome(
        self, trace_id: Optional[int] = None, limit: int = 20
    ) -> dict:
        """Chrome trace-event JSON (the `{"traceEvents": [...]}` object
        form): one "X" complete event per closed span with ts/dur in
        wall-clock µs, plus "M" thread_name metadata rows. Load in
        chrome://tracing or ui.perfetto.dev."""
        with self._lock:
            picked = [
                t for t in self._closed
                if trace_id is None or t.trace_id == trace_id
            ][-max(1, limit):]
            wall0, mono0 = self._wall_anchor, self._mono_anchor
        # one process lane per NODE (the root span's `node` attribute):
        # a stitched fleet trace renders each node's kvstore→decision→fib
        # tree in its own lane; traces without a node attr (e.g.
        # supervisor-restart one-spanners) share a process-named lane
        fallback = f"pid:{os.getpid()}"
        pids: dict[str, int] = {}
        tids: dict[tuple[int, str], int] = {}
        events: list[dict] = []
        for t in picked:
            node = str(t.spans[0].attributes.get("node") or fallback)
            pid = pids.setdefault(node, len(pids) + 1)
            for s in t.spans:
                if s.end is None:
                    continue
                tid = tids.setdefault(
                    (pid, s.thread or "main"), len(tids) + 1
                )
                ts_us = (wall0 + (s.start - mono0)) * 1e6
                events.append({
                    "name": s.name,
                    "cat": t.name,
                    "ph": "X",
                    "ts": ts_us,
                    "dur": max(0.0, (s.end - s.start) * 1e6),
                    "pid": pid,
                    "tid": tid,
                    "args": {
                        "trace_id": s.trace_id,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                        **{
                            k: v for k, v in s.attributes.items()
                            if isinstance(v, (str, int, float, bool))
                            or v is None
                        },
                    },
                })
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": node},
            }
            for node, pid in sorted(pids.items(), key=lambda kv: kv[1])
        ] + [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": thread},
            }
            for (pid, thread), tid in sorted(
                tids.items(), key=lambda kv: kv[1]
            )
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome_json(
        self, trace_id: Optional[int] = None, limit: int = 20
    ) -> str:
        return json.dumps(self.export_chrome(trace_id, limit))

    def convergence_summary(self) -> dict:
        """p50/p95/p99/max over the closed-trace ring (status ok) —
        the per-event incremental-convergence view DeltaPath measures."""
        with self._lock:
            raw = [
                (t.ended - t.started) * 1000.0
                for t in self._closed
                if t.status == "ok" and t.ended is not None
            ]
        durs = sorted(raw)
        n = len(durs)

        def pct(q: float) -> float:
            if not n:
                return 0.0
            idx = (q / 100.0) * (n - 1)
            lo, hi = math.floor(idx), math.ceil(idx)
            if lo == hi:
                return float(durs[lo])
            frac = idx - lo
            return durs[lo] * (1.0 - frac) + durs[hi] * frac

        return {
            "count": n,
            "p50_ms": pct(50.0),
            "p95_ms": pct(95.0),
            "p99_ms": pct(99.0),
            "max_ms": durs[-1] if n else 0.0,
            "last_ms": raw[-1] if n else 0.0,
        }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._closed.clear()
            self._ctx_by_id.clear()


# the process-wide instance (pattern of runtime.counters.counters)
tracer = Tracer()
