"""Origination-policy tests (ref openr/policy/PolicyManager.h role):
the declarative engine, and the PrefixManager advertisement hook."""

import asyncio

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.policy import (
    Policy,
    PolicyAction,
    PolicyManager,
    PolicyMatch,
    PolicyStatement,
)
from openr_tpu.prefix_manager.prefix_manager import (
    OriginatedPrefix,
    PrefixManager,
)
from openr_tpu.types import (
    KeyValueRequest,
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixType,
)
from tests.conftest import run_async


def entry(prefix, ptype=PrefixType.BREEZE, tags=()):
    return PrefixEntry(prefix=prefix, type=ptype, tags=tuple(tags))


DENY_PRIVATE = Policy(
    statements=(
        PolicyStatement(
            name="deny-private-v4",
            match=PolicyMatch(prefixes=("10.0.0.0/8",)),
            action=PolicyAction(accept=False),
        ),
        PolicyStatement(
            name="tag-loopbacks",
            match=PolicyMatch(types=(int(PrefixType.LOOPBACK),)),
            action=PolicyAction(
                set_tags=("loopback",), set_path_preference=900
            ),
        ),
    ),
)


class TestPolicyEngine:
    def test_first_match_wins_and_denies(self):
        pm = PolicyManager({"orig": DENY_PRIVATE})
        assert pm.apply("orig", entry("10.1.2.0/24")) is None
        out = pm.apply("orig", entry("192.168.1.0/24"))
        assert out is not None and out.tags == ()  # default accept

    def test_transform_action(self):
        pm = PolicyManager({"orig": DENY_PRIVATE})
        out = pm.apply(
            "orig", entry("192.0.2.1/32", ptype=PrefixType.LOOPBACK)
        )
        assert out.tags == ("loopback",)
        assert out.metrics.path_preference == 900

    def test_default_deny(self):
        pol = Policy(
            statements=(
                PolicyStatement(
                    match=PolicyMatch(tags=("allowed",)),
                    action=PolicyAction(accept=True),
                ),
            ),
            default_accept=False,
        )
        pm = PolicyManager({"p": pol})
        assert pm.apply("p", entry("1.2.3.0/24")) is None
        assert pm.apply("p", entry("1.2.3.0/24", tags=("allowed",))) is not None

    def test_unknown_policy_accepts(self):
        pm = PolicyManager({})
        e = entry("1.2.3.0/24")
        assert pm.apply("ghost", e) is e

    def test_v6_prefix_space_match(self):
        pol = Policy(
            statements=(
                PolicyStatement(
                    match=PolicyMatch(prefixes=("fd00::/8",)),
                    action=PolicyAction(accept=False),
                ),
            ),
        )
        pm = PolicyManager({"p": pol})
        assert pm.apply("p", entry("fd00:1::/64")) is None
        assert pm.apply("p", entry("2001:db8::/64")) is not None

    def test_apply_all_shape(self):
        pm = PolicyManager({"orig": DENY_PRIVATE})
        accepted, denied = pm.apply_all(
            "orig", [entry("10.0.0.0/24"), entry("192.0.2.0/24")]
        )
        assert denied == ["10.0.0.0/24"]
        assert [e.prefix for e in accepted] == ["192.0.2.0/24"]


class TestPrefixManagerPolicyHook:
    @run_async
    async def test_denied_prefix_not_advertised(self):
        prefix_q = ReplicateQueue("prefixUpdates")
        kv_q = ReplicateQueue("kvRequests")
        kv_reader = kv_q.get_reader("test")
        pm = PrefixManager(
            "node-a",
            ["0"],
            prefix_q.get_reader(),
            None,
            kv_q,
            policy_manager=PolicyManager({"orig": DENY_PRIVATE}),
            origination_policy="orig",
            originated_prefixes=[
                OriginatedPrefix(prefix="10.50.0.0/16")  # policy-denied
            ],
        )
        await pm.start()
        try:
            prefix_q.push(
                PrefixEvent(
                    event_type=PrefixEventType.ADD_PREFIXES,
                    type=PrefixType.BREEZE,
                    prefixes=[
                        entry("10.9.0.0/24"),  # denied
                        entry("198.51.100.0/24"),  # accepted
                    ],
                )
            )

            async def next_persist():
                while True:
                    item = await kv_reader.get()
                    if isinstance(item, KeyValueRequest):
                        return item

            req = await asyncio.wait_for(next_persist(), 5)
            assert "198.51.100.0/24" in req.key
            assert "10.9.0.0/24" not in (await pm.get_prefixes())
            assert "10.50.0.0/16" not in (await pm.get_prefixes())
            advertised = await pm.get_prefixes()
            assert set(advertised) == {"198.51.100.0/24"}
        finally:
            prefix_q.close()
            kv_q.close()
            await pm.stop()

    @run_async
    async def test_policy_transform_applied_to_advertisement(self):
        prefix_q = ReplicateQueue("prefixUpdates")
        kv_q = ReplicateQueue("kvRequests")
        pm = PrefixManager(
            "node-a",
            ["0"],
            prefix_q.get_reader(),
            None,
            kv_q,
            policy_manager=PolicyManager({"orig": DENY_PRIVATE}),
            origination_policy="orig",
        )
        await pm.start()
        try:
            prefix_q.push(
                PrefixEvent(
                    event_type=PrefixEventType.ADD_PREFIXES,
                    type=PrefixType.LOOPBACK,
                    prefixes=[entry("192.0.2.1/32", PrefixType.LOOPBACK)],
                )
            )

            async def advertised():
                while True:
                    got = await pm.get_prefixes()
                    if "192.0.2.1/32" in got:
                        return got["192.0.2.1/32"]
                    await asyncio.sleep(0.01)

            e = await asyncio.wait_for(advertised(), 5)
            assert e.tags == ("loopback",)
            assert e.metrics.path_preference == 900
        finally:
            prefix_q.close()
            kv_q.close()
            await pm.stop()
