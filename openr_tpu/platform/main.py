"""Standalone platform agent binary (ref LinuxPlatformMain.cpp: the
platform_linux process serving FibService separately from the routing
daemon, so a dataplane-agent restart never takes the protocol down).

    python -m openr_tpu.platform.main --port 60100 --backend memory
    python -m openr_tpu.platform.main --backend netlink --table 10099
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from openr_tpu.platform.fib_handler import (
    FibPlatformServer,
    MemoryDataplane,
    NetlinkDataplane,
)

log = logging.getLogger("openr_tpu.platform")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="openr_tpu platform agent")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=60100)
    p.add_argument(
        "--backend",
        choices=["memory", "netlink"],
        default="memory",
        help="dataplane: in-memory tables or kernel rtnetlink",
    )
    p.add_argument(
        "--table",
        type=int,
        default=254,
        help="kernel route table for the netlink backend",
    )
    p.add_argument(
        "--bulk-threshold",
        type=int,
        default=None,
        help="batch size at which the netlink backend switches to the "
        "C++ bulk programmer (platform_config.bulk_threshold; default "
        f"{NetlinkDataplane.BULK_THRESHOLD})",
    )
    p.add_argument("--verbose", action="store_true")
    return p.parse_args(argv)


async def run(args) -> None:
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    dataplane = (
        NetlinkDataplane(table=args.table, bulk_threshold=args.bulk_threshold)
        if args.backend == "netlink"
        else MemoryDataplane()
    )
    server = FibPlatformServer(dataplane)
    port = await server.start(args.host, args.port)
    log.info("platform agent (%s) on %s:%d", args.backend, args.host, port)
    print(f"READY fib={port}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


def main(argv=None) -> None:
    asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    main()
