"""Test bootstrap.

Tests run JAX on CPU with 8 virtual devices so multi-chip sharding
(openr_tpu/parallel) is exercised without TPU hardware; the driver's bench
run uses the real chip. This must happen before jax is imported anywhere.
"""

import asyncio
import functools
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def run_async(fn):
    """Decorator: run an async test in a fresh event loop
    (no pytest-asyncio in the image)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=60))

    return wrapper
