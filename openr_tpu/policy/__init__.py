"""Policy layer (role of openr/policy/ — ref PolicyManager.h:1)."""

from openr_tpu.policy.policy_manager import (  # noqa: F401
    Policy,
    PolicyAction,
    PolicyManager,
    PolicyMatch,
    PolicyStatement,
)
