"""VIP injection plugin (role of the reference's vipPluginStart,
openr/plugin/Plugin.h:30-44): advertise anycast service prefixes into
PrefixManager through the plugin queue boundary.

Load via config:  "plugins": ["examples.vip_plugin:plugin"]
VIPs come from the config extras or the VIPS constant below.
"""

from openr_tpu.plugins import PluginArgs
from openr_tpu.types import (
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixType,
)

VIPS = ["192.0.2.100/32"]


class VipPlugin:
    def __init__(self, args: PluginArgs):
        self.args = args
        self.vips = list(args.extras.get("vips", VIPS))

    async def start(self) -> None:
        self.args.prefix_updates_queue.push(
            PrefixEvent(
                event_type=PrefixEventType.ADD_PREFIXES,
                type=PrefixType.VIP,
                prefixes=[
                    PrefixEntry(prefix=vip, type=PrefixType.VIP)
                    for vip in self.vips
                ],
            )
        )

    async def stop(self) -> None:
        self.args.prefix_updates_queue.push(
            PrefixEvent(
                event_type=PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE,
                type=PrefixType.VIP,
            )
        )


def plugin(args: PluginArgs) -> VipPlugin:
    return VipPlugin(args)
