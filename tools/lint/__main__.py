"""Entry point: `python -m tools.lint [--all] [--checker NAME ...]`.

Runs the eight project checkers over `openr_tpu/` (exit 1 on any
unsuppressed finding); `--all` additionally shells out to ruff when it
is installed (the CI lint lane installs it; a dev box without ruff
gets a skip note, not a failure, since the container image is fixed).

`--files a.py b.py` narrows the REPORT to findings in those files (the
analysis still sees the whole package — the checkers are cross-file).
This is the PR fast lane: lint only what the diff touched, with the
unused-allowlist audit skipped (a partial report can't prove
staleness). Pushes to main run the full `--all` lane.
"""

from __future__ import annotations

import argparse
import shutil
import subprocess
import sys
from pathlib import Path

from tools.lint import (
    affinity,
    blocking,
    donation,
    excepts,
    metric_names,
    purity,
    recompile,
    shardcheck,
)
from tools.lint.core import (
    DEFAULT_ALLOWLIST,
    REPO_ROOT,
    Allowlist,
    Project,
    apply_suppressions,
)

CHECKERS = {
    "affinity": affinity.run,
    "purity": purity.run,
    "blocking": blocking.run,
    "excepts": excepts.run,
    "metric-names": metric_names.run,
    "recompile": recompile.run,
    "shardcheck": shardcheck.run,
    "donation": donation.run,
}


def _run_ruff() -> int | None:
    """Exit code, or None when ruff isn't installed (skip, not fail)."""
    if shutil.which("ruff") is None:
        print(
            "tools.lint: ruff not installed — skipping ruff lane "
            "(CI installs it; config lives in pyproject.toml)"
        )
        return None
    proc = subprocess.run(
        ["ruff", "check", "openr_tpu/", "tools/", "tests/"],
        cwd=REPO_ROOT,
    )
    return proc.returncode


def _normalize_rel(raw: str) -> str:
    """A --files argument as a repo-relative forward-slash path."""
    p = Path(raw)
    if p.is_absolute():
        try:
            p = p.relative_to(REPO_ROOT)
        except ValueError:
            pass
    return p.as_posix()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.lint")
    ap.add_argument(
        "--checker", action="append", choices=sorted(CHECKERS),
        help="run only the named checker(s); default: all eight",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="also run ruff (the full CI lint lane)",
    )
    ap.add_argument(
        "--allowlist", type=Path, default=DEFAULT_ALLOWLIST,
        help="allowlist JSON path (default tools/lint/allowlist.json)",
    )
    ap.add_argument(
        "--package", default="openr_tpu",
        help="package directory to scan (default openr_tpu)",
    )
    ap.add_argument(
        "--files", nargs="*", default=None, metavar="PATH",
        help="report only findings in these files (diff-aware PR "
        "lane); analysis still covers the whole package",
    )
    args = ap.parse_args(argv)

    project = Project(REPO_ROOT, [args.package])
    allowlist = Allowlist.load(args.allowlist)

    failures = 0
    for err in project.parse_errors:
        print(f"tools.lint: {err}", file=sys.stderr)
        failures += 1
    for err in allowlist.errors:
        print(f"tools.lint: {err}", file=sys.stderr)
        failures += 1

    selected = args.checker or sorted(CHECKERS)
    findings = []
    for name in selected:
        findings.extend(CHECKERS[name](project))
    # a pragma without a reason is itself a finding
    for sf in project.files:
        findings.extend(sf.pragma_errors)

    remaining = apply_suppressions(findings, project, allowlist)
    if args.files is not None:
        wanted = {_normalize_rel(f) for f in args.files}
        remaining = [fd for fd in remaining if fd.path in wanted]
    remaining.sort(key=lambda f: (f.path, f.line, f.code))
    for fd in remaining:
        print(fd.render(), file=sys.stderr)
    failures += len(remaining)

    # stale allowlist entries rot into blanket permission — a FAILURE,
    # not a warning: the fix (delete the entry) is always one line
    # (only when every checker saw every file; a partial run can't
    # prove staleness)
    if not args.checker and args.files is None:
        for key in allowlist.unused():
            print(
                f"tools.lint: unused allowlist entry: {key} — the "
                f"finding it suppressed is gone; delete the entry",
                file=sys.stderr,
            )
            failures += 1

    ruff_ran = False
    if args.all:
        rc = _run_ruff()
        ruff_ran = rc is not None
        if ruff_ran and rc != 0:
            failures += 1

    checked = "+".join(selected) + ("+ruff" if ruff_ran else "")
    if failures:
        print(
            f"tools.lint: FAIL — {failures} problem(s) [{checked}] "
            f"(suppress with `# lint: allow(<code>) <reason>` or an "
            f"allowlist entry; see docs/StaticAnalysis.md)",
            file=sys.stderr,
        )
        return 1
    print(
        f"tools.lint: OK — {len(project.files)} files clean [{checked}]"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
