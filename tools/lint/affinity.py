"""Actor-affinity checker (`executor-escape`, `cross-actor-write`).

The concurrency model gives every actor a single writer: its own
fibers on the shared loop. State leaves that protection exactly when a
callable escapes to another thread — `loop.run_in_executor(...)`,
`Executor.submit(...)`, `threading.Thread(target=...)`. This checker
makes every such escape an explicit, reviewed decision:

`executor-escape` — flags an escape whose target can reach actor/solver
state, i.e. a bound method (`self.x`, `obj.attr`) or a closure defined
inside the enclosing function (captures `self`/locals). Exempt:

  - targets whose terminal name carries `@affinity.executor_safe`
    anywhere in the project (e.g. `TpuSpfSolver.collect_route_db`,
    which by contract reads no LSDB state),
  - plain module-level functions and imported callables (no implicit
    path to actor state; they manage their own locking),
  - escapes with a `# lint: allow(executor-escape) <reason>` pragma or
    an allowlist entry — the reason documents WHY the target is safe
    off-thread (single-worker pool serialization, device-buffer-only
    reads, ...).

`cross-actor-write` — flags `self.<actor_attr>.<field> = ...`
assignments where `<actor_attr>` holds an Actor instance (inferred
from `self.X = <param>` bindings whose class is an Actor subclass
name, case-normalized). Writing another actor's state directly — from
a ctrl handler or a sibling actor — bypasses the single-writer
discipline; route it through ReplicateQueue or an async request
method.

The runtime half lives in `openr_tpu/runtime/affinity.py`: what this
checker can't see statically (which thread actually runs a guarded
write), the sentinel asserts at runtime in the CI test+chaos lanes.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.lint.core import Finding, Project, SourceFile

CODE_ESCAPE = "executor-escape"
CODE_XWRITE = "cross-actor-write"

_SUBMIT_ATTRS = {"submit", "run_in_executor"}


def _escape_target(node: ast.Call) -> Optional[ast.AST]:
    """The callable a call-site hands to another thread, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "run_in_executor":
        # loop.run_in_executor(executor, fn, *args)
        if len(node.args) >= 2:
            return node.args[1]
    elif isinstance(fn, ast.Attribute) and fn.attr == "submit":
        if node.args:
            return node.args[0]
    elif (
        isinstance(fn, ast.Attribute)
        and fn.attr == "Thread"
        or isinstance(fn, ast.Name)
        and fn.id == "Thread"
    ):
        for kw in node.keywords:
            if kw.arg == "target":
                return kw.value
    return None


def _binds_name(target: ast.AST, name: str) -> bool:
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and node.id == name:
            return True
    return False


def _mentions_self(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id == "self":
            return True
    return False


def _self_derived(enclosing: ast.AST, name: str) -> bool:
    """True when `name` was bound (assignment or loop unpack) in
    `enclosing` from an expression involving `self` — a factory-made
    closure (`prepare = self._dispatch_one(pv)`, or `for pv, prepare
    in self._dispatch_fused(group):`)."""
    for node in ast.walk(enclosing):
        if isinstance(node, ast.Assign):
            if any(_binds_name(t, name) for t in node.targets):
                if _mentions_self(node.value):
                    return True
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if _binds_name(node.target, name) and _mentions_self(
                node.iter
            ):
                return True
    return False


class _FuncIndex(ast.NodeVisitor):
    """Per-file map of function-def nesting: name -> is it defined at
    module/class level (False) or nested inside another function (True)."""

    def __init__(self):
        self.nested: set[int] = set()  # id() of nested FunctionDef nodes
        self._depth = 0
        # (enclosing function node id, local def name) pairs
        self.local_defs: dict[tuple[int, str], ast.AST] = {}
        self._stack: list[ast.AST] = []

    def _visit_def(self, node) -> None:
        if self._stack:
            self.local_defs[(id(self._stack[-1]), node.name)] = node
            self.nested.add(id(node))
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def


def _check_escapes(
    sf: SourceFile, project: Project, findings: list[Finding]
) -> None:
    idx = _FuncIndex()
    idx.visit(sf.tree)

    def walk(node: ast.AST, enclosing: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            enc = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) else enclosing
            if isinstance(child, ast.Call):
                target = _escape_target(child)
                if target is not None:
                    _judge(child, target, enclosing)
            walk(child, enc)

    def _judge(
        call: ast.Call, target: ast.AST, enclosing: Optional[ast.AST]
    ) -> None:
        detail: Optional[str] = None
        reach = None  # why the target can reach owned state
        if isinstance(target, ast.Attribute):
            # bound method (self.x, obj.attr): state travels with it —
            # unless the terminal name is marked @executor_safe
            if target.attr in project.executor_safe_names:
                return
            detail = ast.unparse(target)
            reach = "a bound method carries its object's state"
        elif isinstance(target, ast.Lambda):
            detail = "<lambda>"
            reach = "a lambda captures the enclosing frame"
        elif isinstance(target, ast.Name) and enclosing is not None:
            if target.id in project.executor_safe_names:
                return
            # a closure defined inside this function captures locals;
            # plain module-level functions resolve no enclosing frame
            # and are not flagged
            if (id(enclosing), target.id) in idx.local_defs:
                detail = target.id
                reach = "a nested closure captures enclosing locals"
            elif _self_derived(enclosing, target.id):
                # prepare = self._dispatch_one(pv) — the factory bakes
                # solver state into the closure it returns
                detail = target.id
                reach = (
                    "a closure built by a self method carries that "
                    "object's state"
                )
        if detail is None:
            return
        findings.append(Finding(
            sf.rel, call.lineno, CODE_ESCAPE,
            sf.scope_at(call.lineno), detail,
            f"`{detail}` escapes to another thread "
            f"({ast.unparse(call.func)}) — {reach}; mark the target "
            f"@affinity.executor_safe after review, or pragma/allowlist "
            f"with the reason it is safe off the owning thread",
        ))

    walk(sf.tree, None)


def _actor_attrs_of_class(
    cls: ast.ClassDef, actor_classes: set[str]
) -> set[str]:
    """Attribute names bound in __init__ from parameters whose names
    case-normalize to a known Actor subclass (self.decision = decision)."""
    norm_actors = {c.lower().replace("_", "") for c in actor_classes}
    attrs: set[str] = set()
    for node in cls.body:
        if not (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "__init__"
        ):
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign):
                continue
            if not isinstance(stmt.value, ast.Name):
                continue
            src = stmt.value.id.lower().replace("_", "")
            if src not in norm_actors:
                continue
            for tgt in stmt.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    attrs.add(tgt.attr)
    return attrs


def _check_cross_writes(
    sf: SourceFile, project: Project, findings: list[Finding]
) -> None:
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        actor_attrs = _actor_attrs_of_class(cls, project.actor_classes)
        if not actor_attrs:
            continue
        for node in ast.walk(cls):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"
                    and tgt.value.attr in actor_attrs
                ):
                    continue
                detail = f"{tgt.value.attr}.{tgt.attr}"
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_XWRITE,
                    sf.scope_at(node.lineno), detail,
                    f"direct write to another actor's state "
                    f"`self.{detail}` — route it through ReplicateQueue "
                    f"or an async request method on the owning actor",
                ))


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        _check_escapes(sf, project, findings)
        _check_cross_writes(sf, project, findings)
    return findings
