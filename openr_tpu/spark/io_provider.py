"""IoProvider — the Spark datagram I/O seam.

Role of the reference's openr/spark/IoProvider.{h,cpp} (raw UDP multicast
socket shim) and openr/tests/mocks/MockIoProvider.h:41 (in-process fake with
per-link latency and ConnectedIfPairs topology wiring). Spark is
constructed against this interface, so tests run an emulated multi-node
mesh in one process with controllable latency and partitions — the
testability seam SURVEY §4 calls out.

A real UDP provider (UdpIoProvider) binds the discovery port per interface;
it exists for the daemon path. Datagrams carry serialized SparkPacket
(serde.py); timestamps for RTT measurement are stamped by the provider
(role of the 4 kernel timestamps, ref Spark.h:233).
"""

from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass
from typing import Optional

from openr_tpu.serde import deserialize, serialize
from openr_tpu.types import SparkPacket


@dataclass
class ReceivedPacket:
    packet: SparkPacket
    from_if_name: str  # OUR interface it arrived on
    sender_addr: str  # opaque sender address (node@iface in the mock)
    recv_ts_us: int  # provider receive timestamp (RTT measurement)
    sent_ts_us: int  # sender's transmit timestamp


class IoProvider:
    """Interface: per-interface multicast-ish datagram send/receive."""

    async def send(self, if_name: str, packet: SparkPacket) -> None:
        raise NotImplementedError

    async def recv(self) -> ReceivedPacket:
        """Next packet on any of our interfaces; blocks."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MockIoProvider(IoProvider):
    """One endpoint of a MockIoMesh; created via mesh.provider(node)."""

    def __init__(self, mesh: "MockIoMesh", node_name: str):
        self._mesh = mesh
        self.node_name = node_name
        self._inbox: asyncio.Queue[ReceivedPacket] = asyncio.Queue()

    async def send(self, if_name: str, packet: SparkPacket) -> None:
        await self._mesh.deliver(self.node_name, if_name, packet)

    async def recv(self) -> ReceivedPacket:
        return await self._inbox.get()

    def _push(self, pkt: ReceivedPacket) -> None:
        self._inbox.put_nowait(pkt)


class MockIoMesh:
    """The wiring: (node, iface) <-> (node, iface) pipes with per-link
    latency and partition control (ref MockIoProvider ConnectedIfPairs,
    MockIoProvider.h:18-20)."""

    def __init__(self) -> None:
        self._providers: dict[str, MockIoProvider] = {}
        # (node, iface) -> list of (peer_node, peer_iface, latency_s)
        self._links: dict[tuple[str, str], list[tuple[str, str, float]]] = (
            collections.defaultdict(list)
        )
        self._partitioned: set[frozenset] = set()
        self.drop_count = 0

    def provider(self, node_name: str) -> MockIoProvider:
        p = self._providers.get(node_name)
        if p is None:
            p = self._providers[node_name] = MockIoProvider(self, node_name)
        return p

    def connect(
        self,
        node_a: str,
        if_a: str,
        node_b: str,
        if_b: str,
        latency_s: float = 0.0,
    ) -> None:
        """Bidirectional wire between two (node, iface) endpoints."""
        self._links[(node_a, if_a)].append((node_b, if_b, latency_s))
        self._links[(node_b, if_b)].append((node_a, if_a, latency_s))

    def disconnect(self, node_a: str, if_a: str, node_b: str, if_b: str) -> None:
        self._links[(node_a, if_a)] = [
            (n, i, l)
            for n, i, l in self._links[(node_a, if_a)]
            if (n, i) != (node_b, if_b)
        ]
        self._links[(node_b, if_b)] = [
            (n, i, l)
            for n, i, l in self._links[(node_b, if_b)]
            if (n, i) != (node_a, if_a)
        ]

    def partition(self, node_a: str, node_b: str) -> None:
        """Drop all traffic between two nodes (both directions)."""
        self._partitioned.add(frozenset((node_a, node_b)))

    def heal(self, node_a: str, node_b: str) -> None:
        self._partitioned.discard(frozenset((node_a, node_b)))

    async def deliver(
        self, from_node: str, from_if: str, packet: SparkPacket
    ) -> None:
        sent_ts_us = int(time.monotonic() * 1e6)
        raw = serialize(packet)  # wire-realistic copy: no shared objects
        for peer_node, peer_if, latency_s in self._links.get(
            (from_node, from_if), ()
        ):
            if frozenset((from_node, peer_node)) in self._partitioned:
                self.drop_count += 1
                continue
            peer = self._providers.get(peer_node)
            if peer is None:
                self.drop_count += 1
                continue
            pkt = ReceivedPacket(
                packet=deserialize(raw, SparkPacket),
                from_if_name=peer_if,
                sender_addr=f"{from_node}@{from_if}",
                recv_ts_us=0,  # stamped at delivery below
                sent_ts_us=sent_ts_us,
            )
            if latency_s > 0:
                asyncio.get_running_loop().call_later(
                    latency_s, self._stamp_and_push, peer, pkt
                )
            else:
                self._stamp_and_push(peer, pkt)

    @staticmethod
    def _stamp_and_push(peer: MockIoProvider, pkt: ReceivedPacket) -> None:
        pkt.recv_ts_us = int(time.monotonic() * 1e6)
        peer._push(pkt)


class UdpIoProvider(IoProvider):
    """Real-socket provider: one UDP socket per interface address on the
    discovery port (role of the raw mcast socket, Spark.h mcastFd_). Used
    by the daemon; tests use the mock mesh."""

    def __init__(self, port: int):
        self.port = port
        self._transports: dict[str, asyncio.DatagramTransport] = {}
        self._if_addrs: dict[str, tuple[str, int]] = {}
        self._inbox: asyncio.Queue[ReceivedPacket] = asyncio.Queue()
        self._peers: dict[str, list[tuple[str, int]]] = {}

    async def add_interface(
        self,
        if_name: str,
        bind_addr: str = "127.0.0.1",
        bind_port: Optional[int] = None,
    ) -> tuple[str, int]:
        loop = asyncio.get_running_loop()
        inbox = self._inbox

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data: bytes, addr) -> None:
                try:
                    packet = deserialize(data, SparkPacket)
                # lint: allow(broad-except) garbage datagrams are normal
                except Exception:
                    return
                inbox.put_nowait(
                    ReceivedPacket(
                        packet=packet,
                        from_if_name=if_name,
                        sender_addr=f"{addr[0]}:{addr[1]}",
                        recv_ts_us=int(time.monotonic() * 1e6),
                        sent_ts_us=0,
                    )
                )

        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(bind_addr, bind_port or 0)
        )
        self._transports[if_name] = transport
        addr = transport.get_extra_info("sockname")[:2]
        self._if_addrs[if_name] = addr
        return addr

    def set_peers(self, if_name: str, peers: list[tuple[str, int]]) -> None:
        """Loopback stand-in for multicast membership: explicit peer list."""
        self._peers[if_name] = peers

    async def send(self, if_name: str, packet: SparkPacket) -> None:
        transport = self._transports.get(if_name)
        if transport is None:
            return
        raw = serialize(packet)
        for addr in self._peers.get(if_name, ()):
            transport.sendto(raw, addr)

    async def recv(self) -> ReceivedPacket:
        return await self._inbox.get()

    def close(self) -> None:
        for t in self._transports.values():
            t.close()
        self._transports.clear()
