"""Monitor + Watchdog actor tests (ref openr/watchdog/Watchdog.h:28-51,
openr/monitor/MonitorBase.h:32)."""

import asyncio
import time

from openr_tpu.config import MonitorConfig, WatchdogConfig
from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.monitor import LogSample, Monitor, Watchdog
from tests.conftest import run_async


class TestMonitor:
    @run_async
    async def test_event_log_retention(self):
        q = ReplicateQueue("logSamples")
        mon = Monitor(
            "node1",
            MonitorConfig(max_event_log_entries=3),
            q.get_reader(),
            interval_s=0.05,
        )
        await mon.start()
        try:
            for i in range(5):
                q.push(LogSample(event=f"EVENT_{i}", node_name="node1"))
            await wait_until(lambda: len(mon.event_logs) == 3)
            logs = await mon.get_event_logs()
            # ring: only the last 3 retained
            assert '"event": "EVENT_4"' in logs[-1]
            assert all("EVENT_0" not in line for line in logs)
        finally:
            await mon.stop()

    @run_async
    async def test_process_gauges_exported(self):
        q = ReplicateQueue("logSamples")
        mon = Monitor("node1", MonitorConfig(), q.get_reader(), interval_s=0.02)
        await mon.start()
        try:
            await wait_until(
                lambda: counters.get_counter("process.memory.rss_mb") is not None
            )
            assert counters.get_counter("process.memory.rss_mb") > 0
            assert counters.get_counter("process.uptime_s") is not None
            # the live gauge and the high-water mark are distinct
            # counters; current can never (meaningfully) exceed peak
            max_rss = counters.get_counter("process.memory.max_rss_mb")
            assert max_rss is not None and max_rss > 0
            assert (
                counters.get_counter("process.memory.rss_mb")
                <= max_rss * 1.05
            )
        finally:
            await mon.stop()

    def test_current_rss_is_live_not_peak(self):
        """ru_maxrss is a high-water mark; the live gauge must come
        from /proc/self/statm and sit at or under the peak."""
        from openr_tpu.runtime.monitor import current_rss_mb, rss_mb

        cur, peak = current_rss_mb(), rss_mb()
        assert cur > 0 and peak > 0
        # small slop: the peak snapshot races the current read
        assert cur <= peak * 1.05, (cur, peak)


class TestWatchdog:
    @run_async
    async def test_fires_on_stalled_actor(self):
        fired = []
        wd = Watchdog(
            "node1",
            # ceiling high enough that suite-wide RSS can't trip it —
            # this test is about stall detection; the memory ceiling
            # has its own test below
            WatchdogConfig(interval_s=0.05, thread_timeout_s=0.2,
                           max_memory_mb=100_000),
            crash_handler=fired.append,
        )
        victim = Actor("victim")
        await victim.start()
        await wd.start()
        try:
            await asyncio.sleep(0.2)
            assert not fired  # healthy heartbeat
            wd.watch_actor(victim)
            # simulate a stall: stop the heartbeat task but keep watching
            await victim.stop()
            victim.last_alive_ts = time.monotonic() - 10
            await wait_until(lambda: fired, timeout_s=3)
            assert "victim" in fired[0]
            assert wd.fired is not None
        finally:
            await wd.stop()

    @run_async
    async def test_memory_ceiling(self):
        fired = []
        wd = Watchdog(
            "node1",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60, max_memory_mb=1),
            crash_handler=fired.append,
        )
        await wd.start()
        try:
            await wait_until(lambda: fired, timeout_s=3)
            assert "memory" in fired[0]
        finally:
            await wd.stop()

    @run_async
    async def test_queue_depth_counters(self):
        wd = Watchdog(
            "node1",
            WatchdogConfig(interval_s=0.05, thread_timeout_s=60,
                           max_memory_mb=100_000),
            crash_handler=lambda reason: None,
        )
        q = ReplicateQueue("testq")
        reader = q.get_reader("r")
        for _ in range(7):
            q.push(1)
        wd.watch_queue(q)
        await wd.start()
        try:
            await wait_until(
                lambda: counters.get_counter("messaging.queue.testq.max_depth")
                == 7
            )
            # per-reader visibility: a wedged reader (depth growing,
            # reads flat) must be observable from the counter fabric
            base = "messaging.queue.testq"
            assert counters.get_counter(f"{base}.replicas") == 1
            assert counters.get_counter(f"{base}.reader.r.depth") == 7
            assert counters.get_counter(f"{base}.reader.r.reads") == 0
            for _ in range(3):
                await reader.get()
            await wait_until(
                lambda: counters.get_counter(f"{base}.reader.r.reads") == 3
            )
            assert counters.get_counter(f"{base}.reader.r.depth") == 4
        finally:
            await wd.stop()


def test_stat_multi_windowed_single_pass():
    """fb303-style multi-window view: nesting (60 within 600 within
    3600), exact aggregates, and the truncation flag when the sample
    ring cannot honor a long window."""
    from openr_tpu.runtime.counters import _Stat

    s = _Stat()
    for i in range(10):
        s.add(float(i))
    out = s.multi_windowed((60.0, 600.0, 3600.0))
    for w in ("60", "600", "3600"):
        assert out[w]["count"] == 10
        assert out[w]["max"] == 9.0
        assert abs(out[w]["avg"] - 4.5) < 1e-9
        assert out[w]["truncated"] is False
    # overflow the ring: long windows flag truncation, a tiny window
    # (whose cutoff is newer than the eviction horizon) does not
    for _ in range(5000):
        s.add(1.0)
    out = s.multi_windowed((0.0, 3600.0))
    assert out["3600"]["truncated"] is True
    assert out["3600"]["count"] == 4096  # ring capacity, not a lie


class TestSloEngine:
    """Burn-rate state machines over the counter fabric (ISSUE 11)."""

    @staticmethod
    def _engine(slos, fast=0.2, slow=0.4, burn=0.5):
        from openr_tpu.runtime.monitor import SloEngine

        cfg = MonitorConfig(
            slos=slos,
            slo_fast_window_s=fast,
            slo_slow_window_s=slow,
            slo_burn_threshold=burn,
        )
        return SloEngine("node-slo", cfg)

    def test_counter_delta_baseline_is_not_retroactive(self):
        src = "slotest.delta.preexisting"
        counters.set_counter(src, 100.0)
        eng = self._engine(
            {"d": {"kind": "counter_delta", "source": src, "threshold": 1.0}}
        )
        # first tick only establishes the baseline: the 100 that
        # predate the engine must not count as a breach
        assert eng.evaluate() == []
        rep = eng.report()["slos"]["d"]
        assert rep["state"] == "ok" and rep["value"] == 0.0
        # a real jump past the threshold burns the (1-sample) window
        counters.set_counter(src, 105.0)
        alerts = eng.evaluate()
        assert [a["slo"] for a in alerts] == ["d"]
        assert alerts[0]["value"] == 5.0
        assert eng.report()["slos"]["d"]["state"] == "fast_burn"
        # sub-threshold drift keeps breach fraction falling, not rising
        counters.set_counter(src, 105.5)
        eng.evaluate()
        assert eng.report()["slos"]["d"]["value"] == 0.5

    def test_stat_quantile_breach_and_empty_window(self):
        src = "slotest.stat.latency_ms"
        eng = self._engine(
            {"s": {"kind": "stat", "source": src, "threshold": 10.0,
                   "quantile": "p50"}},
            fast=60.0, slow=60.0,
        )
        # no samples at all: no breach, value 0
        assert eng.evaluate() == []
        assert eng.report()["slos"]["s"]["state"] == "ok"
        for v in (50.0, 60.0, 70.0):
            counters.add_stat_value(src, v)
        alerts = eng.evaluate()
        assert [a["slo"] for a in alerts] == ["s"]
        rep = eng.report()["slos"]["s"]
        assert rep["state"] == "fast_burn" and rep["value"] > 10.0

    def test_gauge_duration_escalates_then_deasserts(self):
        src = "slotest.gauge.degraded"
        counters.set_counter(src, 0.0)
        eng = self._engine(
            {"g": {"kind": "gauge_duration", "source": src,
                   "threshold": 0.0}}
        )
        assert eng.evaluate() == []  # clean tick
        counters.set_counter(src, 1.0)
        alerts = eng.evaluate()  # breach tick: 1/1 fast samples burn
        assert [a["slo"] for a in alerts] == ["g"]
        assert counters.get_counter("monitor.slo.g.alerts") >= 1
        assert counters.get_counter("monitor.slo.g.burning") == 1.0
        time.sleep(0.05)
        assert eng.evaluate() == []  # escalation is NOT a new page
        rep = eng.report()["slos"]["g"]
        assert rep["state"] == "sustained_burn", rep
        assert counters.get_counter("monitor.slo.g.burning") == 2.0
        # recovery: gauge clears, the fast window drains past the 2x
        # hysteresis, the state machine de-asserts without a page
        counters.set_counter(src, 0.0)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            assert eng.evaluate() == []
            if eng.report()["slos"]["g"]["state"] == "ok":
                break
            time.sleep(0.05)
        rep = eng.report()["slos"]["g"]
        assert rep["state"] == "ok", rep
        assert counters.get_counter("monitor.slo.g.burning") == 0.0
        assert rep["alerts"] == 1  # the whole episode paged exactly once


class TestFlightRecorder:
    @staticmethod
    def _recorder(tmp, **kw):
        from openr_tpu.runtime.monitor import FlightRecorder

        defaults = dict(
            flight_recorder_dir=tmp,
            flight_recorder_ring=4,
            flight_recorder_min_interval_s=60.0,
        )
        defaults.update(kw)
        return FlightRecorder("node-fr", MonitorConfig(**defaults))

    def test_trigger_writes_bundle_rate_limits_and_forces(self, tmp_path):
        import json as _json
        import os

        fr = self._recorder(str(tmp_path))
        for _ in range(10):
            fr.record_tick()
        fr.note_event("SOMETHING_ODD", {"n": 1})
        sup0 = counters.get_counter(
            "monitor.flight_recorder.suppressed") or 0
        r1 = fr.trigger("unit_test", detail={"why": "drill"})
        assert r1 is not None and r1["reason"] == "unit_test"
        doc = _json.load(open(os.path.join(r1["path"], "bundle.json")))
        assert doc["schema"] == "openr-tpu-flight-recorder/1"
        assert doc["node"] == "node-fr"
        assert doc["trigger"]["detail"] == {"why": "drill"}
        # ring bound holds even after 10 ticks
        assert len(doc["counter_history"]) == 4
        assert any(e["event"] == "SOMETHING_ODD" for e in doc["events"])
        assert os.path.exists(os.path.join(r1["path"], "trace.json"))
        # second auto trigger inside the interval is suppressed...
        assert fr.trigger("unit_test_again") is None
        assert (counters.get_counter("monitor.flight_recorder.suppressed")
                > sup0)
        # ...but a manual dump bypasses the limit
        r3 = fr.trigger("manual", force=True)
        assert r3 is not None
        assert [b["reason"] for b in fr.bundles] == ["unit_test", "manual"]

    def test_write_failure_is_counted_not_raised(self, tmp_path):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("file in the way")
        fr = self._recorder(str(blocker / "sub"))
        errs0 = counters.get_counter(
            "monitor.flight_recorder.write_errors") or 0
        assert fr.trigger("doomed", force=True) is None
        assert (counters.get_counter("monitor.flight_recorder.write_errors")
                == errs0 + 1)
        assert list(fr.bundles) == []


class TestMonitorObservability:
    @run_async
    async def test_trigger_events_map_to_bundles_and_manual_dump(
        self, tmp_path
    ):
        q = ReplicateQueue("logSamplesObs")
        mon = Monitor(
            "node-obs",
            MonitorConfig(
                slos={},  # engine off: slo_report must say so
                enable_fleet_health=False,
                flight_recorder_dir=str(tmp_path),
                flight_recorder_min_interval_s=60.0,
            ),
            q.get_reader(),
            interval_s=0.05,
        )
        assert mon.slo_engine is None and mon.flight_recorder is not None
        await mon.start()
        try:
            rep = mon.slo_report()
            assert rep["enabled"] is False and rep["slos"] == {}
            # an anomaly LogSample auto-triggers with attribution
            q.push(LogSample(
                event="DECISION_SENTINEL_ANOMALY",
                node_name="node-obs",
                values={"category": "sentinel", "metric": "spf_ms"},
            ))
            await wait_until(
                lambda: any(
                    b["reason"] == "sentinel_anomaly"
                    for b in mon.flight_recorder.bundles
                )
            )
            # a second trigger event inside the rate window is noted
            # (supervisor category) but writes no second bundle
            q.push(LogSample(
                event="SUPERVISOR_RESTART",
                node_name="node-obs",
                values={"category": "supervisor", "task": "t"},
            ))
            await wait_until(
                lambda: any(
                    e["event"] == "SUPERVISOR_RESTART"
                    for e in mon.flight_recorder._events
                )
            )
            assert len(mon.flight_recorder.bundles) == 1
            # the operator's manual dump bypasses the rate limit
            res = await mon.dump_flight_recorder(reason="manual-drill")
            assert res["ok"] is True and res["reason"] == "manual-drill"
            assert len(mon.flight_recorder.bundles) == 2
        finally:
            await mon.stop()

    @run_async
    async def test_dump_without_recorder_reports_error(self):
        q = ReplicateQueue("logSamplesObs2")
        mon = Monitor(
            "node-obs2",
            MonitorConfig(
                enable_flight_recorder=False, enable_fleet_health=False
            ),
            q.get_reader(),
        )
        assert mon.flight_recorder is None
        res = await mon.dump_flight_recorder()
        assert res["ok"] is False and "error" in res
