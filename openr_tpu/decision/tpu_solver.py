"""TPU route-computation backend — the project's differentiator.

Replaces the reference's per-root memoized Dijkstra + per-prefix scalar
loops (openr/decision/LinkState.cpp:836-911 runSpf + SpfSolver.cpp:460-646
buildRouteDb) with one fused, jit-compiled pipeline over the shift-
decomposed graph mirror (ops/edgeplan.py):

  1. Batched SSSP from the root's D out-slot neighbors in G-minus-root:
     frontier-synchronous Bellman-Ford where each relaxation is a sum of
     **shift-class contributions** `roll(dist + w_class, delta)` (VPU-
     vectorized; no gather for shift-decomposable edges) plus a residual
     ELL gather for irregular edges. Root-as-transit exclusion is ONE
     on-device column mask, so the resident graph arrays serve every
     vantage (any-vantage ctrl queries reuse them).
  2. Via-distances give true distances and first-hop slots in one shot:
     via[d,v] = root_w[d] + dist_d[v]; slot d is on a shortest path to v
     iff via[d,v] == min_d via[d,v] — the same ECMP predicate as runSpf's
     `>=` accumulation (LinkState.cpp:885-901) without a second fixpoint.
  3. Vectorized best-route selection over the prefix x announcer matrix
     in the reference's order (path_preference desc, source_preference
     desc, advertised distance asc — LsdbUtil.cpp:842), drained-announcer
     filter with all-drained fallback (SpfSolver.cpp:709-731), min-IGP
     announcer set, union of their first-hop masks.
  4. **On-device output delta**: results (metric / selected-announcer
     bits / next-hop-slot bits, 16-bit word-packed) are compared on
     device against the previous run's resident outputs; only changed
     rows ship to the host (fixed delta budget, full pull fallback).
     Steady-state link flaps therefore cost O(changed routes) in host
     transfer + materialization — the TPU-idiomatic "incremental SPF":
     recompute everything fast on device, ship and materialize only the
     delta (ref incremental path: openr/decision/Decision.cpp:919-996).

Graph updates ride LinkState's changelog as device scatter writes
(ops/edgeplan.py apply_events / drain_dirty) — a metric flap is a
handful of int32 stores, not a mirror rebuild.

Scope: single-area LSDBs with IP/SP_ECMP prefixes (with optional LFA
backup next-hops) run the fused device pipeline; KSP2 (SR_MPLS +
KSP2_ED_ECMP) prefixes are device-ASSISTED — the per-destination
masked second-pass SSSPs batch on device (ops/ksp2.py) while the
oracle's selection/trace/label assembly stays host-side, primed through
the k-paths cache. UCMP prefixes are likewise device-assisted: the
leaf-to-root weight propagation (ref LinkState.cpp:913-1033) runs as a
masked segment-sum fixpoint over the device SSSP field (ops/ucmp.py,
installed as the oracle's ucmp_resolver via _UcmpAccel), with the
root-local per-interface grouping and gcd normalization on host.
What remains host-only, deliberately:
  - cross-area-announced prefixes: selection and the min-metric
    next-hop union are global across areas; these go to the oracle.
    Multi-area LSDBs otherwise run on device — a prefix announced in
    exactly ONE area (the overwhelmingly common case: loopbacks) is
    dispatched to that area's per-area pipeline, whose answer equals
    the global one because other areas' reachability filters remove
    nothing from its announcer set.
Behavior is identical by construction and enforced by differential
tests (tests/test_tpu_solver.py, test_lfa.py, test_ksp2.py). MPLS label
routes are host-built (they are O(adjacent links), not hot).
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

from openr_tpu.decision.columnar_rib import (
    ColumnarRib,
    LazyUnicastRoutes,
)
from openr_tpu.decision.link_state import LinkState, NodeUcmpResult
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.runtime import affinity
from openr_tpu.runtime.counters import counters
from openr_tpu.ops.csr import (
    INF32,
    EllGraph,
    PrefixMatrix,
    build_prefix_matrix,
)
from openr_tpu.ops.edgeplan import (
    INF32E,
    EdgePlan,
    drain_dirty,
    sync_plan,
)
from openr_tpu.ops import relax as relax_ops
from openr_tpu.ops.xla_cache import bounded_jit_cache, retrace
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)

INF = int(INF32)
INF_E = int(INF32E)
_NEG = -(2**31)

# rows shipped per delta pull; bursts changing more fall back to a full
# pull (one extra round trip, still a single buffer)
_DELTA_BUDGET = 4096

# relaxation steps fused per while_loop trip (steps past the fixpoint are
# no-ops; fusing amortizes per-trip dispatch) — owned by ops/relax.py
_UNROLL = relax_ops.UNROLL

# numerical-health sentinel threshold: finite metrics past 2^28 sit one
# metric-add away from the 2^29 INF_E encoding — saturation territory
# the int32 metric algebra cannot flag on its own
_SENTINEL_SAT = 1 << 28

# incremental-solve dirty buffers pad to one of these sizes (shared by
# the shift and residual buffers): pow4 steps bound the number of
# executable shape classes per fabric to 4, so dirty-set churn settles
# into a handful of incr-namespace buckets instead of thrashing them.
# Larger merged dirty sets fall back to the full solve on host.
_DIRTY_BUCKETS = (64, 256, 1024, 4096)


def _dirty_bucket(n: int) -> Optional[int]:
    for b in _DIRTY_BUCKETS:
        if n <= b:
            return b
    return None


def _merge_drain_log(ad: "_AreaDev", since_epoch: int):
    """Merge the area's drain journal entries newer than `since_epoch`
    into ({shift_flat: old}, {res_flat: old}) maps carrying each dirty
    slot's weight AS OF since_epoch (the epoch of the vantage's
    resident distance plane). Returns None when the window cannot be
    reconstructed — a journal gap (deque overflow), a reset marker
    (mirror rebuild / residual-layout change), or a missing epoch —
    in which case the caller falls back to the full solve."""
    if ad.drain_epoch == since_epoch:
        return {}, {}
    s_map: dict = {}
    r_map: dict = {}
    expected = since_epoch + 1
    for epoch, s_d, r_d in ad.drain_log:
        if epoch <= since_epoch:
            continue
        if epoch != expected or s_d is None:
            return None
        for f, old in s_d.items():
            s_map.setdefault(f, old)
        for f, old in r_d.items():
            r_map.setdefault(f, old)
        expected += 1
    if expected != ad.drain_epoch + 1:
        return None
    return s_map, r_map


def _ucmp_weight_anomalies(w) -> int:
    """Count numerically-unhealthy entries in a UCMP weight field:
    non-finite (NaN/inf) values for float dtypes — a diverged fixpoint —
    and negative values for signed-int dtypes (int32 wraparound that
    slipped past propagate's overflow guard). Unsigned ints cannot
    express either failure mode."""
    arr = np.asarray(w)
    if arr.dtype.kind == "f":
        return int((~np.isfinite(arr)).sum())
    if arr.dtype.kind == "i":
        return int((arr < 0).sum())
    return 0


# ---------------------------------------------------------------------------
# legacy single-graph kernels (driver entry / sharding / whole-fabric path)
# ---------------------------------------------------------------------------

def _sssp_kernel(in_nbr, in_w, in_up, node_over, root):
    """dist[v] fixpoint over the padded in-neighbor mirror; int32 [N_cap]."""
    import jax
    import jax.numpy as jnp

    n = in_nbr.shape[0]
    dist0 = jnp.full((n,), INF, jnp.int32).at[root].set(0)
    usable = in_up & (in_nbr >= 0) & ((in_nbr == root) | ~node_over[in_nbr])

    def relax(dist):
        nbr_dist = dist[in_nbr]
        cand = jnp.where(
            usable & (nbr_dist < INF), nbr_dist + in_w, INF
        ).min(axis=1)
        return jnp.minimum(dist, cand)

    dist, _, _ = relax_ops.run_sync(relax, dist0, relax_ops.max_trips(n))
    return dist


def _next_hop_kernel(in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up):
    """First-hop slot masks nh[v, d] over the shortest-path DAG."""
    import jax
    import jax.numpy as jnp

    n, _ = in_nbr.shape
    d_cap = root_nbr.shape[0]
    slot_ok = (root_nbr >= 0) & root_up & (dist[jnp.clip(root_nbr, 0, n - 1)] == root_w)
    seed = jnp.zeros((n, d_cap), bool).at[
        jnp.where(root_nbr >= 0, root_nbr, n), jnp.arange(d_cap)
    ].set(slot_ok, mode="drop")
    ok_parent = (
        in_up
        & (in_nbr >= 0)
        & (in_nbr != root)
        & ~node_over[in_nbr]
        & (dist[in_nbr] < INF)
        & (dist[in_nbr] + in_w == dist[:, None])
    )

    def step(nh):
        prop = jnp.any(ok_parent[:, :, None] & nh[in_nbr], axis=1)
        return seed | prop

    nh, _, _ = relax_ops.run_sync(step, seed, relax_ops.max_trips(n))
    return nh


def _select_metric_kernel(dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Vectorized per-prefix best-route selection (no next-hop union);
    shared with the sharded step so the selection semantics exist once."""
    import jax.numpy as jnp

    n = dist.shape[0]
    idx = jnp.clip(ann_node, 0, n - 1)
    ann_dist = dist[idx]
    reach = ann_valid & (ann_dist < INF)
    pp = jnp.where(reach, path_pref, _NEG)
    s = reach & (pp == pp.max(axis=1, keepdims=True))
    sp = jnp.where(s, source_pref, _NEG)
    s = s & (sp == sp.max(axis=1, keepdims=True))
    da = jnp.where(s, dist_adv, INF)
    s2 = s & (da == da.min(axis=1, keepdims=True))
    nd = s2 & ~node_over[idx]
    s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
    igp = jnp.where(s3, ann_dist, INF)
    metric = igp.min(axis=1)
    s4 = s3 & (igp == metric[:, None])
    return metric, s3, s4, idx


def _select_kernel(dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Selection + next-hop union."""
    import jax.numpy as jnp

    metric, s3, s4, idx = _select_metric_kernel(
        dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
    )
    nh_mask = jnp.any(s4[:, :, None] & nh[idx], axis=1)
    has_route = s3.any(axis=1) & (metric < INF)
    return metric, s3, nh_mask, has_route


@bounded_jit_cache()
def _jitted_pipeline():
    import jax

    def pipeline(
        in_nbr, in_w, in_up, node_over,
        root, root_nbr, root_w, root_up,
        ann_node, ann_valid, path_pref, source_pref, dist_adv,
    ):
        dist = _sssp_kernel(in_nbr, in_w, in_up, node_over, root)
        nh = _next_hop_kernel(
            in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up
        )
        metric, s3, nh_mask, has_route = _select_kernel(
            dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
        )
        return dist, metric, s3, nh_mask, has_route

    return jax.jit(pipeline)


@bounded_jit_cache()
def _jitted_sssp_batch():
    import jax

    return jax.jit(
        jax.vmap(_sssp_kernel, in_axes=(None, None, None, None, 0))
    )


def sssp_all_pairs(graph: EllGraph, roots: Optional[np.ndarray] = None):
    """Batched SSSP from many roots — [R, N_cap] int32 distances."""
    import jax

    if roots is None:
        roots = np.arange(graph.n_nodes, dtype=np.int32)
    fn = _jitted_sssp_batch()
    args = jax.device_put(
        [
            graph.in_nbr,
            graph.in_w,
            graph.in_up,
            graph.node_overloaded,
            roots.astype(np.int32),
        ]
    )
    return fn(*args)


# ---------------------------------------------------------------------------
# plan pipeline (the production path)
# ---------------------------------------------------------------------------

def _pack_words(bits):
    """bool [P, X] -> int32 [P, ceil(X/16)], 16 bits per word."""
    import jax.numpy as jnp

    p, x = bits.shape
    w = -(-x // 16)
    pad = w * 16 - x
    if pad:
        bits = jnp.pad(bits, ((0, 0), (0, pad)))
    weights = (1 << jnp.arange(16, dtype=jnp.int32))
    return (bits.reshape(p, w, 16).astype(jnp.int32) * weights).sum(axis=2)


def _plan_sssp(deltas, shift_w, res_rows, res_nbr, res_w, root,
               seeds_nbr, seeds_w,
               s_cap: int, has_res: bool, n_cap: int, d_cap: int,
               max_trips: int, kernel: str = "sync",
               delta_exp: int = 0):
    """Batched SSSP [D, N] from seed nodes in G-minus-root over the
    shift-decomposed mirror (relaxation bodies live in ops/relax.py —
    `kernel` selects sync rounds or the bucketed Δ-stepping epochs).
    INF discipline: INF32E = 2^29, weights <= 2^28, so `dist + w` is
    overflow-free and needs no masks. The residual gather is
    row-compact: it touches only destinations with irregular in-edges
    and scatter-mins them back."""
    import jax.numpy as jnp

    sw = shift_w.at[:, root].set(INF_E)
    residual = None
    if has_res:
        rw = jnp.where(res_nbr == root, INF_E, res_w)
        nbr_c = jnp.clip(res_nbr, 0, n_cap - 1)
        rows_c = jnp.clip(res_rows, 0, n_cap - 1)
        # pad rows (res_rows == -1) carry all-INF weights -> no-ops
        residual = (rows_c, nbr_c, rw)
    valid = seeds_w < INF_E
    seed_idx = jnp.clip(seeds_nbr, 0, n_cap - 1)
    dist0 = jnp.full((d_cap, n_cap), INF_E, jnp.int32)
    dist0 = dist0.at[jnp.arange(d_cap), seed_idx].min(
        jnp.where(valid, 0, INF_E).astype(jnp.int32)
    )

    relax = relax_ops.make_relax(
        deltas, s_cap, lambda k: sw[k], residual=residual
    )
    if kernel == "bucketed":
        return relax_ops.run_bucketed(
            relax, dist0, deltas, sw, lambda k: sw[k],
            n_cap, s_cap, delta_exp,
        )
    dist, trips, rounds = relax_ops.run_sync(relax, dist0, max_trips)
    return dist, trips, rounds


def _make_pipeline(n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
                   has_res: bool,
                   d_cap: int, p_cap: int, a_cap: int, budget: int,
                   lfa: bool = False, block_v4: bool = False,
                   sentinels: bool = True, emit_dist: bool = False,
                   incr: bool = False, mesh=None,
                   kernel: str = "sync", delta_exp: int = 0,
                   stream: int = 0):
    """The fused production pipeline (raw closure — _plan_pipeline jits
    it for the single-area path, _fused_pipeline vmaps it over a group
    of same-shape areas). Outputs:
      delta_buf int32 [2 + B + B + B*wa + B*wd (+ 2B with lfa)]: count,
                trips, idx, metric, s3 words, nh words (and lfa slot +
                metric) for up to B changed rows
      full_buf  int32 [2 + P * (2 + wa + wd (+2 with lfa))]: DEVICE-
                COMPACTED cold-rebuild pull — ok-row count, trips, the
                ok row indices (route-level filter computed on device,
                ops/compact.route_ok_device), then the packed outputs
                GATHERED to those rows. The host scatters them straight
                into ColumnarRib columns without an O(P*A) filter pass.
      metric, s3w, nhw, lfa_slot, lfa_metric: resident arrays (the next
                call's prev_*; lfa arrays are passthrough when lfa=False)
      dist_d (emit_dist): the [D, N] SSSP plane, kept resident as the
                next incremental solve's warm seed.

    With `incr=True` the pipeline takes six extra trailing args
    (prev_dist, s_dirty_idx, s_dirty_old, r_dirty_idx, r_dirty_old,
    cone_limit) and swaps the cold SSSP for ops/incremental.py's
    seed-from-previous solve; [cone, fell_back] ride the tail of both
    pull buffers AFTER the sentinel scalars. The incremental fixpoint
    is bit-identical to the cold one, so the ENTIRE selection / LFA /
    packing / delta tail below is shared verbatim between the two
    kernels — output parity by construction.

    With `mesh` (the multichip capacity tier) the SSSP core swaps for
    parallel/sharding.py's shard_mapped twins — shift columns over
    'graph', vantage lanes over 'batch' — and the distance plane is
    re-replicated before the selection tail, which the partitioner
    handles fine (it is only the SSSP's dynamic roll it miscompiles;
    see make_mc_sssp). Fixpoint uniqueness keeps the output
    bit-identical to the single-chip tier.

    With `stream` nonzero (a STREAM_BUDGETS bucket) this is the
    streaming-epoch kernel (jit-cache namespace "stream"): the delta
    payload uses the small bucketed budget instead of the classic
    _DELTA_BUDGET and carries the device route-ok bit per changed row
    (ops/stream.py layout), so the host applies the rows without
    unpacking words. The changed mask and compaction are the SAME
    ops/stream.py stages the classic delta path runs — parity by
    construction.
    """
    import jax
    import jax.numpy as jnp

    from openr_tpu.ops.compact import route_ok_device
    from openr_tpu.ops.incremental import incremental_sssp
    from openr_tpu.ops.stream import column_diff, compact_changed_rows

    wa = -(-a_cap // 16)
    wd = -(-d_cap // 16)
    pa = p_cap * a_cap
    max_trips = relax_ops.max_trips(n_cap)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        from openr_tpu.parallel.sharding import (
            make_mc_incremental_sssp, make_mc_sssp,
        )

        mc_rep = NamedSharding(mesh, PartitionSpec())
        if incr:
            mc_sssp_incr = make_mc_incremental_sssp(
                mesh, s_cap, has_res, n_cap, d_cap, max_trips,
                kernel, delta_exp,
            )
        else:
            mc_sssp = make_mc_sssp(
                mesh, s_cap, has_res, n_cap, d_cap, max_trips,
                kernel, delta_exp,
            )

    def pipeline(deltas, shift_w, res_rows, res_nbr, res_w, mbuf,
                 root, root_nbr, root_w,
                 prev_metric, prev_s3w, prev_nhw,
                 prev_lfa_slot, prev_lfa_metric, *incr_args):
        o = 0
        ann_node = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        ann_flags = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        path_pref = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        source_pref = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        dist_adv = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        min_nh = mbuf[o:o + pa].reshape(p_cap, a_cap); o += pa
        ann_valid = (ann_flags & 1).astype(bool)
        ann_over = (ann_flags & 2).astype(bool)
        # per-prefix v4 bit rides flag bit 2 of announcer slot 0
        v4_blocked = (
            (ann_flags[:, 0] & 4).astype(bool)
            if block_v4
            else jnp.zeros((p_cap,), bool)
        )

        if incr:
            (prev_dist, s_dirty_idx, s_dirty_old,
             r_dirty_idx, r_dirty_old, cone_limit) = incr_args
            if mesh is not None:
                dist_d, trips_v, cone_v, fell_v, rounds_v = mc_sssp_incr(
                    deltas, shift_w, res_rows, res_nbr, res_w, root,
                    root_nbr, root_w, prev_dist,
                    s_dirty_idx, s_dirty_old, r_dirty_idx, r_dirty_old,
                    cone_limit,
                )
                trips = trips_v.max()
                rounds = rounds_v.max()
                cone, fell_back = cone_v[0], fell_v[0]
            else:
                dist_d, trips, cone, fell_back, rounds = incremental_sssp(
                    deltas, shift_w, res_rows, res_nbr, res_w, root,
                    root_nbr, root_w, prev_dist,
                    s_dirty_idx, s_dirty_old, r_dirty_idx, r_dirty_old,
                    cone_limit,
                    s_cap, has_res, n_cap, d_cap, max_trips,
                    kernel, delta_exp,
                )  # [D, N]
        else:
            if mesh is not None:
                dist_d, trips_v, rounds_v = mc_sssp(
                    deltas, shift_w, res_rows, res_nbr, res_w, root,
                    root_nbr, root_w,
                )
                trips = trips_v.max()
                rounds = rounds_v.max()
            else:
                dist_d, trips, rounds = _plan_sssp(
                    deltas, shift_w, res_rows, res_nbr, res_w, root,
                    root_nbr, root_w,
                    s_cap, has_res, n_cap, d_cap, max_trips,
                    kernel, delta_exp,
                )  # [D, N]
        if mesh is not None:
            # the resident copy stays lane-sharded (out_shardings pins
            # it); the selection tail reads a replicated copy so the
            # partitioner never touches a sharded gather axis
            dist_res = dist_d
            dist_d = jax.lax.with_sharding_constraint(dist_d, mc_rep)
        via = root_w[:, None] + dist_d  # <= 2^30, overflow-free
        dist = jnp.minimum(via.min(axis=0), INF_E).at[root].set(0)  # [N]

        # selection (reference order; drain via flags)
        idx = jnp.clip(ann_node, 0, n_cap - 1)
        ann_dist = dist[idx]
        reach = ann_valid & (ann_dist < INF_E)
        pp = jnp.where(reach, path_pref, _NEG)
        s = reach & (pp == pp.max(axis=1, keepdims=True))
        sp = jnp.where(s, source_pref, _NEG)
        s = s & (sp == sp.max(axis=1, keepdims=True))
        da = jnp.where(s, dist_adv, INF_E)
        s2 = s & (da == da.min(axis=1, keepdims=True))
        nd = s2 & ~ann_over
        s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
        igp = jnp.where(s3, ann_dist, INF_E)
        metric = igp.min(axis=1)
        s4 = s3 & (igp == metric[:, None])

        on_sp = (via == dist[None, :]).T  # [N, D]
        nh_mask = jnp.any(s4[:, :, None] & on_sp[idx], axis=1)  # [P, D]

        if lfa:
            # rfc5286 loop-free alternates from the SAME per-slot distance
            # fields: slot d is a valid backup for prefix row p iff its
            # neighbor's own distance to the selected announcer set
            # (min over s3 of dist_d) beats detouring back through the
            # root (dist_d[root] + route metric). Strict < guarantees no
            # micro-loop. One [P, A, D] row-gather — the same shape the
            # ECMP predicate's on_sp[idx] gather already pays.
            d_root = dist_d[:, root]  # [D] neighbor -> root distance
            ann_nd = dist_d.T[idx]  # [P, A, D]
            nbr_pd = jnp.where(
                s3[:, :, None], ann_nd, INF_E
            ).min(axis=1)  # [P, D]
            link_up = root_w < INF_E
            ok_lfa = (
                link_up[None, :]
                & ~nh_mask
                & (nbr_pd < INF_E)  # neighbor actually reaches the prefix
                & (nbr_pd < d_root[None, :] + metric[:, None])
            )
            # alternate cost <= 2^29 + 2^28 < the 2^30 mask fill
            alt = jnp.where(
                ok_lfa, root_w[None, :] + nbr_pd, jnp.int32(1 << 30)
            )
            has_lfa = ok_lfa.any(axis=1)
            # argmin returns the FIRST minimum: lowest slot breaks ties,
            # matching the oracle's ordered-link iteration
            lfa_slot = jnp.where(
                has_lfa, jnp.argmin(alt, axis=1).astype(jnp.int32), -1
            )
            lfa_metric = jnp.where(has_lfa, alt.min(axis=1), 0)
        else:
            lfa_slot = prev_lfa_slot
            lfa_metric = prev_lfa_metric

        s3w = _pack_words(s3)
        nhw = _pack_words(nh_mask)

        # route-level ok computed on device: compacts the cold full
        # pull to ok rows, and on the streaming path rides the delta
        # payload per changed row (the host apply is then unpack-free)
        ok = route_ok_device(
            metric, s3, nh_mask, ann_node, min_nh, v4_blocked, root,
        )
        changed = column_diff(
            metric, s3w, nhw, lfa_slot, lfa_metric,
            prev_metric, prev_s3w, prev_nhw,
            prev_lfa_slot, prev_lfa_metric, lfa,
        )
        count, delta_parts = compact_changed_rows(
            changed, trips, metric, s3w, nhw,
            ok if stream else None,
            lfa_slot, lfa_metric, stream or budget, p_cap, lfa,
        )
        # cold-rebuild compaction: only ok rows' outputs ship (gathered
        # to the front — pad slots past okc carry the last ok row's
        # values and are ignored)
        okc = ok.sum().astype(jnp.int32)
        oidx = jnp.nonzero(ok, size=p_cap, fill_value=p_cap)[0]
        osafe = jnp.clip(oidx, 0, p_cap - 1).astype(jnp.int32)
        full_parts = [
            okc[None],
            trips[None].astype(jnp.int32),
            oidx.astype(jnp.int32),
            metric[osafe],
            s3w[osafe].ravel(),
            nhw[osafe].ravel(),
        ]
        if lfa:
            # delta-side lfa columns already rode compact_changed_rows
            full_parts += [lfa_slot[osafe], lfa_metric[osafe]]
        if sentinels:
            # numerical-health sentinels: two scalar reductions riding
            # the tail of BOTH pull buffers (free — the pull happens
            # anyway). unreachable = rows with a live announcer but no
            # finite metric; saturated = finite metrics past 2^28,
            # within one metric-add of the 2^29 INF_E encoding — the
            # overflow precursor the encoding cannot represent failing.
            unreach = (
                (ann_valid.any(axis=1) & (metric >= INF_E))
                .sum()
                .astype(jnp.int32)
            )
            saturated = (
                ((metric < INF_E) & (metric > _SENTINEL_SAT))
                .sum()
                .astype(jnp.int32)
            )
            delta_parts += [unreach[None], saturated[None]]
            full_parts += [unreach[None], saturated[None]]
        if incr:
            # cone + in-kernel-fallback flag (the host parses the tail
            # back to front: [-3]=cone, [-2]=fell_back, with the
            # sentinels at [-5]/[-4] when enabled, rounds always at [-1])
            tail = [cone[None], fell_back.astype(jnp.int32)[None]]
            delta_parts += tail
            full_parts += tail
        # executed-relaxation work metric rides LAST unconditionally:
        # sync rounds = trips * UNROLL; bucketed rounds = ladder passes
        # + one handoff relaxation per bucket epoch (trips = epochs)
        delta_parts += [rounds[None].astype(jnp.int32)]
        full_parts += [rounds[None].astype(jnp.int32)]
        delta_buf = jnp.concatenate(delta_parts)
        full_buf = jnp.concatenate(full_parts)
        if mesh is not None:
            # pin BOTH pull buffers replicated: on small shape classes
            # GSPMD re-partitions the short concatenate and emits an
            # unreduced partial-sum over 'graph' (every element times
            # the axis size — same artifact family as the dynamic-roll
            # miscompile make_mc_sssp documents). The out_shardings pin
            # alone does not reach back through the concatenate.
            delta_buf = jax.lax.with_sharding_constraint(delta_buf, mc_rep)
            full_buf = jax.lax.with_sharding_constraint(full_buf, mc_rep)
        outs = (delta_buf, full_buf, metric, s3w, nhw, lfa_slot,
                lfa_metric)
        if emit_dist:
            outs += (dist_res if mesh is not None else dist_d,)
        return outs

    return pipeline


@bounded_jit_cache()
def _plan_pipeline(n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
                   has_res: bool,
                   d_cap: int, p_cap: int, a_cap: int, budget: int,
                   lfa: bool = False, block_v4: bool = False,
                   sentinels: bool = True, emit_dist: bool = False,
                   kernel: str = "sync", delta_exp: int = 0):
    import jax

    return jax.jit(_make_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, lfa, block_v4, sentinels, emit_dist,
        kernel=kernel, delta_exp=delta_exp,
    ))


@bounded_jit_cache(namespace="incr")
def _incr_pipeline(n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
                   has_res: bool,
                   d_cap: int, p_cap: int, a_cap: int, budget: int,
                   dirty_cap: int, lfa: bool = False,
                   block_v4: bool = False, sentinels: bool = True,
                   kernel: str = "sync", delta_exp: int = 0):
    """Incremental-solve executable. `dirty_cap` is the quantized pad
    size of BOTH dirty buffers — part of the capacity signature so
    dirty-set shape churn buckets under the `incr` namespace and can
    never evict the full-solve or what-if executables. Always emits the
    distance plane (it is the next solve's warm seed)."""
    import jax

    return jax.jit(_make_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, lfa, block_v4, sentinels, emit_dist=True, incr=True,
        kernel=kernel, delta_exp=delta_exp,
    ))


@bounded_jit_cache()
def _fused_pipeline(g: int, n_cap: int, s_cap: int, r_cap: int,
                    kr_cap: int, has_res: bool,
                    d_cap: int, p_cap: int, a_cap: int, budget: int,
                    lfa: bool, block_v4: bool, sentinels: bool,
                    kernel: str = "sync", delta_exp: int = 0):
    """`g` same-shape areas in ONE device dispatch: each of the 14
    pipeline inputs arrives as a g-tuple of per-area arrays (a pytree —
    still one dispatch), stacks inside the jit, and vmaps through the
    raw pipeline. Per-call dispatch overhead is paid once for the whole
    group instead of per area; the while_loop trip count becomes the
    max across the group (extra trips past a lane's fixpoint are
    no-ops). Outputs unstack back to per-area tuples so the existing
    per-area materialization consumes them unchanged."""
    import jax
    import jax.numpy as jnp

    raw = _make_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, lfa, block_v4, sentinels,
        kernel=kernel, delta_exp=delta_exp,
    )

    def fused(*area_args):
        stacked = [jnp.stack(xs) for xs in area_args]
        outs = jax.vmap(raw)(*stacked)
        return tuple(tuple(o[i] for o in outs) for i in range(g))

    return jax.jit(fused)


@bounded_jit_cache()
def _instrumented_fused(
    g: int, n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
    has_res: bool, d_cap: int, p_cap: int, a_cap: int, budget: int,
    lfa: bool, block_v4: bool, sentinels: bool,
    kernel: str = "sync", delta_exp: int = 0,
) -> tuple:
    """(kernel name, instrumented callable) for a fused group shape —
    the fused analogue of _instrumented_pipeline."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline_fused[g={g},n={n_cap},s={s_cap},d={d_cap},"
        f"p={p_cap},a={a_cap}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _fused_pipeline(
        g, n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, lfa, block_v4, sentinels, kernel, delta_exp,
    )
    # the AOT key carries EVERY factory arg: the display name above
    # omits r_cap/kr_cap/budget and the block/sentinel flags, and two
    # variants must never alias one serialized executable
    aot_key = repr((
        "fused", g, n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, lfa, block_v4, sentinels, kernel, delta_exp,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


@bounded_jit_cache()
def _instrumented_pipeline(
    n_cap: int, s_cap: int, r_cap: int, kr_cap: int, has_res: bool,
    d_cap: int, p_cap: int, a_cap: int, budget: int,
    lfa: bool, block_v4: bool, sentinels: bool,
    emit_dist: bool = False,
    kernel: str = "sync", delta_exp: int = 0,
) -> tuple:
    """(kernel name, instrumented callable) for a pipeline shape class.
    The wrapper AOT-compiles on first call, recording compile time +
    XLA cost_analysis into the kernel ledger (ops/xla_cache.ledger) so
    ctrl.tpu.kernels can report estimated vs achieved throughput.
    lru-cached on the same key as _plan_pipeline: one wrapper instance
    per shape class keeps the compile-once state stable."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline[n={n_cap},s={s_cap},d={d_cap},p={p_cap},a={a_cap}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _plan_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, lfa, block_v4, sentinels, emit_dist,
        kernel, delta_exp,
    )
    aot_key = repr((
        "full", n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, lfa, block_v4, sentinels, emit_dist, kernel,
        delta_exp,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


@bounded_jit_cache(namespace="incr")
def _instrumented_incr(
    n_cap: int, s_cap: int, r_cap: int, kr_cap: int, has_res: bool,
    d_cap: int, p_cap: int, a_cap: int, budget: int, dirty_cap: int,
    lfa: bool, block_v4: bool, sentinels: bool,
    kernel: str = "sync", delta_exp: int = 0,
) -> tuple:
    """(kernel name, instrumented callable) for an incremental-solve
    shape class — the incr-namespace analogue of
    _instrumented_pipeline."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline_incr[n={n_cap},s={s_cap},d={d_cap},p={p_cap},"
        f"a={a_cap},dd={dirty_cap}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _incr_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, dirty_cap, lfa, block_v4, sentinels,
        kernel, delta_exp,
    )
    aot_key = repr((
        "incr", n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, dirty_cap, lfa, block_v4, sentinels, kernel,
        delta_exp,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


@bounded_jit_cache(namespace="stream")
def _stream_pipeline(n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
                     has_res: bool,
                     d_cap: int, p_cap: int, a_cap: int, budget: int,
                     dirty_cap: int, sbudget: int, lfa: bool = False,
                     block_v4: bool = False, sentinels: bool = True,
                     kernel: str = "sync", delta_exp: int = 0,
                     donate: bool = True):
    """Streaming-epoch executable: one dispatch chains the incremental
    relax, selection/LFA and the on-device column diff, downloading a
    `sbudget`-row compacted payload with the device route-ok bit
    (ops/stream.py). The previous epoch's published planes and warm
    distance seed are DONATED — the epoch double-buffer updates HBM in
    place, so keeping the columns resident across solves costs one
    plane set, not two. `sbudget` (a STREAM_BUDGETS bucket) and
    `dirty_cap` are both capacity-signature ints, so budget churn
    buckets inside the "stream" namespace and can never evict the
    full-solve or incr executables. Donation is gated off on CPU
    (XLA cannot honor it there and jax warns) and whenever a transfer
    guard is armed (the guarded-retry path would replay consumed
    buffers)."""
    import jax

    kw = {"donate_argnums": (9, 10, 11, 12, 13, 14)} if donate else {}
    return jax.jit(
        _make_pipeline(
            n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
            budget, lfa, block_v4, sentinels, emit_dist=True, incr=True,
            kernel=kernel, delta_exp=delta_exp, stream=sbudget,
        ),
        **kw,
    )


@bounded_jit_cache(namespace="stream")
def _instrumented_stream(
    n_cap: int, s_cap: int, r_cap: int, kr_cap: int, has_res: bool,
    d_cap: int, p_cap: int, a_cap: int, budget: int, dirty_cap: int,
    sbudget: int, lfa: bool, block_v4: bool, sentinels: bool,
    kernel: str = "sync", delta_exp: int = 0, donate: bool = True,
) -> tuple:
    """(kernel name, instrumented callable) for a streaming-epoch shape
    class — the stream-namespace analogue of _instrumented_incr."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline_stream[n={n_cap},s={s_cap},d={d_cap},p={p_cap},"
        f"a={a_cap},dd={dirty_cap},sb={sbudget}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _stream_pipeline(
        n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
        budget, dirty_cap, sbudget, lfa, block_v4, sentinels,
        kernel, delta_exp, donate,
    )
    aot_key = repr((
        "stream", n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, dirty_cap, sbudget, lfa, block_v4, sentinels,
        kernel, delta_exp, donate,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


def _mc_shardings(mesh, n_cap: int, r_cap: int, d_cap: int,
                  emit_dist: bool):
    """(in_shardings, out_shardings) for the 14-arg pipeline closure
    under the multichip tier's ('batch','graph') mesh. Input placements
    come from parallel.sharding.plan_shardings (weight state over
    'graph', root tables over 'batch', small planes replicated); BOTH
    sides are pinned so the executable is stable across calls — without
    pinned out_shardings the second call would see prev outputs in
    whatever layout GSPMD chose and recompile."""
    from openr_tpu.parallel.sharding import plan_shardings

    sh = plan_shardings(mesh, n_cap, r_cap, d_cap)
    rep = sh["replicated"]
    in_sh = (
        rep,              # deltas
        sh["shift_w"],
        sh["res_rows"],
        sh["res_2d"],     # res_nbr
        sh["res_2d"],     # res_w
        rep,              # mbuf
        rep,              # root scalar
        sh["root_vec"],   # root_nbr
        sh["root_vec"],   # root_w
        rep, rep, rep, rep, rep,  # prev outputs
    )
    out_sh = [rep] * 7
    if emit_dist:
        out_sh.append(sh["dist"])
    return in_sh, tuple(out_sh), sh


@bounded_jit_cache(namespace="multichip")
def _mc_pipeline(mesh, n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
                 has_res: bool,
                 d_cap: int, p_cap: int, a_cap: int, budget: int,
                 lfa: bool = False, block_v4: bool = False,
                 sentinels: bool = True, emit_dist: bool = False,
                 kernel: str = "sync", delta_exp: int = 0):
    """The multichip capacity tier's full-solve executable: the SAME
    pipeline closure as _plan_pipeline, jitted with NamedSharding
    annotations over the ('batch','graph') mesh so GSPMD partitions the
    weight state across devices — parity with the single-chip tier by
    construction (the int32 min/add/compare algebra is partitioning-
    invariant, and XLA argmin keeps lowest-index tie-breaks). The mesh
    rides the cache key as a within-bucket variant; the "multichip"
    namespace keeps sharded executables from evicting single-chip
    ones."""
    import jax

    in_sh, out_sh, _ = _mc_shardings(mesh, n_cap, r_cap, d_cap, emit_dist)
    return jax.jit(
        _make_pipeline(
            n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
            budget, lfa, block_v4, sentinels, emit_dist, mesh=mesh,
            kernel=kernel, delta_exp=delta_exp,
        ),
        in_shardings=in_sh, out_shardings=out_sh,
    )


@bounded_jit_cache(namespace="multichip")
def _mc_incr_pipeline(mesh, n_cap: int, s_cap: int, r_cap: int,
                      kr_cap: int, has_res: bool,
                      d_cap: int, p_cap: int, a_cap: int, budget: int,
                      dirty_cap: int, lfa: bool = False,
                      block_v4: bool = False, sentinels: bool = True,
                      kernel: str = "sync", delta_exp: int = 0):
    """Incremental-solve executable under the multichip tier: the warm
    seed plane stays device-resident in its sharded layout (in AND out
    pinned to the same spec, so chaining solves never reshards)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    in_sh, out_sh, sh = _mc_shardings(mesh, n_cap, r_cap, d_cap, True)
    rep = sh["replicated"]
    # + prev_dist [D, N] and the five replicated dirty-tail args
    in_sh = in_sh + (sh["dist"], rep, rep, rep, rep, rep)
    return jax.jit(
        _make_pipeline(
            n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap,
            budget, lfa, block_v4, sentinels, emit_dist=True, incr=True,
            mesh=mesh, kernel=kernel, delta_exp=delta_exp,
        ),
        in_shardings=in_sh, out_shardings=out_sh,
    )


def _mesh_tag(mesh) -> str:
    return f"{mesh.shape['batch']}x{mesh.shape['graph']}"


@bounded_jit_cache(namespace="multichip")
def _instrumented_mc(
    mesh, n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
    has_res: bool, d_cap: int, p_cap: int, a_cap: int, budget: int,
    lfa: bool, block_v4: bool, sentinels: bool,
    emit_dist: bool = False,
    kernel: str = "sync", delta_exp: int = 0,
) -> tuple:
    """(kernel name, instrumented callable) for a multichip shape
    class — the multichip-namespace analogue of
    _instrumented_pipeline."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline_mc[n={n_cap},s={s_cap},d={d_cap},p={p_cap},"
        f"a={a_cap},mesh={_mesh_tag(mesh)}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _mc_pipeline(
        mesh, n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, lfa, block_v4, sentinels, emit_dist,
        kernel, delta_exp,
    )
    aot_key = repr((
        "mc", _mesh_tag(mesh), n_cap, s_cap, r_cap, kr_cap, has_res,
        d_cap, p_cap, a_cap, budget, lfa, block_v4, sentinels,
        emit_dist, kernel, delta_exp,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


@bounded_jit_cache(namespace="multichip")
def _instrumented_mc_incr(
    mesh, n_cap: int, s_cap: int, r_cap: int, kr_cap: int,
    has_res: bool, d_cap: int, p_cap: int, a_cap: int, budget: int,
    dirty_cap: int, lfa: bool, block_v4: bool, sentinels: bool,
    kernel: str = "sync", delta_exp: int = 0,
) -> tuple:
    """(kernel name, instrumented callable) for a multichip
    incremental-solve shape class."""
    from openr_tpu.ops.xla_cache import instrument_jit

    name = (
        f"pipeline_mc_incr[n={n_cap},s={s_cap},d={d_cap},p={p_cap},"
        f"a={a_cap},dd={dirty_cap},mesh={_mesh_tag(mesh)}"
        + (",res" if has_res else "")
        + (",lfa" if lfa else "")
        + (f",bk{delta_exp}" if kernel == "bucketed" else "")
        + "]"
    )
    jitted = _mc_incr_pipeline(
        mesh, n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap,
        a_cap, budget, dirty_cap, lfa, block_v4, sentinels,
        kernel, delta_exp,
    )
    aot_key = repr((
        "mc_incr", _mesh_tag(mesh), n_cap, s_cap, r_cap, kr_cap,
        has_res, d_cap, p_cap, a_cap, budget, dirty_cap, lfa, block_v4,
        sentinels, kernel, delta_exp,
    ))
    return name, instrument_jit(name, jitted, aot_key=aot_key)


# -- speculative next-class bake (ISSUE 20) ---------------------------------


def _pipeline_avals(shape_key: tuple) -> tuple:
    """Abstract avals for the 14-arg pipeline closure of a shape class —
    exactly the shapes _lane_args uploads (deltas, shift plane,
    residual tables, packed matrix buffer, root tables, prev outputs).
    jitted.lower() accepts these in place of real arrays, so the
    speculative baker compiles a class the fabric has not reached yet
    without materializing a single array."""
    import jax

    n_cap, s_cap, r_cap, kr_cap, _has_res, d_cap, p_cap, a_cap = shape_key
    i32 = np.int32
    wa, wd = -(-a_cap // 16), -(-d_cap // 16)
    S = jax.ShapeDtypeStruct
    return (
        S((s_cap,), i32),           # deltas
        S((s_cap, n_cap), i32),     # shift_w
        S((r_cap,), i32),           # res_rows
        S((r_cap, kr_cap), i32),    # res_nbr
        S((r_cap, kr_cap), i32),    # res_w
        S((6 * p_cap * a_cap,), i32),  # packed matrix buffer
        S((), i32),                 # root index
        S((d_cap,), i32),           # root_nbr
        S((d_cap,), i32),           # root_w
        S((p_cap,), i32),           # prev metric
        S((p_cap, wa), i32),        # prev s3 words
        S((p_cap, wd), i32),        # prev nh words
        S((p_cap,), i32),           # prev lfa slot
        S((p_cap,), i32),           # prev lfa metric
    )


def _next_shape_key(shape_key: tuple) -> tuple:
    """The capacity class one tier up from `shape_key`: n_cap doubles
    and the node-proportional caps follow (residual rows when the class
    has any, prefix rows), while the topology-local caps hold (shift
    classes, per-row residual fanout, root degree, announcer width) —
    capacities are pow2 (ops/edgeplan.py), so doubling lands exactly on
    the next bucket a growing fabric pads into."""
    n_cap, s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap = shape_key
    return (
        n_cap * 2, s_cap, r_cap * 2 if has_res else r_cap, kr_cap,
        has_res, d_cap, p_cap * 2, a_cap,
    )


@bounded_jit_cache()
def _scatter_jit(donate: bool = False):
    import jax

    def scatter(arr, idx, vals):
        shape = arr.shape
        return arr.ravel().at[idx].set(vals).reshape(shape)

    if donate:
        # the resident array's buffer is reused in place — a delta sync
        # never doubles the plan mirror's HBM footprint. Gated off on
        # CPU, where XLA cannot honor the donation and jax warns.
        return jax.jit(scatter, donate_argnums=(0,))
    return jax.jit(scatter)


@bounded_jit_cache(namespace="multichip")
def _mc_scatter_jit(sharding, donate: bool = False):
    """Delta scatter that PRESERVES the resident array's NamedSharding:
    pinning out_shardings keeps the multichip tier's weight shards in
    place, so GSPMD routes each update to the owning device and churn
    never re-uploads (or re-shards) the full graph."""
    import jax

    def scatter(arr, idx, vals):
        shape = arr.shape
        return arr.ravel().at[idx].set(vals).reshape(shape)

    kw = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(scatter, out_shardings=sharding, **kw)


def _pack_matrix(matrix: PrefixMatrix, node_over: np.ndarray) -> tuple:
    """(flags [P,A], mbuf int32 [6*P*A]) — validity, per-announcer drain
    and the per-prefix v4 bit (flag bit 2, announcer slot 0) fold into
    flag bits host-side; min_nexthop ships so the device can run the
    route-level ok filter (ops/compact.route_ok_device)."""
    idx = np.clip(matrix.ann_node, 0, None)
    flags = matrix.ann_valid.astype(np.int32) | (
        node_over[idx].astype(np.int32) << 1
    )
    if flags.shape[1]:
        flags[:, 0] |= matrix.is_v4.astype(np.int32) << 2
    mbuf = matrix._mbuf
    if mbuf is None:
        mbuf = matrix._mbuf = np.concatenate([
            matrix.ann_node.ravel(),
            flags.ravel(),
            matrix.path_pref.ravel(),
            matrix.source_pref.ravel(),
            matrix.dist_adv.ravel(),
            matrix.min_nexthop.ravel(),
        ]).astype(np.int32, copy=False)
    else:
        # only the flags plane depends on node_over; every other plane
        # is a pure function of this matrix instance — patch in place
        # (device_put copies, so the resident buffer is unaffected)
        pa = flags.size
        mbuf[pa:2 * pa] = flags.ravel()
    return flags, mbuf


class _AreaDev:
    """Per-area resident device state: plan arrays + announcer matrix."""

    __slots__ = (
        "plan", "d_deltas", "d_shift_w", "d_res_rows", "d_res_nbr",
        "d_res_w", "matrix_key", "matrix", "flags", "d_mbuf",
        "matrix_version", "pack_over", "drain_epoch", "drain_log",
        "mc_mesh",
    )

    def __init__(self):
        from collections import deque

        self.plan: Optional[EdgePlan] = None
        self.d_deltas = self.d_shift_w = None
        self.d_res_rows = self.d_res_nbr = self.d_res_w = None
        self.matrix_key = None
        self.matrix: Optional[PrefixMatrix] = None
        self.flags: Optional[np.ndarray] = None
        self.d_mbuf = None
        # drain journal for the incremental solver: one entry per
        # _sync_area epoch — ({shift_flat: old_w}, {res_flat: old_w})
        # maps of that drain's pre-write weights, or (None, None) as a
        # reset marker (rebuild / residual-layout change). A vantage
        # whose distance plane is k epochs old merges the last k
        # entries to reconstruct its old weight plane on device; the
        # bounded deque turns long-idle vantages into journal gaps
        # (-> full-solve fallback) instead of unbounded host state.
        self.drain_epoch = 0
        self.drain_log = deque(maxlen=16)
        # node_overloaded snapshot at the last _pack_matrix: packing is
        # a pure function of (matrix, overload set), so an unchanged
        # snapshot skips the O(6*P*A) host concat entirely
        self.pack_over: Optional[np.ndarray] = None
        # bumped whenever the matrix is rebuilt: row -> prefix mapping may
        # change even at identical shapes, so every vantage's delta state
        # (prev outputs + route cache) must reset against the new rows
        self.matrix_version = 0
        # the ('batch','graph') mesh this area's mirrors are sharded
        # over when the multichip capacity tier is engaged; None =
        # single-chip placement. A tier flip forces a full re-put under
        # the new placement (_sync_area).
        self.mc_mesh = None


class _VantageState:
    """Per-(area, vantage) output state: resident prev outputs + the
    columnar RIB the host patches from delta pulls."""

    __slots__ = (
        "shape_key", "matrix_version", "prev", "crib",
        "links_tuple", "valid", "prev_dist", "dist_epoch", "root_sig",
        "stream_budget",
    )

    def __init__(self):
        self.shape_key = None
        self.matrix_version = -1
        self.prev = None  # (metric, s3w, nhw) device handles
        self.crib: Optional[ColumnarRib] = None
        self.links_tuple: tuple = ()
        self.valid = False
        # streaming-epoch changed-rows budget (ops/stream.py bucket):
        # tracks this vantage's recent churn — grows on payload
        # overflow, shrinks back toward the floor on quiet epochs.
        # Floor literal mirrors STREAM_BUDGETS[0] (importing the ops
        # module pulls in jax, which this module defers to solve time).
        self.stream_budget = 64
        # incremental-solve seed state: the [D, N] distance plane of
        # the last single-area dispatch, the area drain epoch it
        # corresponds to, and the root out-link signature it was
        # computed under (lane <-> neighbor mapping + per-lane link-up
        # mask; a changed mask flips lanes between all-INF and finite,
        # which a warm re-relax cannot express)
        self.prev_dist = None
        self.dist_epoch = -1
        self.root_sig = None


# areas at or below this node capacity are candidates for the fused
# (vmapped) multi-area dispatch; larger areas keep their own dispatch so
# one giant area never serializes behind a stack of small ones
_FUSE_MAX_NCAP = 4096


class _PendingBuild:
    """An in-flight solve between dispatch_route_db (all LSDB reads +
    device dispatches, no blocking sync) and collect_route_db (the one
    blocking host sync at materialize). Snapshot-only: consuming it
    never touches LinkState/PrefixState."""

    __slots__ = (
        "route_db", "futures", "t_pipe0", "ksp2_timing",
        "bytes_uploaded", "delegated", "dispatch_wall_ms",
    )

    def __init__(self, route_db, futures=None, t_pipe0=0.0,
                 delegated: bool = False):
        self.route_db = route_db
        self.futures = futures or []
        self.t_pipe0 = t_pipe0
        self.ksp2_timing: dict = {}
        self.bytes_uploaded = 0
        self.delegated = delegated
        self.dispatch_wall_ms = 0.0


_UCMP_ALGOS = (
    PrefixForwardingAlgorithm.SP_UCMP_ADJ_WEIGHT_PROPAGATION,
    PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION,
)


class _UcmpAccel:
    """Device-backed `ucmp_resolver` installed on the internal CPU
    oracle: replaces the host heap walk of resolve_ucmp_weights
    (ref LinkState.cpp:913-1033) with the ops/ucmp.py fixpoint over the
    device SSSP field. Falls back (NotImplemented) whenever its area
    state is stale — single-prefix incremental rebuilds, small graphs
    routed entirely to the oracle, cross-area UCMP prefixes — so the
    host path remains the correctness backstop."""

    def __init__(self, solver: "TpuSpfSolver"):
        self.solver = solver
        # area -> (generation, plan, UcmpEdges)
        self.edges: dict[str, tuple] = {}
        # (area, root) -> (generation, plan, d_base, base_np) — the
        # unmasked SSSP field, shared with the KSP2 base when present
        self.base: dict[tuple, tuple] = {}
        # per-generation memo: many prefixes share one announcer set
        # (anycast), so identical (leaves, mode) resolve once
        self.results: dict[tuple, object] = {}
        self._results_gen: dict[str, int] = {}

    def _base_for(self, area: str, root: str, ridx: int, link_state,
                  ad: _AreaDev):
        from openr_tpu.ops.ksp2 import base_dist

        gen = link_state.generation
        plan = ad.plan
        cached = self.solver._ksp2_base.get((area, root))
        if cached is not None and cached[0] == gen and cached[1] is plan:
            return cached[2], cached[3]
        mine = self.base.get((area, root))
        if mine is not None and mine[0] == gen and mine[1] is plan:
            return mine[2], mine[3]
        d_base = base_dist(
            plan, ad.d_shift_w, ad.d_res_rows, ad.d_res_nbr, ad.d_res_w,
            ad.d_deltas, ridx,
        )
        base_np = np.asarray(d_base)
        self.base[(area, root)] = (gen, plan, d_base, base_np)
        return d_base, base_np

    def _edges_for(self, area: str, link_state, plan) -> "object":
        from openr_tpu.ops.ucmp import UcmpEdges

        gen = link_state.generation
        hit = self.edges.get(area)
        if hit is not None and hit[0] == gen and hit[1] is plan:
            return hit[2]
        edges = UcmpEdges(link_state, plan.node_overloaded, plan.n_cap)
        self.edges[area] = (gen, plan, edges)
        return edges

    def __call__(self, root, area, link_state, dst_weights,
                 use_prefix_weight):
        from openr_tpu.ops import ucmp as ucmp_ops

        solver = self.solver
        ad = solver._area_dev.get(area)
        gen = link_state.generation
        if (
            not dst_weights
            or ad is None
            or ad.plan is None
            or ad.plan.synced_generation != gen
            or link_state.is_node_overloaded(root)
        ):
            return NotImplemented
        plan = ad.plan
        ridx = plan.node_index.get(root)
        if ridx is None:
            return NotImplemented
        if self._results_gen.get(area) != gen:
            self.results = {
                k: v for k, v in self.results.items() if k[0] != area
            }
            self._results_gen[area] = gen
        rkey = (
            area, root, tuple(sorted(dst_weights.items())),
            bool(use_prefix_weight),
        )
        if rkey in self.results:
            return self.results[rkey]
        d_base, base_np = self._base_for(area, root, ridx, link_state, ad)
        # the caller filtered leaves to the best metric, so they are
        # equidistant by construction — mirror the host guard anyway
        leaf_metrics = {
            int(base_np[plan.node_index[n]])
            for n in dst_weights
            if n in plan.node_index
        }
        if len(leaf_metrics) != 1 or INF_E in leaf_metrics:
            self.results[rkey] = None
            return None
        edges = self._edges_for(area, link_state, plan)
        reach, w, overflow = ucmp_ops.propagate(
            edges, d_base, dst_weights, use_prefix_weight
        )
        if solver.enable_sentinels:
            if overflow:
                solver.last_sentinels["ucmp_overflow"] = (
                    solver.last_sentinels.get("ucmp_overflow", 0) + 1
                )
                counters.increment("decision.sentinel.ucmp_overflow")
            bad = _ucmp_weight_anomalies(w)
            if bad:
                # weights that are NaN/inf/negative would quietly become
                # garbage next-hop ratios — flag before assembly
                solver.last_sentinels["ucmp_bad_weights"] = (
                    solver.last_sentinels.get("ucmp_bad_weights", 0) + bad
                )
                counters.increment(
                    "decision.sentinel.ucmp_bad_weights", bad
                )
        if overflow:
            # weighted path counts exceeded int32 — the host walk's
            # Python ints are exact. Memoize the fallback sentinel so
            # sibling anycast prefixes skip the wasted device round trip
            self.results[rkey] = NotImplemented
            return NotImplemented
        res = self._assemble(
            root, ridx, link_state, plan, base_np, reach, w, dst_weights
        )
        self.results[rkey] = res
        return res

    @staticmethod
    def _assemble(root, ridx, link_state, plan, base_np, reach, w,
                  dst_weights):
        """Root-local finish: per-interface next-hop weights from the
        propagated field, gcd-normalized (host NodeUcmpResult shape,
        O(degree(root)))."""
        res = NodeUcmpResult(0)
        if root in dst_weights:
            # the root itself announces: a leaf's weight is its own
            # advertisement; equidistant leaves cannot chain, so no
            # next-hop links accumulate (matches the host walk)
            res.weight = dst_weights[root]
            return res
        if not reach[ridx]:
            return None
        my_dist = int(base_np[ridx])
        index = plan.node_index
        for link in link_state.ordered_links_from_node(root):
            if not link.is_up():
                continue
            nbr = link.other_node(root)
            j = index.get(nbr)
            if j is None or not reach[j]:
                continue
            if my_dist + link.metric_from_node(root) != int(base_np[j]):
                continue  # not a shortest-path DAG edge
            res.add_next_hop_link(
                link.iface_from_node(root), link, nbr, int(w[j])
            )
        res.weight = int(w[ridx])
        res.normalize_next_hop_weights()
        return res


def _fast_path_eligible(entries) -> bool:
    """Device fast path covers IP + SP_ECMP announcements without prepend
    labels; anything else routes through the CPU oracle."""
    for entry in entries.values():
        if (
            entry.forwarding_type != PrefixForwardingType.IP
            or entry.forwarding_algorithm != PrefixForwardingAlgorithm.SP_ECMP
            or entry.prepend_label is not None
        ):
            return False
    return True


def _ksp2_eligible(entries) -> bool:
    """KSP2 prefixes (SR_MPLS + KSP2_ED_ECMP on every announcement) get
    the device-assisted path: batched masked SSSP for the per-destination
    second pass, oracle code for selection/trace/label assembly."""
    for entry in entries.values():
        if (
            entry.forwarding_type != PrefixForwardingType.SR_MPLS
            or entry.forwarding_algorithm
            != PrefixForwardingAlgorithm.KSP2_ED_ECMP
        ):
            return False
    return True


class TpuSpfSolver:
    """Drop-in replacement for SpfSolver.build_route_db with the hot path
    on device. Differentially tested against the CPU oracle."""

    def __init__(
        self, my_node_name: str, small_graph_nodes: int = 0,
        xla_cache_dir: str | None = None,
        enable_numerical_sentinels: bool = True,
        fuse_small_areas: bool = True,
        fuse_n_cap: int = _FUSE_MAX_NCAP,
        incremental_spf: bool = False,
        incremental_cone_frac: float = 0.25,
        multichip_n_cap_threshold: int = 131072,
        multichip_batch: int = 0,
        spf_kernel: str = "bucketed",
        transfer_guard: str = "off",
        streaming_pipeline: bool = False,
        aot_cache_dir: str | None = None,
        aot_speculate: bool = False, **solver_kwargs
    ):
        # a restarting daemon must not pay the ~80s 100k-node compile
        # again — load executables from the persistent cache
        from openr_tpu.ops.xla_cache import enable_compilation_cache

        enable_compilation_cache(xla_cache_dir)
        # persistent AOT executable cache (ops/xla_cache.py): None
        # leaves the process-global cache as configured (daemon boot /
        # prewarm own it); a non-empty value points/enables it here —
        # "auto" resolves the default directory, "off" disables.
        if aot_cache_dir:
            from openr_tpu.ops.xla_cache import configure_aot

            configure_aot(aot_cache_dir)
        # speculative next-class bake (ops/xla_cache.baker): after each
        # dispatch, background-compile the capacity class one tier up
        # (and its multichip variant past the threshold) so a tier flip
        # finds its executable ready. Off by default — the bake burns a
        # core per untaken tier; churny production fabrics opt in.
        self.aot_speculate = bool(aot_speculate)
        self.my_node_name = my_node_name
        # numerical-health sentinels: on-device unreachable/saturation
        # reductions ride the pull buffers; UCMP weight checks run on
        # the pulled field (config kill-switch, DecisionConfig)
        self.enable_sentinels = enable_numerical_sentinels
        # aggregated per solve by build_route_db (+ UCMP hook); the
        # Decision actor turns anomalies into counter/LogSample/span
        self.last_sentinels: dict = {}
        # graphs below this node count solve entirely on the CPU oracle:
        # the fixed device dispatch + result-pull round trip exceeds the
        # whole CPU solve there (the "auto" backend sets this)
        self.small_graph_nodes = small_graph_nodes
        # batch same-shape small areas into one vmapped dispatch; areas
        # above fuse_n_cap keep their own dispatch (decision_config
        # fuse_n_cap knob — the what-if sweep batcher sizes its scenario
        # chunks off the same value)
        self.fuse_small_areas = fuse_small_areas
        self.fuse_n_cap = int(fuse_n_cap)
        # incremental SSSP: seed single-area dispatches from the
        # previous resident distance plane and re-relax only the
        # affected cone of the drained dirty edges (ops/incremental.py).
        # Bit-identical to the full solve; falls back automatically on
        # first solve, shape/root churn, journal gaps, zero-weight
        # edges, or when the cone exceeds incremental_cone_frac of the
        # fabric's node-lanes (decided on device, same dispatch).
        # streaming churn pipeline (ops/stream.py): fuse the incremental
        # relax, selection and the on-device column diff into one
        # dispatch per epoch, download a bucketed changed-rows payload
        # carrying the device route-ok bit, and DONATE the previous
        # epoch's resident planes (in-place HBM double-buffer). Implies
        # incremental_spf — the streaming epoch is the incremental solve
        # with a different download contract; every incremental
        # fallback rung (first solve, shape/root churn, journal gaps,
        # payload overflow, CPU failover) drops to the classic path.
        if not isinstance(streaming_pipeline, bool):
            raise ValueError(
                f"streaming_pipeline must be a bool, "
                f"got {streaming_pipeline!r}"
            )
        self.streaming_pipeline = streaming_pipeline
        self.incremental_spf = bool(incremental_spf) or streaming_pipeline
        self.incremental_cone_frac = float(incremental_cone_frac)
        # multichip capacity tier (parallel/sharding.py): an area whose
        # padded n_cap exceeds the threshold — with >1 device visible —
        # solves through NamedSharding-resident mirrors over the
        # ('batch','graph') mesh, lifting the single-HBM ceiling.
        # 0 disables the tier.
        self.multichip_n_cap_threshold = int(multichip_n_cap_threshold)
        self.multichip_batch = int(multichip_batch)
        # overload shedding rung (runtime/overload.py): Decision toggles
        # this post-construction; _mc_mesh_for returns None while set
        self.force_single_chip = False
        # SSSP round-loop implementation (ops/relax.py): "bucketed"
        # selects the Δ-stepping kernel wherever the plan is eligible
        # (plan.delta_exp > 0, i.e. it has usable shift classes) and
        # falls back to the synchronous rounds otherwise; "sync" forces
        # the classic rounds everywhere (the bisection first step —
        # docs/Operations.md)
        if spf_kernel not in ("sync", "bucketed"):
            raise ValueError(f"unknown spf_kernel {spf_kernel!r}")
        self.spf_kernel = spf_kernel
        # opt-in jax.transfer_guard around the exec hot path: "log"
        # logs implicit host<->device transfers, "disallow" turns each
        # into a counted, attributed finding (the dispatch retries
        # unguarded so routing converges regardless). Default off.
        if transfer_guard not in ("off", "log", "disallow"):
            raise ValueError(
                f"unknown transfer_guard {transfer_guard!r}"
            )
        self.transfer_guard = transfer_guard
        # memoized tier mesh: built once per process (device topology is
        # static within a solver's lifetime; device LOSS surfaces as a
        # dispatch failure -> CPU-oracle failover, not a mesh rebuild)
        self._mc_mesh: object = False  # False = not yet resolved
        self.cpu = SpfSolver(my_node_name, **solver_kwargs)
        # UCMP weight resolution runs on device through the oracle's
        # resolver hook (falls back to the host walk when stale)
        self._ucmp_accel = _UcmpAccel(self)
        self.cpu.ucmp_resolver = self._ucmp_accel
        self._area_dev: dict[str, _AreaDev] = {}
        self._vstates: dict[tuple, _VantageState] = {}
        self._vantage_lru: OrderedDict[tuple, None] = OrderedDict()
        self._partition = None  # (ps.generation, fast, slow)
        # host->device transfer accounting for the current solve; read
        # into last_timing by collect_route_db (bench bytes_uploaded)
        self._bytes_uploaded = 0
        # buffer donation for delta scatters (resolved lazily from the
        # backend: CPU cannot honor donation and warns)
        self._donate: Optional[bool] = None
        self.last_device_stats: dict = {}
        # wall-time breakdown of the last fast-path solve (bench.py)
        self.last_timing: dict = {}
        self._ksp2_timing: dict = {}
        # (area, vantage) -> (generation, plan, device base field, np
        # base field): the unmasked KSP2 base, reused across solves at
        # the same topology generation
        self._ksp2_base: dict[tuple, tuple] = {}
        # (area, vantage) -> resident masked-row state (ops/ksp2.py)
        self._ksp2_rows: dict[tuple, object] = {}
        # (area, vantage) -> trace-reuse certificates: per-dest read
        # sets + paths from the last prime (see _prime_ksp2)
        self._ksp2_certs: dict[tuple, dict] = {}
        # LRU over the per-vantage KSP2 state above: each entry pins
        # ~2x b_cap x n_cap int32 (device rows + host mirror), so the
        # multi-vantage fabric path must evict, not accumulate
        self._ksp2_lru: OrderedDict[tuple, None] = OrderedDict()
        # unrolled while_loop trips of the last device SSSP — a measured
        # diameter bound the sharded fabric path reuses
        self.last_trips: int = 0
        # (jitted pipeline, device args, prev outputs) of the last fast
        # solve, for device-only throughput probes
        self._last_exec = None
        # (jitted incr pipeline, device args, prev outputs, prev dist,
        # dirty tail) of the last incremental solve — the
        # incr_device_compute_ms probe (bench incr_device_ms)
        self._last_exec_incr = None
        # single worker that runs each area's blocking result pull +
        # columnar scatter while the main thread dispatches the next
        # area and walks the host slow path (created lazily; one worker
        # keeps per-vantage state access serial)
        self._mat_pool = None
        # live-buffer census attribution (runtime/device_stats.py):
        # weakref so a dropped solver's pool reads empty instead of
        # pinning the solver (and its device mirrors) forever
        import weakref

        from openr_tpu.runtime.device_stats import register_pool

        ref = weakref.ref(self)

        def _pool_arrays():
            s = ref()
            return [] if s is None else list(s._device_arrays(mc=False))

        def _mc_pool_arrays():
            s = ref()
            return [] if s is None else list(s._device_arrays(mc=True))

        register_pool(f"tpu_solver:{my_node_name}", _pool_arrays)
        # the multichip tier's sharded mirrors report as their own pool
        # so the HBM census attributes per-device bytes to the tier
        # (breeze tpu devices)
        register_pool(
            f"tpu_solver.multichip:{my_node_name}", _mc_pool_arrays
        )

    def _device_arrays(self, mc: Optional[bool] = None):
        """Device buffers this solver pins: per-area topology mirrors
        plus per-vantage resident pipeline outputs. `mc` filters by
        tier: True = only multichip-sharded areas' state, False = only
        single-chip, None = everything."""
        for ad in self._area_dev.values():
            if mc is not None and (ad.mc_mesh is not None) != mc:
                continue
            for attr in (
                "d_deltas", "d_shift_w", "d_res_rows", "d_res_nbr",
                "d_res_w", "d_mbuf",
            ):
                arr = getattr(ad, attr, None)
                if arr is not None:
                    yield arr
        for (area, _), vs in self._vstates.items():
            if mc is not None:
                ad = self._area_dev.get(area)
                if ((ad is not None and ad.mc_mesh is not None) != mc):
                    continue
            yield from (getattr(vs, "prev", None) or ())
            pd = getattr(vs, "prev_dist", None)
            if pd is not None:
                yield pd

    def _pool(self):
        if self._mat_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._mat_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="rib-mat"
            )
        return self._mat_pool

    # static-route passthroughs keep the Decision actor backend-agnostic
    def update_static_unicast_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_unicast_routes(to_update, to_delete)

    def update_static_mpls_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_mpls_routes(to_update, to_delete)

    def create_route_for_prefix_or_get_static(
        self, my_node_name, area_link_states, prefix_state, prefix
    ):
        """Incremental per-prefix path (Decision's changed-prefix rebuild):
        single-prefix work has no batch to amortize a device launch over,
        so it delegates to the CPU oracle. Topology churn takes the full
        device path, which is itself incremental end-to-end (on-device
        output delta -> O(changed) host work)."""
        return self.cpu.create_route_for_prefix_or_get_static(
            my_node_name, area_link_states, prefix_state, prefix
        )

    @property
    def static_unicast_routes(self):
        return self.cpu.static_unicast_routes

    @property
    def static_mpls_routes(self):
        return self.cpu.static_mpls_routes

    # -- vantage cache management ------------------------------------------

    _MAX_FOREIGN_VANTAGES = 4
    _MAX_KSP2_STATES = 4

    def _touch_ksp2_state(self, bkey: tuple) -> None:
        # O(1) recency bump (an OrderedDict move_to_end, not a list
        # scan — the fabric path touches every vantage per pass)
        lru = self._ksp2_lru
        lru[bkey] = None
        lru.move_to_end(bkey)
        while len(lru) > self._MAX_KSP2_STATES:
            old, _ = lru.popitem(last=False)
            self._ksp2_rows.pop(old, None)
            self._ksp2_base.pop(old, None)
            self._ksp2_certs.pop(old, None)

    def _touch_foreign_vantage(self, vkey: tuple) -> None:
        lru = self._vantage_lru
        lru[vkey] = None
        lru.move_to_end(vkey)
        while len(lru) > self._MAX_FOREIGN_VANTAGES:
            old, _ = lru.popitem(last=False)
            self._vstates.pop(old, None)

    # -- build -------------------------------------------------------------

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        pending = self.dispatch_route_db(
            my_node_name, area_link_states, prefix_state
        )
        if pending is None:
            return None
        return self.collect_route_db(pending)

    def dispatch_route_db(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[_PendingBuild]:
        """Phase 1 of a solve: every LSDB read, device sync, pipeline
        dispatch and async result copy — NO blocking host sync. Returns
        a _PendingBuild for collect_route_db, or None when this vantage
        is in no area's graph. Must run on the thread that owns the
        LinkState/PrefixState (the actor loop); collect_route_db touches
        only device buffers and the pending snapshot, so the async
        dispatch fiber may run it in an executor."""
        # TSan-lite: the docstring's "must run on the owning thread" is
        # asserted when runtime affinity checks are on (CI test+chaos
        # lanes); first call binds the owner, later calls verify it
        if affinity.enabled():
            affinity.assert_owner(self, "dispatch_route_db")
        if not any(
            ls.has_node(my_node_name) for ls in area_link_states.values()
        ):
            return None
        # reset per-solve so a CPU-delegated or deviceless build doesn't
        # leave a previous solve's breakdown for timing consumers
        self.last_timing = {}
        # sentinel aggregation restarts per solve; the UCMP hook below
        # and the per-area pipelines both add into it
        self.last_sentinels = {}
        self._bytes_uploaded = 0
        if all(
            ls.node_count() < self.small_graph_nodes
            for ls in area_link_states.values()
        ):
            db = self.cpu.build_route_db(
                my_node_name, area_link_states, prefix_state
            )
            return _PendingBuild(db, delegated=True)

        fast_by_area, slow, ksp2, ksp2_by_area = self._partition_prefixes(
            prefix_state, area_link_states
        )

        # a KSP2 prime with no subsequent fast-path finish must not leak
        # its timing into a later solve's breakdown
        self._ksp2_timing = {}
        import time as _time

        t_pipe0 = _time.perf_counter()
        route_db = DecisionRouteDb()
        futures = []
        # per-area device dispatch: a prefix announced in exactly one
        # area selects over that area's announcers only (the other
        # areas' reachability filters remove nothing), so the per-area
        # pipeline computes the oracle's answer verbatim. Prefixes
        # spanning areas — where selection and the min-metric next-hop
        # union are genuinely global — go through the oracle below.
        # All dispatches START before any result is consumed: the device
        # round trips overlap each other AND the host slow path.
        small: list[str] = []
        preps: list[dict] = []
        for area, plist in fast_by_area.items():
            link_state = area_link_states[area]
            if not link_state.has_node(my_node_name):
                continue  # unreachable area for this vantage: no routes
            if link_state.node_count() < self.small_graph_nodes:
                # a tiny area (e.g. a hub-only backbone) solves faster on
                # the oracle than one device round trip
                small.extend(plist)
                continue
            preps.append(self._prep_vantage(
                my_node_name, area, link_state, prefix_state, plist
            ))
        # areas whose capacity buckets (and pipeline flags) match batch
        # into ONE vmapped dispatch — per-call overhead paid once for
        # the group, not per area
        singles: list[dict] = []
        groups: dict[tuple, list] = {}
        if self.fuse_small_areas:
            for pv in preps:
                # a multichip-tier area never fuses: the vmapped group
                # dispatch carries no sharding annotations, and its
                # whole point (amortizing tiny-area dispatch overhead)
                # is moot above the multichip threshold
                if (
                    pv.get("mc") is None
                    and pv["plan"].n_cap <= self.fuse_n_cap
                ):
                    groups.setdefault(pv["fuse_key"], []).append(pv)
                else:
                    singles.append(pv)
        else:
            singles = preps
        for group in groups.values():
            if len(group) < 2:
                singles.extend(group)
                continue
            # the worker pulls + scatters one area's result while the
            # main thread dispatches the rest and runs the host slow
            # path — sync/exec/mat pipeline instead of serializing
            for pv, prepare in self._dispatch_fused(group):
                # lint: allow(executor-escape) rib-mat pool is single-worker
                futures.append((pv["area"], self._pool().submit(prepare)))
        for pv in singles:
            prepare = self._dispatch_one(pv)
            # the prepare closures touch per-vantage state, but the
            # rib-mat pool has exactly ONE worker (_pool), so their
            # execution is serialized by construction — the escape is
            # the whole point of the sync/exec/mat pipeline
            # lint: allow(executor-escape) rib-mat pool is single-worker
            futures.append((pv["area"], self._pool().submit(prepare)))
        # batch the per-destination second-pass SSSPs on device and prime
        # the k-paths cache; the oracle loop below then assembles KSP2
        # routes through its unchanged code path. Like the fast path,
        # a KSP2 prefix announced in a single area primes that area.
        for area, plist in ksp2_by_area.items():
            link_state = area_link_states[area]
            if not link_state.has_node(my_node_name):
                continue
            if link_state.node_count() < self.small_graph_nodes:
                continue  # host Dijkstras beat a device batch here
            self._prime_ksp2(
                my_node_name, area, link_state, prefix_state, plist,
                fast_by_area.get(area, []),
            )

        if self.cpu.enable_ucmp:
            self._prime_ucmp(
                my_node_name, area_link_states, prefix_state, slow,
                fast_by_area,
            )
        self._host_routes(
            my_node_name, area_link_states, prefix_state,
            slow + ksp2 + small, route_db,
        )
        pending = _PendingBuild(route_db, futures, t_pipe0)
        pending.ksp2_timing = self._ksp2_timing
        self._ksp2_timing = {}
        pending.bytes_uploaded = self._bytes_uploaded
        # dispatch/collect boundary for the latency-budget ledger: how
        # much of the pipeline wall was phase 1 (on-loop) vs phase 2
        pending.dispatch_wall_ms = (_time.perf_counter() - t_pipe0) * 1e3
        return pending

    @affinity.executor_safe
    def collect_route_db(
        self, pending: Optional[_PendingBuild]
    ) -> Optional[DecisionRouteDb]:
        """Phase 2 of a solve: the at-most-ONE blocking host sync —
        drain the per-area materialization futures and assemble the
        timing breakdown. Reads no LSDB state, so it may run off the
        actor loop."""
        if pending is None:
            return None
        route_db = pending.route_db
        if pending.delegated or not pending.futures:
            return route_db
        import time as _time

        t_collect0 = _time.perf_counter()
        views = []
        stages = {"sync_ms": 0.0, "exec_ms": 0.0, "mat_ms": 0.0}
        area_timing: dict[str, dict] = {}
        incremental = False
        multichip: dict | bool = False
        rounds_total = 0
        bucket_epochs_total = 0
        halo_total = 0
        bucketed_engaged = False
        bytes_downloaded = 0
        stream_epochs = 0
        stream_changed = 0
        stream_overflows = 0
        for area, fut in pending.futures:
            res = fut.result()
            views.append(res["view"])
            stats = res["stats"]
            # relaxation-work ledger (ISSUE 13): per-solve totals feed
            # decision.device.* stats + last_timing for bench/convergence
            rounds_total += int(stats.get("rounds") or 0)
            bucket_epochs_total += int(stats.get("bucket_epochs") or 0)
            halo_total += int(stats.get("halo_exchanges") or 0)
            # download ledger (ISSUE 16): every path reports its pulled
            # bytes; streaming epochs additionally report budget use
            bytes_downloaded += int(stats.get("bytes_downloaded") or 0)
            if stats.get("stream"):
                stream_epochs += 1
                stream_changed += int(stats.get("changed_rows") or 0)
                if stats["stream"].get("overflow"):
                    stream_overflows += 1
            if stats.get("spf_kernel") == "bucketed":
                bucketed_engaged = True
            if stats.get("incremental"):
                # a warm re-relax converges in a trip or two — not a
                # diameter bound the sharded fabric path may reuse
                incremental = True
            else:
                self.last_trips = stats["trips"]
            if stats.get("multichip"):
                multichip = stats["multichip"]
            self.last_device_stats = stats
            for k, v in res["timing"].items():
                stages[k] = stages.get(k, 0.0) + v
            area_timing[area] = dict(res["timing"])
            # the shape-class kernel this area executed, for the
            # ctrl.tpu.kernels estimated-vs-achieved join
            if stats.get("kernel"):
                area_timing[area]["kernel"] = stats["kernel"]
            for sk, sv in (stats.get("sentinels") or {}).items():
                self.last_sentinels[sk] = (
                    self.last_sentinels.get(sk, 0) + sv
                )
            # per-area solve/materialize latency percentiles
            # (the per-event stage timing ISSUE 2 reports against)
            counters.add_stat_value(
                f"decision.area.{area}.spf_ms",
                res["timing"]["sync_ms"] + res["timing"]["exec_ms"],
            )
            counters.add_stat_value(
                f"decision.area.{area}.mat_ms", res["timing"]["mat_ms"]
            )
        # device routes shadow host/static entries for the same
        # prefix — same override order as the seed's dict.update
        route_db.unicast_routes = LazyUnicastRoutes(
            route_db.unicast_routes, views
        )
        if multichip:
            # once per SOLVE (dispatches count per area): the signal an
            # operator alerts on is "the tier is live", not its fan-out
            counters.increment("decision.solver.multichip.engaged")
        counters.add_stat_value("decision.device.rounds", rounds_total)
        counters.add_stat_value(
            "decision.device.bucket_epochs", bucket_epochs_total
        )
        if halo_total:
            counters.add_stat_value(
                "decision.device.halo_exchanges", halo_total
            )
        counters.add_stat_value(
            "decision.device.bytes_downloaded", bytes_downloaded
        )
        wall = (_time.perf_counter() - pending.t_pipe0) * 1e3
        self.last_timing = {
            **stages,
            "pipeline_wall_ms": wall,
            "pipeline_stages_ms": sum(stages.values()),
            "dispatch_wall_ms": pending.dispatch_wall_ms,
            "collect_wall_ms": (_time.perf_counter() - t_collect0) * 1e3,
            "areas": area_timing,
            "bytes_uploaded": float(pending.bytes_uploaded),
            "bytes_downloaded": float(bytes_downloaded),
            "incremental": incremental,
            "multichip": multichip,
            "rounds": rounds_total,
            "bucket_epochs": bucket_epochs_total,
            "halo_exchanges": halo_total,
            "spf_kernel": "bucketed" if bucketed_engaged else "sync",
            **pending.ksp2_timing,
        }
        if stream_epochs:
            self.last_timing["stream"] = {
                "epochs": stream_epochs,
                "changed_rows": stream_changed,
                "bytes_downloaded": bytes_downloaded,
                "overflows": stream_overflows,
            }
        return route_db

    def _prime_ucmp(
        self, my_node_name, area_link_states, prefix_state, slow,
        fast_by_area,
    ) -> None:
        """Before the oracle loop touches UCMP prefixes, sync their
        areas' device mirrors and prime LinkState's SPF memo from the
        device base field — the oracle's `get_spf_result(root)` in
        _get_node_ucmp_result then answers lazily instead of running a
        host Dijkstra, and the resolver hook finds fresh area state."""
        by_area: dict[str, bool] = {}
        for prefix in slow:
            entries = prefix_state.entries_for(prefix) or {}
            areas = {a for _, a in entries}
            if len(areas) != 1:
                continue  # cross-area: oracle host path by design
            if any(
                e.forwarding_algorithm in _UCMP_ALGOS
                for e in entries.values()
            ):
                by_area[next(iter(areas))] = True
        for area in by_area:
            link_state = area_link_states.get(area)
            if (
                link_state is None
                or not link_state.has_node(my_node_name)
                or link_state.node_count() < self.small_graph_nodes
                or link_state.is_node_overloaded(my_node_name)
            ):
                continue
            ad = self._sync_area(
                area, link_state, prefix_state, fast_by_area.get(area, [])
            )
            ridx = ad.plan.node_index.get(my_node_name)
            if ridx is None:
                continue
            _, base_np = self._ucmp_accel._base_for(
                area, my_node_name, ridx, link_state, ad
            )
            node_index = ad.plan.node_index

            def metric_of(n, _idx=node_index, _base=base_np):
                j = _idx.get(n)
                if j is None:
                    return None
                v = int(_base[j])
                return None if v >= INF_E else v

            link_state.prime_spf_metrics(my_node_name, metric_of)

    def _partition_prefixes(
        self,
        prefix_state: PrefixState,
        area_link_states: dict[str, LinkState],
    ):
        """-> (fast prefixes grouped by their single announcer area,
        slow prefixes for the oracle — ineligible attributes OR announcers
        spanning areas, all ksp2 prefixes, ksp2 prefixes grouped by
        single announcer area for device priming). Cached per
        (prefix generation, area set)."""
        areas_key = tuple(sorted(area_link_states))
        if (
            self._partition is not None
            and self._partition[0] == (prefix_state.generation, areas_key)
        ):
            return self._partition[1:]
        fast_by_area: dict[str, list] = {}
        ksp2_by_area: dict[str, list] = {}
        slow, ksp2 = [], []
        for prefix, entries in prefix_state.prefixes().items():
            areas = {a for _, a in entries}
            single = (
                next(iter(areas))
                if len(areas) == 1 and next(iter(areas)) in area_link_states
                else None
            )
            if _fast_path_eligible(entries):
                if single is not None:
                    fast_by_area.setdefault(single, []).append(prefix)
                else:
                    slow.append(prefix)
            elif _ksp2_eligible(entries):
                ksp2.append(prefix)
                if single is not None:
                    ksp2_by_area.setdefault(single, []).append(prefix)
            else:
                slow.append(prefix)
        self._partition = (
            (prefix_state.generation, areas_key),
            fast_by_area, slow, ksp2, ksp2_by_area,
        )
        return fast_by_area, slow, ksp2, ksp2_by_area

    def _host_routes(
        self, my_node_name, area_link_states, prefix_state, slow, route_db
    ) -> None:
        """CPU oracle path for irregular prefixes + statics + MPLS."""
        self.cpu.best_routes_cache.clear()
        for prefix in slow:
            route = self.cpu.create_route_for_prefix(
                my_node_name, area_link_states, prefix_state, prefix
            )
            if route is not None:
                route_db.add_unicast_route(route)
        for prefix, entry in self.cpu.static_unicast_routes.items():
            if prefix not in route_db.unicast_routes:
                route_db.add_unicast_route(entry)
        if self.cpu.enable_node_segment_label:
            for entry in self.cpu._node_label_routes(
                my_node_name, area_link_states
            ).values():
                route_db.add_mpls_route(entry)
        if self.cpu.enable_adjacency_labels:
            for entry in self.cpu._adj_label_routes(my_node_name, area_link_states):
                route_db.add_mpls_route(entry)
        for entry in self.cpu.static_mpls_routes.values():
            route_db.add_mpls_route(entry)

    # -- whole-fabric sharded path ------------------------------------------

    def build_fabric_route_dbs(
        self,
        root_names: list[str],
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        mesh=None,
    ) -> dict[str, Optional[DecisionRouteDb]]:
        """Every requested vantage's full RIB in ONE sharded device pass:
        roots are data-parallel over the mesh's 'batch' axis and the
        graph's node columns shard over 'graph' with a pmin halo exchange
        per relaxation (parallel/sharding.py). This is the multi-chip
        scale path — the reference's closest analogue is per-area
        partitioning (openr/kvstore/KvStore.h:148); here the LSDB stays
        whole and the work shards.

        Fast-path (IP/SP_ECMP) prefixes compute on the mesh, with LFA
        when enabled; irregular prefixes + statics + MPLS go through the
        CPU oracle per vantage, exactly as build_route_db. The trip bound
        seeds from the single-chip pipeline's measured count and is
        verified by the kernel's per-root convergence vote — on
        Unconverged the bound doubles and the step reruns (each retry is
        one recompile of the fixed-trip loop; converged bounds are cached
        by shape)."""
        from openr_tpu.parallel.sharding import (
            Unconverged,
            make_mesh,
            sharded_fabric_step,
        )

        if len(area_link_states) != 1:
            return {
                r: self.cpu.build_route_db(r, area_link_states, prefix_state)
                for r in root_names
            }
        area, link_state = next(iter(area_link_states.items()))

        fast_by_area, slow, ksp2, _ksp2_by_area = self._partition_prefixes(
            prefix_state, area_link_states
        )
        fast = fast_by_area.get(area, [])

        result: dict[str, Optional[DecisionRouteDb]] = {}
        known = [r for r in root_names if link_state.has_node(r)]
        for r in root_names:
            if r not in known:
                result[r] = None

        if fast and known:
            ad = self._sync_area(area, link_state, prefix_state, fast)
            plan, matrix = ad.plan, ad.matrix
            if mesh is None:
                mesh = make_mesh()
            batch = int(mesh.shape["batch"])
            n_pad = -(-len(known) // batch) * batch
            padded = known + [known[0]] * (n_pad - len(known))
            roots = np.array(
                [plan.node_index[nm] for nm in padded], np.int32
            )
            outs = [plan.out_links(link_state, nm) for nm in padded]
            d_cap = max(o[0].shape[0] for o in outs)
            out_nbr = np.full((n_pad, d_cap), -1, np.int32)
            out_w = np.full((n_pad, d_cap), INF_E, np.int32)
            for i, (nbr, w, _links) in enumerate(outs):
                out_nbr[i, : nbr.shape[0]] = nbr
                out_w[i, : w.shape[0]] = w

            lfa = self.cpu.enable_lfa
            block_v4 = not (
                self.cpu.enable_v4 or self.cpu.v4_over_v6_nexthop
            )
            use_v4_allowed = not self.cpu.v4_over_v6_nexthop
            # one vantage's measured eccentricity bound; another root's
            # can be ~2x it, so seed with 2x + 1 slack
            n_trips = max(2, 2 * self.last_trips + 1)
            cap_trips = max(4, relax_ops.max_trips(plan.n_cap))
            while True:
                try:
                    (_dist, metric, s3, nh_mask, lfa_slot, lfa_metric,
                     ok) = sharded_fabric_step(
                        mesh, plan, matrix, roots, out_nbr, out_w,
                        n_trips, lfa=lfa, block_v4=block_v4,
                        with_ok=True,
                    )
                    break
                except Unconverged:
                    if n_trips >= cap_trips:
                        raise
                    n_trips = min(2 * n_trips, cap_trips)

            metric = np.asarray(metric)
            s3 = np.asarray(s3)
            nh_mask = np.asarray(nh_mask)
            lfa_slot = np.asarray(lfa_slot)
            lfa_metric = np.asarray(lfa_metric)
            ok = np.asarray(ok)
            p_n = len(matrix.prefix_list)
            for i, nm in enumerate(known):
                links = outs[i][2]
                crib = ColumnarRib(
                    nm, matrix, list(links), int(roots[i]),
                    block_v4, use_v4_allowed, lfa,
                )
                crib.set_full_arrays(
                    metric[i][:p_n].astype(np.int32), s3[i][:p_n],
                    nh_mask[i][:p_n],
                    lfa_slot[i][:p_n] if lfa else None,
                    lfa_metric[i][:p_n] if lfa else None,
                    ok=ok[i][:p_n],
                )
                db = DecisionRouteDb()
                # routes stay columnar until a consumer iterates; slow/
                # static host routes land in the Lazy's overrides, which
                # shadow the view — the seed's merge order
                db.unicast_routes = LazyUnicastRoutes({}, [crib.view()])
                result[nm] = db

        for nm in known:
            db = result.get(nm)
            if db is None:
                db = result[nm] = DecisionRouteDb()
            if ksp2:
                # one batched masked-SSSP device pass per vantage instead
                # of one host Dijkstra per (vantage, KSP2 destination)
                self._prime_ksp2(
                    nm, area, link_state, prefix_state, ksp2, fast
                )
            self._host_routes(
                nm, area_link_states, prefix_state, slow + ksp2, db
            )
        return result

    # -- device state sync -------------------------------------------------

    def _donation_on(self) -> bool:
        """Donate resident buffers into delta scatters (in-place HBM
        update). CPU cannot honor donation and warns, so gate there."""
        if self._donate is None:
            import jax

            self._donate = jax.default_backend() != "cpu"
        return self._donate

    def _mc_mesh_for(self, n_cap: int):
        """The ('batch','graph') mesh the multichip tier solves this
        capacity class on, or None when the tier stays off: threshold
        disabled or not exceeded, or fewer than two visible devices
        (the eligibility ladder's first rung — every rung below it,
        incremental seeding included, applies unchanged within the
        chosen tier). The shard_mapped SSSP needs the node axis to
        divide the graph axis; capacity classes are pow2 so this only
        trips on exotic meshes, and the tier then stays off rather
        than fall over.

        `force_single_chip` is the overload ladder's shedding rung
        (runtime/overload.py): while set, the tier stays off and the
        next _sync_area tier flip re-puts the mirrors single-chip,
        releasing the mesh's HBM; clearing it restores the tier by the
        same flip path — reversible by construction."""
        if self.force_single_chip:
            return None
        thr = self.multichip_n_cap_threshold
        if thr <= 0 or n_cap <= thr:
            return None
        if self._mc_mesh is False:
            import jax

            from openr_tpu.parallel.sharding import make_mesh

            if len(jax.devices()) < 2:
                self._mc_mesh = None
            else:
                self._mc_mesh = make_mesh(
                    batch=self.multichip_batch or None
                )
        mesh = self._mc_mesh
        if mesh is not None and n_cap % mesh.shape["graph"] != 0:
            return None
        return mesh

    def _put_counted(self, arr, sharding=None):
        import jax

        self._bytes_uploaded += arr.nbytes
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jax.device_put(arr)

    def _scatter_counted(self, d_arr, idx, vals, sharding=None):
        """Scatter (idx, vals) into the resident array; uploads only the
        delta-sized index/value buffers. With `sharding` (multichip
        tier) the result is pinned to the resident NamedSharding — a
        per-shard update, not a gather-to-one-device round trip."""
        self._bytes_uploaded += idx.nbytes + vals.nbytes
        donate = self._donation_on()
        if donate:
            # the donated input may be referenced by the last-exec probe
            # tuples; those handles die with the donation
            self._last_exec = None
            self._last_exec_incr = None
        if sharding is not None:
            return _mc_scatter_jit(sharding, donate)(d_arr, idx, vals)
        return _scatter_jit(donate)(d_arr, idx, vals)

    def _diff_scatter(self, d_arr, old_np, new_np, extra_idx=None,
                      sharding=None):
        """Reconcile a resident device array to `new_np` by scattering
        only the positions where it differs. The device holds `old_np`'s
        content except at `extra_idx` (undrained dirty slots whose
        device values are unknown) — those are force-included so the
        result is exact regardless. Falls back to a full re-put when
        the diff is no longer delta-sized."""
        diff = np.flatnonzero(old_np.ravel() != new_np.ravel())
        if extra_idx:
            diff = np.union1d(
                diff, np.asarray(extra_idx, np.int64)
            )
        if diff.size == 0:
            return d_arr
        if diff.size * 4 > new_np.size:
            # >25% changed: per-element scatter traffic approaches the
            # full array — one contiguous re-put is cheaper
            return self._put_counted(new_np, sharding)
        idx = diff.astype(np.int32)
        vals = np.ascontiguousarray(new_np.ravel()[diff])
        return self._scatter_counted(d_arr, idx, vals, sharding)

    def _sync_area(self, area: str, link_state: LinkState,
                   prefix_state: PrefixState, prefixes: list) -> _AreaDev:
        # guards the LSDB reads AND the drain-journal writes
        # (ad.drain_log / drain_epoch) — the state a cross-thread
        # caller would silently corrupt
        if affinity.enabled():
            affinity.assert_owner(self, "_sync_area")
        ad = self._area_dev.get(area)
        if ad is None:
            ad = self._area_dev[area] = _AreaDev()
        old_plan = ad.plan
        plan = sync_plan(link_state, old_plan)
        rebuilt = plan is not old_plan
        ad.plan = plan
        # multichip tier decision: placement is part of the mirror's
        # identity, so a tier flip (a capacity-class crossing of the
        # threshold in either direction) forces the full re-put branch
        # below under the NEW placement, drops the probe handles into
        # the old one, and — via that branch's drain-log reset marker —
        # makes incremental seeding fall back exactly once.
        mc_mesh = self._mc_mesh_for(plan.n_cap)
        if mc_mesh != ad.mc_mesh:
            ad.mc_mesh = mc_mesh
            ad.d_deltas = None  # forces the full re-put branch
            ad.flags = None  # matrix mirror re-ships, new placement
            self._last_exec = None
            self._last_exec_incr = None
        mc_sh = None
        if mc_mesh is not None:
            from openr_tpu.parallel.sharding import plan_shardings

            # d_cap is per-vantage: the root tables get their placement
            # from the jit's in_shardings at dispatch, so 0 here is an
            # unused slot
            mc_sh = plan_shardings(
                mc_mesh, plan.n_cap, plan.res_rows.shape[0], 0
            )
            counters.set_counter(
                "decision.solver.multichip.shards", mc_mesh.size
            )

        def shp(key):
            return None if mc_sh is None else mc_sh[key]

        if rebuilt or ad.d_deltas is None:
            # same-capacity rebuild (index renumbering, class reshuffle
            # without a pow2 bucket change): the resident arrays stay on
            # device and only changed slices ship. The device holds the
            # OLD plan's content except at its undrained dirty slots —
            # _diff_scatter folds those in, so the reconcile is exact.
            same_caps = (
                old_plan is not None
                and ad.d_deltas is not None
                and old_plan.deltas.shape == plan.deltas.shape
                and old_plan.shift_w.shape == plan.shift_w.shape
                and old_plan.res_rows.shape == plan.res_rows.shape
                and old_plan.res_nbr.shape == plan.res_nbr.shape
                and old_plan.res_w.shape == plan.res_w.shape
            )
            if same_caps:
                n_cap_o = old_plan.n_cap
                kr_o = old_plan.res_nbr.shape[1]
                sd = [
                    k * n_cap_o + u
                    for k, u, _, _ in old_plan.dirty_shift
                ]
                rd = [
                    r * kr_o + c for r, c, _, _ in old_plan.dirty_res
                ]
                ad.d_deltas = self._diff_scatter(
                    ad.d_deltas, old_plan.deltas, plan.deltas,
                    sharding=shp("replicated"),
                )
                ad.d_shift_w = self._diff_scatter(
                    ad.d_shift_w, old_plan.shift_w, plan.shift_w, sd,
                    sharding=shp("shift_w"),
                )
                if old_plan.dirty_res_nbr:
                    # residual slot layout changed without tracked
                    # indices — the residual mirror re-ships whole
                    ad.d_res_rows = self._put_counted(
                        plan.res_rows, shp("res_rows")
                    )
                    ad.d_res_nbr = self._put_counted(
                        plan.res_nbr, shp("res_2d")
                    )
                    ad.d_res_w = self._put_counted(
                        plan.res_w, shp("res_2d")
                    )
                else:
                    ad.d_res_rows = self._diff_scatter(
                        ad.d_res_rows, old_plan.res_rows, plan.res_rows,
                        sharding=shp("res_rows"),
                    )
                    ad.d_res_nbr = self._diff_scatter(
                        ad.d_res_nbr, old_plan.res_nbr, plan.res_nbr,
                        sharding=shp("res_2d"),
                    )
                    ad.d_res_w = self._diff_scatter(
                        ad.d_res_w, old_plan.res_w, plan.res_w, rd,
                        sharding=shp("res_2d"),
                    )
            else:
                ad.d_deltas = self._put_counted(
                    plan.deltas, shp("replicated")
                )
                ad.d_shift_w = self._put_counted(
                    plan.shift_w, shp("shift_w")
                )
                ad.d_res_rows = self._put_counted(
                    plan.res_rows, shp("res_rows")
                )
                ad.d_res_nbr = self._put_counted(
                    plan.res_nbr, shp("res_2d")
                )
                ad.d_res_w = self._put_counted(plan.res_w, shp("res_2d"))
            plan.dirty_shift = []
            plan.dirty_res = []
            plan.dirty_res_nbr = False
            # mirror content changed without per-slot old values — any
            # resident distance plane from before this epoch cannot be
            # incrementally advanced across it
            ad.drain_epoch += 1
            ad.drain_log.append((ad.drain_epoch, None, None))
            # first churn after a cold build must not pay the edge
            # locator build inside its convergence window
            from openr_tpu.ops.edgeplan import prewarm_edge_loc

            prewarm_edge_loc(plan)
        else:
            ((s_idx, s_val, s_old), (r_idx, r_val, r_old),
             nbr_changed) = drain_dirty(plan)
            if s_idx is not None:
                ad.d_shift_w = self._scatter_counted(
                    ad.d_shift_w, s_idx, s_val, shp("shift_w")
                )
            if r_idx is not None:
                ad.d_res_w = self._scatter_counted(
                    ad.d_res_w, r_idx, r_val, shp("res_2d")
                )
            ad.drain_epoch += 1
            if nbr_changed:
                ad.d_res_rows = self._put_counted(
                    plan.res_rows, shp("res_rows")
                )
                ad.d_res_nbr = self._put_counted(
                    plan.res_nbr, shp("res_2d")
                )
                # residual slot layout changed: journal old values no
                # longer name stable (row, col) edges — reset marker
                ad.drain_log.append((ad.drain_epoch, None, None))
            else:
                s_map = (
                    {} if s_idx is None
                    else dict(zip(s_idx.tolist(), s_old.tolist()))
                )
                r_map = (
                    {} if r_idx is None
                    else dict(zip(r_idx.tolist(), r_old.tolist()))
                )
                ad.drain_log.append((ad.drain_epoch, s_map, r_map))

        # announcer matrix: keyed on prefix churn + node-index stability
        mkey = (prefix_state.generation, plan.index_version)
        if ad.matrix_key != mkey or ad.matrix is None:
            # packed matrices are pure derivations — memoized on the
            # PrefixState so a fresh solver over live state (restart-in-
            # process, any-vantage, sharded fabric) skips the ~1s
            # 100k-prefix packing loop
            cache = getattr(prefix_state, "_matrix_memo", None)
            if cache is None:
                cache = prefix_state._matrix_memo = {}
            # link_state.generation pins the node-index mapping (the
            # mirror_source memo rebuilds it only on a new generation)
            ckey = (
                prefix_state.generation, area, link_state.generation,
            )
            hit = cache.get(area)
            if (
                hit is not None
                and hit[0] == ckey
                and hit[1] == prefixes
            ):
                ad.matrix = hit[2]
            else:
                ad.matrix = build_prefix_matrix(
                    prefix_state, plan.node_index, area, prefixes
                )
                cache[area] = (ckey, prefixes, ad.matrix)
            ad.matrix_key = mkey
            ad.matrix_version += 1
            ad.flags = None  # force re-pack
        # packing is a pure function of (matrix, overload set): with an
        # unchanged matrix and an unchanged overload snapshot the packed
        # mirror on device is already current — skip the O(6*P*A) host
        # concat that used to run on every sync
        if ad.flags is None or not np.array_equal(
            plan.node_overloaded, ad.pack_over
        ):
            flags, mbuf = _pack_matrix(ad.matrix, plan.node_overloaded)
            ad.pack_over = plan.node_overloaded.copy()
            if ad.flags is None or not np.array_equal(flags, ad.flags):
                ad.flags = flags
                ad.d_mbuf = self._put_counted(mbuf, shp("replicated"))
        return ad

    # -- the fast path ------------------------------------------------------

    def _solve_fast(
        self,
        my_node_name: str,
        area: str,
        link_state: LinkState,
        prefix_state: PrefixState,
        prefixes: list[str],
    ):
        """Single-area prep + dispatch (the unfused path, kept for
        callers outside dispatch_route_db's grouping loop); returns the
        prepare() closure."""
        return self._dispatch_one(self._prep_vantage(
            my_node_name, area, link_state, prefix_state, prefixes
        ))

    def _prep_vantage(
        self,
        my_node_name: str,
        area: str,
        link_state: LinkState,
        prefix_state: PrefixState,
        prefixes: list[str],
    ) -> dict:
        """Host half of a fast-path solve (the tpu.sync span): device
        mirror sync, out-link extraction, vantage-state (re)init. Reads
        LSDB state, so it must run on the owning thread. Returns the
        dispatch context consumed by _dispatch_one/_dispatch_fused."""
        import time as _time

        t0 = _time.perf_counter()
        ad = self._sync_area(area, link_state, prefix_state, prefixes)
        plan, matrix = ad.plan, ad.matrix
        root_idx = plan.node_index[my_node_name]
        root_nbr, root_w, links = plan.out_links(link_state, my_node_name)
        d_cap = root_nbr.shape[0]
        mc = ad.mc_mesh
        if mc is not None:
            # pad the out-slot axis to the batch-axis size so the
            # vantage rows shard evenly. Padded lanes are inert: their
            # seeds are invalid (INF_E weight -> all-INF distance rows),
            # via[pad] = INF_E + dist never wins the ECMP predicate, LFA
            # sees them link-down, and the crib unpacks only
            # len(links) next-hop bits.
            from openr_tpu.parallel.sharding import pad_to

            b = mc.shape["batch"]
            d_pad = -(-d_cap // b) * b
            if d_pad != d_cap:
                root_nbr = pad_to(root_nbr, d_pad, -1)
                root_w = pad_to(root_w, d_pad, INF_E)
                d_cap = d_pad
        p_cap, a_cap = matrix.ann_node.shape
        r_cap, kr_cap = plan.res_nbr.shape
        has_res = plan.k_res > 0
        shape_key = (
            plan.n_cap, plan.s_cap, r_cap, kr_cap, has_res, d_cap, p_cap, a_cap
        )
        # the vantage cache key ALSO folds in the next-hop address
        # version: in-place renumbering invalidates materialized routes
        # without any shape change (the jit pipeline itself is
        # address-free and keys on shape alone) — and the multichip
        # mesh: a tier flip reinitializes the vantage, so prev outputs
        # and distance planes from one placement never feed the other
        # tier's executable.
        cache_key = shape_key + (link_state.nh_addr_version, mc)

        vkey = (area, my_node_name)
        if my_node_name != self.my_node_name:
            self._touch_foreign_vantage(vkey)
        vs = self._vstates.get(vkey)
        if vs is None:
            vs = self._vstates[vkey] = _VantageState()
        links_tuple = tuple(links)
        lfa = self.cpu.enable_lfa
        block_v4 = not (self.cpu.enable_v4 or self.cpu.v4_over_v6_nexthop)
        # round-loop selection (ops/relax.py): the bucketed Δ-stepping
        # kernel engages only when the plan derived a usable Δ
        # (delta_exp > 0 — it has nonzero shift classes with finite
        # weights); ineligible plans fall back to the sync rounds
        # silently. delta_exp joins the executable's capacity signature,
        # kernel the fuse key (sync and bucketed lanes never vmap
        # together).
        if self.spf_kernel == "bucketed" and plan.delta_exp > 0:
            spf_kernel, delta_exp = "bucketed", plan.delta_exp
        else:
            spf_kernel, delta_exp = "sync", 0
        if (
            vs.shape_key != cache_key
            or vs.matrix_version != ad.matrix_version
            or not vs.valid
            or vs.links_tuple != links_tuple
        ):
            # (re)initialize prev outputs to zeros -> every row reads as
            # changed -> full pull path below
            wa = -(-a_cap // 16)
            wd = -(-d_cap // 16)
            vs.prev = (
                self._put_counted(np.zeros(p_cap, np.int32)),
                self._put_counted(np.zeros((p_cap, wa), np.int32)),
                self._put_counted(np.zeros((p_cap, wd), np.int32)),
                self._put_counted(np.zeros(p_cap, np.int32)),
                self._put_counted(np.zeros(p_cap, np.int32)),
            )
            vs.shape_key = cache_key
            vs.matrix_version = ad.matrix_version
            vs.crib = ColumnarRib(
                my_node_name, matrix, list(links), root_idx,
                block_v4, not self.cpu.v4_over_v6_nexthop, lfa,
            )
            vs.links_tuple = links_tuple
            vs.valid = False
            vs.prev_dist = None
            vs.dist_epoch = -1
            vs.root_sig = None

        # incremental eligibility: a resident distance plane whose
        # epoch window is covered by the drain journal, an unchanged
        # root out-link signature, and no zero-weight edges (equal-
        # distance parent cycles break subtree invalidation). Any
        # failed gate simply dispatches the full pipeline.
        root_sig = (root_nbr.tobytes(), (root_w < INF_E).tobytes())
        incr = None
        if (
            self.incremental_spf
            and vs.valid
            and vs.prev_dist is not None
            and vs.root_sig == root_sig
            and not plan.has_zero_w
        ):
            merged = _merge_drain_log(ad, vs.dist_epoch)
            if merged is not None:
                s_map, r_map = merged
                cap = _dirty_bucket(max(len(s_map), len(r_map), 1))
                if cap is not None:
                    s_pad = plan.s_cap * plan.n_cap  # OOB -> dropped
                    r_pad = r_cap * kr_cap
                    sd_idx = np.full(cap, s_pad, np.int32)
                    sd_old = np.zeros(cap, np.int32)
                    sd_idx[:len(s_map)] = list(s_map.keys())
                    sd_old[:len(s_map)] = list(s_map.values())
                    rd_idx = np.full(cap, r_pad, np.int32)
                    rd_old = np.zeros(cap, np.int32)
                    rd_idx[:len(r_map)] = list(r_map.keys())
                    rd_old[:len(r_map)] = list(r_map.values())
                    denom = d_cap * plan.n_nodes
                    incr = {
                        "cap": cap,
                        "sd_idx": sd_idx, "sd_old": sd_old,
                        "rd_idx": rd_idx, "rd_old": rd_old,
                        "cone_limit": np.int32(
                            self.incremental_cone_frac * denom
                        ),
                        "denom": denom,
                    }

        t1 = _time.perf_counter()
        return {
            "area": area, "ad": ad, "plan": plan, "matrix": matrix,
            "root_idx": root_idx, "root_nbr": root_nbr, "root_w": root_w,
            "shape_key": shape_key,
            "fuse_key": (shape_key, lfa, block_v4, spf_kernel, delta_exp),
            "vs": vs, "lfa": lfa, "block_v4": block_v4,
            "kernel": spf_kernel, "delta_exp": delta_exp,
            "d_cap": d_cap, "p_cap": p_cap, "a_cap": a_cap,
            "mc": mc, "incr": incr, "root_sig": root_sig,
            "dist_epoch": ad.drain_epoch,
            "t0": t0, "t1": t1,
        }

    def _lane_args(self, pv: dict) -> tuple:
        ad, vs = pv["ad"], pv["vs"]
        root_idx = np.int32(pv["root_idx"])
        root_nbr, root_w = pv["root_nbr"], pv["root_w"]
        if self._transfer_guard_mode() is not None and pv.get("mc") is None:
            # under the guard the per-dispatch root-table uploads go
            # explicit (jax.device_put), so only UNexpected implicit
            # transfers remain to trip it
            root_idx = self._put_counted(np.asarray(root_idx))
            root_nbr = self._put_counted(np.ascontiguousarray(root_nbr))
            root_w = self._put_counted(np.ascontiguousarray(root_w))
        return (
            ad.d_deltas, ad.d_shift_w, ad.d_res_rows, ad.d_res_nbr,
            ad.d_res_w, ad.d_mbuf,
            root_idx, root_nbr, root_w,
            *vs.prev,
        )

    def _transfer_guard_mode(self) -> Optional[str]:
        """Active jax.transfer_guard level for the exec hot path, or
        None when the knob is off (decision_config.transfer_guard)."""
        mode = self.transfer_guard
        return mode if mode in ("log", "disallow") else None

    def _run_exec(self, namespace: str, kernel_name: str, signature,
                  run, args, area: str):
        """ONE executable invocation under the retrace sentinel's scope
        and — opt-in — jax.transfer_guard. A compile firing here after
        the kernel's warmup is a retrace (ops/xla_cache.retrace); with
        transfer_guard="disallow" an implicit host<->device transfer
        raises, is counted + attributed as a finding, and the dispatch
        retries unguarded so routing still converges. The multichip
        tier skips the guard: its root tables take their placement from
        the jit's in_shardings, which the guard cannot distinguish from
        a stray implicit upload."""
        mode = self._transfer_guard_mode()
        if mode is None or namespace == "multichip":
            with retrace.scope(namespace, kernel_name, signature):
                return run(*args)
        import jax

        try:
            with retrace.scope(namespace, kernel_name, signature):
                with jax.transfer_guard(mode):
                    return run(*args)
        # lint: allow(broad-except) guard findings downgrade, not fail
        except Exception as e:
            if "transfer" not in str(e).lower():
                raise
            counters.increment("decision.solver.transfer_guard.findings")
            self.last_sentinels["transfer_guard_findings"] = (
                self.last_sentinels.get("transfer_guard_findings", 0) + 1
            )
            log.warning(
                "transfer_guard finding: implicit transfer in area %s "
                "kernel %s (%s); re-dispatching unguarded", area,
                kernel_name, e,
            )
            with retrace.scope(namespace, kernel_name, signature):
                return run(*args)

    # backstop for the speculative doubler: never bake past this class
    # (a misparsed cap would otherwise queue an absurd compile)
    _SPECULATE_MAX_NCAP = 1 << 21

    def _maybe_speculate(self, pv: dict) -> None:
        """Hand the background-compile fiber (ops/xla_cache.baker) the
        NEXT capacity class's full-solve executable (ISSUE 20): the
        class one pow2 tier up per _next_shape_key, under this
        dispatch's variant flags, compiled from abstract avals and
        persisted to the AOT cache — so a fabric that grows through the
        tier flip finds the executable installed instead of stalling
        its first post-flip solve behind XLA. When the next class
        crosses the multichip threshold the sharded variant is baked on
        the tier mesh (with the root-degree axis padded to the batch
        axis, mirroring _prep_vantage). The baker dedups by label, so
        an oscillating fabric bakes each tier once; a wrong guess costs
        one background compile and one retained cache file."""
        if not self.aot_speculate:
            return
        from openr_tpu.ops.xla_cache import baker

        nxt = _next_shape_key(pv["shape_key"])
        if nxt[0] > self._SPECULATE_MAX_NCAP:
            return
        mesh = self._mc_mesh_for(nxt[0])
        if mesh is not None:
            b = mesh.shape["batch"]
            d_pad = -(-nxt[5] // b) * b
            nxt = nxt[:5] + (d_pad,) + nxt[6:]
        lfa, block_v4 = pv["lfa"], pv["block_v4"]
        sent, emit = self.enable_sentinels, self.incremental_spf
        kern, dexp = pv["kernel"], pv["delta_exp"]
        tier = _mesh_tag(mesh) if mesh is not None else "1chip"
        label = f"next:{nxt}:{lfa}:{block_v4}:{kern}:{dexp}:{emit}:{tier}"

        def bake():
            if mesh is not None:
                _, run = _instrumented_mc(
                    mesh, *nxt, _DELTA_BUDGET, lfa, block_v4, sent,
                    emit, kern, dexp,
                )
            else:
                _, run = _instrumented_pipeline(
                    *nxt, _DELTA_BUDGET, lfa, block_v4, sent, emit,
                    kern, dexp,
                )
            run.prime(*_pipeline_avals(nxt))

        baker.submit(label, bake)

    def _dispatch_one(self, pv: dict):
        """Dispatch one area's pipeline and start the async result copy;
        returns the prepare() closure for the materialization worker.
        With incremental_spf on, an eligible vantage dispatches the
        incr-namespace kernel seeded from its resident distance plane;
        either way the distance plane is emitted and kept resident as
        the next solve's seed."""
        emit = self.incremental_spf
        incr = pv.get("incr")
        mc = pv.get("mc")
        self._maybe_speculate(pv)
        if mc is not None:
            counters.increment("decision.solver.multichip.dispatches")
        if incr is not None:
            if mc is None and self.streaming_pipeline:
                # streaming epoch: same eligibility ladder as the
                # incremental solve (its rungs ARE the fallback ladder
                # — first solve, shape/root churn, journal gaps all
                # land in the full branch below), different download
                # contract + donated double-buffer
                return self._dispatch_stream(pv)
            if mc is not None:
                kernel_name, run = _instrumented_mc_incr(
                    mc, *pv["shape_key"], _DELTA_BUDGET, incr["cap"],
                    pv["lfa"], pv["block_v4"], self.enable_sentinels,
                    pv["kernel"], pv["delta_exp"],
                )
            else:
                kernel_name, run = _instrumented_incr(
                    *pv["shape_key"], _DELTA_BUDGET, incr["cap"],
                    pv["lfa"], pv["block_v4"], self.enable_sentinels,
                    pv["kernel"], pv["delta_exp"],
                )
            args = self._lane_args(pv) + (
                pv["vs"].prev_dist,
                incr["sd_idx"], incr["sd_old"],
                incr["rd_idx"], incr["rd_old"], incr["cone_limit"],
            )
            ns = "multichip" if mc is not None else "incr"
            delta_buf, full_buf, *new_prev = self._run_exec(
                ns, kernel_name, pv["shape_key"], run, args, pv["area"]
            )
            # resident incremental state for the device-only probe
            # (bench.py incr_device_ms): prev outputs chain through
            # o[2:7], the distance plane through o[7], the dirty tail
            # re-applies verbatim
            self._last_exec_incr = (
                run, args[:9], tuple(new_prev[:5]), new_prev[5],
                args[15:],
            )
            return self._make_prepare(
                pv, kernel_name, delta_buf, full_buf, new_prev,
                emit=True, incr=True,
            )
        if mc is not None:
            kernel_name, run = _instrumented_mc(
                mc, *pv["shape_key"], _DELTA_BUDGET, pv["lfa"],
                pv["block_v4"], self.enable_sentinels, emit,
                pv["kernel"], pv["delta_exp"],
            )
        else:
            kernel_name, run = _instrumented_pipeline(
                *pv["shape_key"], _DELTA_BUDGET, pv["lfa"],
                pv["block_v4"], self.enable_sentinels, emit,
                pv["kernel"], pv["delta_exp"],
            )
        args = self._lane_args(pv)
        ns = "multichip" if mc is not None else ""
        delta_buf, full_buf, *new_prev = self._run_exec(
            ns, kernel_name, pv["shape_key"], run, args, pv["area"]
        )
        counters.increment("decision.solver.full.solves")
        if self.incremental_spf:
            # full dispatch while incremental is on: first / ineligible
            # solve or a host-gate fallback (journal gap, root churn,
            # zero-weight edges, oversized dirty set)
            counters.increment("decision.solver.incr.full_fallbacks")
        # resident pipeline state for device-only throughput probes
        # (bench.py device_compute_ms): re-invokable with outputs fed
        # forward as the next prev
        self._last_exec = (run, args[:9], tuple(new_prev[:5]))
        return self._make_prepare(
            pv, kernel_name, delta_buf, full_buf, new_prev, emit=emit
        )

    def _dispatch_stream(self, pv: dict):
        """Streaming-epoch dispatch (jit-cache namespace "stream"): ONE
        fused executable chains the incremental relax, selection/LFA
        and the on-device column diff, and the download is the bucketed
        changed-rows payload carrying the device route-ok bit
        (ops/stream.py). The previous epoch's published planes + warm
        distance seed are DONATED into the dispatch — the epoch
        double-buffer flips in place in HBM — so the vantage advances
        to the new handles IMMEDIATELY after dispatch and stays invalid
        until prepare() commits the columnar patch: an abandoned
        prepare costs one clean full rebuild, never a crib that has
        silently diverged from the resident planes. Donation also kills
        the device-probe replay state (its stored prev handles), so
        both probes are cleared."""
        incr, vs = pv["incr"], pv["vs"]
        sbudget = int(vs.stream_budget) or 64
        # the guarded-retry path in _run_exec replays the call after a
        # finding — impossible once the inputs are donated — and CPU
        # cannot honor donation at all: gate it off for both
        donate = (
            self._donation_on() and self._transfer_guard_mode() is None
        )
        kernel_name, run = _instrumented_stream(
            *pv["shape_key"], _DELTA_BUDGET, incr["cap"], sbudget,
            pv["lfa"], pv["block_v4"], self.enable_sentinels,
            pv["kernel"], pv["delta_exp"], donate,
        )
        args = self._lane_args(pv) + (
            vs.prev_dist,
            incr["sd_idx"], incr["sd_old"],
            incr["rd_idx"], incr["rd_old"], incr["cone_limit"],
        )
        delta_buf, full_buf, *new_prev = self._run_exec(
            "stream", kernel_name, pv["shape_key"], run, args,
            pv["area"],
        )
        prepare = self._make_prepare(
            pv, kernel_name, delta_buf, full_buf, new_prev,
            emit=True, incr=True, stream=sbudget,
        )
        # post-donation hygiene, on the dispatch thread before anything
        # can observe the dead handles: advance the double-buffer,
        # invalidate until the prepare lands, drop the replay probes
        vs.prev = tuple(new_prev[:5])
        vs.prev_dist = new_prev[5]
        vs.dist_epoch = pv["dist_epoch"]
        vs.root_sig = pv["root_sig"]
        vs.valid = False
        self._last_exec = None
        self._last_exec_incr = None
        return prepare

    def _dispatch_fused(self, group: list[dict]) -> list[tuple]:
        """ONE vmapped dispatch for a group of same-shape areas; returns
        (pv, prepare) pairs. Per-area inputs travel as g-tuples (a
        pytree — still a single dispatch), so the per-call overhead the
        single path pays per area is paid once for the group."""
        g = len(group)
        pv0 = group[0]
        kernel_name, run = _instrumented_fused(
            g, *pv0["shape_key"], _DELTA_BUDGET, pv0["lfa"],
            pv0["block_v4"], self.enable_sentinels,
            pv0["kernel"], pv0["delta_exp"],
        )
        lanes = [self._lane_args(pv) for pv in group]
        area_args = tuple(
            tuple(lane[i] for lane in lanes) for i in range(14)
        )
        outs = self._run_exec(
            "", kernel_name, pv0["shape_key"], run, area_args,
            pv0["area"],
        )
        counters.increment("decision.device.fused_dispatches")
        counters.increment("decision.device.fused_areas", g)
        counters.increment("decision.solver.full.solves", g)
        result = []
        for pv, out in zip(group, outs):
            delta_buf, full_buf, *new_prev = out
            result.append((pv, self._make_prepare(
                pv, kernel_name, delta_buf, full_buf, new_prev, fused=g
            )))
        return result

    def _make_prepare(self, pv: dict, kernel_name: str, delta_buf,
                      full_buf, new_prev, fused: int = 0,
                      emit: bool = False, incr: bool = False,
                      stream: int = 0):
        """Start the async device->host copy of the buffer the solve
        will consume and build the prepare() closure that patches the
        vantage's columnar RIB on the materialization worker.
        Thread-safety: one worker thread, and the caller does not touch
        this vantage's state until it collects the future.

        With `stream` (the streaming epoch's changed-rows bucket) the
        delta payload is the bucketed ops/stream.py layout: the device
        route-ok bit rides per changed row, so the patch goes through
        apply_rows_packed — no host word-unpack, and the crib journal
        entry is marked device-exact (fast_unicast_column_diff then
        skips its re-compare). An over-budget epoch falls back to the
        device-compacted full pull and the budget grows for the next
        epoch."""
        import time as _time

        from openr_tpu.ops.stream import STREAM_BUDGETS, stream_budget

        plan, matrix, vs = pv["plan"], pv["matrix"], pv["vs"]
        lfa = pv["lfa"]
        sentinels = self.enable_sentinels
        d_cap, p_cap, a_cap = pv["d_cap"], pv["p_cap"], pv["a_cap"]
        t0, t1 = pv["t0"], pv["t1"]
        spf_kernel = pv.get("kernel", "sync")
        mc = pv.get("mc")
        mc_info = None if mc is None else {
            "shards": mc.size,
            "batch": mc.shape["batch"],
            "graph": mc.shape["graph"],
        }
        was_valid = vs.valid
        incr_denom = (pv.get("incr") or {}).get("denom", 1)
        # start the device->host copy of the buffer we will consume; it
        # flies while the caller does unrelated host work
        (delta_buf if was_valid else full_buf).copy_to_host_async()

        def prepare() -> dict:
            # runs on the materialization worker. prev advances HERE,
            # atomically with the columnar update: if interleaved host
            # work raises before collection, the next solve still
            # compares against the outputs last applied, so the aborted
            # solve's changed rows are not silently treated as applied
            vs.prev = tuple(new_prev[:5])
            if emit:
                # the emitted distance plane becomes the next solve's
                # warm seed, stamped with the drain epoch and root
                # signature it was computed under
                vs.prev_dist = new_prev[5]
                vs.dist_epoch = pv["dist_epoch"]
                vs.root_sig = pv["root_sig"]
            wa = -(-a_cap // 16)
            wd = -(-d_cap // 16)
            b = stream or _DELTA_BUDGET
            crib = vs.crib
            count = None
            trips = 0
            if mc_info is not None:
                # per-shard kernel timing: this worker is about to
                # block on these buffers anyway, so blocking each
                # device's replica in sequence costs nothing extra and
                # yields per-device completion latency since dispatch —
                # a straggler chip shows up as one outlier entry
                per_shard = {}
                try:
                    for _sh in new_prev[0].addressable_shards:
                        _sh.data.block_until_ready()
                        per_shard[str(getattr(_sh.device, "id", len(per_shard)))] = round(
                            (_time.perf_counter() - t1) * 1e3, 3
                        )
                # lint: allow(broad-except) timing is best-effort
                except Exception:
                    per_shard = {}
                if per_shard:
                    mc_info["shard_ms"] = per_shard
            if was_valid:
                dbuf = np.asarray(delta_buf)  # ONE pull
                count = int(dbuf[0])
                trips = int(dbuf[1])
            t2 = _time.perf_counter()
            full_pull = count is None or count > b
            stats = {
                "n_cap": plan.n_cap,
                "s_cap": plan.s_cap,
                "k_res": plan.k_res,
                "n_prefixes": len(matrix.prefix_list),
                "changed_rows": count,
                "full_pull": full_pull,
                "kernel": kernel_name,
                "fused": fused,
            }
            if mc_info is not None:
                stats["multichip"] = mc_info
            if full_pull:
                fbuf = np.asarray(full_buf)
                t2 = _time.perf_counter()
                okc = int(fbuf[0])
                trips = int(fbuf[1])
                o = 2
                oidx = fbuf[o:o + p_cap]; o += p_cap
                metric = fbuf[o:o + p_cap]; o += p_cap
                s3w = fbuf[o:o + p_cap * wa].reshape(p_cap, wa); o += p_cap * wa
                nhw = fbuf[o:o + p_cap * wd].reshape(p_cap, wd); o += p_cap * wd
                lfa_slot = lfa_metric = None
                if lfa:
                    lfa_slot = fbuf[o:o + p_cap]; o += p_cap
                    lfa_metric = fbuf[o:o + p_cap]
                crib.set_full_packed(
                    oidx[:okc], metric[:okc], s3w[:okc], nhw[:okc],
                    None if lfa_slot is None else lfa_slot[:okc],
                    None if lfa_metric is None else lfa_metric[:okc],
                )
                vs.valid = True
            elif count:
                o = 2
                cidx = dbuf[o:o + b]; o += b
                metric = dbuf[o:o + b]; o += b
                s3w = dbuf[o:o + b * wa].reshape(b, wa); o += b * wa
                nhw = dbuf[o:o + b * wd].reshape(b, wd); o += b * wd
                okb = None
                if stream:
                    # streaming payload: device route-ok bit per row
                    okb = dbuf[o:o + b]; o += b
                lfa_slot = lfa_metric = None
                if lfa:
                    lfa_slot = dbuf[o:o + b]; o += b
                    lfa_metric = dbuf[o:o + b]
                live = cidx < p_cap
                if stream:
                    crib.apply_rows_packed(
                        cidx[live][:count], metric[live][:count],
                        s3w[live][:count], nhw[live][:count],
                        okb[live][:count].astype(bool),
                        None if lfa_slot is None
                        else lfa_slot[live][:count],
                        None if lfa_metric is None
                        else lfa_metric[live][:count],
                    )
                else:
                    crib.apply_rows(
                        cidx[live][:count], metric[live][:count],
                        s3w[live][:count], nhw[live][:count],
                        None if lfa_slot is None else lfa_slot[live][:count],
                        None if lfa_metric is None else lfa_metric[live][:count],
                    )
            # tail layout, back to front: [-1] is always the executed-
            # relaxation rounds scalar; the incremental kernel's
            # [cone, fell_back] sit at [-3]/[-2]; the sentinel scalars
            # precede whichever of those are present
            sbuf = fbuf if full_pull else dbuf
            rounds = int(sbuf[-1])
            if incr:
                cone = int(sbuf[-3])
                fell_back = bool(sbuf[-2])
                stats["incremental"] = True
                stats["cone"] = cone
                stats["fell_back"] = fell_back
                if fell_back:
                    counters.increment(
                        "decision.solver.incr.full_fallbacks"
                    )
                else:
                    counters.increment("decision.solver.incr.solves")
                counters.add_stat_value(
                    "decision.solver.incr.cone_frac",
                    cone / max(incr_denom, 1),
                )
                counters.add_stat_value(
                    "decision.solver.incr.changed_rows", count or 0
                )
            if sentinels:
                off = -3 if incr else -1
                stats["sentinels"] = {
                    "unreachable_rows": int(sbuf[off - 2]),
                    "saturated_rows": int(sbuf[off - 1]),
                }
            # device->host download accounting: every pulled buffer
            # counts (an over-budget streaming epoch pays both the
            # delta head-peek and the full pull)
            bytes_dl = 0
            if was_valid:
                bytes_dl += int(dbuf.nbytes)
            if full_pull:
                bytes_dl += int(fbuf.nbytes)
            stats["bytes_downloaded"] = bytes_dl
            if stream:
                stats["stream"] = {
                    "budget": b,
                    "overflow": bool(full_pull),
                }
                # adapt next epoch's bucket to the observed churn: grow
                # past an overflow, settle back toward the floor when
                # the storm quiets (quantized — budget churn can't
                # thrash the "stream" jit-cache namespace)
                vs.stream_budget = (
                    stream_budget(count or 0) or STREAM_BUDGETS[-1]
                )
                # donation left the vantage invalid across the dispatch
                # window; the columnar patch above committed, so the
                # resident planes and the crib agree again
                vs.valid = True
                counters.increment("decision.stream.epochs")
                counters.add_stat_value(
                    "decision.stream.changed_rows", count or 0
                )
                counters.add_stat_value(
                    "decision.stream.bytes_downloaded", bytes_dl
                )
                if full_pull:
                    counters.increment("decision.stream.overflows")
            stats["trips"] = trips
            # executed-relaxation work accounting (ISSUE 13): rounds is
            # the device-counted relaxation passes; under the bucketed
            # kernel trips counts bucket epochs, and in the multichip
            # tier each sync relaxation (= round) costs one pmin halo
            # exchange while bucketed pays one per EPOCH
            stats["rounds"] = rounds
            stats["spf_kernel"] = spf_kernel
            stats["bucket_epochs"] = trips if spf_kernel == "bucketed" else 0
            if mc_info is not None:
                stats["halo_exchanges"] = (
                    trips if spf_kernel == "bucketed" else rounds
                )
            # prime the ok-row index off the actor thread: the columnar
            # diff downstream starts from key_rows(), and computing it
            # here (still on the materialization worker) keeps the
            # Decision loop's first touch O(1)
            stats["ok_rows"] = int(len(crib.cols.key_rows()))
            t3 = _time.perf_counter()
            return {
                "view": crib.view(),
                "stats": stats,
                "timing": {
                    "sync_ms": (t1 - t0) * 1e3,
                    "exec_ms": (t2 - t1) * 1e3,
                    "mat_ms": (t3 - t2) * 1e3,
                },
            }

        return prepare

    # -- device-assisted KSP2 ----------------------------------------------

    def _prime_ksp2(
        self, my_node_name, area, link_state, prefix_state, prefixes, fast
    ) -> None:
        """Prime LinkState's SPF + k-paths caches from device distance
        fields so the oracle's unchanged KSP2 assembly (selection,
        canonical trace, label stacks — spf_solver._select_best_paths_ksp2)
        runs with ZERO host Dijkstras:

          1. The unmasked base field (ops/ksp2.base_dist) is pulled once
             per topology generation; it backs a LazySpfResult (the
             reachability filter + k=1 trace metric source) — replacing
             the 50k-node host Dijkstra that dominated steady-state KSP2.
          2. The per-destination masked second-pass fields batch on
             device and ship as sparse deltas against the base
             (masked_sssp_delta_batch): a masked row deviates only where
             every shortest path used a removed first-path edge.

        Parity is structural: the fields equal run_spf's metrics (SSSP
        has unique values), and the canonical trace depends only on
        those values. Ref hot loop replaced:
        openr/decision/LinkState.cpp:790-819."""
        import time as _time

        from openr_tpu.ops.edgeplan import _ensure_edge_loc, edge_loc_of
        from openr_tpu.ops.ksp2 import (
            MaskedRowsState,
            base_dist,
            masked_rows_dispatch,
            masked_rows_update,
        )

        import jax

        dests = sorted({
            node
            for pfx in prefixes
            for (node, a) in (prefix_state.entries_for(pfx) or {})
            if a == area
            and node != my_node_name
            and link_state.has_node(node)
        })
        if all(
            (my_node_name, d, 2) in link_state._kth_paths for d in dests
        ) and (my_node_name, True) in link_state._spf_results:
            return  # warm: nothing to prime, skip all device work

        _t0 = _time.perf_counter()
        ad = self._sync_area(area, link_state, prefix_state, fast)
        plan = ad.plan
        _ensure_edge_loc(plan)
        root_idx = plan.node_index[my_node_name]
        node_index = plan.node_index

        d_shift_w, d_res_w = ad.d_shift_w, ad.d_res_w
        root_overloaded = link_state.is_node_overloaded(my_node_name)
        if root_overloaded:
            # run_spf exempts the root from its own transit drain; the
            # mirror folded the drain into the root's out-edge weights,
            # so restore them for this (rare) case
            sw = plan.shift_w.copy()
            rw = plan.res_w.copy()
            for link in link_state.links_from_node(my_node_name):
                if not link.is_up():
                    continue
                w = min(link.metric_from_node(my_node_name), 1 << 28)
                kind, a, b = edge_loc_of(plan, link, my_node_name)
                if kind == "s":
                    sw[a, b] = w
                else:
                    rw[a, b] = w
            d_shift_w = jax.device_put(sw)
            d_res_w = jax.device_put(rw)

        # base (k=1) field: one device SSSP + one [n_cap] pull per
        # (vantage, topology generation). The masked batch dispatches
        # SPECULATIVELY (previous masks) right behind it, so its compute
        # and transfer overlap the base pull + the host trace work.
        bkey = (area, my_node_name)
        self._touch_ksp2_state(bkey)
        gen = link_state.generation
        cached = None if root_overloaded else self._ksp2_base.get(bkey)
        rstate = self._ksp2_rows.get(bkey)
        if rstate is None:
            rstate = self._ksp2_rows[bkey] = MaskedRowsState()
        if cached is not None and cached[0] == gen and cached[1] is plan:
            d_base, base_np = cached[2], cached[3]
            spec = None  # same generation: rows already current
        else:
            d_base = base_dist(
                plan, d_shift_w, ad.d_res_rows, ad.d_res_nbr, d_res_w,
                ad.d_deltas, root_idx,
            )
            d_base.copy_to_host_async()
            spec = masked_rows_dispatch(
                rstate, plan, d_shift_w, ad.d_res_rows, ad.d_res_nbr,
                d_res_w, ad.d_deltas, root_idx,
            )
            base_np = np.asarray(d_base)
            if not root_overloaded:
                self._ksp2_base[bkey] = (gen, plan, d_base, base_np)
        _t1 = _time.perf_counter()

        def metric_of(n, _idx=node_index, _base=base_np):
            j = _idx.get(n)
            if j is None:
                return None
            v = int(_base[j])
            return None if v >= INF_E else v

        link_state.prime_spf_metrics(my_node_name, metric_of)

        # -- trace-reuse certificates ---------------------------------------
        # A canonical trace is a pure function of (the dist values it
        # read, the link attributes at the nodes it visited). Remember
        # each dest's read-set; if since the last prime (a) only "links"
        # changelog events occurred, (b) no flapped link endpoint and no
        # base-field change touches the read-set, and (c) for k=2 the
        # masked row is value-identical (device-verified), the previous
        # paths are re-primed without re-tracing. One victim flap then
        # re-traces only the destinations it actually affects.
        ck = (area, my_node_name)
        certs = None if root_overloaded else self._ksp2_certs.get(ck)
        reusable = certs is not None and certs["plan"] is plan
        flap_dirty: set = set()
        dirty: set = set()
        if reusable:
            events = link_state.events_since(certs["gen"])
            reusable = events is not None and all(
                ev[0] == "links" for ev in events
            )
            if reusable:
                for _kind, links in events:
                    for lk in links:
                        flap_dirty.add(lk.n1)
                        flap_dirty.add(lk.n2)
                dirty = set(flap_dirty)
                prev_base = certs["base_np"]
                if prev_base is not base_np:
                    names = plan.node_names
                    for j in np.nonzero(base_np != prev_base)[0]:
                        if j < len(names):
                            dirty.add(names[j])
        cert_dests = certs["dests"] if reusable else {}

        new_dests: dict = {}
        jobs = []  # (dest, ignore_set, mask_locs, cert, reads1, paths1)
        for dest in dests:
            if (my_node_name, dest, 2) in link_state._kth_paths:
                continue
            c = cert_dests.get(dest)
            reads1 = None
            paths1 = link_state._kth_paths.get((my_node_name, dest, 1))
            if paths1 is None:
                if (
                    c is not None
                    and c["reads1"] is not None
                    and not (c["reads1"] & dirty)
                ):
                    paths1, reads1 = c["paths1"], c["reads1"]
                else:
                    reads1 = set()

                    def rd1(n, _r=reads1, _m=metric_of):
                        _r.add(n)
                        return _m(n)

                    paths1 = link_state.trace_paths_on_dist(
                        my_node_name, dest, rd1, set()
                    )
                link_state.prime_kth_paths(my_node_name, dest, 1, paths1)
            if not paths1:
                link_state.prime_kth_paths(my_node_name, dest, 2, [])
                new_dests[dest] = {
                    "reads1": reads1, "paths1": paths1,
                    "locs": None, "reads2": set(), "paths2": [],
                }
                continue
            ignore = link_state.kth_paths_ignore_set(my_node_name, dest, 2)
            locs = []
            for link in ignore:
                locs.append(edge_loc_of(plan, link, link.n1))
                locs.append(edge_loc_of(plan, link, link.n2))
            jobs.append((dest, ignore, locs, c, reads1, paths1))
        _t2 = _time.perf_counter()
        if not jobs:
            if not root_overloaded:
                self._ksp2_certs[ck] = {
                    "gen": link_state.generation, "plan": plan,
                    "base_np": base_np, "dests": new_dests,
                }
            self._ksp2_timing = {
                "ksp2_base_ms": (_t1 - _t0) * 1e3,
                "ksp2_k1_ms": (_t2 - _t1) * 1e3,
            }
            return

        changed = masked_rows_update(
            rstate, plan, d_shift_w, ad.d_res_rows, ad.d_res_nbr, d_res_w,
            ad.d_deltas, root_idx,
            tuple(j[0] for j in jobs), [j[2] for j in jobs],
            spec=spec,
        )
        _t3 = _time.perf_counter()
        node_names = plan.node_names
        reused_traces = 0
        for i, (dest, ignore, locs, c, reads1, paths1) in enumerate(jobs):
            ch = changed[i]
            reuse = (
                c is not None
                and ch is not True
                and c["locs"] == locs
                and not (c["reads2"] & flap_dirty)
            )
            if reuse and ch is not None:
                # the row changed, but maybe nowhere this trace looked
                reuse = not any(
                    node_names[j] in c["reads2"]
                    for j in ch.tolist()
                    if j < len(node_names)
                )
            if reuse:
                paths2, reads2 = c["paths2"], c["reads2"]
                reused_traces += 1
            else:
                reads2 = set()
                row = rstate.host_rows[i]

                def dist_of(n, _r=reads2, _row=row, _idx=node_index):
                    _r.add(n)
                    j = _idx.get(n)
                    if j is None:
                        return None
                    v = int(_row[j])
                    return None if v >= INF_E else v

                paths2 = link_state.trace_paths_on_dist(
                    my_node_name, dest, dist_of, ignore
                )
            link_state.prime_kth_paths(my_node_name, dest, 2, paths2)
            new_dests[dest] = {
                "reads1": reads1 if reads1 is not None else (
                    c["reads1"] if c else None
                ),
                "paths1": paths1, "locs": locs,
                "reads2": reads2, "paths2": paths2,
            }
        if not root_overloaded:
            self._ksp2_certs[ck] = {
                "gen": link_state.generation, "plan": plan,
                "base_np": base_np, "dests": new_dests,
            }
        from openr_tpu.ops import ksp2 as _ksp2_ops

        self._ksp2_timing = dict(
            ksp2_base_ms=(_t1 - _t0) * 1e3,
            ksp2_k1_ms=(_t2 - _t1) * 1e3,
            ksp2_batch_ms=(_t3 - _t2) * 1e3,
            ksp2_trace_ms=(_time.perf_counter() - _t3) * 1e3,
            ksp2_reused_traces=reused_traces,
            **{f"ksp2_{k}": v for k, v in _ksp2_ops.last_stats.items()},
        )

    def device_compute_ms(self, iters: int = 8) -> Optional[float]:
        """Amortized device-only time per full pipeline execution: chain
        `iters` dispatches of the last solve's pipeline, feeding each
        run's resident outputs forward as the next run's prev (exactly
        the steady-state dependency), and block once at the end. The one
        host round trip is amortized across the chain, so this measures
        what the chip does per solve — bench.py reports it next to the
        e2e number, whose gap is the rig's fixed transfer RTT."""
        import time as _time

        import jax

        if self._last_exec is None:
            return None
        run, dev_args, prev = self._last_exec
        out = run(*dev_args, *prev)
        jax.block_until_ready(out)
        t0 = _time.perf_counter()
        o = out
        for _ in range(iters):
            # outputs 2..6 are the 5 resident prev_* arrays (slot 7,
            # when present, is the emitted distance plane)
            o = run(*dev_args, *o[2:7])
        jax.block_until_ready(o)
        return (_time.perf_counter() - t0) * 1e3 / iters

    def incr_device_compute_ms(self, iters: int = 8) -> Optional[float]:
        """Amortized device-only time per INCREMENTAL pipeline
        execution — the incremental analogue of device_compute_ms.
        Chains the last incremental dispatch with its own dirty tail
        re-applied each iteration: prev outputs feed through o[2:7],
        the emitted distance plane through o[7], so every link in the
        chain pays the full parent-plane + cone + warm-re-relax cost
        (bench.py incr_device_ms)."""
        import time as _time

        import jax

        if self._last_exec_incr is None:
            return None
        run, dev_args, prev, prev_dist, tail = self._last_exec_incr
        out = run(*dev_args, *prev, prev_dist, *tail)
        jax.block_until_ready(out)
        t0 = _time.perf_counter()
        o = out
        for _ in range(iters):
            o = run(*dev_args, *o[2:7], o[7], *tail)
        jax.block_until_ready(o)
        return (_time.perf_counter() - t0) * 1e3 / iters

    def probe_device(self) -> None:
        """Health canary for Decision's degraded-mode re-promotion: run
        ONE device execution and block on the result, raising whatever
        the runtime raises when the device is unhealthy. Re-runs the
        last compiled pipeline when one is resident (the cheapest real
        execution — no recompilation); otherwise a trivial on-device
        reduction proves dispatch + transfer work."""
        import jax

        if self._last_exec is not None:
            run, dev_args, prev = self._last_exec
            jax.block_until_ready(run(*dev_args, *prev))
            return
        import jax.numpy as jnp

        jax.block_until_ready(jnp.arange(8, dtype=jnp.int32).sum())
