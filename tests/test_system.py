"""N-node single-process system tests — the end-to-end slice.

Role of the reference's openr/tests/OpenrSystemTest.cpp: multiple complete
node stacks (OpenrWrapper) share a MockIoMesh, forming an emulated network
in one process with sped-up timers; tests assert end-to-end route
convergence (ref RingTopologyMultiPathTest :243; 4-node mesh = BASELINE
config #1's example_openr.conf topology).
"""

import asyncio
import itertools

from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.runtime.openr_wrapper import OpenrWrapper
from openr_tpu.spark import MockIoMesh
from tests.conftest import run_async

CONVERGENCE_S = 20.0  # generous bound; typ. < 3s (ref kMaxOpenrSyncTime)


async def start_mesh(names, links):
    """links: list of (node_a, if_a, node_b, if_b)."""
    mesh = MockIoMesh()
    kv_ports: dict[str, int] = {}
    nodes = {n: OpenrWrapper(n, mesh.provider(n), kv_ports) for n in names}
    for a, if_a, b, if_b in links:
        mesh.connect(a, if_a, b, if_b)
    ifaces = {n: [] for n in names}
    for a, if_a, b, if_b in links:
        ifaces[a].append(if_a)
        ifaces[b].append(if_b)
    for n, w in nodes.items():
        await w.start(*ifaces[n])
    return mesh, nodes


async def stop_all(nodes):
    for w in nodes.values():
        await w.stop()


def loopback(i: int) -> str:
    return f"10.0.0.{i + 1}/32"


class TestFourNodeMesh:
    """BASELINE config #1: 4-node full mesh, every node originates its
    loopback; every node must program routes to the other three."""

    @run_async
    async def test_full_mesh_converges(self):
        names = [f"node-{i}" for i in range(4)]
        links = [
            (a, f"if-{a}-{b}", b, f"if-{b}-{a}")
            for a, b in itertools.combinations(names, 2)
        ]
        mesh, nodes = await start_mesh(names, links)
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))

            def converged():
                for i, n in enumerate(names):
                    expect = {loopback(j) for j in range(4) if j != i}
                    if set(nodes[n].fib_routes) != expect:
                        return False
                return True

            await wait_until(converged, timeout_s=CONVERGENCE_S)
            # direct single-hop next hops in a full mesh
            for i, n in enumerate(names):
                for j, m in enumerate(names):
                    if i == j:
                        continue
                    entry = nodes[n].fib_routes[loopback(j)]
                    assert {nh.neighbor_node_name for nh in entry.nexthops} == {m}
        finally:
            await stop_all(nodes)

    @run_async
    async def test_node_failure_reroutes(self):
        """Ring 0-1-2-3-0: kill the 0-1 link; 0 must reach 1's loopback
        the long way (via 3)."""
        names = [f"node-{i}" for i in range(4)]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-23", "node-3", "if-32"),
            ("node-3", "if-30", "node-0", "if-03"),
        ]
        mesh, nodes = await start_mesh(names, links)
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(loopback(i))
            await wait_until(
                lambda: loopback(1) in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            entry = nodes["node-0"].fib_routes[loopback(1)]
            assert {nh.neighbor_node_name for nh in entry.nexthops} == {
                "node-1"
            }
            # cut the direct link (both the wire and the hellos)
            mesh.disconnect("node-0", "if-01", "node-1", "if-10")

            def rerouted():
                entry = nodes["node-0"].fib_routes.get(loopback(1))
                if entry is None:
                    return False
                return {nh.neighbor_node_name for nh in entry.nexthops} == {
                    "node-3"
                }

            await wait_until(rerouted, timeout_s=CONVERGENCE_S)
        finally:
            await stop_all(nodes)

    @run_async
    async def test_prefix_withdrawal_propagates(self):
        names = ["node-0", "node-1", "node-2"]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
        ]
        mesh, nodes = await start_mesh(names, links)
        try:
            nodes["node-2"].advertise_prefix("10.9.0.0/24")
            await wait_until(
                lambda: "10.9.0.0/24" in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            # multihop: node-0 reaches it via node-1
            entry = nodes["node-0"].fib_routes["10.9.0.0/24"]
            assert {nh.neighbor_node_name for nh in entry.nexthops} == {
                "node-1"
            }
            nodes["node-2"].withdraw_prefix("10.9.0.0/24")
            await wait_until(
                lambda: "10.9.0.0/24" not in nodes["node-0"].fib_routes,
                timeout_s=CONVERGENCE_S,
            )
        finally:
            await stop_all(nodes)


class TestEcmpSystem:
    @run_async
    async def test_diamond_ecmp_end_to_end(self):
        """0-1-3 / 0-2-3 diamond: 0's route to 3's loopback carries both
        next hops all the way into the programmed FIB."""
        names = [f"node-{i}" for i in range(4)]
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-0", "if-02", "node-2", "if-20"),
            ("node-1", "if-13", "node-3", "if-31"),
            ("node-2", "if-23", "node-3", "if-32"),
        ]
        mesh, nodes = await start_mesh(names, links)
        try:
            nodes["node-3"].advertise_prefix(loopback(3))

            def has_ecmp():
                entry = nodes["node-0"].fib_routes.get(loopback(3))
                if entry is None:
                    return False
                return {nh.neighbor_node_name for nh in entry.nexthops} == {
                    "node-1",
                    "node-2",
                }

            await wait_until(has_ecmp, timeout_s=CONVERGENCE_S)
        finally:
            await stop_all(nodes)


class TestMultiAreaRedistribution:
    """left --(area1)-- center --(area2)-- right (ref lab 201_areas):
    a prefix originated in area1 must cross the area boundary via
    center's RIB redistribution and land in right's FIB, with
    provenance on the area stack."""

    @run_async
    async def test_prefix_crosses_areas_through_center(self):
        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}

        def center_area(node, iface):
            return "area1" if iface == "if-c-l" else "area2"

        left = OpenrWrapper(
            "left", mesh.provider("left"), kv_ports, areas=["area1"]
        )
        center = OpenrWrapper(
            "center", mesh.provider("center"), kv_ports,
            areas=["area1", "area2"], resolve_area=center_area,
        )
        right = OpenrWrapper(
            "right", mesh.provider("right"), kv_ports, areas=["area2"]
        )
        mesh.connect("left", "if-l-c", "center", "if-c-l")
        mesh.connect("center", "if-c-r", "right", "if-r-c")
        await left.start("if-l-c")
        await center.start("if-c-l", "if-c-r")
        await right.start("if-r-c")
        try:
            left.advertise_prefix("10.31.0.0/24", dest_areas=("area1",))
            right.advertise_prefix("10.32.0.0/24", dest_areas=("area2",))

            # center programs both originals
            await wait_until(
                lambda: {"10.31.0.0/24", "10.32.0.0/24"}
                <= set(center.fib_routes),
                timeout_s=CONVERGENCE_S,
            )
            # the redistributed copies cross the boundary into the
            # opposite side's kernel-facing FIB
            await wait_until(
                lambda: "10.31.0.0/24" in right.fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            await wait_until(
                lambda: "10.32.0.0/24" in left.fib_routes,
                timeout_s=CONVERGENCE_S,
            )
            # provenance: right sees center's RIB-type re-advertisement
            # with area1 on the stack and a bumped distance
            vals = await right.kvstore.dump_all("area2")
            from openr_tpu.serde import deserialize
            from openr_tpu.types import PrefixDatabase, PrefixType

            key = [
                k for k in vals
                if "center" in k and "10.31.0.0/24" in k
            ]
            assert key, sorted(vals)
            db = deserialize(vals[key[0]].value, PrefixDatabase)
            e = db.prefix_entries[0]
            assert e.type == PrefixType.RIB
            assert e.area_stack == ("area1",)
            assert e.metrics.distance >= 1

            # withdrawal propagates all the way back out
            left.withdraw_prefix("10.31.0.0/24")
            await wait_until(
                lambda: "10.31.0.0/24" not in right.fib_routes,
                timeout_s=CONVERGENCE_S,
            )
        finally:
            await stop_all({"l": left, "c": center, "r": right})


class TestRingPartitionSoak:
    """Randomized partition/heal soak on a 6-node ring (ref
    OpenrSystemTest RingTopology tests, scaled): every round cuts one
    ring link, asserts traffic reroutes the long way for every
    affected loopback, heals it, and asserts the short paths return.
    Exercises Spark hold-timer loss detection, KvStore re-peering +
    full sync after heal, and Decision/Fib reconvergence repeatedly in
    one process."""

    @run_async
    async def test_partition_heal_rounds(self):
        import random

        rng = random.Random(7)
        n = 6
        names = [f"node-{i}" for i in range(n)]
        links = [
            (
                names[i], f"if-{i}{(i + 1) % n}",
                names[(i + 1) % n], f"if-{(i + 1) % n}{i}",
            )
            for i in range(n)
        ]
        mesh, nodes = await start_mesh(names, links)
        try:
            for i, name in enumerate(names):
                nodes[name].advertise_prefix(loopback(i))

            def all_reach_all():
                return all(
                    loopback(j) in nodes[nm].fib_routes
                    for nm in names
                    for j in range(n)
                    if names[j] != nm
                )

            await wait_until(all_reach_all, timeout_s=CONVERGENCE_S)

            for round_no in range(3):
                i = rng.randrange(n)
                a, if_a, b, if_b = links[i]
                lb_a, lb_b = loopback(i), loopback((i + 1) % n)
                mesh.disconnect(a, if_a, b, if_b)

                # first wait for the loss to be DETECTED ON BOTH SIDES
                # (stale direct routes satisfy reachability until the
                # hold timer fires): each endpoint must reroute the
                # other's loopback away from the cut link
                def rerouted(src, dst, lb):
                    e = nodes[src].fib_routes.get(lb)
                    return e is not None and all(
                        nh.neighbor_node_name != dst for nh in e.nexthops
                    )

                await wait_until(
                    lambda: rerouted(a, b, lb_b) and rerouted(b, a, lb_a),
                    timeout_s=CONVERGENCE_S,
                )
                # the ring minus one link is a line: everyone still
                # reaches everyone, now the long way around
                await wait_until(all_reach_all, timeout_s=CONVERGENCE_S)

                mesh.connect(a, if_a, b, if_b)
                # heal: the direct adjacency must come back and win
                # again on both sides
                def direct_again(src, dst, lb):
                    e = nodes[src].fib_routes.get(lb)
                    return e is not None and {
                        nh.neighbor_node_name for nh in e.nexthops
                    } == {dst}

                await wait_until(
                    lambda: direct_again(a, b, lb_b)
                    and direct_again(b, a, lb_a),
                    timeout_s=CONVERGENCE_S,
                )
                await wait_until(all_reach_all, timeout_s=CONVERGENCE_S)
        finally:
            await stop_all(nodes)
