"""RIB value types + route-delta containers.

Role of the reference's openr/decision/RibEntry.h (RibUnicastEntry:43,
RibMplsEntry:112, filterNexthopsToUniqueAction:158) and RouteUpdate.h:29
(DecisionRouteUpdate), plus the delta computation DecisionRouteDb::
calculateUpdate (SpfSolver.h:57-98).

NextHop re-expresses thrift::NextHopThrift: in this framework a next hop is
identified structurally by (neighbor node, local interface, area) — the
address fields are carried for Fib programming but excluded from routing
equality only where the reference does the same (it compares full structs;
so do we).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace  # noqa: F401
from typing import Optional

from openr_tpu.types import PerfEvents, PrefixEntry


class MplsActionCode(enum.IntEnum):
    """ref Network.thrift MplsActionCode."""

    PUSH = 0
    SWAP = 1
    PHP = 2  # Penultimate hop popping: POP and FORWARD
    POP_AND_LOOKUP = 3


@dataclass(frozen=True)
class MplsAction:
    action: MplsActionCode
    swap_label: Optional[int] = None
    push_labels: tuple[int, ...] = ()


@dataclass(frozen=True)
class NextHop:
    """ref Network.thrift NextHopThrift / createNextHop (LsdbUtil)."""

    address: str  # neighbor's link address (v4 or v6), "" if abstract
    if_name: str = ""
    metric: int = 0  # IGP cost to destination over this next hop
    mpls_action: Optional[MplsAction] = None
    area: str = ""
    neighbor_node_name: str = ""
    weight: int = 0  # 0 = ECMP; >0 = UCMP normalized weight


# MPLS label validity range (ref LsdbUtil isMplsLabelValid; RFC 3032:
# 16 reserved labels, 20-bit label space)
MAX_MPLS_LABEL = (1 << 20) - 1
MIN_MPLS_LABEL = 16


def is_mpls_label_valid(label: int) -> bool:
    return MIN_MPLS_LABEL <= label <= MAX_MPLS_LABEL


def filter_nexthops_to_unique_action(
    nexthops: frozenset[NextHop],
) -> frozenset[NextHop]:
    """Keep only next hops whose MPLS action matches the min-metric next
    hop's action (hardware can't mix SWAP/PHP in one ECMP group;
    ref RibEntry.h:158)."""
    if not nexthops:
        return nexthops
    best = min(
        nexthops,
        key=lambda nh: (
            nh.metric,
            nh.mpls_action.action if nh.mpls_action else -1,
        ),
    )
    best_action = best.mpls_action.action if best.mpls_action else None
    return frozenset(
        nh
        for nh in nexthops
        if (nh.mpls_action.action if nh.mpls_action else None) == best_action
    )


@dataclass(frozen=True)
class RibUnicastEntry:
    """One computed unicast route (ref RibEntry.h:43-110).

    lfa_nexthops carries the loop-free-alternate backup next hop(s)
    (rfc5286) when the solver runs with LFA enabled: a neighbor N is a
    valid alternate for this prefix iff dist_N(P) < dist_N(self) +
    dist_self(P), which guarantees N's own shortest path to P does not
    loop back through this node. Alternates are kept separate from the
    primary ECMP set — Fib programs them as backup next hops, never as
    load-balanced members (their metric is the alternate path cost,
    strictly greater than igp_cost). The reference has no LFA; this is
    the TPU build's fast-reroute extension (BASELINE config 3), derived
    on device from the same per-neighbor distance fields the ECMP
    next-hop predicate uses (ref next-hop machinery this extends:
    openr/decision/SpfSolver.cpp:1043-1285)."""

    prefix: str
    nexthops: frozenset[NextHop] = frozenset()
    best_prefix_entry: Optional[PrefixEntry] = None
    best_node_area: tuple[str, str] = ("", "")
    do_not_install: bool = False
    igp_cost: int = 0
    ucmp_weight: Optional[int] = None
    counter_id: Optional[str] = None  # set by RibPolicy (ref RibEntry.h:70)
    lfa_nexthops: frozenset[NextHop] = frozenset()


@dataclass(frozen=True)
class RibMplsEntry:
    """One computed MPLS label route (ref RibEntry.h:112-156)."""

    label: int
    nexthops: frozenset[NextHop] = frozenset()


@dataclass(frozen=True)
class RouteProvenance:
    """Originating-event tag for one RIB entry: which kv-store event
    last changed this route and which solve materialized it. Kept in a
    per-prefix side map beside DecisionRouteDb (RibUnicastEntry is
    frozen and flows through the columnar RIB's row compare — widening
    it would dirty every row on upgrade). Queryable per prefix via
    ctrl.decision.explain / `breeze decision explain`. The reference
    has no provenance; this is the TPU build's auditability extension
    for the incremental solver (a route produced by seed-from-previous
    must be attributable to its triggering event)."""

    kv_key: str = ""  # originating kvstore key ("" = static/unknown)
    originator: str = ""  # advertising node (Value.originator_id)
    area: str = ""
    solve_epoch: int = 0  # monotonic per-Decision build counter
    solver_kind: str = "full"  # full | incremental | failover-cpu
    ts_ms: int = 0  # wall clock at stamping


class ProvenanceLedger:
    """Drop-in for Decision's per-prefix provenance dict with a bulk
    column lane: a large build (cold rebuild, mass churn) stamps ONE
    layer recording (membership map, per-prefix event tags, topology
    fallback, ingest-tag snapshot, solve meta) instead of constructing
    one RouteProvenance per route — at 100k..1M routes that object loop
    was the last O(routes) allocation left on the columnar spine. The
    record object is built only when `breeze decision explain` actually
    asks for a prefix.

    get / pop / __setitem__ match dict semantics exactly (the only
    operations Decision performs); newest stamp wins via a global
    sequence, so an explicit re-stamp or delete always shadows an older
    layer and a newer layer shadows older explicit stamps. Layers are
    capped: the oldest folds into explicit records (preserving its
    original sequence) once more than _LAYER_MAX bulk builds coexist."""

    _LAYER_MAX = 4

    __slots__ = ("_explicit", "_layers", "_seq")

    def __init__(self):
        # prefix -> (seq, RouteProvenance | None); None = tombstone
        self._explicit: dict = {}
        # (seq, members, tags, topo, ingest, epoch, kind, ts_ms), seq
        # ascending; `members` is any Mapping with cheap iter/contains
        self._layers: list = []
        self._seq = 0

    def _next(self) -> int:
        self._seq += 1
        return self._seq

    @staticmethod
    def _build(layer, prefix: str) -> RouteProvenance:
        _, _, tags, topo, ingest, epoch, kind, ts_ms = layer
        tag = (
            tags.get(prefix)
            or topo
            or (ingest.get(prefix) if ingest else None)
            or ("", "", "")
        )
        return RouteProvenance(
            kv_key=tag[0], originator=tag[1], area=tag[2],
            solve_epoch=epoch, solver_kind=kind, ts_ms=ts_ms,
        )

    def __setitem__(self, prefix: str, prov: RouteProvenance) -> None:
        self._explicit[prefix] = (self._next(), prov)

    def pop(self, prefix: str, default=None):
        out = self.get(prefix, default)
        if self._layers:
            self._explicit[prefix] = (self._next(), None)
        else:
            self._explicit.pop(prefix, None)
        return out

    def get(self, prefix: str, default=None):
        seq, prov = self._explicit.get(prefix, (0, None))
        for layer in reversed(self._layers):
            if layer[0] <= seq:
                break
            if prefix in layer[1]:
                return self._build(layer, prefix)
        return prov if prov is not None else default

    def stamp_layer(self, members, tags, topo, ingest, epoch, kind,
                    ts_ms) -> None:
        self._layers.append(
            (self._next(), members, tags, topo, ingest, epoch, kind, ts_ms)
        )
        if len(self._layers) > self._LAYER_MAX:
            self._fold_oldest()

    def _fold_oldest(self) -> None:
        layer = self._layers.pop(0)
        seq = layer[0]
        for prefix in layer[1]:
            es, _ = self._explicit.get(prefix, (0, None))
            if es > seq:
                continue
            if any(prefix in nl[1] for nl in self._layers):
                continue  # a newer layer answers for it anyway
            self._explicit[prefix] = (seq, self._build(layer, prefix))


class RouteUpdateType(enum.IntEnum):
    """ref RouteUpdate.h:34."""

    FULL_SYNC = 1
    INCREMENTAL = 2


@dataclass
class DecisionRouteUpdate:
    """Delta container Decision -> Fib/PrefixManager (ref RouteUpdate.h:29)."""

    type: RouteUpdateType = RouteUpdateType.INCREMENTAL
    unicast_routes_to_update: dict[str, RibUnicastEntry] = field(default_factory=dict)
    unicast_routes_to_delete: list[str] = field(default_factory=list)
    mpls_routes_to_update: dict[int, RibMplsEntry] = field(default_factory=dict)
    mpls_routes_to_delete: list[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None
    prefix_type: Optional[int] = None  # set for static-route updates
    # columnar spine: when the diff stayed in packed-array land this is
    # the ColumnDelta behind unicast_routes_to_update (which is then a
    # lazy ColumnUpdateMap, not a dict) — Fib and the platform consume
    # the arrays, object consumers force the Mapping. None on the
    # legacy/object path; excluded from serde (dataclass field order
    # keeps wire compat because serde emits by name).
    columns: Optional[object] = None
    # epoch fence provenance: Decision's solve epoch that produced this
    # delta. Fib coalesces deltas, so its programmed/ack publications
    # carry the NEWEST epoch folded into the pass — with the streaming
    # pipeline overlapping epochs, this is what keeps FIB acks and
    # convergence traces attributed to the right solve. None on static
    # and synthetic updates.
    solve_epoch: Optional[int] = None

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )


@dataclass
class DecisionRouteDb:
    """Full computed RIB (ref SpfSolver.h:57-98)."""

    unicast_routes: dict[str, RibUnicastEntry] = field(default_factory=dict)
    mpls_routes: dict[int, RibMplsEntry] = field(default_factory=dict)

    def add_unicast_route(self, entry: RibUnicastEntry) -> None:
        self.unicast_routes[entry.prefix] = entry

    def add_mpls_route(self, entry: RibMplsEntry) -> None:
        self.mpls_routes[entry.label] = entry

    def calculate_update(self, new_db: "DecisionRouteDb") -> DecisionRouteUpdate:
        """Delta from self -> new_db (ref DecisionRouteDb::calculateUpdate)."""
        upd = DecisionRouteUpdate()
        # columnar spine (ISSUE 12): when the new RIB is a live lazy view
        # over the column stores, the diff itself stays in packed-array
        # land — cold rebuilds ship every ok row with zero compares and
        # zero entry builds, warm rebuilds column-compare only the
        # journaled rows. unicast_routes_to_update becomes a lazy
        # ColumnUpdateMap; Fib/platform consume upd.columns directly.
        from openr_tpu.decision.column_delta import fast_unicast_column_diff
        from openr_tpu.decision.columnar_rib import fast_unicast_diff

        delta = fast_unicast_column_diff(
            self.unicast_routes, new_db.unicast_routes
        )
        if delta is not None:
            upd.columns = delta
            upd.unicast_routes_to_update = delta.lazy_map()
            upd.unicast_routes_to_delete = delta.deletes
            upd.fast_diff = not delta.full  # observability (not a field)
        else:
            # legacy entry-level journal diff (kept as the parity oracle
            # for the columnar path), then the full O(P) compare
            res = fast_unicast_diff(
                self.unicast_routes, new_db.unicast_routes
            )
            if res is not None:
                upd.unicast_routes_to_update, dels = res
                upd.unicast_routes_to_delete = dels
                upd.fast_diff = True  # observability (not a field)
            else:
                for prefix, entry in new_db.unicast_routes.items():
                    old = self.unicast_routes.get(prefix)
                    if old is None or old != entry:
                        upd.unicast_routes_to_update[prefix] = entry
                for prefix in self.unicast_routes:
                    if prefix not in new_db.unicast_routes:
                        upd.unicast_routes_to_delete.append(prefix)
        for label, entry in new_db.mpls_routes.items():
            old = self.mpls_routes.get(label)
            if old is None or old != entry:
                upd.mpls_routes_to_update[label] = entry
        for label in self.mpls_routes:
            if label not in new_db.mpls_routes:
                upd.mpls_routes_to_delete.append(label)
        return upd
