"""Packed column deltas: the zero-copy RIB -> FIB spine.

BENCH_r05 put the cold 100k bottleneck at host materialization: the
solver's packed device output was immediately re-expressed as ~100k
`RibUnicastEntry` objects so the diff, the Fib actor, and the platform
agent could each walk them one at a time. This module keeps that state
columnar end-to-end (the DeltaPath argument — routing state as columnar
dataflow deltas, PAPERS.md arXiv 1808.06893):

  `ColumnDelta`        what `DecisionRouteDb.calculate_update` now
                       produces on the device path: per-segment changed
                       row arrays over live `RibView`s + the small
                       host-touched remainder as real entries. Carries a
                       cheap `LazyUnicastRoutes` snapshot of the new
                       table so the Fib actor can swap desired state in
                       O(1) instead of re-keying 100k dict slots.
  `ColumnUpdateMap`    the Mapping face of a delta
                       (`DecisionRouteUpdate.unicast_routes_to_update`):
                       len/iter/contains are array-backed; values
                       materialize entries in one bulk pass only when a
                       consumer (ctrl/breeze/policy) actually asks.
  `RouteColumnBatch`   the wire/dataplane form: packed
                       (family, prefixlen, address, metric) arrays + a
                       shared next-hop group table, built without
                       constructing route objects. The platform bulk
                       programmer encodes native netlink records
                       straight from these arrays.

The diff (`fast_unicast_column_diff`) compares COLUMNS, not entries:
entry construction is a pure function of (columns, matrix, links), so
byte-equal rows are route-equal and only host-touched keys (bases,
overrides, deletions, cross-segment shadowing) need the object path.
That extends the PR-1 journal diff to the COLD case — an empty old side
is a full-table delta with zero compares and zero entry builds.
"""

from __future__ import annotations

import socket as _socket
from collections.abc import Mapping
from typing import Optional

import numpy as np

from openr_tpu.decision.columnar_rib import (
    LazyUnicastRoutes,
    RibView,
    _lookup,
    unpack_words,
)
from openr_tpu.runtime.counters import counters


def prefix_codec(matrix) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(family u8[P], prefixlen u8[P], address u8[P,16]) for every row of
    a PrefixMatrix, parsed ONCE per matrix generation and cached on the
    matrix — every subsequent batch build indexes these arrays instead of
    re-parsing prefix strings per route."""
    codec = getattr(matrix, "_prefix_codec", None)
    if codec is not None:
        return codec
    plist = matrix.prefix_list
    p_n = len(plist)
    family = np.zeros(p_n, np.uint8)
    plen = np.zeros(p_n, np.uint8)
    addr = np.zeros((p_n, 16), np.uint8)
    v4 = _socket.AF_INET
    v6 = _socket.AF_INET6
    for i, pfx in enumerate(plist):
        ip, _, ln = pfx.partition("/")
        if ":" in ip:
            family[i] = v6
            plen[i] = int(ln) if ln else 128
            addr[i] = np.frombuffer(_socket.inet_pton(v6, ip), np.uint8)
        else:
            family[i] = v4
            plen[i] = int(ln) if ln else 32
            addr[i, :4] = np.frombuffer(_socket.inet_pton(v4, ip), np.uint8)
    # mask host bits so addr is the NETWORK address, matching what the
    # per-route pack derives via ip_network(prefix, strict=False)
    span = np.clip(
        plen.astype(np.int32)[:, None]
        - np.arange(16, dtype=np.int32) * 8,
        0, 8,
    )
    addr &= ((0xFF00 >> span) & 0xFF).astype(np.uint8)
    codec = (family, plen, addr)
    matrix._prefix_codec = codec
    return codec


def _plain_entry(entry) -> dict:
    from openr_tpu.serde import to_plain

    return entry if isinstance(entry, dict) else to_plain(entry)


class RouteColumnBatch:
    """Packed route table/delta at the platform seam. Row i programs
    prefixes[i] with metric[i] via next-hop group nh_gid[i]; `extra` is
    the small host-built remainder (statics, policy overrides) as plain
    route dicts — it rides the batch but takes the object path."""

    __slots__ = (
        "prefixes", "family", "plen", "addr", "metric", "nh_gid",
        "nh_groups", "extra",
    )

    def __init__(self, prefixes, family, plen, addr, metric, nh_gid,
                 nh_groups, extra=None):
        self.prefixes: list[str] = prefixes
        self.family = family
        self.plen = plen
        self.addr = addr
        self.metric = metric
        self.nh_gid = nh_gid
        # group -> list of next-hop descriptor dicts (address, if_name,
        # weight, area, neighbor_node_name); per-route metric is filled
        # at materialization, never stored per group
        self.nh_groups: list[list[dict]] = nh_groups
        self.extra: dict[str, dict] = {
            p: _plain_entry(e) for p, e in (extra or {}).items()
        }

    def __len__(self) -> int:
        return len(self.prefixes)

    def route_count(self) -> int:
        return len(self.prefixes) + len(self.extra)

    def prefix_set(self) -> set:
        s = set(self.prefixes)
        s.update(self.extra)
        return s

    # -- object-path views (dump / fallback / oracle) ----------------------

    def route_dict(self, i: int) -> dict:
        m = int(self.metric[i])
        nhs = [
            dict(nh, metric=m) for nh in self.nh_groups[int(self.nh_gid[i])]
        ]
        return {
            "prefix": self.prefixes[i],
            "nexthops": nhs,
            "igp_cost": m,
            "best_node_area": None,
            "best_prefix_entry": None,
            "do_not_install": False,
        }

    def iter_route_dicts(self):
        for i in range(len(self.prefixes)):
            yield self.prefixes[i], self.route_dict(i)
        yield from self.extra.items()

    def as_route_dicts(self) -> dict[str, dict]:
        return dict(self.iter_route_dicts())

    # -- wire form (runtime/rpc JSON frames) -------------------------------

    def to_wire(self) -> dict:
        import base64

        b64 = lambda a: base64.b64encode(  # noqa: E731
            np.ascontiguousarray(a).tobytes()
        ).decode()
        return {
            "n": len(self.prefixes),
            "prefixes": self.prefixes,
            "family": b64(self.family),
            "plen": b64(self.plen),
            "addr": b64(self.addr),
            "metric": b64(self.metric.astype(np.int32)),
            "nh_gid": b64(self.nh_gid.astype(np.int32)),
            "nh_groups": self.nh_groups,
            "extra": self.extra,
        }

    @classmethod
    def from_wire(cls, obj: dict) -> "RouteColumnBatch":
        import base64

        n = int(obj["n"])
        arr = lambda k, dt: np.frombuffer(  # noqa: E731
            base64.b64decode(obj[k]), dt
        )
        return cls(
            prefixes=list(obj["prefixes"]),
            family=arr("family", np.uint8),
            plen=arr("plen", np.uint8),
            addr=arr("addr", np.uint8).reshape(n, 16),
            metric=arr("metric", np.int32),
            nh_gid=arr("nh_gid", np.int32),
            nh_groups=[list(g) for g in obj["nh_groups"]],
            extra=dict(obj.get("extra") or {}),
        )


def _segment_batch_parts(view: RibView, rows: np.ndarray, gid_base: int):
    """Column arrays + next-hop group table for `rows` of one RibView —
    no per-route Python objects, only the per-GROUP descriptor decode."""
    crib = view.crib
    cols = view.cols
    matrix = crib.matrix
    family, plen, addr = prefix_codec(matrix)
    d_n = max(len(crib.links), 1)
    nhw = cols.nhw[rows]
    use_v4 = matrix.is_v4[rows] if crib.use_v4_allowed else np.zeros(
        len(rows), bool
    )
    aug = np.concatenate(
        [nhw, use_v4.astype(np.int32)[:, None]], axis=1
    )
    uniq, inv = np.unique(aug, axis=0, return_inverse=True)
    inv = inv.reshape(-1)  # numpy 2.0 returned [N,1] for axis-unique
    bits = unpack_words(uniq[:, :-1], d_n)
    me = crib.my_node_name
    groups = []
    for g in range(len(uniq)):
        v4 = bool(uniq[g, -1])
        groups.append([
            {
                "address": crib.links[d].nh_from_node(me, v4),
                "if_name": crib.links[d].iface_from_node(me),
                "area": crib.links[d].area,
                "neighbor_node_name": crib.links[d].other_node(me),
                "weight": 0,
                "mpls_action": None,
            }
            for d in np.flatnonzero(bits[g]).tolist()
        ])
    plist = matrix.prefix_list
    prefixes = [plist[r] for r in rows.tolist()]
    return (
        prefixes, family[rows], plen[rows], addr[rows],
        cols.met[rows].astype(np.int32),
        (inv + gid_base).astype(np.int32), groups,
    )


def _shadowed_rows(lazy: LazyUnicastRoutes, i: int, view: RibView,
                   rows: np.ndarray) -> np.ndarray:
    """Mask of `rows` whose prefix is NOT visible through segment i —
    overridden/deleted by the host, or shadowed by a later segment."""
    later = lazy.segments[i + 1:]
    if not later and not lazy.overrides and not lazy.deleted:
        return np.zeros(len(rows), bool)
    plist = view.crib.matrix.prefix_list
    mask = np.zeros(len(rows), bool)
    ov, dl = lazy.overrides, lazy.deleted
    for j, r in enumerate(rows.tolist()):
        p = plist[r]
        if p in ov or p in dl or any(s.has(p) for s in later):
            mask[j] = True
    return mask


def build_column_batch(lazy) -> Optional[RouteColumnBatch]:
    """Pack a LazyUnicastRoutes table into a RouteColumnBatch, or None
    when the table is not column-backed (plain dict fallback)."""
    if not isinstance(lazy, LazyUnicastRoutes):
        return None
    parts = []
    gid_base = 0
    for i, view in enumerate(lazy.segments):
        rows = view.key_rows()
        shadow = _shadowed_rows(lazy, i, view, rows)
        if shadow.any():
            rows = rows[~shadow]
        if not len(rows):
            continue
        part = _segment_batch_parts(view, rows, gid_base)
        gid_base += len(part[6])
        parts.append(part)
    # host remainder: base routes not shadowed by any view + overrides
    extra = {
        p: e
        for p, e in lazy.base.items()
        if p not in lazy.deleted
        and p not in lazy.overrides
        and not any(s.has(p) for s in lazy.segments)
    }
    extra.update(
        {p: e for p, e in lazy.overrides.items() if p not in lazy.deleted}
    )
    if not parts:
        return RouteColumnBatch(
            [], np.zeros(0, np.uint8), np.zeros(0, np.uint8),
            np.zeros((0, 16), np.uint8), np.zeros(0, np.int32),
            np.zeros(0, np.int32), [], extra,
        )
    return RouteColumnBatch(
        prefixes=[p for part in parts for p in part[0]],
        family=np.concatenate([part[1] for part in parts]),
        plen=np.concatenate([part[2] for part in parts]),
        addr=np.concatenate([part[3] for part in parts]),
        metric=np.concatenate([part[4] for part in parts]),
        nh_gid=np.concatenate([part[5] for part in parts]),
        nh_groups=[g for part in parts for g in part[6]],
        extra=extra,
    )


class ColumnUpdateMap(Mapping):
    """`unicast_routes_to_update` of a columnar build: iteration, len
    and membership run on the packed arrays; reading a VALUE builds the
    entries (bulk on full reads, single-row on point lookups) — the
    lazy object view ctrl/breeze/policy consumers get."""

    __slots__ = ("_delta", "_forced", "_row_sets")

    def __init__(self, delta: "ColumnDelta"):
        self._delta = delta
        self._forced: Optional[dict] = None
        self._row_sets: Optional[list] = None

    def __len__(self) -> int:
        if self._forced is not None:
            return len(self._forced)
        d = self._delta
        return sum(len(r) for _, r in d.segments) + len(d.extra_updates)

    def __iter__(self):
        if self._forced is not None:
            return iter(self._forced)
        return self._delta.update_prefixes()

    def _rows_of(self, i: int) -> set:
        if self._row_sets is None:
            self._row_sets = [None] * len(self._delta.segments)
        s = self._row_sets[i]
        if s is None:
            s = self._row_sets[i] = set(
                self._delta.segments[i][1].tolist()
            )
        return s

    def __contains__(self, k):
        if self._forced is not None:
            return k in self._forced
        d = self._delta
        if k in d.extra_updates:
            return True
        for i, (view, _rows) in enumerate(d.segments):
            r = view._row_of(k)
            if r is not None and r in self._rows_of(i):
                return True
        return False

    def __getitem__(self, k):
        if self._forced is not None:
            return self._forced[k]
        d = self._delta
        e = d.extra_updates.get(k)
        if e is not None:
            return e
        for i, (view, _rows) in enumerate(d.segments):
            r = view._row_of(k)
            if r is not None and r in self._rows_of(i):
                e = view.get(k, bulk=False)
                if e is not None:
                    return e
        raise KeyError(k)

    def items(self):
        return self.materialized().items()

    def values(self):
        return self.materialized().values()

    def materialized(self) -> dict:
        if self._forced is None:
            self._forced = self._delta.materialize_updates()
        return self._forced

    def __eq__(self, other):
        if isinstance(other, ColumnUpdateMap):
            other = other.materialized()
        if isinstance(other, Mapping):
            return self.materialized() == dict(other)
        return NotImplemented

    __hash__ = None  # type: ignore[assignment]

    def __repr__(self):
        return (
            f"ColumnUpdateMap(len={len(self)}, "
            f"segments={len(self._delta.segments)}, "
            f"extra={len(self._delta.extra_updates)})"
        )


class ColumnDelta:
    """One build's route delta in column form: per-segment changed-row
    arrays over the new table's views, host-touched updates as entries,
    deletes as prefix strings, and a cheap snapshot of the whole new
    table so consumers replacing state (Fib full sync) never re-key."""

    __slots__ = (
        "segments", "extra_updates", "deletes", "full", "new_mapping",
        "_batch",
    )

    def __init__(self, segments, extra_updates, deletes, full,
                 new_mapping):
        self.segments: list[tuple[RibView, np.ndarray]] = segments
        self.extra_updates: dict = extra_updates
        self.deletes: list[str] = deletes
        self.full: bool = full  # True = delta covers the whole table
        self.new_mapping: Optional[LazyUnicastRoutes] = new_mapping
        self._batch: Optional[RouteColumnBatch] = None

    def update_count(self) -> int:
        return sum(len(r) for _, r in self.segments) + len(
            self.extra_updates
        )

    def update_prefixes(self):
        for view, rows in self.segments:
            plist = view.crib.matrix.prefix_list
            for r in rows.tolist():
                yield plist[r]
        yield from self.extra_updates

    def lazy_map(self) -> ColumnUpdateMap:
        return ColumnUpdateMap(self)

    def materialize_updates(self) -> dict:
        out = {}
        for view, rows in self.segments:
            if len(rows):
                view.crib._build_rows_into(view.cols, rows, out)
        out.update(self.extra_updates)
        return out

    def to_batch(self) -> RouteColumnBatch:
        """Packed form of the UPDATE side (the delta's own rows, not the
        whole table — for a full/cold delta they coincide)."""
        if self._batch is None:
            parts = []
            gid_base = 0
            for view, rows in self.segments:
                if not len(rows):
                    continue
                part = _segment_batch_parts(view, rows, gid_base)
                gid_base += len(part[6])
                parts.append(part)
            if parts:
                self._batch = RouteColumnBatch(
                    prefixes=[p for pt in parts for p in pt[0]],
                    family=np.concatenate([pt[1] for pt in parts]),
                    plen=np.concatenate([pt[2] for pt in parts]),
                    addr=np.concatenate([pt[3] for pt in parts]),
                    metric=np.concatenate([pt[4] for pt in parts]),
                    nh_gid=np.concatenate([pt[5] for pt in parts]),
                    nh_groups=[g for pt in parts for g in pt[6]],
                    extra=self.extra_updates,
                )
            else:
                self._batch = RouteColumnBatch(
                    [], np.zeros(0, np.uint8), np.zeros(0, np.uint8),
                    np.zeros((0, 16), np.uint8), np.zeros(0, np.int32),
                    np.zeros(0, np.int32), [], self.extra_updates,
                )
        return self._batch


def _col_changed_mask(oc, nc, rows: np.ndarray) -> np.ndarray:
    """Row-wise column compare between two bundles: entry construction
    is a pure function of these columns (same matrix/links per crib), so
    byte-equal rows are route-equal."""
    m = (oc.met[rows] != nc.met[rows])
    m |= (oc.s3w[rows] != nc.s3w[rows]).any(axis=1)
    m |= (oc.nhw[rows] != nc.nhw[rows]).any(axis=1)
    m |= oc.ok[rows] != nc.ok[rows]
    if oc.lfa_slot is not None and nc.lfa_slot is not None:
        m |= oc.lfa_slot[rows] != nc.lfa_slot[rows]
        m |= oc.lfa_metric[rows] != nc.lfa_metric[rows]
    elif (oc.lfa_slot is None) != (nc.lfa_slot is None):
        m |= True
    return m


def fast_unicast_column_diff(old, new) -> Optional[ColumnDelta]:
    """Column-native unicast diff old -> new. Requires `new` to be a
    LazyUnicastRoutes whose segments are their cribs' live tips. Two
    modes:

      cold  — `old` is empty: the delta is every ok row + host routes,
              with zero compares and zero entry builds;
      warm  — `old` shares the same cribs within journal reach: the
              device's changed-row journal bounds a vectorized COLUMN
              compare; only host-touched keys take the entry path.

    Returns None when ineligible — the caller falls back to the legacy
    entry-level diff (kept as the parity oracle)."""
    if not isinstance(new, LazyUnicastRoutes):
        return None
    for sn in new.segments:
        crib = sn.crib
        if sn.cols is not crib.cols or sn.epoch != crib.epoch:
            return None

    new_mapping = new.snapshot()

    if len(old) == 0:
        segments = []
        for i, sn in enumerate(new.segments):
            rows = sn.key_rows()
            shadow = _shadowed_rows(new, i, sn, rows)
            if shadow.any():
                rows = rows[~shadow]
            segments.append((sn, rows))
        extra = {
            p: e
            for p, e in new.base.items()
            if p not in new.deleted
            and p not in new.overrides
            and not any(s.has(p) for s in new.segments)
        }
        extra.update(
            {p: e for p, e in new.overrides.items() if p not in new.deleted}
        )
        counters.increment("decision.column_diffs")
        return ColumnDelta(segments, extra, [], True, new_mapping)

    if not isinstance(old, LazyUnicastRoutes):
        return None
    if len(old.segments) != len(new.segments):
        return None
    pairs = []
    for so, sn in zip(old.segments, new.segments):
        crib = sn.crib
        if so.crib is not crib or not crib.covers(so.epoch):
            return None
        pairs.append((so, sn, crib))

    # host-touched keys resolve entry-wise, exactly like the legacy diff
    candidates = (
        set(old.base) | set(new.base)
        | set(old.overrides) | set(new.overrides)
        | old.deleted | new.deleted
    )
    multi = len(new.segments) > 1
    segments = []
    del_prefixes: list[str] = []
    for i, (so, sn, crib) in enumerate(pairs):
        jrows = crib.changed_rows_since(so.epoch)
        jrows = jrows[jrows < crib.p_n]
        oc, nc = so.cols, sn.cols
        if not len(jrows) or oc is nc:
            segments.append((sn, np.zeros(0, np.int64)))
            continue
        if crib.exact_since(so.epoch):
            # streaming steady state: the journal entry came from the
            # on-device column diff (apply_rows_packed), so its row set
            # is exactly the changed set — no host re-compare needed
            changed = jrows
        else:
            changed = jrows[_col_changed_mask(oc, nc, jrows)]
        plist = crib.matrix.prefix_list
        upd = changed[nc.ok[changed]]
        dels = changed[oc.ok[changed] & ~nc.ok[changed]]
        # rows the host also touched (or that another layer shadows)
        # leave the column path and join the entry-compare candidates
        keep = np.ones(len(upd), bool)
        for j, r in enumerate(upd.tolist()):
            p = plist[r]
            if (
                p in candidates
                or (multi and any(
                    s.has(p) for k, s in enumerate(new.segments) if k != i
                ))
            ):
                keep[j] = False
                candidates.add(p)
        segments.append((sn, upd[keep]))
        for r in dels.tolist():
            p = plist[r]
            if (
                p in candidates
                or p in old.base or p in new.base
                or (multi and any(
                    s.has(p)
                    for k, s in enumerate(new.segments) if k != i
                ) or (multi and any(
                    s.has(p)
                    for k, s in enumerate(old.segments) if k != i
                )))
            ):
                candidates.add(p)
            else:
                del_prefixes.append(p)

    extra: dict = {}
    for k in candidates:
        nv = _lookup(new, k)
        ov = _lookup(old, k)
        if nv is None:
            if ov is not None:
                del_prefixes.append(k)
        elif ov is None or ov != nv:
            extra[k] = nv
    del_prefixes.sort()
    counters.increment("decision.column_diffs")
    return ColumnDelta(segments, extra, del_prefixes, False, new_mapping)
