from openr_tpu.prefix_manager.prefix_manager import (  # noqa: F401
    OriginatedPrefix,
    PrefixManager,
)
