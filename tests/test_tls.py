"""TLS on the ctrl RPC plane (role of the reference's secure thrift
server with acceptable peers — OpenrThriftCtrlServer SSL option)."""

import subprocess

import pytest

from openr_tpu.config import (
    Config,
    OpenrConfig,
    ThriftServerConfig,
    build_client_ssl_context,
)
from openr_tpu.ctrl.ctrl_server import CtrlServer
from openr_tpu.runtime.rpc import RpcClient, RpcConnectionError
from tests.conftest import run_async


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """Self-signed CA + server cert + client cert via the openssl CLI."""
    d = tmp_path_factory.mktemp("pki")

    def sh(*args):
        subprocess.run(args, check=True, capture_output=True)

    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
       "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
       "-subj", "/CN=openr-test-ca")
    for name in ("server", "client"):
        key, csr, crt = d / f"{name}.key", d / f"{name}.csr", d / f"{name}.crt"
        sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
           "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={name}")
        sh("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
           "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
           "-days", "1")
    return d


def secure_config(pki, mutual: bool, acceptable_peers: str = "") -> Config:
    return Config(
        OpenrConfig(
            node_name="tls-node",
            thrift_server=ThriftServerConfig(
                enable_secure_thrift_server=True,
                x509_cert_path=str(pki / "server.crt"),
                x509_key_path=str(pki / "server.key"),
                x509_ca_path=str(pki / "ca.crt") if mutual else "",
                acceptable_peers=acceptable_peers,
            ),
        )
    )


@run_async
async def test_tls_server_rejects_plaintext_and_serves_tls(pki):
    server = CtrlServer("tls-node", config=secure_config(pki, mutual=False))
    await server.start()
    try:
        plain = RpcClient("127.0.0.1", server.port, name="plain")
        with pytest.raises((RpcConnectionError, Exception)):
            await plain.request("openr.version", timeout_s=2.0)
        await plain.close()

        ctx = build_client_ssl_context(ca_path=str(pki / "ca.crt"))
        tls = RpcClient("127.0.0.1", server.port, name="tls", ssl=ctx)
        try:
            version = await tls.request("openr.version")
            assert version["node"] == "tls-node"
        finally:
            await tls.close()
    finally:
        await server.stop()


@run_async
async def test_mutual_tls_requires_client_cert(pki):
    server = CtrlServer("tls-node", config=secure_config(pki, mutual=True))
    await server.start()
    try:
        # CA-verified but certless client: handshake must fail
        bare = RpcClient(
            "127.0.0.1", server.port, name="bare",
            ssl=build_client_ssl_context(ca_path=str(pki / "ca.crt")),
        )
        with pytest.raises((RpcConnectionError, Exception)):
            await bare.request("openr.version", timeout_s=2.0)
        await bare.close()

        ctx = build_client_ssl_context(
            ca_path=str(pki / "ca.crt"),
            cert_path=str(pki / "client.crt"),
            key_path=str(pki / "client.key"),
        )
        authed = RpcClient("127.0.0.1", server.port, name="authed", ssl=ctx)
        try:
            version = await authed.request("openr.version")
            assert version["node"] == "tls-node"
        finally:
            await authed.close()
    finally:
        await server.stop()


@run_async
async def test_acceptable_peers_enforces_client_identity(pki):
    """CA membership alone must not be enough when acceptable_peers is
    set (role of the reference's acceptable-peers list on its secure
    thrift server)."""

    def client_ctx():
        return build_client_ssl_context(
            ca_path=str(pki / "ca.crt"),
            cert_path=str(pki / "client.crt"),
            key_path=str(pki / "client.key"),
        )

    # our client cert has CN=client; a server allowing only "other-node"
    # must reject it even though the CA signed it
    server = CtrlServer(
        "tls-node",
        config=secure_config(pki, mutual=True, acceptable_peers="other-node"),
    )
    await server.start()
    try:
        denied = RpcClient(
            "127.0.0.1", server.port, name="denied", ssl=client_ctx()
        )
        # the server drops the connection post-handshake, so the client
        # sees a transport failure, not a TLS error
        with pytest.raises((RpcConnectionError, ConnectionError, OSError)):
            await denied.request("openr.version", timeout_s=2.0)
        await denied.close()
    finally:
        await server.stop()

    server = CtrlServer(
        "tls-node",
        config=secure_config(
            pki, mutual=True, acceptable_peers="other-node, client"
        ),
    )
    await server.start()
    try:
        allowed = RpcClient(
            "127.0.0.1", server.port, name="allowed", ssl=client_ctx()
        )
        try:
            version = await allowed.request("openr.version")
            assert version["node"] == "tls-node"
        finally:
            await allowed.close()
    finally:
        await server.stop()


@run_async
async def test_client_pins_server_identity(pki):
    """A client given expected_peer must reject a CA-valid server whose
    cert claims a different node name (CN/SAN pinning — CA membership
    alone would let any node impersonate any other)."""
    server = CtrlServer("tls-node", config=secure_config(pki, mutual=False))
    await server.start()
    try:
        # server cert has CN=server
        pinned_wrong = RpcClient(
            "127.0.0.1", server.port, name="pin-wrong",
            ssl=build_client_ssl_context(ca_path=str(pki / "ca.crt")),
            expected_peer="some-other-node",
        )
        with pytest.raises(RpcConnectionError, match="expected peer"):
            await pinned_wrong.request("openr.version", timeout_s=2.0)
        await pinned_wrong.close()

        pinned_right = RpcClient(
            "127.0.0.1", server.port, name="pin-right",
            ssl=build_client_ssl_context(ca_path=str(pki / "ca.crt")),
            expected_peer="server",
        )
        try:
            version = await pinned_right.request("openr.version")
            assert version["node"] == "tls-node"
        finally:
            await pinned_right.close()
    finally:
        await server.stop()


# -- KvStore peer plane ------------------------------------------------------

import asyncio

from openr_tpu.config import build_server_ssl_context
from openr_tpu.kvstore.wrapper import KvStoreWrapper, wait_until
from openr_tpu.types import KvStorePeerState


@pytest.fixture(scope="module")
def node_pki(tmp_path_factory):
    """CA + per-node certs (CN = node name), plus a rogue CA, a
    rogue-signed cert, an expired cert, and a wrong-name cert."""
    d = tmp_path_factory.mktemp("node_pki")

    def sh(*args):
        subprocess.run(args, check=True, capture_output=True)

    def mk_ca(name):
        sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
           "-keyout", str(d / f"{name}.key"), "-out", str(d / f"{name}.crt"),
           "-days", "1", "-subj", f"/CN={name}")

    def mk_cert(name, cn, ca="ca", days="1"):
        sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
           "-keyout", str(d / f"{name}.key"), "-out", str(d / f"{name}.csr"),
           "-subj", f"/CN={cn}")
        sh("openssl", "x509", "-req", "-in", str(d / f"{name}.csr"),
           "-CA", str(d / f"{ca}.crt"), "-CAkey", str(d / f"{ca}.key"),
           "-CAcreateserial", "-out", str(d / f"{name}.crt"), "-days", days)

    mk_ca("ca")
    mk_ca("rogue-ca")
    for node in ("storeA", "storeB"):
        mk_cert(node, node)
    mk_cert("rogue", "storeB", ca="rogue-ca")  # right name, wrong CA
    mk_cert("expired", "storeB", days="0")  # notAfter == notBefore
    mk_cert("imposter", "not-storeB")  # right CA, wrong identity
    return d


def _ssl_pair(pki, cert_name, with_client_cert=True):
    """(server_ssl, client_ssl) for one node from its cert files."""
    from openr_tpu.config import ThriftServerConfig

    ts = ThriftServerConfig(
        enable_secure_thrift_server=True,
        x509_cert_path=str(pki / f"{cert_name}.crt"),
        x509_key_path=str(pki / f"{cert_name}.key"),
        x509_ca_path=str(pki / "ca.crt"),
    )
    server_ssl = build_server_ssl_context(ts)
    client_ssl = build_client_ssl_context(
        str(pki / "ca.crt"),
        str(pki / f"{cert_name}.crt") if with_client_cert else "",
        str(pki / f"{cert_name}.key") if with_client_cert else "",
    )
    return server_ssl, client_ssl


async def _start_secure_pair(pki, b_cert="storeB", b_client_cert=True):
    sa, ca_ = _ssl_pair(pki, "storeA")
    sb, cb = _ssl_pair(pki, b_cert, with_client_cert=b_client_cert)
    a = KvStoreWrapper("storeA", server_ssl=sa, client_ssl=ca_)
    b = KvStoreWrapper("storeB", server_ssl=sb, client_ssl=cb)
    await a.start()
    await b.start()
    a.add_peer(b)
    b.add_peer(a)
    return a, b


async def _stop_pair(a, b):
    await a.stop()
    await b.stop()


class TestKvStorePeerTls:
    """Mutual-auth matrix on the peer plane (ref the reference's secure
    inter-store thrift): flooding + full sync over TLS; every broken
    credential must keep the peer session down and the data out."""

    @run_async
    async def test_sync_and_flood_over_tls(self, node_pki):
        a, b = await _start_secure_pair(node_pki)
        try:
            await wait_until(
                lambda: (p := a.store.get_peers("0").get("storeB"))
                is not None
                and p.state == KvStorePeerState.INITIALIZED
            )
            # full sync + incremental flooding both ride TLS sessions
            a.set_key("secure-key", b"v1")
            await wait_until(lambda: b.get_key("secure-key") is not None)
            assert b.get_key("secure-key").value == b"v1"
        finally:
            await _stop_pair(a, b)

    @run_async
    async def test_wrong_ca_peer_never_syncs(self, node_pki):
        a, b = await _start_secure_pair(node_pki, b_cert="rogue")
        try:
            a.set_key("secret", b"v")
            await asyncio.sleep(1.0)
            assert b.get_key("secret") is None
            assert (
                a.store.get_peers("0")["storeB"].state
                != KvStorePeerState.INITIALIZED
            )
        finally:
            await _stop_pair(a, b)

    @run_async
    async def test_expired_cert_peer_never_syncs(self, node_pki):
        a, b = await _start_secure_pair(node_pki, b_cert="expired")
        try:
            a.set_key("secret", b"v")
            await asyncio.sleep(1.0)
            assert b.get_key("secret") is None
        finally:
            await _stop_pair(a, b)

    @run_async
    async def test_certless_peer_cannot_pull(self, node_pki):
        # B presents no client certificate: A's server (CERT_REQUIRED)
        # refuses B's connections, so B can never complete a sync. (B
        # may still RECEIVE pushes — its own server cert authenticates
        # it as a domain member.)
        a, b = await _start_secure_pair(node_pki, b_client_cert=False)
        try:
            await asyncio.sleep(1.0)
            peer = b.store.get_peers("0").get("storeA")
            assert (
                peer is None
                or peer.state != KvStorePeerState.INITIALIZED
            )
        finally:
            await _stop_pair(a, b)

    @run_async
    async def test_identity_mismatch_rejected_by_pin(self, node_pki):
        # B's cert is CA-valid but claims another node's name: A's
        # client-side pin (expected_peer == peer node name) rejects it
        a, b = await _start_secure_pair(node_pki, b_cert="imposter")
        try:
            a.set_key("secret", b"v")
            await asyncio.sleep(1.0)
            # A cannot push to B (pin rejects B's server identity)
            assert b.get_key("secret") is None
            assert (
                a.store.get_peers("0")["storeB"].state
                != KvStorePeerState.INITIALIZED
            )
        finally:
            await _stop_pair(a, b)


class TestSecurePeersConfigPath:
    """The enable_secure_peers config flag through OpenrWrapper."""

    def test_wrapper_builds_peer_contexts_from_config(self, node_pki):
        from openr_tpu.config import Config, KvstoreConfig, OpenrConfig
        from openr_tpu.runtime.openr_wrapper import OpenrWrapper
        from openr_tpu.spark import MockIoMesh

        cfg = Config(
            OpenrConfig(
                node_name="storeA",
                thrift_server=ThriftServerConfig(
                    x509_cert_path=str(node_pki / "storeA.crt"),
                    x509_key_path=str(node_pki / "storeA.key"),
                    x509_ca_path=str(node_pki / "ca.crt"),
                ),
            )
        )
        mesh = MockIoMesh()
        w = OpenrWrapper(
            "storeA", mesh.provider("storeA"), {},
            kvstore_config=KvstoreConfig(enable_secure_peers=True),
            running_config=cfg,
        )
        assert w.kvstore._server_ssl is not None
        assert w.kvstore._client_ssl is not None

    def test_secure_peers_without_ca_is_config_error(self, node_pki):
        from openr_tpu.config import (
            Config,
            ConfigError,
            KvstoreConfig,
            OpenrConfig,
        )
        from openr_tpu.runtime.openr_wrapper import OpenrWrapper
        from openr_tpu.spark import MockIoMesh

        cfg = Config(
            OpenrConfig(
                node_name="storeA",
                thrift_server=ThriftServerConfig(
                    x509_cert_path=str(node_pki / "storeA.crt"),
                    x509_key_path=str(node_pki / "storeA.key"),
                ),
            )
        )
        mesh = MockIoMesh()
        with pytest.raises(ConfigError, match="x509_ca_path"):
            OpenrWrapper(
                "storeA", mesh.provider("storeA"), {},
                kvstore_config=KvstoreConfig(enable_secure_peers=True),
                running_config=cfg,
            )
