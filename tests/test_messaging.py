"""Queue fabric tests (semantics of ref openr/messaging/tests)."""

import asyncio

import pytest

from openr_tpu.messaging import QueueClosedError, ReplicateQueue
from tests.conftest import run_async


@run_async
async def test_fanout_every_reader_sees_every_write():
    q = ReplicateQueue(name="test")
    r1 = q.get_reader()
    r2 = q.get_reader()
    q.push(1)
    q.push(2)
    assert await r1.get() == 1
    assert await r2.get() == 1
    assert await r1.get() == 2
    assert await r2.get() == 2
    assert q.num_writes == 2


@run_async
async def test_blocking_get_wakes_on_push():
    q = ReplicateQueue()
    r = q.get_reader()

    async def producer():
        await asyncio.sleep(0.01)
        q.push("x")

    task = asyncio.ensure_future(producer())
    assert await r.get() == "x"
    await task


@run_async
async def test_close_unblocks_with_queue_closed():
    q = ReplicateQueue()
    r = q.get_reader()

    async def reader():
        with pytest.raises(QueueClosedError):
            await r.get()

    task = asyncio.ensure_future(reader())
    await asyncio.sleep(0.01)
    q.close()
    await task
    with pytest.raises(QueueClosedError):
        q.push(1)


@run_async
async def test_close_drains_buffered_items_first():
    q = ReplicateQueue()
    r = q.get_reader()
    q.push(1)
    q.close()
    assert await r.get() == 1
    with pytest.raises(QueueClosedError):
        await r.get()


@run_async
async def test_late_reader_misses_earlier_writes():
    q = ReplicateQueue()
    r1 = q.get_reader()
    q.push(1)
    r2 = q.get_reader()
    q.push(2)
    assert await r1.get() == 1
    assert await r2.get() == 2  # r2 only sees writes after creation
    assert r1.size() == 1


@run_async
async def test_try_get_and_stats():
    q = ReplicateQueue(name="stats")
    r = q.get_reader("rd")
    ok, item = r.try_get()
    assert not ok and item is None
    q.push(7)
    ok, item = r.try_get()
    assert ok and item == 7
    s = q.stats()
    assert s["writes"] == 1
    assert s["readers"][0]["reads"] == 1
