"""Device-plane telemetry: HBM gauges, live-buffer census, and
on-demand JAX profiler capture.

The host-side fabric (counters.py, tracing.py) stops at the device
boundary; this module crosses it. Three concerns live here:

- **gauges** — `export_device_gauges()` reads
  `jax.local_devices()[i].memory_stats()` and publishes
  `device.<i>.hbm_in_use_mb` / `.peak_mb` / `.num_allocs` into the
  counter fabric, plus a `jax.live_arrays()` census attributed to
  registered solver pools. CPU backends expose no memory_stats — the
  snapshot then carries only the backend label, never an error.
- **pools** — long-lived device-buffer owners (the TPU solver's
  per-area mirrors) register a provider so the census can split live
  bytes into "pool X" vs "unattributed" — the shape of an HBM leak.
- **profiler** — single-flight `jax.profiler.start_trace`/`stop_trace`
  with an optional auto-stop timer, served by the ctrl API so an
  operator captures a Perfetto-compatible XLA trace from a live daemon.

Passive polling (the Monitor's metrics loop) must not *cause* a jax
import in processes that never touched the device — `_jax()` only
returns the module if something else already imported it. Explicit
requests (profiler start, bench) import it on purpose.
"""

from __future__ import annotations

import logging
import os
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Iterable, Optional

from openr_tpu.runtime.counters import counters

log = logging.getLogger(__name__)

_BYTES_PER_MB = 1024.0 * 1024.0

# -- solver-pool registry ---------------------------------------------------

_pools: dict[str, Callable[[], Iterable[Any]]] = {}
_pools_lock = threading.Lock()


def register_pool(name: str, arrays_fn: Callable[[], Iterable[Any]]) -> None:
    """Register a named owner of long-lived device buffers. `arrays_fn`
    returns the arrays the pool currently holds; the census charges
    their bytes to the pool. Re-registering a name replaces it."""
    with _pools_lock:
        _pools[name] = arrays_fn


def unregister_pool(name: str) -> None:
    with _pools_lock:
        _pools.pop(name, None)
        counters.erase_prefix(f"device.pool.{name}.")


def _jax(allow_import: bool):
    if allow_import:
        try:
            import jax

            return jax
        # lint: allow(broad-except) stats degrade to "unavailable"
        except Exception:  # pragma: no cover - jax is baked into the image
            return None
    return sys.modules.get("jax")


# -- device snapshot --------------------------------------------------------


def collect_device_stats(allow_import: bool = False) -> dict:
    """One snapshot of every local device's memory stats. Backends
    without memory_stats (CPU) yield devices with only id/platform —
    the caller distinguishes "no HBM accounting" from "no devices"."""
    jax = _jax(allow_import)
    if jax is None:
        return {"backend": "unavailable", "devices": []}
    try:
        backend = jax.default_backend()
        devices = jax.local_devices()
    # lint: allow(broad-except) failure surfaced in the returned payload
    except Exception as e:  # pragma: no cover - backend init failure
        return {"backend": "error", "error": str(e), "devices": []}
    out: dict = {"backend": backend, "devices": []}
    for i, dev in enumerate(devices):
        entry: dict = {"id": i, "platform": getattr(dev, "platform", backend)}
        try:
            ms = dev.memory_stats()
        # lint: allow(broad-except) CPU backends have no HBM accounting
        except Exception:
            ms = None
        if ms:
            entry["hbm_in_use_mb"] = round(
                ms.get("bytes_in_use", 0) / _BYTES_PER_MB, 3
            )
            entry["peak_mb"] = round(
                ms.get("peak_bytes_in_use", 0) / _BYTES_PER_MB, 3
            )
            entry["num_allocs"] = int(ms.get("num_allocs", 0))
            limit = ms.get("bytes_limit", 0)
            if limit:
                entry["hbm_limit_mb"] = round(limit / _BYTES_PER_MB, 3)
                entry["hbm_frac"] = round(
                    ms.get("bytes_in_use", 0) / limit, 4
                )
        out["devices"].append(entry)
    return out


def live_buffer_census(allow_import: bool = False) -> dict:
    """Count/bytes of every live jax array, split by registered pool.
    `other_bytes` is what no pool claims — a growing `other` with flat
    pools is the classic leak signature."""
    jax = _jax(allow_import)
    if jax is None:
        return {"count": 0, "bytes": 0, "pools": {}, "other_bytes": 0}
    try:
        arrays = jax.live_arrays()
    # lint: allow(broad-except) census degrades to empty, never crashes
    except Exception:
        arrays = []
    total_n, total_b = 0, 0
    for a in arrays:
        total_n += 1
        total_b += int(getattr(a, "nbytes", 0) or 0)
    pools_out: dict[str, dict] = {}
    attributed = 0
    with _pools_lock:
        providers = list(_pools.items())
    for name, fn in providers:
        n, b = 0, 0
        by_dev: dict[int, int] = {}
        try:
            for a in fn():
                n += 1
                b += int(getattr(a, "nbytes", 0) or 0)
                # per-device attribution: a sharded array (the
                # multichip solver tier) charges each shard's bytes to
                # the device that holds it, so the census shows how a
                # pool's footprint spreads across the mesh instead of
                # lumping it on device 0
                try:
                    for shard in a.addressable_shards:
                        d = getattr(shard.device, "id", 0)
                        sb = int(
                            getattr(shard.data, "nbytes", 0) or 0
                        )
                        by_dev[d] = by_dev.get(d, 0) + sb
                # lint: allow(broad-except) non-jax arrays have no shards
                except Exception:
                    pass
        # lint: allow(broad-except) torn-down pool reads as empty
        except Exception:
            pass  # a torn-down pool reads as empty, not as a crash
        pools_out[name] = {"count": n, "bytes": b}
        if by_dev:
            pools_out[name]["by_device"] = {
                str(d): by_dev[d] for d in sorted(by_dev)
            }
        attributed += b
    return {
        "count": total_n,
        "bytes": total_b,
        "pools": pools_out,
        "other_bytes": max(0, total_b - attributed),
    }


def export_device_gauges(allow_import: bool = False) -> dict:
    """Publish the snapshot into the counter fabric (the Monitor calls
    this every interval). Returns the snapshot for callers that want
    the structured form too."""
    snap = collect_device_stats(allow_import)
    counters.set_counter("device.count", len(snap["devices"]))
    for entry in snap["devices"]:
        if "hbm_in_use_mb" not in entry:
            continue
        base = f"device.{entry['id']}"
        counters.set_counter(f"{base}.hbm_in_use_mb", entry["hbm_in_use_mb"])
        counters.set_counter(f"{base}.peak_mb", entry["peak_mb"])
        counters.set_counter(f"{base}.num_allocs", entry["num_allocs"])
        if "hbm_frac" in entry:
            counters.set_counter(f"{base}.hbm_frac", entry["hbm_frac"])
    census = live_buffer_census(allow_import)
    snap["live"] = census
    counters.set_counter("device.live_arrays.count", census["count"])
    counters.set_counter(
        "device.live_arrays.bytes_mb", round(census["bytes"] / _BYTES_PER_MB, 3)
    )
    counters.set_counter(
        "device.live_arrays.other_mb",
        round(census["other_bytes"] / _BYTES_PER_MB, 3),
    )
    for name, p in census["pools"].items():
        counters.set_counter(f"device.pool.{name}.count", p["count"])
        counters.set_counter(
            f"device.pool.{name}.bytes_mb", round(p["bytes"] / _BYTES_PER_MB, 3)
        )
        for d, db in (p.get("by_device") or {}).items():
            counters.set_counter(
                f"device.pool.{name}.dev{d}.bytes_mb",
                round(db / _BYTES_PER_MB, 3),
            )
    return snap


def hbm_pressure(allow_import: bool = False) -> Optional[float]:
    """Worst-device HBM pressure: max over local devices of
    bytes_in_use / bytes_limit. The overload controller's brownout
    watermark input (runtime/overload.py). None where no backend keeps
    both numbers (CPU) — the ladder then runs on queue/RSS signals
    alone, it never guesses."""
    snap = collect_device_stats(allow_import)
    fracs = [
        e["hbm_frac"] for e in snap["devices"] if "hbm_frac" in e
    ]
    return max(fracs) if fracs else None


def peak_hbm_mb(allow_import: bool = True) -> tuple[Optional[float], str]:
    """(max over devices of peak_bytes_in_use, backend label) — bench
    records this next to wall-time. None where the backend keeps no
    HBM accounting (CPU)."""
    snap = collect_device_stats(allow_import)
    peaks = [e["peak_mb"] for e in snap["devices"] if "peak_mb" in e]
    return (max(peaks) if peaks else None), snap["backend"]


# -- profiler capture -------------------------------------------------------

_prof_lock = threading.Lock()
_prof_state: Optional[dict] = None


def profiler_start(
    out_dir: Optional[str] = None, seconds: Optional[float] = None
) -> dict:
    """Start a jax profiler trace. Single-flight: a second start while
    one is capturing raises (the XLA profiler is process-global). With
    `seconds`, a daemon timer stops the capture even if the requesting
    client vanishes — a forgotten trace must not run forever."""
    global _prof_state
    import jax  # explicit request: importing jax here is the point

    with _prof_lock:
        if _prof_state is not None:
            raise RuntimeError(
                f"profiler already capturing to {_prof_state['out_dir']}"
            )
        out = out_dir or tempfile.mkdtemp(prefix="openr-tpu-trace-")
        os.makedirs(out, exist_ok=True)
        jax.profiler.start_trace(out)
        timer = None
        if seconds is not None and seconds > 0:
            timer = threading.Timer(seconds, _profiler_auto_stop)
            timer.daemon = True
            timer.start()
        _prof_state = {
            "out_dir": out,
            "started_ts": time.time(),
            "seconds": seconds,
            "timer": timer,
        }
    counters.increment("device.profiler.starts")
    log.info("profiler capture started -> %s", out)
    return {"ok": True, "out_dir": out, "auto_stop_s": seconds}


def profiler_stop() -> dict:
    """Stop the active capture; returns the trace directory and how
    many files the profiler wrote there (>0 is the smoke signal that
    the capture actually produced a trace)."""
    global _prof_state
    with _prof_lock:
        if _prof_state is None:
            raise RuntimeError("profiler is not capturing")
        state, _prof_state = _prof_state, None
    timer = state.get("timer")
    if timer is not None:
        timer.cancel()
    import jax

    jax.profiler.stop_trace()
    files = 0
    for _, _, names in os.walk(state["out_dir"]):
        files += len(names)
    counters.increment("device.profiler.stops")
    duration = round(time.time() - state["started_ts"], 3)
    log.info(
        "profiler capture stopped after %.1fs -> %s (%d files)",
        duration,
        state["out_dir"],
        files,
    )
    return {
        "ok": True,
        "out_dir": state["out_dir"],
        "duration_s": duration,
        "files": files,
    }


def _profiler_auto_stop() -> None:
    try:
        profiler_stop()
    except RuntimeError:
        pass  # operator beat the timer to it


def profiler_status() -> dict:
    with _prof_lock:
        if _prof_state is None:
            return {"capturing": False}
        return {
            "capturing": True,
            "out_dir": _prof_state["out_dir"],
            "elapsed_s": round(time.time() - _prof_state["started_ts"], 3),
            "auto_stop_s": _prof_state["seconds"],
        }
