"""Offline incident replay: re-execute a flight-recorder bundle's
`inputs` annex through the real Decision ingest path and bit-compare
per-epoch RIB digests against the recording.

    python -m tools.replay <bundle-dir | bundle.json> [--solver cpu|tpu]
                           [--streaming on|off] [-v]
    python -m tools.replay --selftest --out <dir>

A RIB is a deterministic function of the ordered LSDB event stream
plus config, so replay is exact, not approximate: the harness loads
the bundle's LSDB snapshot anchor, ingests it through the same
deserialize/apply path live publications take, then replays the
recorded event ring epoch by epoch — coalescing driven by the
RECORDED epoch boundaries (each epoch's event-ring cursor, captured at
the live solve's LSDB read), never by timers — and recomputes the
per-epoch RIB digest after each solve. The run is headless and
synchronous on CPU jax by default; no actors, no queues with readers,
no debounce.

The verdict is a bisection: the first epoch whose replayed digest
differs from the recording is printed with its recorded solver
kind/kernel and the event window that fed it — from there the
subsystem runbook takes over (docs/Operations.md § Incident replay).
`--solver cpu|tpu` and `--streaming on|off` turn the same bundle into
an A/B parity test: a recording made by the streaming device pipeline
must replay bit-identically on the CPU oracle, so a digest mismatch
localizes WHICH side (and which epoch) diverged over real incident
data.

Exit status: 0 bit-identical, 1 diverged (first divergent epoch
printed), 2 not replayable (no annex, or the event ring had a gap).

`--selftest` records a short two-node churn session in-process through
a real Decision, writes the bundle to --out, replays it bit-identically
AND verifies that an injected divergence bisects to the right epoch —
the CI replay smoke lane.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
from typing import Optional

# headless on CPU jax by default: replay must run on machines with no
# accelerator (and must not grab one on machines that have it)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPLAYABLE_SCHEMAS = ("openr-tpu-replay/1",)


def load_bundle(path: str) -> dict:
    """Accept a bundle directory, a bundle.json, or a bare annex."""
    if os.path.isdir(path):
        path = os.path.join(path, "bundle.json")
    with open(path) as f:
        bundle = json.load(f)
    if bundle.get("schema") in REPLAYABLE_SCHEMAS:
        # bare inputs annex (recorder export written directly)
        return {"node": bundle.get("node", ""), "inputs": bundle}
    return bundle


def _headless_decision(node: str, solver: str, streaming: bool,
                       spf_kernel: str):
    """A real Decision, driven synchronously: no event loop, no
    debounce, readerless route-updates queue, recorder off (replay
    must not re-record itself)."""
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging import ReplicateQueue

    cfg = DecisionConfig(
        solver_backend=solver,
        spf_kernel=spf_kernel,
        streaming_pipeline=streaming,
        async_dispatch=False,
        replay_recorder=False,
    )
    return Decision(
        node_name=node,
        config=cfg,
        kvstore_updates_queue=None,
        static_routes_queue=None,
        route_updates_queue=ReplicateQueue("replay.routes"),
    )


def _ingest_snapshot(d, snapshot: dict) -> None:
    for area, kvs in snapshot.get("areas", {}).items():
        for key, (_version, _orig, value_b64) in kvs.items():
            d._update_key_in_lsdb(area, key, base64.b64decode(value_b64))


def _apply_event(d, ev: dict) -> None:
    from openr_tpu.types import Publication, Value

    if ev["kind"] == "kv":
        pub = Publication(
            key_vals={
                ev["key"]: Value(
                    version=int(ev.get("version") or 1),
                    originator_id=ev.get("originator") or "",
                    value=base64.b64decode(ev["value_b64"]),
                )
            },
            area=ev["area"],
        )
    else:
        pub = Publication(expired_keys=[ev["key"]], area=ev["area"])
    d.process_publication(pub)


def _solve(d, full: bool) -> str:
    """One manual rebuild over whatever is pending; returns the epoch's
    RIB digest (computed by the same _finish_rebuild path as live)."""
    from openr_tpu.decision.decision import PendingUpdates

    pending = d.pending
    d.pending = PendingUpdates()
    if full:
        pending.needs_full_rebuild = True
    d._rebuild(pending)
    return d.last_rib_digest


def replay_bundle(
    bundle: dict,
    solver: str = "cpu",
    streaming: bool = False,
    verbose: bool = False,
    out=sys.stdout,
) -> dict:
    """Replay one bundle; returns the report dict (see `status` key:
    "identical" | "diverged" | "unreplayable")."""

    def say(msg: str) -> None:
        if verbose:
            print(msg, file=out)

    inputs = bundle.get("inputs")
    if not isinstance(inputs, dict) or inputs.get("schema") not in (
        REPLAYABLE_SCHEMAS
    ):
        return {
            "status": "unreplayable",
            "error": "bundle carries no replayable `inputs` annex "
            "(recorded before the replay recorder, or recorder "
            "disabled)",
        }
    if inputs.get("gap"):
        return {
            "status": "unreplayable",
            "error": "event ring overflowed past the snapshot anchor: "
            "the recording has a hole (see replay.ring_gaps; raise "
            "decision_config.replay_ring or lower "
            "replay_snapshot_every_epochs)",
        }
    snapshot = inputs["snapshot"]
    events = sorted(inputs["events"], key=lambda e: e["seq"])
    epochs = [
        e for e in inputs["epochs"] if e["cursor"] > snapshot["cursor"]
    ]
    meta = inputs.get("meta", {})
    node = inputs.get("node", bundle.get("node", ""))
    spf_kernel = meta.get("spf_kernel", "bucketed")

    d = _headless_decision(node, solver, streaming, spf_kernel)
    say(
        f"replaying node={node!r} solver={solver} "
        f"streaming={'on' if streaming else 'off'}: "
        f"snapshot@cursor={snapshot['cursor']} "
        f"base_epoch={snapshot['base_epoch']}, "
        f"{len(events)} events, {len(epochs)} epochs"
    )
    _ingest_snapshot(d, snapshot)
    # baseline build: materializes the anchor epoch's full table so the
    # first replayed epoch diffs against the same previous RIB the live
    # solve did. Its digest is a full-table fingerprint — the recording
    # has a DELTA digest for that epoch, so the baseline is not compared.
    _solve(d, full=True)
    base_epoch = snapshot.get("base_epoch")
    if base_epoch is not None:
        d._solve_epoch = int(base_epoch)

    compared = []
    first_divergent: Optional[dict] = None
    prev_cursor = snapshot["cursor"]
    ei = 0
    for ep in epochs:
        window = []
        while ei < len(events) and events[ei]["seq"] <= ep["cursor"]:
            if events[ei]["seq"] > prev_cursor:
                window.append(events[ei])
            ei += 1
        prev_cursor = ep["cursor"]
        for ev in window:
            # flap-damping withheld this event from the live LSDB
            # (runtime/overload.py) — it is recorded for incident
            # fidelity, but applying it here would perturb state the
            # live solve never saw and break the digest bit-compare
            if ev.get("suppressed"):
                continue
            _apply_event(d, ev)
        replayed = _solve(d, full=ep.get("full", True))
        match = replayed == ep["digest"]
        compared.append({
            "epoch": ep["epoch"],
            "recorded": ep["digest"],
            "replayed": replayed,
            "match": match,
            "events": len(window),
        })
        say(
            f"  epoch {ep['epoch']}: recorded={ep['digest']} "
            f"replayed={replayed} "
            f"{'ok' if match else '** DIVERGED **'} "
            f"({len(window)} events, {ep.get('solver_kind')}/"
            f"{ep.get('spf_kernel')})"
        )
        if not match and first_divergent is None:
            first_divergent = {
                "epoch": ep["epoch"],
                "recorded": ep["digest"],
                "replayed": replayed,
                "solver_kind": ep.get("solver_kind"),
                "spf_kernel": ep.get("spf_kernel"),
                "stream": ep.get("stream"),
                "event_keys": [ev["key"] for ev in window],
            }

    report = {
        "status": "diverged" if first_divergent else "identical",
        "node": node,
        "solver": solver,
        "streaming": streaming,
        "recorded_meta": meta,
        "epochs_compared": len(compared),
        "epochs": compared,
        "first_divergent": first_divergent,
    }
    return report


def _print_verdict(report: dict, out=sys.stdout) -> None:
    if report["status"] == "unreplayable":
        print(f"UNREPLAYABLE: {report['error']}", file=out)
        return
    n = report["epochs_compared"]
    if report["status"] == "identical":
        print(
            f"IDENTICAL: {n} epoch digests replayed bit-identically "
            f"(solver={report['solver']}, "
            f"streaming={'on' if report['streaming'] else 'off'})",
            file=out,
        )
        return
    fd = report["first_divergent"]
    print(
        f"DIVERGED at epoch {fd['epoch']} "
        f"(first of {n} compared): recorded {fd['recorded']} != "
        f"replayed {fd['replayed']}\n"
        f"  recorded solver_kind={fd['solver_kind']} "
        f"spf_kernel={fd['spf_kernel']} stream={fd['stream']}\n"
        f"  epoch's event window ({len(fd['event_keys'])} keys): "
        f"{', '.join(fd['event_keys'][:8])}"
        f"{' ...' if len(fd['event_keys']) > 8 else ''}\n"
        f"  next: docs/Operations.md § Incident replay",
        file=out,
    )


# -- selftest: the CI replay smoke lane --------------------------------


def _selftest_record(tmp_dir: str) -> str:
    """Record a short two-node churn session through a real Decision
    (recorder on) and write a flight-recorder-shaped bundle; returns
    the bundle directory."""
    import random

    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging import ReplicateQueue
    from openr_tpu.runtime.replay_log import get_recorder
    from openr_tpu.serde import serialize
    from openr_tpu.types import (
        Adjacency,
        AdjacencyDatabase,
        PrefixDatabase,
        PrefixEntry,
        Publication,
        Value,
        adj_key,
        prefix_key,
    )

    cfg = DecisionConfig(solver_backend="cpu", replay_recorder=True)
    d = Decision(
        node_name="replay-smoke",
        config=cfg,
        kvstore_updates_queue=None,
        static_routes_queue=None,
        route_updates_queue=ReplicateQueue("selftest.routes"),
    )

    def adj_db(node: str, other: str, metric: int) -> bytes:
        return serialize(AdjacencyDatabase(
            this_node_name=node,
            adjacencies=(Adjacency(
                other_node_name=other,
                if_name=f"if-{node}-{other}",
                other_if_name=f"if-{other}-{node}",
                metric=metric,
            ),),
        ))

    def pfx_db(node: str, prefix: str) -> bytes:
        return serialize(PrefixDatabase(
            this_node_name=node,
            prefix_entries=(PrefixEntry(prefix=prefix),),
        ))

    def publish(key: str, raw: bytes, originator: str, version: int):
        d.process_publication(Publication(
            key_vals={key: Value(
                version=version, originator_id=originator, value=raw
            )},
        ))

    # two-node mesh; replay-smoke computes routes to node "peer"
    names = {"replay-smoke": "peer", "peer": "replay-smoke"}
    for node, other in names.items():
        publish(adj_key(node), adj_db(node, other, 10), node, 1)
    for i in range(4):
        publish(
            prefix_key("peer", "0", f"10.0.{i}.0/24"),
            pfx_db("peer", f"10.0.{i}.0/24"),
            "peer", 1,
        )
    from openr_tpu.decision.decision import PendingUpdates

    pending = d.pending
    d.pending = PendingUpdates()
    pending.needs_full_rebuild = True
    d._rebuild(pending)  # anchor epoch: first solve takes the snapshot

    # randomized churn: metric flaps + a withdrawal/re-advertise
    rng = random.Random(18)
    version = {n: 1 for n in names}
    for _ in range(12):
        node = rng.choice(list(names))
        version[node] += 1
        publish(
            adj_key(node),
            adj_db(node, names[node], rng.randint(1, 100)),
            node, version[node],
        )
        if rng.random() < 0.3:
            d.process_publication(Publication(
                expired_keys=[prefix_key("peer", "0", "10.0.3.0/24")],
            ))
        elif rng.random() < 0.5:
            publish(
                prefix_key("peer", "0", "10.0.3.0/24"),
                pfx_db("peer", "10.0.3.0/24"),
                "peer", 1,
            )
        pending = d.pending
        d.pending = PendingUpdates()
        d._rebuild(pending)

    rec = get_recorder("replay-smoke")
    inputs = rec.export()
    assert inputs is not None and not inputs["gap"], "recorder gap"
    bundle_dir = os.path.join(tmp_dir, "replay-smoke-selftest")
    os.makedirs(bundle_dir, exist_ok=True)
    with open(os.path.join(bundle_dir, "bundle.json"), "w") as f:
        json.dump({
            "schema": "openr-tpu-flight-recorder/1",
            "node": "replay-smoke",
            "trigger": {"reason": "selftest", "ts_ms": 0, "detail": {}},
            "inputs": inputs,
        }, f, indent=1, sort_keys=True, default=str)
    return bundle_dir


def selftest(out_dir: str, verbose: bool = False) -> int:
    bundle_dir = _selftest_record(out_dir)
    print(f"recorded selftest bundle: {bundle_dir}")
    bundle = load_bundle(bundle_dir)
    report = replay_bundle(bundle, solver="cpu", verbose=verbose)
    _print_verdict(report)
    if report["status"] != "identical" or report["epochs_compared"] < 3:
        print("selftest FAILED: recording did not replay bit-identically")
        return 1
    # injected divergence must bisect to exactly the tampered epoch
    tampered = json.loads(json.dumps(bundle))
    victim = tampered["inputs"]["epochs"][1]
    victim["digest"] = ("0" * 16 if victim["digest"] != "0" * 16
                        else "f" * 16)
    report2 = replay_bundle(tampered, solver="cpu", verbose=verbose)
    fd = report2.get("first_divergent")
    if report2["status"] != "diverged" or fd["epoch"] != victim["epoch"]:
        print(
            f"selftest FAILED: injected divergence at epoch "
            f"{victim['epoch']} not bisected (got {fd})"
        )
        return 1
    print(
        f"selftest OK: bit-identical replay + injected divergence "
        f"bisected to epoch {fd['epoch']}"
    )
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.replay",
        description="replay a flight-recorder bundle's inputs annex and "
        "bit-compare per-epoch RIB digests",
    )
    ap.add_argument("bundle", nargs="?", help="bundle dir or bundle.json")
    ap.add_argument(
        "--solver", choices=("cpu", "tpu"), default="cpu",
        help="solver backend to replay on (default cpu)",
    )
    ap.add_argument(
        "--streaming", choices=("on", "off"), default="off",
        help="streaming pipeline for the replay solver (tpu only)",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="record + replay a two-node churn session "
                    "(CI smoke lane)")
    ap.add_argument("--out", default=".",
                    help="selftest bundle output directory")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest(args.out, verbose=args.verbose)
    if not args.bundle:
        ap.error("bundle path required (or --selftest)")
    bundle = load_bundle(args.bundle)
    report = replay_bundle(
        bundle,
        solver=args.solver,
        streaming=args.streaming == "on",
        verbose=args.verbose,
    )
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        _print_verdict(report)
    return {"identical": 0, "diverged": 1}.get(report["status"], 2)


if __name__ == "__main__":
    sys.exit(main())
