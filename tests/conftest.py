"""Test bootstrap.

Tests run JAX on CPU with 8 virtual devices so multi-chip sharding
(openr_tpu/parallel) is exercised without TPU hardware; the driver's bench
run uses the real chip. The axon sitecustomize pre-imports jax and pins
JAX_PLATFORMS=axon, so env-var overrides are ineffective — we override via
jax.config before any backend initializes (backends init lazily at first
device use, not at import).
"""

import asyncio
import functools
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# hermetic tests: never load persistent-cache AOT artifacts compiled for
# a different backend/machine-feature set (ops/xla_cache.py)
os.environ["OPENR_TPU_XLA_CACHE"] = "off"
# same for the serialized-executable cache: a developer's fleet-wide
# $OPENR_TPU_AOT_CACHE opt-in must not leak entries into (or out of)
# the suite; tests that exercise it configure a tmp dir explicitly
os.environ["OPENR_TPU_AOT_CACHE"] = "off"
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass


def run_async(fn):
    """Decorator: run an async test in a fresh event loop
    (no pytest-asyncio in the image)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=60))

    return wrapper
